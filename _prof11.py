import time, jax, jax.numpy as jnp, numpy as np
import xllm_service_tpu.runtime.engine as E
from xllm_service_tpu.config import EngineConfig, ModelConfig
from xllm_service_tpu.utils.types import SamplingParams

cfg = ModelConfig.llama3_1b()
ecfg = EngineConfig(page_size=64, num_pages=1024, max_model_len=1024,
                    max_batch_size=64, max_prefill_tokens=4096,
                    prefill_buckets=(128,), decode_steps=64)
t0 = time.perf_counter(); eng = E.Engine(cfg, ecfg, seed=0)
print(f"init {time.perf_counter()-t0:.1f}s")
t0 = time.perf_counter(); eng.warmup(); print(f"warmup {time.perf_counter()-t0:.1f}s")

sp = SamplingParams(max_tokens=8, temperature=0.0, ignore_eos=True)
t0 = time.perf_counter()
for i in range(64):
    eng.add_request(E.EngineRequest(request_id=f"r{i}", token_ids=list(range(1, 129)), sampling=sp))
print(f"add_requests {time.perf_counter()-t0:.2f}s")

orig_run = eng._run_prefill
def timed_run(batch):
    t = time.perf_counter()
    out = orig_run(batch)
    print(f"  _run_prefill batch={len(batch)}: {time.perf_counter()-t:.2f}s")
    return out
eng._run_prefill = timed_run

while eng.waiting:
    t = time.perf_counter()
    eng.step()
    print(f"step total {time.perf_counter()-t:.2f}s")
# one decode burst
t = time.perf_counter(); eng.step(); print(f"decode burst {time.perf_counter()-t:.2f}s")
