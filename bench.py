"""Headline benchmark: continuous-batching decode throughput + MFU on one chip.

Runs the flagship model (Llama-3.2-1B shapes, random weights) through the
real serving engine — paged KV cache, fused sampling, donated buffers — and
measures steady-state decode throughput, per-token latency (TPOT), and MFU
(model FLOPs utilization against the chip's bf16 peak).

The reference publishes no benchmark numbers (BASELINE.md); its implicit
performance envelope is the SLO default ``target_tpot`` = 50 ms/token
(reference common/global_gflags.cpp:100-102). ``vs_baseline`` is therefore
measured-TPOT headroom against that 50 ms SLO: value N means each token
arrives N× faster than the reference's own default target.

Resilience contract (this file must ALWAYS print exactly one JSON line):
- The default TPU backend is probed in a subprocess with a hard timeout —
  a hung or broken TPU tunnel (the round-1 failure: backend init raised
  UNAVAILABLE, and it can also hang indefinitely) can neither crash nor
  stall the bench; it falls back to CPU.
- The TPU measured run itself executes in a killable subprocess with its
  own sub-budget (the round-3 failure: warmup compiles through the
  tunnel's remote-compile path ran past the WHOLE budget, so the
  watchdog fired holding only an error payload — a 0.0 artifact). On
  timeout the parent still has time to land the CPU fallback number.
- Warmup inside the bench is scoped to exactly the programs its schedule
  hits (~4 compiles instead of the ~24 pow2-sweep — minutes each through
  the tunnel), and compiled programs persist in a jax compilation cache
  under the repo (.jax_cache/) so a rerun — in particular the driver's
  end-of-round run after a builder session already warmed the cache —
  pays no tunnel compiles at all.
- A watchdog thread emits an error-annotated JSON line and exits 0 if the
  whole run exceeds its budget.
- The measured run falls back down a ladder: TPU → tiny CPU run.

Prints exactly one JSON line:
  {"metric": "decode_throughput", "value": ..., "unit": "tokens/s",
   "vs_baseline": ..., "detail": {..., "mfu": ..., "tpot_ms": ...}}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

_EMIT_LOCK = threading.Lock()
_RESULT_EMITTED = threading.Event()
_STAGE = {"name": "start"}

_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".jax_cache")


def _enable_compile_cache() -> None:
    """Persist compiled executables across processes/sessions (shared
    helper: xllm_service_tpu/utils/jaxcache.py — same .jax_cache/ dir as
    the conviction-ladder tools and the worker). Through the tunneled TPU
    backend a single compile can take minutes; the cache is the
    difference between a bench that fits its budget and one that dies in
    warmup."""
    try:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from xllm_service_tpu.utils.jaxcache import enable_compile_cache
    except Exception:  # noqa: BLE001 — cache is an optimization only
        return
    enable_compile_cache(_CACHE_DIR)


def _emit(obj) -> None:
    # One JSON line, exactly once — the watchdog thread and the main
    # thread can race here at the budget boundary.
    with _EMIT_LOCK:
        if _RESULT_EMITTED.is_set():
            return
        _RESULT_EMITTED.set()
        print(json.dumps(obj), flush=True)


def _error_payload(msg: str) -> dict:
    return {
        "metric": "decode_throughput", "value": 0.0, "unit": "tokens/s",
        "vs_baseline": 0.0,
        "detail": {"error": msg, "stage": _STAGE["name"]},
    }


_DEADLINE = {"t": float("inf")}


def _watchdog(budget_s: float) -> threading.Timer:
    _DEADLINE["t"] = time.monotonic() + budget_s

    def fire() -> None:
        _emit(_error_payload(f"watchdog: exceeded {budget_s}s budget"))
        os._exit(0)
    t = threading.Timer(budget_s, fire)
    t.daemon = True
    t.start()
    return t


def _probe_backend(timeout_s: float) -> str:
    """Ask a subprocess whether the default JAX backend initializes.

    Returns the platform name ("tpu", "cpu", ...) or "" on failure/timeout.
    Run out-of-process so a hung PJRT plugin (tunneled TPU) can be killed.
    """
    code = ("import jax, sys; d = jax.devices(); "
            "sys.stdout.write('PLATFORM=' + d[0].platform)")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except (subprocess.TimeoutExpired, OSError):
        return ""
    if r.returncode != 0:
        return ""
    for tok in r.stdout.split():
        if tok.startswith("PLATFORM="):
            return tok.split("=", 1)[1]
    return ""


# Dense bf16 peak FLOP/s per chip, by device_kind substring (public specs).
_PEAK_FLOPS = (
    ("v6", 918e12),          # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),          # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def _chip_peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for tag, peak in _PEAK_FLOPS:
        if tag in kind:
            return peak
    return 0.0


def _matmul_params(params, cfg) -> int:
    """Parameters that each decoded token multiplies against (embedding
    gather excluded; tied lm_head counted once, as the head matmul)."""
    import jax
    total = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    if not cfg.tie_word_embeddings:
        total -= cfg.vocab_size * cfg.hidden_size   # embed is a gather
    return total


def scoped_warmup_shapes(ecfg, batch: int, prompt_len: int, gen_len: int):
    """Predict exactly the (prefill, decode) programs the bench schedule
    compiles, for Engine.warmup's scoped mode. The prediction mirrors the
    engine: prefill batches fill max_prefill_tokens at one prompt_len
    window each (pow2-padded batch, table wide enough for the sampled
    token's page); decode table widths are pow2(pages(live context))
    across the whole decoded trajectory including the fused burst's page
    lookahead (covered by the range endpoint prompt+gen). A missed shape
    is not a correctness problem — it compiles lazily and shows up in
    detail.phases recompile counters. Unit-tested against the real engine
    in tests/test_engine.py (zero post-warmup recompiles)."""
    pages = lambda n: -(-n // ecfg.page_size)   # noqa: E731
    pow2 = lambda n: 1 << max(n - 1, 0).bit_length()  # noqa: E731
    # The engine buckets each prefill window's T (engine._bucket): predict
    # with the bucketed value or a non-bucket-aligned prompt_len warms a
    # program the engine never runs.
    t_pf = next((b for b in ecfg.prefill_buckets if b >= prompt_len), None)
    if t_pf is None:
        raise ValueError(
            f"prompt_len {prompt_len} exceeds largest prefill bucket "
            f"{ecfg.prefill_buckets[-1]} — fix the bench shape, don't "
            "let it silently fall back to CPU")
    n_pf = min(batch, max(ecfg.max_prefill_tokens // prompt_len, 1))
    mp_pf = pow2(max(pages(prompt_len + 1), pages(t_pf)))
    sizes = {n_pf}
    if getattr(ecfg, "interleave", None) is not False:
        # Token-budget interleaving (engine._step_interleaved): once the
        # first batch is decoding, every iteration's fused decode burst
        # consumes part of the step budget, so later prefill batches
        # shrink down a batch-size ladder the warmup must cover too.
        # Bucket-snapped quanta keep T and MP fixed — only B varies.
        # Mirror the bench drain: full prompt_len windows, decode burst
        # of decode_steps tokens per running sequence, and the
        # starvation-deadline floor (engine._starvation_quantum)
        # admitting one prompt when the residual fits no window.
        waiting, running = batch - n_pf, n_pf
        while waiting > 0:
            budget = ecfg.max_prefill_tokens - running * ecfg.decode_steps
            n = min(waiting, max(budget // prompt_len, 0),
                    ecfg.max_batch_size - running)
            if n <= 0:
                n = 1
            sizes.add(n)
            waiting -= n
            running += n
    widths = sorted({
        min(pow2(pages(t)), ecfg.max_pages_per_seq)
        for t in range(prompt_len + 1, prompt_len + gen_len + 1)})
    return sorted({(pow2(n), t_pf, mp_pf) for n in sizes}), widths


def _run_bench(tiny: bool, force_cpu: bool = False,
               probe_failed: bool = False) -> dict:
    import jax

    from xllm_service_tpu.config import EngineConfig, ModelConfig
    from xllm_service_tpu.obs import (
        default_registry, histogram_fraction_le, histogram_quantile)
    from xllm_service_tpu.obs import steptrace
    from xllm_service_tpu.obs.slo import SloConfig
    from xllm_service_tpu.runtime.engine import Engine, EngineRequest
    from xllm_service_tpu.utils.types import FinishReason, SamplingParams

    if not (force_cpu or os.environ.get("JAX_PLATFORMS") == "cpu"):
        # Tunnel runs only: the CPU AOT cache path spams feature-mismatch
        # warnings and carries a SIGILL caveat (utils/jaxcache.py).
        _enable_compile_cache()
    if force_cpu:
        # The site hook pins jax_platforms="axon,cpu" at import, which
        # overrides the JAX_PLATFORMS env var — only an explicit config
        # update reliably keeps backend init away from a hung TPU tunnel.
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001
            pass
    dev = jax.devices()[0]
    platform = dev.platform
    if tiny:
        cfg = ModelConfig.tiny(vocab_size=1024)
        batch, prompt_len, gen_len, pages = 4, 32, 64, 64
        # BENCH_TINY_GEN trims the decode loop (same BENCH_* override
        # idiom as the TPU shape knobs) — the tier-1 provenance test
        # shrinks it so the full-suite budget doesn't pay 64 steps of
        # tiny-model decode for fields that 8 steps prove identically.
        gen_len = int(os.environ.get("BENCH_TINY_GEN", str(gen_len)))
        ecfg = EngineConfig(page_size=16, num_pages=pages,
                            max_model_len=256, max_batch_size=batch,
                            max_prefill_tokens=256,
                            prefill_buckets=(32, 64),
                            # Honored on the tiny path too so a CPU run
                            # can demonstrate the decode-pipeline
                            # overlap counters (default stays 1).
                            decode_steps=int(os.environ.get(
                                "BENCH_DECODE_STEPS", "1")))
    else:
        cfg = ModelConfig.llama3_1b()
        # Throughput shape: decode is weight-read-bound, so tokens/s (and
        # MFU) scale ~linearly with batch until HBM pressure; 64-step
        # fused bursts amortize the tunneled backend's ~80 ms host
        # round-trip (measured round 2) down to ~1.3 ms/token.
        batch, prompt_len, gen_len = 64, 128, 256
        # max_prefill_tokens covers the whole prompt set in ONE call:
        # round-3 hardware data showed ~5.6 s/prefill-call where the
        # math says tens of ms — per-call overhead dominates on the
        # tunneled backend, so fewer+bigger calls is both the honest
        # serving configuration and the faster one. Override to A/B:
        # BENCH_PREFILL_TOKENS=4096 restores the two-call split.
        # page_size 128 = the reference's own block-size default
        # (global_gflags.cpp:87-89) and HALVES the decode-attention
        # pallas grid (B x pages x layers cells/step) vs 64 — per-cell
        # overhead is a first-order term at B=64. Same pool bytes.
        ecfg = EngineConfig(page_size=int(os.environ.get(
                                "BENCH_PAGE_SIZE", "128")),
                            num_pages=int(os.environ.get(
                                "BENCH_NUM_PAGES", "512")),
                            max_model_len=1024, max_batch_size=batch,
                            max_prefill_tokens=int(os.environ.get(
                                "BENCH_PREFILL_TOKENS", "8192")),
                            prefill_buckets=(128,),
                            decode_steps=int(os.environ.get(
                                "BENCH_DECODE_STEPS", "64")))

    _STAGE["name"] = "engine-init"
    t_boot0 = time.monotonic()
    engine = Engine(cfg, ecfg, seed=0)
    _STAGE["name"] = "warmup"
    tw0 = time.monotonic()
    pf_shapes = widths = None
    if tiny:
        engine.warmup()
    else:
        # Scoped warmup: exactly the programs this schedule compiles.
        # Tunnel compiles run minutes each; the full pow2 sweep (~24
        # programs) belongs to serving startup, not a budgeted bench.
        pf_shapes, widths = scoped_warmup_shapes(
            ecfg, batch, prompt_len, gen_len)
        engine.warmup(prefill_shapes=pf_shapes, decode_widths=widths)
    warmup_s = time.monotonic() - tw0
    # Cold boot = engine construction + first warmup of this process
    # (through a persistent .jax_cache a rerun's "cold" is already
    # cache-served — detail.warmup_s vs boot_warm_s shows the split).
    boot_cold_s = time.monotonic() - t_boot0

    # Per-request latency trajectory, recorded into the SAME
    # service-plane histogram series (names + log buckets) the front
    # door exports, then scraped back out of the rendered exposition
    # with obs.histogram_quantile — the arithmetic a dashboard would
    # run, so BENCH_*.json percentiles and /metrics cannot drift apart.
    lat = default_registry()
    h_ttft = lat.histogram("xllm_service_ttft_ms")
    h_tpot = lat.histogram("xllm_service_tpot_ms")
    h_queue = lat.histogram("xllm_service_queue_wait_ms")
    h_e2e = lat.histogram("xllm_service_e2e_ms")

    sp = SamplingParams(max_tokens=gen_len, temperature=0.0, ignore_eos=True)
    t_add = {}
    t_submit = {}       # survives the first-token pop: e2e needs it
    for i in range(batch):
        # Distinct prompts: identical ones would prefix-cache-hit after
        # the first batch, silently benchmarking cache lookups instead of
        # prefill compute (and shifting later batch shapes off the scoped
        # warmup's prediction).
        engine.add_request(EngineRequest(
            request_id=f"bench-{i}",
            token_ids=[(i + j) % (cfg.vocab_size - 1) + 1
                       for j in range(prompt_len)],
            sampling=sp))
        t_add[f"bench-{i}"] = t_submit[f"bench-{i}"] = time.monotonic()
    # Prefill outside the timed window: the metric is steady-state decode.
    # Still measured — prefill is the compute-bound phase, so its MFU shows
    # what the matmul path achieves when not weight-read-bound.
    _STAGE["name"] = "prefill"
    tp0 = time.monotonic()
    while engine.waiting:
        t_step = time.monotonic()
        step_outs = engine.step()
        now = time.monotonic()
        for out in step_outs:
            # First output of a request = its first sampled token:
            # TTFT from submission; queue wait = time spent waiting for
            # the step that scheduled its prefill to begin.
            ta = t_add.pop(out.request_id, None)
            if ta is not None:
                h_ttft.observe(1000.0 * (now - ta))
                h_queue.observe(1000.0 * (t_step - ta))
            if out.finish_reason != FinishReason.NONE:
                h_e2e.observe(1000.0 * (now - t_submit[out.request_id]))
    prefill_s = time.monotonic() - tp0
    prefill_tokens = batch * prompt_len

    _STAGE["name"] = "decode"
    t0 = time.monotonic()
    tokens = 0
    # Per-step roofline attribution against the warmup-captured
    # cost_analysis table (same verdict arithmetic as the worker's
    # flight recorder) — (wall ms, tokens, ragged?) per iteration.
    step_samples = []
    while engine.has_work():
        t_step = time.monotonic()
        step_outs = engine.step()
        step_el = time.monotonic() - t_step
        step_tok = sum(len(out.new_token_ids) for out in step_outs)
        step_samples.append(
            (1000.0 * step_el, step_tok, engine.last_step_ragged))
        for out in step_outs:
            tokens += len(out.new_token_ids)
            if out.new_token_ids:
                # Per-token latency of this sequence in this step; a
                # fused burst amortizes one step across N tokens.
                h_tpot.observe(1000.0 * step_el / len(out.new_token_ids))
            if out.finish_reason != FinishReason.NONE:
                h_e2e.observe(1000.0 * (time.monotonic()
                                        - t_submit[out.request_id]))
    elapsed = time.monotonic() - t0

    # Prefix-reuse health, through the SAME rendered-exposition path a
    # live worker exports (scrape-don't-peek: the detail number comes
    # from parsing the text exposition, so it is the dashboard's number,
    # not a parallel bookkeeping path).
    pc = engine.prefix_cache_stats()
    lat.counter("xllm_worker_prefix_cache_hit_tokens_total").set_total(
        pc["hit_tokens_total"])
    lat.counter("xllm_worker_prefix_cache_lookups_total").set_total(
        pc["lookups_total"])

    lat_scrape = lat.render()

    def _q(family: str, q: float):
        v = histogram_quantile(lat_scrape, family, q)
        return round(v, 3) if v is not None else None

    # SLO attainment against the configured targets (XLLM_SLO_* env,
    # same defaults as the live /admin/slo engine), from the SAME
    # scraped buckets as the percentiles above — BENCH_*.json tracks
    # the fraction of requests under target per round.
    slo_thr = {o.name: o.threshold_ms
               for o in SloConfig.from_env().objectives}

    def _attainment(family: str, threshold_ms: float):
        v = histogram_fraction_le(lat_scrape, family, threshold_ms)
        return round(v, 4) if v is not None else None

    def _counter(family: str) -> float:
        from xllm_service_tpu.obs.expfmt import parse_exposition
        samples, _types, _errs = parse_exposition(lat_scrape)
        return sum(v for name, _labels, v in samples if name == family)

    # Fraction of prompt tokens the prefix cache covered this run
    # (local hits + tier restores + cross-worker fetches over ALL
    # prompt tokens the run admitted) — scraped back out of the
    # rendered exposition like the latency percentiles.
    pc_hit = _counter("xllm_worker_prefix_cache_hit_tokens_total")
    prefix_cached_token_ratio = (
        round(pc_hit / prefill_tokens, 4) if prefill_tokens else None)

    # "No routed request ever pays a compile", proven per round: the
    # post-warmup recompile counters after the measured run, and the
    # warm re-boot cost (same warmup sweep with every program already
    # compiled — dispatch-only, so seconds of delta vs boot_cold_s IS
    # the compile bill warmup absorbed).
    recompiles_post_warmup = sum(
        v for k, v in engine.phase_counts.items()
        if k.endswith(".recompile"))
    _STAGE["name"] = "warm-reboot"
    tb0 = time.monotonic()
    if tiny:
        engine.warmup()
    else:
        engine.warmup(prefill_shapes=pf_shapes, decode_widths=widths)
    boot_warm_s = time.monotonic() - tb0

    throughput = tokens / elapsed
    steps = tokens / batch              # decode iterations per sequence
    tpot_ms = 1000.0 * elapsed / max(steps, 1)
    # Pipelined-decode overlap health (speculative next-burst dispatch,
    # XLLM_DECODE_PIPELINE): how often burst k+1 was consumed as
    # speculated, and the host-side device-idle bubble per burst
    # boundary the pipeline did not cover — with the split
    # device_wait/host_copy readback phases (detail.phases below) this
    # is what proves the overlap win on the next BENCH_*.json.
    overlap = engine.overlap_metrics()

    # MFU: FLOPs each decoded token costs = 2 * matmul params + attention
    # reads over the mean live context (2 FLOPs/MAC; QK^T and PV each touch
    # Hq*Dh*context per layer).
    n_matmul = _matmul_params(engine.params, cfg)
    mean_ctx = prompt_len + gen_len / 2.0
    attn_flops = 4.0 * cfg.num_layers * cfg.num_heads * cfg.head_dim \
        * mean_ctx
    flops_per_token = 2.0 * n_matmul + attn_flops
    achieved = flops_per_token * throughput
    peak = _chip_peak_flops(dev)
    mfu = achieved / peak if peak > 0 else None

    # Per-step roofline verdicts over the decode loop: MFU and debt
    # (wall ms minus the modeled floor) of the MEDIAN iteration, from
    # the warmup-captured cost_analysis table — the BENCH-side twin of
    # xllm_worker_step_mfu / xllm_worker_step_debt_ms, so the artifact
    # and the live exposition share numerators. None when the capture
    # is off (XLLM_ROOFLINE=0) or the backend would not answer.
    step_mfu_p50 = decode_debt_ms = None
    if engine.roofline and step_samples:
        st_pf, st_pb = steptrace.peaks_for(getattr(dev, "device_kind", ""))
        verdicts = [steptrace.attribute_step(
            engine.roofline, kind="decode", step_ms=ms,
            prefill_tokens=0, decode_tokens=tok,
            batch_size=ecfg.max_batch_size,
            decode_steps=ecfg.decode_steps, ragged=ragged,
            peak_flops=st_pf, peak_bytes_s=st_pb)
            for ms, tok, ragged in step_samples]
        mfus = sorted(v["mfu"] for v in verdicts)
        debts = sorted(v["debt_ms"] for v in verdicts)
        step_mfu_p50 = round(mfus[len(mfus) // 2], 4)
        decode_debt_ms = round(debts[len(debts) // 2], 3)

    burst = None
    if tiny or os.environ.get("BENCH_BURST") == "1":
        _STAGE["name"] = "burst-goodput"
        burst = _burst_goodput_section(
            engine, cfg, ecfg, prompt_len, gen_len,
            target_ttft_ms=slo_thr["ttft"])

    mixed = None
    if tiny or os.environ.get("BENCH_MIXED") == "1":
        _STAGE["name"] = "mixed-step"
        mixed = _mixed_step_section(cfg, ecfg, prompt_len, gen_len)

    kv_probe = None
    if not tiny and platform != "cpu":
        # BASELINE.md north-star row: KV-migration GB/s on the real chip,
        # folded into the headline artifact. Skipped (with a reason) when
        # the remaining budget can't absorb its second-engine init +
        # probe compiles, or when BENCH_KV_PROBE=0.
        _STAGE["name"] = "kv-probe"
        kv_probe = _maybe_kv_probe(engine, cfg, ecfg)

    return {
        "metric": "decode_throughput",
        "value": round(throughput, 2),
        "unit": "tokens/s",
        "vs_baseline": round(50.0 / tpot_ms, 3),
        "detail": {
            # Distinguishes "CPU because the TPU tunnel never answered"
            # from an intentional CPU run when reading fallback results.
            **({"tpu_probe": "failed"} if probe_failed else {}),
            "model": cfg.name, "platform": platform,
            "device_kind": getattr(dev, "device_kind", ""),
            # Which gated kernels this run used (A/B bookkeeping).
            # XLLM_PALLAS_KV / XLLM_WRITE_THEN_ATTEND default to AUTO
            # (follow XLLM_PALLAS), not off — recording unset as "0"
            # would claim a feature-off run for a feature-on number.
            "kernel_flags": {
                **{k: os.environ.get(k, "0") for k in
                   ("XLLM_PALLAS", "XLLM_PALLAS_PREFILL",
                    "XLLM_RAGGED_ATTN")},
                **{k: os.environ.get(k, "auto") for k in
                   ("XLLM_PALLAS_KV", "XLLM_WRITE_THEN_ATTEND",
                    "XLLM_DECODE_PIPELINE")}},
            # The .bench_env lines applied at startup (key → effective
            # value), so a headline number records which hands-free
            # conviction gates were active when it was measured.
            "bench_env": dict(_BENCH_ENV),
            "batch": batch, "prompt_len": prompt_len, "gen_len": gen_len,
            # Same precision as boot_cold_s: boot_cold ⊇ warmup must
            # survive rounding (boot_cold_s >= warmup_s is asserted in
            # tests/test_engine.py).
            "warmup_s": round(warmup_s, 2),
            "boot_cold_s": round(boot_cold_s, 2),
            "boot_warm_s": round(boot_warm_s, 2),
            "recompiles_post_warmup": recompiles_post_warmup,
            "tpot_ms": round(tpot_ms, 3),
            "decode_overlap_hit_ratio": round(overlap["hit_ratio"], 4),
            "decode_device_idle_ms_per_burst": round(
                overlap["device_idle_ms_per_burst"], 3),
            "decode_overlap_spec": {
                "dispatches": overlap["spec_dispatches"],
                "hits": overlap["spec_hits"],
                "rollbacks": overlap["spec_rollbacks"]},
            # Latency trajectory, scraped from the service-plane
            # histogram series recorded above (log-bucket interpolated
            # — dashboard-faithful, not exact order statistics).
            "ttft_ms_p50": _q("xllm_service_ttft_ms", 0.50),
            "ttft_ms_p90": _q("xllm_service_ttft_ms", 0.90),
            "ttft_ms_p99": _q("xllm_service_ttft_ms", 0.99),
            "tpot_ms_p50": _q("xllm_service_tpot_ms", 0.50),
            "tpot_ms_p90": _q("xllm_service_tpot_ms", 0.90),
            "tpot_ms_p99": _q("xllm_service_tpot_ms", 0.99),
            "queue_wait_ms_p99": _q("xllm_service_queue_wait_ms", 0.99),
            "e2e_ms_p99": _q("xllm_service_e2e_ms", 0.99),
            "prefix_cached_token_ratio": prefix_cached_token_ratio,
            "slo_ttft_attainment": _attainment(
                "xllm_service_ttft_ms", slo_thr["ttft"]),
            "slo_e2e_attainment": _attainment(
                "xllm_service_e2e_ms", slo_thr["e2e"]),
            "slo_targets_ms": {"ttft": slo_thr["ttft"],
                               "e2e": slo_thr["e2e"]},
            "mfu": round(mfu, 4) if mfu is not None else None,
            # Median per-step roofline verdict (computed above); the
            # aggregate "mfu" smooths over scheduling, these do not.
            "step_mfu_p50": step_mfu_p50,
            "decode_debt_ms": decode_debt_ms,
            "prefill_tokens_per_s": round(prefill_tokens / prefill_s, 1),
            # Prefill runs the lm_head only on the LAST position per
            # sequence (forward_prefill return_all_logits=False), so
            # per-prompt-token FLOPs exclude the head matmul.
            "prefill_mfu": round(
                2.0 * (n_matmul - cfg.vocab_size * cfg.hidden_size)
                * (prefill_tokens / prefill_s) / peak, 4)
            if peak > 0 else None,
            "model_flops_per_token": flops_per_token,
            "chip_peak_flops": peak,
            # Host/device wall-time attribution per engine phase
            # (dispatch is async-call time; the former conflated
            # readback is split into device_wait — wait for the
            # producing computation — vs host_copy — the residual
            # device→host materialization).
            "phases": engine.phase_report(),
            # Burst goodput through the loadgen summarizer (same verdict
            # arithmetic as the closed-loop harness); the top-level
            # goodput key tracks the burst scenario — the number the
            # interleaver is accountable for.
            **({"goodput_under_slo": burst["goodput_under_slo"],
                "burst": burst} if burst else {}),
            # One-dispatch mixed-iteration A/B (XLLM_RAGGED_ATTN);
            # dispatches_per_mixed_step is the headline pair — 1.0 on
            # the ragged path vs >=2 on the split per-phase path.
            **({"mixed_step": mixed,
                "dispatches_per_mixed_step":
                    mixed["dispatches_per_mixed_step"]}
               if mixed else {}),
            **({"kv_migration": kv_probe} if kv_probe else {}),
            "reference_baseline": "target_tpot=50ms SLO default "
                                  "(no published numbers)",
        },
    }


def _burst_goodput_section(engine, cfg, ecfg, prompt_len: int,
                           gen_len: int, target_ttft_ms: float) -> dict:
    """Goodput-under-SLO under a prompt burst, at the engine level.

    Short decode streams run steady, then a wave of long prompts lands
    mid-decode — the scenario the token-budget interleaver exists for.
    Per-request TTFT/TPOT feed benchmarks.loadgen.summarize_results, the
    SAME verdict + percentile arithmetic as the closed-loop HTTP
    harness, so BENCH_*.json and loadgen cannot drift. Tiny/CPU runs
    only by default (BENCH_BURST=1 forces): its small prefill batches
    are outside the scoped warmup's shape prediction, and a tunneled
    TPU compile costs minutes per shape."""
    from benchmarks.loadgen import RequestResult, summarize_results
    from xllm_service_tpu.runtime.engine import EngineRequest
    from xllm_service_tpu.utils.types import SamplingParams

    n = min(ecfg.max_batch_size, 4)
    vocab = cfg.vocab_size - 1
    t_sub: dict = {}
    first: dict = {}
    last: dict = {}
    ntok: dict = {}

    def _add(rid: str, plen: int, max_tokens: int, salt: int) -> None:
        engine.add_request(EngineRequest(
            request_id=rid,
            token_ids=[(salt + j) % vocab + 1 for j in range(plen)],
            sampling=SamplingParams(max_tokens=max_tokens,
                                    temperature=0.0, ignore_eos=True)))
        t_sub[rid] = time.monotonic()

    def _drain_steps(stop_when_idle: bool, steps: int = 0) -> None:
        done = 0
        while engine.has_work() if stop_when_idle else done < steps:
            outs = engine.step()
            now = time.monotonic()
            done += 1
            for out in outs:
                if out.new_token_ids:
                    rid = out.request_id
                    first.setdefault(rid, now)
                    last[rid] = now
                    ntok[rid] = ntok.get(rid, 0) + len(out.new_token_ids)

    t0 = time.monotonic()
    for i in range(n):
        _add(f"stream-{i}", max(prompt_len // 4, 4),
             min(gen_len, 32), salt=7000 + 31 * i)
    _drain_steps(stop_when_idle=False, steps=4)
    for i in range(n):
        _add(f"burst-{i}", prompt_len, 8, salt=9000 + 53 * i)
    _drain_steps(stop_when_idle=True)
    wall = time.monotonic() - t0

    results = []
    for rid, ts in t_sub.items():
        f, l, k = first.get(rid), last.get(rid), ntok.get(rid, 0)
        r = RequestResult(ok=f is not None, num_tokens=k)
        if f is not None:
            r.ttft_ms = 1000.0 * (f - ts)
            r.total_ms = 1000.0 * (l - ts)
            if k > 1:
                r.tpot_ms = 1000.0 * (l - f) / (k - 1)
        results.append(r)
    s = summarize_results(results, wall, target_ttft_ms=target_ttft_ms,
                          target_tpot_ms=50.0)
    return {"goodput_under_slo": s["goodput_under_slo"],
            "num_ok": s["num_ok"],
            "ttft_ms_p99": s["ttft_ms"]["p99"],
            "tpot_ms_p99_under_burst": s["tpot_ms"]["p99"]}


def _mixed_step_section(cfg, ecfg, prompt_len: int,
                        gen_len: int) -> dict:
    """One-dispatch ragged mixed iterations vs the split per-phase
    path, at the engine level (XLLM_RAGGED_ATTN A/B).

    Two fresh engines — identical except ``ragged_attn`` — each drive
    decode streams and land a prompt mid-decode, and every MIXED
    iteration logs its attention-dispatch count
    (``last_step_attn_dispatches``) and wall ms. The ragged leg must
    average exactly 1.0 dispatches per mixed step; the split leg pays
    one decode program plus one prefill program (>= 2). Tiny/CPU runs
    only by default (BENCH_MIXED=1 forces): like the burst section,
    its small shapes sit outside a hardware run's scoped warmup."""
    import dataclasses

    from xllm_service_tpu.runtime.engine import Engine, EngineRequest
    from xllm_service_tpu.utils.types import SamplingParams

    vocab = cfg.vocab_size - 1
    plen = max(prompt_len // 4, 4)

    def drive(ragged: bool) -> dict:
        e2 = dataclasses.replace(ecfg, ragged_attn=ragged)
        # Defeat any XLLM_RAGGED_ATTN env override __post_init__
        # applied — the A/B must flip the gate regardless of env.
        e2.ragged_attn = ragged
        eng = Engine(cfg, e2, seed=0)
        toks: dict = {}
        mixed_ms: list = []
        dispatches: list = []
        ragged_steps = 0

        def _step():
            nonlocal ragged_steps
            t0 = time.monotonic()
            outs = eng.step()
            ms = 1000.0 * (time.monotonic() - t0)
            if eng.last_step_kind == "mixed":
                mixed_ms.append(ms)
                dispatches.append(eng.last_step_attn_dispatches)
                if eng.last_step_ragged:
                    ragged_steps += 1
            for o in outs:
                toks.setdefault(o.request_id, []).extend(o.new_token_ids)

        sp = SamplingParams(max_tokens=min(gen_len, 16),
                            temperature=0.0, ignore_eos=True)
        eng.add_request(EngineRequest(
            request_id="stream-0",
            token_ids=[(7001 + j) % vocab + 1 for j in range(plen)],
            sampling=sp))
        for _ in range(2):
            _step()
        # Prompts landing mid-decode — the mixed iterations under test.
        for i in range(max(min(ecfg.max_batch_size, 4) - 1, 1)):
            eng.add_request(EngineRequest(
                request_id=f"mid-{i}",
                token_ids=[(9001 + 53 * i + j) % vocab + 1
                           for j in range(plen)],
                sampling=sp))
        steps = 0
        while eng.has_work() and steps < 500:
            _step()
            steps += 1
        n = len(dispatches)
        return {
            "mixed_steps": n,
            "ragged_steps": ragged_steps,
            "dispatches_per_mixed_step":
                round(sum(dispatches) / n, 3) if n else None,
            "mixed_step_ms_mean":
                round(sum(mixed_ms) / n, 3) if n else None,
            "tokens": toks,
        }

    on = drive(True)
    off = drive(False)
    # Temperature-0 streams must not depend on the dispatch plan.
    identical = on.pop("tokens") == off.pop("tokens")
    return {
        "ragged_on": on, "ragged_off": off,
        "streams_identical": identical,
        "dispatches_per_mixed_step": {
            "ragged_on": on["dispatches_per_mixed_step"],
            "ragged_off": off["dispatches_per_mixed_step"]},
    }


def _maybe_kv_probe(engine, cfg, ecfg) -> dict:
    """KV GB/s (direct + host-shuttle) using the bench engine as source
    and a fresh pool-identical engine as destination."""
    if os.environ.get("BENCH_KV_PROBE", "1") == "0":
        return {"skipped": "BENCH_KV_PROBE=0"}
    remaining = _DEADLINE["t"] - time.monotonic()
    if remaining < 240:
        return {"skipped": f"only {remaining:.0f}s of budget left"}
    try:
        from xllm_service_tpu.runtime.engine import Engine
        from xllm_service_tpu.runtime.kv_transfer import probe_kv_migration
        dst = Engine(cfg, ecfg, seed=1)
        out = probe_kv_migration(engine, dst,
                                 n_pages=min(128, ecfg.num_pages // 2),
                                 iters=3)
        return {"direct_gbps": round(out["direct_gbps"], 2),
                "host_shuttle_gbps": round(out["host_gbps"], 2),
                "host_pipelined_gbps": round(
                    out["host_pipelined_gbps"], 2),
                "block_mb": round(out["bytes"] / 1e6, 1),
                "pages": int(out["pages"])}
    except Exception as exc:  # noqa: BLE001 — probe must not kill the bench
        return {"error": f"{type(exc).__name__}: {exc}"}


# Keys/values .bench_env carried this run, with the value actually in
# effect (a caller's explicit env overrides the file). Lands in the
# result JSON so headline numbers record which gates were active —
# every process (parent, TPU child, CPU fallback) re-reads the same
# file at its own startup, so the snapshot is always populated.
_BENCH_ENV: dict = {}


def _load_bench_env() -> None:
    """Apply KEY=VAL lines from .bench_env (written by
    tools/act_on_convictions.py after the conviction ladder) without
    overriding anything the caller set explicitly — the hands-free path
    for validated-and-winning kernel gates to reach the watcher's
    headline bench and the driver's end-of-round rerun."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench_env")
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                k, v = line.split("=", 1)
                k = k.strip()
                os.environ.setdefault(k, v.strip())
                _BENCH_ENV[k] = os.environ[k]   # the EFFECTIVE value
    except OSError:
        pass


def main() -> None:
    _load_bench_env()
    budget = float(os.environ.get("BENCH_WATCHDOG_S", "900"))
    _watchdog(budget)
    t_start = time.monotonic()

    if os.environ.get("BENCH_ROLE") == "measure":
        # Child of the orchestrating parent below: the backend probe
        # already succeeded, so measure directly and print the one line.
        # A hang here is killed by the parent's subprocess timeout.
        try:
            _emit(_run_bench(tiny=bool(os.environ.get("BENCH_TINY"))))
        except Exception as exc:  # noqa: BLE001
            _emit(_error_payload(f"{type(exc).__name__}: {exc}"))
        return

    _STAGE["name"] = "backend-probe"
    requested_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    if requested_cpu:
        platform = "cpu"           # caller pinned CPU on purpose
    else:
        # A wedged TPU tunnel can recover minutes later (observed: a
        # killed holder process stalls the chip, then it comes back) —
        # keep probing instead of writing the round off after one
        # attempt. Retries use a SHORT timeout (a hung first probe would
        # otherwise eat the whole retry window), and the guard accounts
        # for the sleep so the loop truly stops by 1/3 of the budget,
        # leaving the rest for tunnel-speed warmup + the measured run
        # (and, failing that, the CPU fallback) before the watchdog.
        probe_t = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "180"))
        retry_t = min(probe_t, 60.0)
        deadline = time.monotonic() + budget / 3.0
        platform = _probe_backend(probe_t)
        while not platform and \
                time.monotonic() + 30 + retry_t < deadline:
            time.sleep(30)
            platform = _probe_backend(retry_t)
    probe_failed = not platform
    if not platform:
        # TPU tunnel broken or hung — pin this process to CPU before any
        # backend initialization happens.
        os.environ["JAX_PLATFORMS"] = "cpu"
        platform = "cpu"

    tiny = bool(os.environ.get("BENCH_TINY")) or platform == "cpu"
    last_err = "no attempts ran"
    # Whether this invocation ever WANTED a TPU: distinguishes a genuine
    # fallback (probe failed / measure subprocess died) from an ordinary
    # CPU run (pinned by caller, or a machine with no TPU to begin with).
    tpu_expected = probe_failed or platform not in ("", "cpu")

    if platform != "cpu":
        # TPU measured run in a KILLABLE subprocess: a warmup/compile that
        # outlives its sub-budget (round-3 failure mode: tunnel compiles
        # run minutes each) must not eat the parent's whole budget — the
        # parent still needs time to land the CPU fallback number.
        elapsed = time.monotonic() - t_start
        reserve = 180.0                      # CPU fallback headroom
        tpu_budget = max(budget - elapsed - reserve, 120.0)
        # The 120s floor must never push past the watchdog itself: cap at
        # what actually remains, less a margin for the fallback child.
        tpu_budget = min(tpu_budget,
                         max(budget - elapsed - 60.0, 60.0))
        env = dict(os.environ, BENCH_ROLE="measure",
                   BENCH_WATCHDOG_S=str(int(tpu_budget + 60)))
        if tiny:
            env["BENCH_TINY"] = "1"
        try:
            _STAGE["name"] = "tpu-subprocess"
            r = subprocess.run([sys.executable, __file__],
                               capture_output=True, text=True,
                               timeout=tpu_budget, env=env)
            line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() \
                else ""
            parsed = json.loads(line)
            if parsed.get("value", 0) > 0:
                _emit(parsed)
                return
            last_err = "tpu subprocess: " + str(
                parsed.get("detail", {}).get("error", "value 0"))
        except Exception as exc:  # noqa: BLE001
            last_err = f"tpu subprocess failed: {exc!r}"

    if platform == "cpu" and os.environ.get("BENCH_NO_FALLBACK"):
        # Pinned-CPU leaf invocation: measure inline, no recursion.
        try:
            _emit(_run_bench(tiny=True, force_cpu=True,
                             probe_failed=probe_failed))
        except Exception as exc:  # noqa: BLE001
            _emit(_error_payload(f"{type(exc).__name__}: {exc}"))
        return

    # CPU fallback. The backend may already be initialized in-process;
    # a clean run needs a fresh process pinned to CPU.
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_TINY="1",
               BENCH_NO_FALLBACK="1")
    env.pop("BENCH_ROLE", None)
    try:
        remaining = budget - (time.monotonic() - t_start)
        r = subprocess.run([sys.executable, __file__],
                           capture_output=True, text=True,
                           timeout=max(remaining - 20, 30), env=env)
        line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
        parsed = json.loads(line)
        if tpu_expected:
            # Only a run that WANTED a TPU and landed here is a fallback;
            # a CPU-pinned run or a machine with no TPU is just a CPU run.
            parsed.setdefault("detail", {})["fallback"] = "cpu-subprocess"
            if probe_failed:
                parsed["detail"]["tpu_probe"] = "failed"
            if last_err != "no attempts ran":
                parsed["detail"]["tpu_error"] = last_err
            # Provenance, clearly labeled: the most recent BUILDER-run
            # TPU result (committed as BENCH_TPU_LAST.json), so a
            # wedged-chip fallback still records what the chip did
            # earlier. value/platform above remain THIS run's truth.
            last_tpu = os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "BENCH_TPU_LAST.json")
            if os.path.exists(last_tpu):
                try:
                    with open(last_tpu, "r", encoding="utf-8") as f:
                        prior = json.load(f)
                    parsed["detail"]["last_builder_tpu_run"] = {
                        "value": prior.get("value"),
                        "unit": prior.get("unit"),
                        "captured": prior.get("captured"),
                        "mfu": prior.get("detail", {}).get("mfu"),
                        "tpot_ms": prior.get("detail", {}).get("tpot_ms"),
                        "kv_migration": prior.get("detail", {}).get(
                            "kv_migration"),
                    }
                except Exception:  # noqa: BLE001 — provenance is optional
                    pass
        _emit(parsed)
        return
    except Exception as exc:  # noqa: BLE001
        last_err = f"cpu-subprocess fallback failed: {exc!r} (after {last_err})"

    _emit(_error_payload(last_err))


if __name__ == "__main__":
    main()
    sys.exit(0)
