"""Headline benchmark: continuous-batching decode throughput on one chip.

Runs the flagship model (Llama-3.2-1B shapes, random weights) through the
real serving engine — paged KV cache, fused sampling, donated buffers — and
measures steady-state decode throughput and per-token latency (TPOT).

The reference publishes no benchmark numbers (BASELINE.md); its implicit
performance envelope is the SLO default ``target_tpot`` = 50 ms/token
(reference common/global_gflags.cpp:100-102). ``vs_baseline`` is therefore
measured-TPOT headroom against that 50 ms SLO: value N means each token
arrives N× faster than the reference's own default target.

Prints exactly one JSON line:
  {"metric": "decode_throughput", "value": ..., "unit": "tokens/s",
   "vs_baseline": ...}
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> None:
    import jax

    from xllm_service_tpu.config import EngineConfig, ModelConfig
    from xllm_service_tpu.runtime.engine import Engine, EngineRequest
    from xllm_service_tpu.utils.types import SamplingParams

    platform = jax.devices()[0].platform
    tiny = bool(os.environ.get("BENCH_TINY")) or platform == "cpu"
    if tiny:
        cfg = ModelConfig.tiny(vocab_size=1024)
        batch, prompt_len, gen_len, pages = 4, 32, 64, 64
        ecfg = EngineConfig(page_size=16, num_pages=pages,
                            max_model_len=256, max_batch_size=batch,
                            max_prefill_tokens=256,
                            prefill_buckets=(32, 64))
    else:
        cfg = ModelConfig.llama3_1b()
        batch, prompt_len, gen_len = 8, 128, 256
        ecfg = EngineConfig(page_size=64, num_pages=512,
                            max_model_len=1024, max_batch_size=batch,
                            max_prefill_tokens=2048,
                            prefill_buckets=(128,),
                            decode_steps=int(os.environ.get(
                                "BENCH_DECODE_STEPS", "8")))

    engine = Engine(cfg, ecfg, seed=0)
    engine.warmup()

    sp = SamplingParams(max_tokens=gen_len, temperature=0.0, ignore_eos=True)
    for i in range(batch):
        engine.add_request(EngineRequest(
            request_id=f"bench-{i}",
            token_ids=list(range(1, prompt_len + 1)),
            sampling=sp))
    # Prefill outside the timed window: the metric is steady-state decode.
    while engine.waiting:
        engine.step()

    t0 = time.monotonic()
    tokens = 0
    while engine.has_work():
        for out in engine.step():
            tokens += len(out.new_token_ids)
    elapsed = time.monotonic() - t0

    throughput = tokens / elapsed
    steps = tokens / batch              # decode iterations per sequence
    tpot_ms = 1000.0 * elapsed / max(steps, 1)
    print(json.dumps({
        "metric": "decode_throughput",
        "value": round(throughput, 2),
        "unit": "tokens/s",
        "vs_baseline": round(50.0 / tpot_ms, 3),
        "detail": {
            "model": cfg.name, "platform": platform, "batch": batch,
            "prompt_len": prompt_len, "gen_len": gen_len,
            "tpot_ms": round(tpot_ms, 3),
            "reference_baseline": "target_tpot=50ms SLO default "
                                  "(no published numbers)",
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
