"""TPU-native compute ops: norms, rotary embeddings, paged attention, sampling.

These are the building blocks of the worker engine's forward pass — the part
of the stack the reference (`czynb666/xllm-service`) delegates to the
out-of-repo NPU engine (SURVEY.md §2.3). Everything here is pure-functional
JAX, static-shaped, and jit-friendly; the Pallas kernels in
``xllm_service_tpu.ops.pallas`` provide TPU-optimized versions of the hot
paths with these as reference/fallback implementations.
"""

from xllm_service_tpu.ops.norm import rms_norm
from xllm_service_tpu.ops.rope import apply_rope, rope_cos_sin
from xllm_service_tpu.ops.attention import (
    mha_prefill,
    paged_decode_attention,
    gather_pages,
    write_prefill_kv,
    write_decode_kv,
)
from xllm_service_tpu.ops.sampling import sample_tokens, greedy

__all__ = [
    "rms_norm",
    "apply_rope",
    "rope_cos_sin",
    "mha_prefill",
    "paged_decode_attention",
    "gather_pages",
    "write_prefill_kv",
    "write_decode_kv",
    "sample_tokens",
    "greedy",
]
