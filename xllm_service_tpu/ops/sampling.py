"""Token sampling: greedy, temperature, top-k, top-p, penalties — batched
and jit-safe.

Per-sequence sampling parameters arrive as dense arrays (one scalar per batch
slot) so a single compiled program serves every request mix; there is no
per-request recompilation. ``temperature == 0`` selects greedy via
``jnp.where``, not Python control flow.

OpenAI contract coverage (reference proto carries these end to end,
xllm/chat.proto:1-192 — the rebuild must not silently drop them):
- per-request ``seed``: each row derives its own PRNG key inside the
  compiled step — ``fold_in(PRNGKey(seed), position)`` — so a seeded
  request's token stream is deterministic regardless of batch composition;
- ``presence_penalty`` / ``frequency_penalty``: applied against a [B, V]
  output-token count tensor that lives on device (engine carries it only
  while some active slot uses penalties);
- ``logprobs`` / ``top_logprobs``: chosen-token logprob always computed;
  top-k alternatives computed in-step when the engine enables them.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


class SamplingTensors(NamedTuple):
    """Per-slot sampling state, shape [B] each."""

    temperature: jnp.ndarray            # float32; 0.0 → greedy
    top_p: jnp.ndarray                  # float32 in (0, 1]
    top_k: jnp.ndarray                  # int32; 0 → disabled
    # Defaults (None) mean "feature off for the whole batch" — direct
    # construction stays terse; ``unpack`` always fills them in.
    seed: Optional[jnp.ndarray] = None        # int32; -1 → unseeded
    presence: Optional[jnp.ndarray] = None    # float32; 0.0 → off
    frequency: Optional[jnp.ndarray] = None   # float32; 0.0 → off

    # Packed-transfer form: six per-slot vectors ride host->device as TWO
    # arrays (float [B,4], int [B,2]) instead of six — each separate
    # upload pays the backend's fixed dispatch RTT (~80 ms through the
    # tunneled TPU), so the hot engine paths ship the packed pair and
    # reconstruct the tuple *inside* the jitted step via ``unpack``.
    @staticmethod
    def pack_batch(params_list):
        import numpy as np
        f32 = np.empty((len(params_list), 4), np.float32)
        i32 = np.empty((len(params_list), 2), np.int32)
        for i, p in enumerate(params_list):
            f32[i, 0] = p.temperature
            f32[i, 1] = p.top_p
            f32[i, 2] = p.presence_penalty
            f32[i, 3] = p.frequency_penalty
            i32[i, 0] = p.top_k
            i32[i, 1] = -1 if p.seed is None else int(p.seed)
        return f32, i32

    @classmethod
    def unpack(cls, f32: jnp.ndarray, i32: jnp.ndarray) -> "SamplingTensors":
        return cls(temperature=f32[:, 0], top_p=f32[:, 1],
                   presence=f32[:, 2], frequency=f32[:, 3],
                   top_k=i32[:, 0], seed=i32[:, 1])

def greedy(logits: jnp.ndarray) -> jnp.ndarray:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def apply_penalties(logits: jnp.ndarray, counts: jnp.ndarray,
                    tensors: SamplingTensors) -> jnp.ndarray:
    """OpenAI presence/frequency penalties over output-token ``counts``
    [B, V] (vLLM semantics: generated tokens only, prompt excluded)."""
    logits = logits.astype(jnp.float32)
    return logits \
        - tensors.frequency[:, None] * counts.astype(jnp.float32) \
        - tensors.presence[:, None] * (counts > 0).astype(jnp.float32)


def update_counts(counts: jnp.ndarray, tokens: jnp.ndarray,
                  active: jnp.ndarray) -> jnp.ndarray:
    """Add this step's sampled ``tokens`` [B] to the output-token histogram
    (inactive slots unchanged)."""
    B = tokens.shape[0]
    return counts.at[jnp.arange(B), tokens].add(
        active.astype(counts.dtype))


def _apply_top_k_top_p(logits: jnp.ndarray, top_k: jnp.ndarray,
                       top_p: jnp.ndarray) -> jnp.ndarray:
    """Joint top-k + nucleus filtering from ONE descending sort of the
    logits (sorts over a 152k vocab are the dominant sampling-filter cost;
    softmax of the already-sorted values is monotone-equivalent to softmax of
    the originals, so both thresholds fall out of the same sorted array).

    top_k == 0 disables top-k; the nucleus set always keeps the top token.
    """
    vocab = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]          # desc
    # Top-k threshold: the kth largest logit.
    k = jnp.where(top_k > 0, top_k, vocab)
    kth = jnp.take_along_axis(
        sorted_logits, jnp.clip(k[:, None] - 1, 0, vocab - 1), axis=-1)
    # Nucleus: keep ranks whose *exclusive* cumulative mass is below top_p,
    # then convert the boundary rank back to a logit threshold (softmax is
    # monotone in logit, so prob-space and logit-space cuts are identical).
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(sorted_probs, axis=-1)
    num_keep = jnp.sum(cumulative - sorted_probs < top_p[:, None], axis=-1)
    nucleus_kth = jnp.take_along_axis(
        sorted_logits, jnp.clip(num_keep[:, None] - 1, 0, vocab - 1), axis=-1)
    return jnp.where(logits >= jnp.maximum(kth, nucleus_kth), logits,
                     _NEG_INF)


def _row_keys(tensors: SamplingTensors, key: jax.Array,
              positions: jnp.ndarray) -> jnp.ndarray:
    """Per-row PRNG keys [B, 2]: seeded rows use
    ``fold_in(PRNGKey(seed), position)`` (deterministic across batch
    compositions and restarts); unseeded rows split the shared step key."""
    B = positions.shape[0]
    seeded = jax.vmap(
        lambda s, p: jax.random.fold_in(
            jax.random.PRNGKey(jnp.maximum(s, 0)), p))(
        tensors.seed, positions)
    unseeded = jax.random.split(key, B)
    return jnp.where((tensors.seed >= 0)[:, None], seeded, unseeded)


def sample_tokens(logits: jnp.ndarray, tensors: SamplingTensors,
                  key: jax.Array, positions: Optional[jnp.ndarray] = None,
                  counts: Optional[jnp.ndarray] = None,
                  bias_ids: Optional[jnp.ndarray] = None,
                  bias_vals: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Sample one token per row of ``logits`` [B, V] → int32 [B].

    ``positions`` [B] (generation position per row) drives per-request
    seeded determinism; None falls back to the shared key for every row.
    ``counts`` [B, V] enables presence/frequency penalties.
    ``bias_ids``/``bias_vals`` [B, K] are the OpenAI logit_bias surface
    in padded sparse form (pad entries (0, +0.0) are additive no-ops);
    it applies to greedy too — reported logprobs stay those of the
    model's true distribution.
    """
    logits = logits.astype(jnp.float32)
    if bias_ids is not None:
        B = logits.shape[0]
        logits = logits.at[jnp.arange(B)[:, None], bias_ids].add(
            bias_vals)
    if counts is not None:
        logits = apply_penalties(logits, counts, tensors)
    greedy_tok = greedy(logits)
    temp = jnp.maximum(tensors.temperature, 1e-6)[:, None]
    scaled = logits / temp
    # The joint filter needs a full-vocab sort (~2 ms/step on a 128k vocab
    # — measured 20% of a 1B model's decode step). Greedy rows take the
    # argmax below and unfiltered rows keep every logit, so the sort only
    # runs when some sampled row actually set top_k/top_p: lax.cond
    # executes ONE branch at runtime inside jit.
    needs_filter = jnp.any(
        (tensors.temperature > 0.0)
        & ((tensors.top_k > 0) | (tensors.top_p < 1.0)))
    scaled = jax.lax.cond(
        needs_filter,
        lambda s: _apply_top_k_top_p(s, tensors.top_k, tensors.top_p),
        lambda s: s, scaled)
    if positions is None or tensors.seed is None:
        sampled = jax.random.categorical(key, scaled, axis=-1).astype(
            jnp.int32)
    else:
        keys = _row_keys(tensors, key, positions)
        sampled = jax.vmap(
            lambda k, row: jax.random.categorical(k, row))(
            keys, scaled).astype(jnp.int32)
    return jnp.where(tensors.temperature <= 0.0, greedy_tok, sampled)


def compute_logprobs(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Log-prob of each chosen token: [B, V], [B] → [B] float32."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]


def compute_top_logprobs(logits: jnp.ndarray, k: int
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-``k`` alternative logprobs of the model distribution:
    [B, V] → (ids [B, k] int32, logprobs [B, k] float32)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    top_lps, top_ids = jax.lax.top_k(logp, k)
    return top_ids.astype(jnp.int32), top_lps
