"""Token sampling: greedy, temperature, top-k, top-p — batched and jit-safe.

Per-sequence sampling parameters arrive as dense arrays (one scalar per batch
slot) so a single compiled program serves every request mix; there is no
per-request recompilation. ``temperature == 0`` selects greedy via
``jnp.where``, not Python control flow.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


class SamplingTensors(NamedTuple):
    """Per-slot sampling state, shape [B] each."""

    temperature: jnp.ndarray   # float32; 0.0 → greedy
    top_p: jnp.ndarray         # float32 in (0, 1]
    top_k: jnp.ndarray         # int32; 0 → disabled

    @classmethod
    def for_batch(cls, params_list) -> "SamplingTensors":
        import numpy as np
        return cls(
            temperature=jnp.asarray(
                np.array([p.temperature for p in params_list], np.float32)),
            top_p=jnp.asarray(np.array([p.top_p for p in params_list],
                                       np.float32)),
            top_k=jnp.asarray(np.array([p.top_k for p in params_list],
                                       np.int32)),
        )


def greedy(logits: jnp.ndarray) -> jnp.ndarray:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _apply_top_k(logits: jnp.ndarray, top_k: jnp.ndarray) -> jnp.ndarray:
    """Mask all but the top-k logits per row. top_k==0 disables. Uses a full
    sort — vocab-sized sorts are cheap on TPU relative to the lm_head matmul."""
    vocab = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]          # desc
    k = jnp.where(top_k > 0, top_k, vocab)
    kth = jnp.take_along_axis(
        sorted_logits, jnp.clip(k[:, None] - 1, 0, vocab - 1), axis=-1)
    return jnp.where(logits >= kth, logits, _NEG_INF)


def _apply_top_p(logits: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest prefix of the sorted distribution
    with cumulative probability >= top_p (the kept set always includes the
    top token)."""
    probs = jax.nn.softmax(logits, axis=-1)
    sorted_probs = jnp.sort(probs, axis=-1)[:, ::-1]
    cumulative = jnp.cumsum(sorted_probs, axis=-1)
    # Threshold probability: smallest kept prob mass row-wise.
    keep_sorted = (cumulative - sorted_probs) < top_p[:, None]
    min_kept = jnp.min(jnp.where(keep_sorted, sorted_probs, 2.0), axis=-1)
    return jnp.where(probs >= min_kept[:, None], logits, _NEG_INF)


def sample_tokens(logits: jnp.ndarray, tensors: SamplingTensors,
                  key: jax.Array) -> jnp.ndarray:
    """Sample one token per row of ``logits`` [B, V] → int32 [B]."""
    greedy_tok = greedy(logits)
    temp = jnp.maximum(tensors.temperature, 1e-6)[:, None]
    scaled = logits.astype(jnp.float32) / temp
    scaled = _apply_top_k(scaled, tensors.top_k)
    scaled = _apply_top_p(scaled, tensors.top_p)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(tensors.temperature <= 0.0, greedy_tok, sampled)


def compute_logprobs(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Log-prob of each chosen token: [B, V], [B] → [B] float32."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
