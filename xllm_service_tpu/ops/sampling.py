"""Token sampling: greedy, temperature, top-k, top-p — batched and jit-safe.

Per-sequence sampling parameters arrive as dense arrays (one scalar per batch
slot) so a single compiled program serves every request mix; there is no
per-request recompilation. ``temperature == 0`` selects greedy via
``jnp.where``, not Python control flow.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


class SamplingTensors(NamedTuple):
    """Per-slot sampling state, shape [B] each."""

    temperature: jnp.ndarray   # float32; 0.0 → greedy
    top_p: jnp.ndarray         # float32 in (0, 1]
    top_k: jnp.ndarray         # int32; 0 → disabled

    @classmethod
    def for_batch(cls, params_list) -> "SamplingTensors":
        import numpy as np
        return cls(
            temperature=jnp.asarray(
                np.array([p.temperature for p in params_list], np.float32)),
            top_p=jnp.asarray(np.array([p.top_p for p in params_list],
                                       np.float32)),
            top_k=jnp.asarray(np.array([p.top_k for p in params_list],
                                       np.int32)),
        )


def greedy(logits: jnp.ndarray) -> jnp.ndarray:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _apply_top_k_top_p(logits: jnp.ndarray, top_k: jnp.ndarray,
                       top_p: jnp.ndarray) -> jnp.ndarray:
    """Joint top-k + nucleus filtering from ONE descending sort of the
    logits (sorts over a 152k vocab are the dominant sampling-filter cost;
    softmax of the already-sorted values is monotone-equivalent to softmax of
    the originals, so both thresholds fall out of the same sorted array).

    top_k == 0 disables top-k; the nucleus set always keeps the top token.
    """
    vocab = logits.shape[-1]
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]          # desc
    # Top-k threshold: the kth largest logit.
    k = jnp.where(top_k > 0, top_k, vocab)
    kth = jnp.take_along_axis(
        sorted_logits, jnp.clip(k[:, None] - 1, 0, vocab - 1), axis=-1)
    # Nucleus: keep ranks whose *exclusive* cumulative mass is below top_p,
    # then convert the boundary rank back to a logit threshold (softmax is
    # monotone in logit, so prob-space and logit-space cuts are identical).
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(sorted_probs, axis=-1)
    num_keep = jnp.sum(cumulative - sorted_probs < top_p[:, None], axis=-1)
    nucleus_kth = jnp.take_along_axis(
        sorted_logits, jnp.clip(num_keep[:, None] - 1, 0, vocab - 1), axis=-1)
    return jnp.where(logits >= jnp.maximum(kth, nucleus_kth), logits,
                     _NEG_INF)


def sample_tokens(logits: jnp.ndarray, tensors: SamplingTensors,
                  key: jax.Array) -> jnp.ndarray:
    """Sample one token per row of ``logits`` [B, V] → int32 [B]."""
    greedy_tok = greedy(logits)
    temp = jnp.maximum(tensors.temperature, 1e-6)[:, None]
    scaled = logits.astype(jnp.float32) / temp
    scaled = _apply_top_k_top_p(scaled, tensors.top_k, tensors.top_p)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(tensors.temperature <= 0.0, greedy_tok, sampled)


def compute_logprobs(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Log-prob of each chosen token: [B, V], [B] → [B] float32."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
