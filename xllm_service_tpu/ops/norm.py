"""RMSNorm. Accumulates in float32 regardless of activation dtype — on TPU the
VPU does the reduction in fp32 and XLA fuses the normalize+scale into the
surrounding matmul's epilogue, so there is no reason to ever norm in bf16."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-6) -> jnp.ndarray:
    """Mean-subtracting LayerNorm (the Qwen2-VL vision tower's norm; the
    text stack is RMSNorm-only). Same fp32-accumulate policy as above."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(dtype)
