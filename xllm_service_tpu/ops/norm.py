"""RMSNorm. Accumulates in float32 regardless of activation dtype — on TPU the
VPU does the reduction in fp32 and XLA fuses the normalize+scale into the
surrounding matmul's epilogue, so there is no reason to ever norm in bf16."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32)).astype(dtype)
