"""Pallas TPU kernel: causal GQA prefill attention over the paged KV cache.

Replaces the XLA prefill path (ops/attention.py) which, per layer, gathers
every referenced page into a dense [B, S, Hkv, D] view and overlays the
window's fresh K/V before attending — a full cache materialization whose
HBM traffic grows with table width even for short windows. Here each
(batch, query-block) program walks the KV sources directly:

- the first ``MP`` steps of the kv axis stream the sequence's *pool pages*
  HBM→VMEM via a scalar-prefetched page table (exactly the decode kernel's
  pattern, ops/pallas/paged_attention.py) — these cover the cached prefix
  positions ``[0, q_start)``;
- the remaining ``T // ps`` steps stream the *fresh* K/V blocks of the
  current window (global positions ``[q_start, q_start + len)``), which at
  attention time are not yet written to the pool (the engine defers pool
  writes to one post-scan scatter, models/transformer.py).

Each step folds one ``ps``-wide KV block into a flash-style online-softmax
accumulator in VMEM scratch. The query block is re-laid out for the MXU
once per (b, q-block) — at kv step 0, into scratch as [Hkv, QB·G, D] — so
every fold uses the same batched-over-Hkv 3D dot shapes the decode kernel
uses, with no per-step relayout.

Both KV refs are DMA'd every step (Pallas loads every input block per grid
cell); the unused source indexes block 0 and its bytes are ignored. The
pipeline overlaps these DMAs with the previous step's compute.

Masking: pool positions are valid while ``pos < q_start[b]`` (the cached
prefix only — pool content past it is stale); fresh positions are valid
while their window-local index is ``< lengths[b]``; causality masks
``pos > q_pos``. Fully-masked steps skip their MXU work via ``pl.when``.

Model deltas beyond plain causal GQA (so SWA families are NOT bypassed to
the gather path — round-4 verdict item 3):

- ``sliding_window`` — a DYNAMIC int32 scalar (4th scalar-prefetch
  operand), so Gemma-2/3 / GPT-OSS per-layer window vectors can ride the
  layer scan as traced values (full-attention layers pass 0 or the
  larger-than-any-context sentinel). The mask keeps
  ``kv_pos > q_pos − W`` (HF semantics, ops/attention.py:200-202) and a
  kv step entirely below every query's window skips its MXU work AND its
  fold — with the engine's O(W) page trimming the dead steps are exactly
  the trimmed (NULL) pages, whose stale bytes the mask would discard
  anyway.
- ``logits_soft_cap`` — Gemma-2's ``cap·tanh(logits/cap)``, static.
- ``scale`` — Gemma's ``query_pre_attn_scalar**-0.5`` override, static.
- ``sinks`` — GPT-OSS per-head sink logits, folded into the softmax
  denominator at finalize (never capped, never scaled — matching
  ``mha_prefill``'s concat-column-then-drop reference semantics). The
  caller pre-broadcasts them to the kernel's [Hkv, QB·G, 1] block layout
  in XLA, where the relayout is free.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from xllm_service_tpu.ops.pallas._compat import (
    CompilerParams as _CompilerParams)

from xllm_service_tpu.ops.attention import FULL_WINDOW

_NEG_INF = -1e30

# Read ONCE at import: this feeds the jit static arg q_block, and an
# env read per call is both hot-path overhead and a recompile hazard if
# the variable ever changes mid-run (xlint recompile-hazard). 64 is the
# shape-safe default — the offline v5e AOT envelope
# (tools/aot_kernel_probes.py, round 5) showed q_block=128 blowing
# XLA's default scoped-VMEM budget at several serving shapes (incl.
# B=32/64 with T=128 — the bench prefill shape) while 64 compiles
# everywhere tested (T 128-2048, B 1-64). Override for on-chip A/Bs;
# 128 also works with --xla_tpu_scoped_vmem_limit_kib=32768.
try:
    _QBLOCK_DEFAULT = int(os.environ.get(
        "XLLM_PALLAS_PREFILL_QBLOCK", "64"))
except ValueError:
    _QBLOCK_DEFAULT = 64
# Larger than any context: a window of 0 (= disabled) is normalized to
# this so the mask arithmetic stays branch-free in-kernel. A plain int
# (not a jnp constant — module-level jax arrays would be captured as
# pallas closure constants, which pallas_call rejects); the shared
# definition documents the <= 2^30 int32-safety bound.
_FULL = FULL_WINDOW


def prefill_kernel_enabled() -> bool:
    """Call-time gate (sibling of XLLM_PALLAS / XLLM_RAGGED_ATTN):
    off by default until validated on hardware. Requires the base Pallas
    gate too — there is no interpret fallback on the serving path."""
    if os.environ.get("XLLM_PALLAS_PREFILL", "0") != "1":
        return False
    from xllm_service_tpu.ops import pallas
    return pallas.enabled()


def _kernel_layered(qstart_ref, lens_ref, pt_ref, win_ref, lyr_ref,
                    *rest, **kw):
    """Layered-pool entry: the 5th scalar-prefetch ref (layer) is
    consumed by the BLOCK INDEX MAPS only."""
    return _kernel(qstart_ref, lens_ref, pt_ref, win_ref, *rest,
                   layered=True, **kw)


def _kernel_pool(qstart_ref, lens_ref, pt_ref, win_ref, q_ref, kp_ref,
                 vp_ref, sk_ref, o_ref, m_ref, l_ref, acc_ref, **kw):
    """Pool-only entry (write-then-attend): no fresh-block operands —
    the window's K/V is already IN the pool, so every kv step streams
    pool pages and the ragged tail reads through the page table."""
    return _kernel(qstart_ref, lens_ref, pt_ref, win_ref, q_ref, kp_ref,
                   vp_ref, None, None, sk_ref, o_ref, m_ref, l_ref,
                   acc_ref, pool_only=True, **kw)


def _kernel_layered_pool(qstart_ref, lens_ref, pt_ref, win_ref, lyr_ref,
                         q_ref, kp_ref, vp_ref, sk_ref, o_ref, m_ref,
                         l_ref, acc_ref, **kw):
    """Layered pool-only entry (the write-then-attend serving form)."""
    return _kernel(qstart_ref, lens_ref, pt_ref, win_ref, q_ref, kp_ref,
                   vp_ref, None, None, sk_ref, o_ref, m_ref, l_ref,
                   acc_ref, layered=True, pool_only=True, **kw)


def _kernel(qstart_ref, lens_ref, pt_ref, win_ref, q_ref, kp_ref, vp_ref,
            kf_ref, vf_ref, sk_ref, o_ref, m_ref, l_ref, acc_ref, *,
            page_size: int, q_block: int, num_pool_steps: int,
            num_kv_steps: int, logits_soft_cap: float, scale: float,
            has_sinks: bool, layered: bool = False,
            pool_only: bool = False):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    s = pl.program_id(2)

    # q arrives PRE-relaid as [Hkv, QB*G, D] (the caller does the 4D
    # transpose in XLA where it is free): in-kernel 4D transposes are a
    # known Mosaic lowering hazard on v5e (the V3 decode kernel died on
    # exactly this class — docs/PERF_NOTES.md round 3).
    g = q_ref.shape[3] // q_block
    q_start = qstart_ref[b]
    length = lens_ref[b]
    w = win_ref[0]
    w_eff = jnp.where(w > 0, w, _FULL)

    @pl.when(s == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Global position of this block's first kv token.
    pool_base = s * page_size
    is_pool = (s < num_pool_steps) if not pool_only else True
    if pool_only:
        # Write-then-attend: the pool holds the window too, so every
        # step is a pool step and positions are valid through
        # q_start + length (the ragged tail reads through the table).
        base = pool_base
    else:
        fresh_local_base = (s - num_pool_steps) * page_size
        base = jnp.where(is_pool, pool_base, q_start + fresh_local_base)

    # Query rows of this block sit at global positions q_start + qi*QB + t
    # (padded rows past ``length`` produce garbage that the engine never
    # reads — the last valid row is selected downstream).
    q_lo = q_start + qi * q_block

    # A kv step is live while some (q, kv) pair satisfies causality AND
    # the window: needs kv ≤ q for some q in the block (base ≤ last query
    # row) and kv > q − W for some q (block's last kv position above the
    # FIRST query row's window floor). Pool steps additionally intersect
    # the cached prefix; fresh steps the true window.
    in_win = base + page_size - 1 > q_lo - w_eff
    if pool_only:
        live = (pool_base < q_start + length) \
            & (base <= q_lo + q_block - 1) & in_win
    else:
        live_pool = is_pool & (pool_base < q_start) & in_win
        live_fresh = jnp.logical_not(is_pool) & \
            (fresh_local_base < length) & (base <= q_lo + q_block - 1) \
            & in_win
        live = live_pool | live_fresh

    @pl.when(live)
    def _fold():
        kp_blk = kp_ref[0, 0] if layered else kp_ref[0]
        vp_blk = vp_ref[0, 0] if layered else vp_ref[0]
        if pool_only:
            kb = kp_blk.astype(jnp.float32)                  # [ps, Hkv, D]
            vb = vp_blk.astype(jnp.float32)
        else:
            kb = jnp.where(is_pool, kp_blk.astype(jnp.float32),
                           kf_ref[0, 0].astype(jnp.float32))
            vb = jnp.where(is_pool, vp_blk.astype(jnp.float32),
                           vf_ref[0, 0].astype(jnp.float32))
        qt = q_ref[0, 0].astype(jnp.float32)                 # [Hkv, QB*G, D]
        kt = jnp.transpose(kb, (1, 0, 2))                    # [Hkv, ps, D]
        vt = jnp.transpose(vb, (1, 0, 2))
        # [Hkv, QB*G, D] x [Hkv, ps, D] -> [Hkv, QB*G, ps]
        logits = jax.lax.dot_general(
            qt, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        if logits_soft_cap > 0.0:
            logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)

        # Positions: kv along ps, queries along QB (replicated over G).
        kv_pos = base + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, g, page_size), 2)
        q_pos = q_lo + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, g, page_size), 0)
        # Pool: valid while pos < q_start. Fresh: valid while the local
        # index < length. Both: causal + inside the sliding window.
        # Select the scalar THRESHOLD, not the boolean vectors: a select
        # whose operands are i1 VECTORS is unlegalizable for Mosaic
        # ("failed to legalize arith.select" on vector<...xi1> — found
        # by the offline v5e AOT probe, tools/aot_kernel_probes.py).
        # Pool-only: the pool holds the window too, so the whole
        # [0, q_start + length) range is valid.
        if pool_only:
            src_limit = q_start + length
        else:
            src_limit = jnp.where(is_pool, q_start, q_start + length)
        src_ok = kv_pos < src_limit
        mask3 = (src_ok & (kv_pos <= q_pos)
                 & (kv_pos > q_pos - w_eff)).reshape(
            1, q_block * g, page_size)                       # [1, QB*G, ps]

        logits = jnp.where(mask3, logits, _NEG_INF)
        m_prev = m_ref[:]                                    # [Hkv, QB*G, 1]
        blk_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_max)
        prob = jnp.exp(logits - m_new)
        prob = jnp.where(mask3, prob, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(prob, axis=-1,
                                             keepdims=True)
        # [Hkv, QB*G, ps] x [Hkv, ps, D] -> [Hkv, QB*G, D]
        pv = jax.lax.dot_general(
            prob, vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = m_new

    @pl.when(s == num_kv_steps - 1)
    def _finalize():
        m_fin = m_ref[:]
        l_fin = l_ref[:]
        acc_fin = acc_ref[:]
        if has_sinks:
            # GPT-OSS sinks: one per-head logit joins the denominator and
            # its probability mass is dropped — fold it as a final
            # single-position rescale of the accumulator.
            sk = sk_ref[:].astype(jnp.float32)               # [Hkv,QB*G,1]
            m_sk = jnp.maximum(m_fin, sk)
            corr = jnp.exp(m_fin - m_sk)
            l_fin = l_fin * corr + jnp.exp(sk - m_sk)
            acc_fin = acc_fin * corr
        denom = jnp.maximum(l_fin, 1e-30)
        # Written in the kernel's native [Hkv, QB*G, D] layout; the
        # caller transposes back in XLA (same hazard-avoidance as the
        # pre-relaid q input).
        o_ref[0, 0] = (acc_fin / denom).astype(o_ref.dtype)


def paged_prefill_attention_pallas(q: jnp.ndarray, k_fresh: jnp.ndarray,
                                   v_fresh: jnp.ndarray,
                                   k_pages: jnp.ndarray,
                                   v_pages: jnp.ndarray,
                                   page_table: jnp.ndarray,
                                   q_start: jnp.ndarray,
                                   lengths: jnp.ndarray,
                                   q_block: Optional[int] = None,
                                   interpret: bool = None,
                                   sliding_window=0,
                                   logits_soft_cap: float = 0.0,
                                   scale=None,
                                   sinks=None,
                                   layer=None,
                                   from_pool: bool = False) -> jnp.ndarray:
    """q/k_fresh/v_fresh: [B, T, H*, D] (this window, already roped);
    k/v_pages: [P, ps, Hkv, D] — or, with ``layer`` (a traced int32
    scalar), the FULL stacked [L, P, ps, Hkv, D] pools, whose page DMAs
    the kernel indexes at (layer, page) directly so no per-layer slice
    is ever materialized (the serving path always uses this form);
    page_table: [B, MP]; q_start: [B] cached
    prefix length; lengths: [B] true window length. Requires T % ps == 0
    (engine buckets are pow2 multiples of the page size — callers check).
    ``sliding_window`` is a static int OR a traced int32 scalar (per-layer
    window vectors riding the layer scan); 0 disables. ``logits_soft_cap``
    and ``scale`` are static floats (Gemma); ``sinks`` an optional [Hq]
    array (GPT-OSS). ``interpret=None`` → Pallas interpreter off TPU (so
    the gated serving path stays runnable in CPU tests), Mosaic on TPU.

    ``from_pool`` (static) — the write-then-attend form: the window's
    K/V was already written into the pool (ops/pallas/kv_update.py
    layered writers), so there is NO separate fresh-block stream —
    ``k_fresh``/``v_fresh`` are ignored (pass None), every kv step is a
    pool step, and positions are valid through q_start + length (the
    ragged window tail reads through the page table).
    Returns [B, T, Hq, D]."""
    if interpret is None:
        from xllm_service_tpu.ops import pallas
        interpret = pallas.default_interpret()
    if q_block is None:
        q_block = _QBLOCK_DEFAULT
    win = jnp.asarray(sliding_window, jnp.int32).reshape(1)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if from_pool:
        k_fresh = v_fresh = None
    return _impl(q, k_fresh, v_fresh, k_pages, v_pages, page_table,
                 q_start, lengths, win, sinks, layer, q_block=q_block,
                 logits_soft_cap=float(logits_soft_cap),
                 scale=float(scale), interpret=interpret,
                 from_pool=from_pool)


@functools.partial(jax.jit, static_argnames=("q_block", "logits_soft_cap",
                                             "scale", "interpret",
                                             "from_pool"))
def _impl(q, k_fresh, v_fresh, k_pages, v_pages, page_table, q_start,
          lengths, win, sinks, layer=None, *, q_block: int,
          logits_soft_cap: float, scale: float, interpret: bool,
          from_pool: bool = False):
    B, T, Hq, D = q.shape
    layered = layer is not None
    if layered:
        _, _, page_size, Hkv, _ = k_pages.shape
    else:
        _, page_size, Hkv, _ = k_pages.shape
    MP = page_table.shape[1]
    if not from_pool and T % page_size != 0:
        raise ValueError(f"window {T} not a multiple of page {page_size}")
    # Largest block ≤ q_block that tiles T exactly — any window passing
    # the page-multiple check above gets a valid (if smaller) q block
    # rather than a trace-time crash on non-pow2 buckets.
    QB = math.gcd(T, min(q_block, T))
    nQ = T // QB
    nF = 0 if from_pool else T // page_size
    n_kv = MP + nF
    G = Hq // Hkv
    has_sinks = sinks is not None

    # ``layered``: the pools ride FULL as [L, P, ps, Hkv, D] and the
    # traced layer index (5th prefetch scalar) joins the page in the
    # block index — no per-layer pool slice for XLA to materialize
    # (the round-5 decode conviction applies to prefill identically).
    # One set of index maps for both arities: the layered form appends
    # the layer prefetch ref, which only pool_idx consumes (*_ swallows
    # it elsewhere — the decode kernel's adapter pattern).
    def fresh_idx(b, qi, s, qstart, lens, pt, w, *_):
        # Fresh steps DMA their T-block; pool steps block 0 (unused).
        return (b, jnp.maximum(s - MP, 0), 0, 0, 0)

    def fixed_idx(b, qi, s, qstart, lens, pt, w, *_):
        return (0, 0, 0)

    def q_idx(b, qi, s, qstart, lens, pt, w, *_):
        return (b, qi, 0, 0, 0)

    if layered:
        def pool_idx(b, qi, s, qstart, lens, pt, w, l):
            return (l[0],
                    jnp.where(s < MP, pt[b, jnp.minimum(s, MP - 1)], 0),
                    0, 0, 0)

        pool_block = (1, 1, page_size, Hkv, D)
        n_prefetch = 5
    else:
        def pool_idx(b, qi, s, qstart, lens, pt, w):
            # Pool steps DMA the mapped page; fresh steps page 0 (unused).
            return (jnp.where(s < MP, pt[b, jnp.minimum(s, MP - 1)], 0),
                    0, 0, 0)

        pool_block = (1, page_size, Hkv, D)
        n_prefetch = 4

    in_specs = [
        pl.BlockSpec((1, 1, Hkv, QB * G, D), q_idx),
        pl.BlockSpec(pool_block, pool_idx),
        pl.BlockSpec(pool_block, pool_idx),
    ]
    if not from_pool:
        in_specs += [
            pl.BlockSpec((1, 1, page_size, Hkv, D), fresh_idx),
            pl.BlockSpec((1, 1, page_size, Hkv, D), fresh_idx),
        ]
    in_specs.append(pl.BlockSpec((Hkv, QB * G, 1), fixed_idx))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,  # q_start, lens, pt, win[, layer]
        grid=(B, nQ, n_kv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, Hkv, QB * G, D), q_idx),
        scratch_shapes=[
            pltpu.VMEM((Hkv, QB * G, 1), jnp.float32),   # running max
            pltpu.VMEM((Hkv, QB * G, 1), jnp.float32),   # running denom
            pltpu.VMEM((Hkv, QB * G, D), jnp.float32),   # accumulator
        ],
    )
    # q is PRE-relaid to the kernel's [Hkv, QB*G, D] block layout (and
    # the output un-relaid below) in XLA, where these transposes are
    # fused and free — in-kernel 4D transposes are a Mosaic lowering
    # hazard on v5e (see the V3 decode kernel history).
    q6 = q.reshape(B, nQ, QB, Hkv, G, D).transpose(0, 1, 3, 2, 4, 5) \
        .reshape(B, nQ, Hkv, QB * G, D)
    if not from_pool:
        kf5 = k_fresh.reshape(B, nF, page_size, Hkv, D)
        vf5 = v_fresh.reshape(B, nF, page_size, Hkv, D)
    if has_sinks:
        # [Hq] → the kernel's [Hkv, QB*G, 1] block layout (replicated
        # over QB), pre-broadcast in XLA where the relayout is free.
        sk3 = jnp.broadcast_to(
            sinks.astype(jnp.float32).reshape(Hkv, 1, G),
            (Hkv, QB, G)).reshape(Hkv, QB * G, 1)
    else:
        sk3 = jnp.zeros((Hkv, QB * G, 1), jnp.float32)
    if from_pool:
        body = _kernel_layered_pool if layered else _kernel_pool
    else:
        body = _kernel_layered if layered else _kernel
    out = pl.pallas_call(
        functools.partial(body,
                          page_size=page_size, q_block=QB,
                          num_pool_steps=MP, num_kv_steps=n_kv,
                          logits_soft_cap=logits_soft_cap, scale=scale,
                          has_sinks=has_sinks),
        out_shape=jax.ShapeDtypeStruct((B, nQ, Hkv, QB * G, D), q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(q_start.astype(jnp.int32), lengths.astype(jnp.int32),
      page_table, win,
      *((layer.reshape(1).astype(jnp.int32),) if layered else ()),
      q6, k_pages, v_pages,
      *(() if from_pool else (kf5, vf5)), sk3)
    out = out.reshape(B, nQ, Hkv, QB, G, D).transpose(0, 1, 3, 2, 4, 5)
    return out.reshape(B, T, Hq, D)
