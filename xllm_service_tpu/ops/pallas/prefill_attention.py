"""Pallas TPU kernel: causal GQA prefill attention over the paged KV cache.

Replaces the XLA prefill path (ops/attention.py) which, per layer, gathers
every referenced page into a dense [B, S, Hkv, D] view and overlays the
window's fresh K/V before attending — a full cache materialization whose
HBM traffic grows with table width even for short windows. Here each
(batch, query-block) program walks the KV sources directly:

- the first ``MP`` steps of the kv axis stream the sequence's *pool pages*
  HBM→VMEM via a scalar-prefetched page table (exactly the decode kernel's
  pattern, ops/pallas/paged_attention.py) — these cover the cached prefix
  positions ``[0, q_start)``;
- the remaining ``T // ps`` steps stream the *fresh* K/V blocks of the
  current window (global positions ``[q_start, q_start + len)``), which at
  attention time are not yet written to the pool (the engine defers pool
  writes to one post-scan scatter, models/transformer.py).

Each step folds one ``ps``-wide KV block into a flash-style online-softmax
accumulator in VMEM scratch. The query block is re-laid out for the MXU
once per (b, q-block) — at kv step 0, into scratch as [Hkv, QB·G, D] — so
every fold uses the same batched-over-Hkv 3D dot shapes the decode kernel
uses, with no per-step relayout.

Both KV refs are DMA'd every step (Pallas loads every input block per grid
cell); the unused source indexes block 0 and its bytes are ignored. The
pipeline overlaps these DMAs with the previous step's compute.

Masking: pool positions are valid while ``pos < q_start[b]`` (the cached
prefix only — pool content past it is stale); fresh positions are valid
while their window-local index is ``< lengths[b]``; causality masks
``pos > q_pos``. Fully-masked steps skip their MXU work via ``pl.when``.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def prefill_kernel_enabled() -> bool:
    """Call-time gate (sibling of XLLM_PALLAS / XLLM_PALLAS_DECODE_V2):
    off by default until validated on hardware. Requires the base Pallas
    gate too — there is no interpret fallback on the serving path."""
    if os.environ.get("XLLM_PALLAS_PREFILL", "0") != "1":
        return False
    from xllm_service_tpu.ops import pallas
    return pallas.enabled()


def _kernel(qstart_ref, lens_ref, pt_ref, q_ref, kp_ref, vp_ref, kf_ref,
            vf_ref, o_ref, m_ref, l_ref, acc_ref, *,
            page_size: int, q_block: int, num_pool_steps: int,
            num_kv_steps: int):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    s = pl.program_id(2)

    # q arrives PRE-relaid as [Hkv, QB*G, D] (the caller does the 4D
    # transpose in XLA where it is free): in-kernel 4D transposes are a
    # known Mosaic lowering hazard on v5e (the V3 decode kernel died on
    # exactly this class — docs/PERF_NOTES.md round 3).
    d = q_ref.shape[4]
    g = q_ref.shape[3] // q_block
    q_start = qstart_ref[b]
    length = lens_ref[b]

    @pl.when(s == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    is_pool = s < num_pool_steps
    # Global position of this block's first kv token.
    pool_base = s * page_size
    fresh_local_base = (s - num_pool_steps) * page_size
    base = jnp.where(is_pool, pool_base, q_start + fresh_local_base)

    # Query rows of this block sit at global positions q_start + qi*QB + t
    # (padded rows past ``length`` produce garbage that the engine never
    # reads — the last valid row is selected downstream).
    q_lo = q_start + qi * q_block

    # A pool step is live while it intersects the cached prefix; a fresh
    # step while it intersects the true window AND is not entirely above
    # the causal diagonal of this query block.
    live_pool = is_pool & (pool_base < q_start)
    live_fresh = jnp.logical_not(is_pool) & \
        (fresh_local_base < length) & (base <= q_lo + q_block - 1)

    @pl.when(live_pool | live_fresh)
    def _fold():
        kb = jnp.where(is_pool, kp_ref[0].astype(jnp.float32),
                       kf_ref[0, 0].astype(jnp.float32))     # [ps, Hkv, D]
        vb = jnp.where(is_pool, vp_ref[0].astype(jnp.float32),
                       vf_ref[0, 0].astype(jnp.float32))
        scale = 1.0 / (d ** 0.5)
        qt = q_ref[0, 0].astype(jnp.float32)                 # [Hkv, QB*G, D]
        kt = jnp.transpose(kb, (1, 0, 2))                    # [Hkv, ps, D]
        vt = jnp.transpose(vb, (1, 0, 2))
        # [Hkv, QB*G, D] x [Hkv, ps, D] -> [Hkv, QB*G, ps]
        logits = jax.lax.dot_general(
            qt, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale

        # Positions: kv along ps, queries along QB (replicated over G).
        kv_pos = base + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, g, page_size), 2)
        q_pos = q_lo + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, g, page_size), 0)
        # Pool: valid while pos < q_start. Fresh: valid while the local
        # index < length. Both: causal.
        src_ok = jnp.where(is_pool, kv_pos < q_start,
                           kv_pos < q_start + length)
        mask3 = (src_ok & (kv_pos <= q_pos)).reshape(
            1, q_block * g, page_size)                       # [1, QB*G, ps]

        logits = jnp.where(mask3, logits, _NEG_INF)
        m_prev = m_ref[:]                                    # [Hkv, QB*G, 1]
        blk_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_max)
        prob = jnp.exp(logits - m_new)
        prob = jnp.where(mask3, prob, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(prob, axis=-1,
                                             keepdims=True)
        # [Hkv, QB*G, ps] x [Hkv, ps, D] -> [Hkv, QB*G, D]
        pv = jax.lax.dot_general(
            prob, vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = m_new

    @pl.when(s == num_kv_steps - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:], 1e-30)
        # Written in the kernel's native [Hkv, QB*G, D] layout; the
        # caller transposes back in XLA (same hazard-avoidance as the
        # pre-relaid q input).
        o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def paged_prefill_attention_pallas(q: jnp.ndarray, k_fresh: jnp.ndarray,
                                   v_fresh: jnp.ndarray,
                                   k_pages: jnp.ndarray,
                                   v_pages: jnp.ndarray,
                                   page_table: jnp.ndarray,
                                   q_start: jnp.ndarray,
                                   lengths: jnp.ndarray,
                                   q_block: int = 128,
                                   interpret: bool = None) -> jnp.ndarray:
    """q/k_fresh/v_fresh: [B, T, H*, D] (this window, already roped);
    k/v_pages: [P, ps, Hkv, D]; page_table: [B, MP]; q_start: [B] cached
    prefix length; lengths: [B] true window length. Requires T % ps == 0
    (engine buckets are pow2 multiples of the page size — callers check).
    ``interpret=None`` → Pallas interpreter off TPU (so the gated serving
    path stays runnable in CPU tests), Mosaic on TPU. Returns
    [B, T, Hq, D]."""
    if interpret is None:
        from xllm_service_tpu.ops import pallas
        interpret = pallas.default_interpret()
    return _impl(q, k_fresh, v_fresh, k_pages, v_pages, page_table,
                 q_start, lengths, q_block=q_block, interpret=interpret)


@functools.partial(jax.jit, static_argnames=("q_block", "interpret"))
def _impl(q, k_fresh, v_fresh, k_pages, v_pages, page_table, q_start,
          lengths, *, q_block: int, interpret: bool):
    B, T, Hq, D = q.shape
    _, page_size, Hkv, _ = k_pages.shape
    MP = page_table.shape[1]
    if T % page_size != 0:
        raise ValueError(f"window {T} not a multiple of page {page_size}")
    # Largest block ≤ q_block that tiles T exactly — any window passing
    # the page-multiple check above gets a valid (if smaller) q block
    # rather than a trace-time crash on non-pow2 buckets.
    QB = math.gcd(T, min(q_block, T))
    nQ = T // QB
    nF = T // page_size
    n_kv = MP + nF
    G = Hq // Hkv

    def pool_idx(b, qi, s, qstart, lens, pt):
        # Pool steps DMA the mapped page; fresh steps DMA page 0 (unused).
        return (jnp.where(s < MP, pt[b, jnp.minimum(s, MP - 1)], 0),
                0, 0, 0)

    def fresh_idx(b, qi, s, qstart, lens, pt):
        # Fresh steps DMA their T-block; pool steps DMA block 0 (unused).
        return (b, jnp.maximum(s - MP, 0), 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,              # q_start, lengths, page_table
        grid=(B, nQ, n_kv),
        in_specs=[
            pl.BlockSpec((1, 1, Hkv, QB * G, D),
                         lambda b, qi, s, qstart, lens, pt:
                         (b, qi, 0, 0, 0)),
            pl.BlockSpec((1, page_size, Hkv, D), pool_idx),
            pl.BlockSpec((1, page_size, Hkv, D), pool_idx),
            pl.BlockSpec((1, 1, page_size, Hkv, D), fresh_idx),
            pl.BlockSpec((1, 1, page_size, Hkv, D), fresh_idx),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, Hkv, QB * G, D),
            lambda b, qi, s, qstart, lens, pt: (b, qi, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hkv, QB * G, 1), jnp.float32),   # running max
            pltpu.VMEM((Hkv, QB * G, 1), jnp.float32),   # running denom
            pltpu.VMEM((Hkv, QB * G, D), jnp.float32),   # accumulator
        ],
    )
    # q is PRE-relaid to the kernel's [Hkv, QB*G, D] block layout (and
    # the output un-relaid below) in XLA, where these transposes are
    # fused and free — in-kernel 4D transposes are a Mosaic lowering
    # hazard on v5e (see the V3 decode kernel history).
    q6 = q.reshape(B, nQ, QB, Hkv, G, D).transpose(0, 1, 3, 2, 4, 5) \
        .reshape(B, nQ, Hkv, QB * G, D)
    kf5 = k_fresh.reshape(B, nF, page_size, Hkv, D)
    vf5 = v_fresh.reshape(B, nF, page_size, Hkv, D)
    out = pl.pallas_call(
        functools.partial(_kernel, page_size=page_size, q_block=QB,
                          num_pool_steps=MP, num_kv_steps=n_kv),
        out_shape=jax.ShapeDtypeStruct((B, nQ, Hkv, QB * G, D), q.dtype),
        grid_spec=grid_spec,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(q_start.astype(jnp.int32), lengths.astype(jnp.int32),
      page_table, q6, k_pages, v_pages, kf5, vf5)
    out = out.reshape(B, nQ, Hkv, QB, G, D).transpose(0, 1, 3, 2, 4, 5)
    return out.reshape(B, T, Hq, D)
