"""Pallas TPU kernel: ragged paged attention for mixed prefill+decode.

One kernel, one dispatch, for an arbitrary mix of prefill windows
(new_tokens > 1) and decode rows (new_tokens = 1). Each row of the ragged
batch is described by ``(q_start, length)`` — ``q_start`` cached-prefix
tokens already in the pool, ``length`` new tokens whose K/V the engine has
ALSO already written to the pool (write-then-attend) — plus the shared
page table. A decode row is just the degenerate ``length = 1``
continuation window, so the same grid serves both phases and the engine's
interleaved step needs a single program launch instead of one prefill
dispatch plus one decode dispatch (the Ragged Paged Attention framing:
chunked prefill and decode share one ragged kernel).

Layout and masking are the write-then-attend pool form of the prefill
kernel (ops/pallas/prefill_attention.py): every kv step streams one pool
page HBM→VMEM via the scalar-prefetched page table, folding it into a
flash-style online-softmax accumulator in VMEM scratch. Positions are
valid through ``q_start + length`` (the ragged tail reads through the
table); causality masks ``kv_pos > q_pos`` within each row's new-token
span; ``sliding_window`` clamps ``kv_pos > q_pos − W``. Rows whose pages
end early (decode rows in a batch bucketed for a long prefill window)
skip the dead kv steps' MXU work AND their DMA-fold via ``pl.when`` —
that per-row early-out is what makes the shared grid cheap for ragged
mixes. ``length = 0`` rows are fully masked (the denominator clamp keeps
the padded output finite; the engine never reads those rows).

Model deltas (same surface as the prefill kernel, so no model family
falls back): traced per-layer ``sliding_window`` scalars, Gemma
``logits_soft_cap`` and ``scale``, GPT-OSS ``sinks`` folded into the
denominator at finalize. The ``layer`` scalar routes page DMAs into the
FULL stacked [L, P, ps, Hkv, D] pools so no per-layer slice ever
materializes.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from xllm_service_tpu.ops.pallas._compat import (
    CompilerParams as _CompilerParams)

from xllm_service_tpu.ops.attention import FULL_WINDOW

_NEG_INF = -1e30

# Read ONCE at import (the PR-10 QBLOCK convention): this feeds a jit
# static, and an env read per call is hot-path overhead plus a recompile
# hazard if the variable changes mid-run (xlint recompile-hazard). 64 is
# the shape-safe default from the prefill kernel's offline v5e AOT
# envelope (q_block=128 blows the default scoped-VMEM budget at several
# serving shapes); override for on-chip A/Bs.
try:
    _QBLOCK_DEFAULT = int(os.environ.get("XLLM_RAGGED_QBLOCK", "64"))
except ValueError:
    _QBLOCK_DEFAULT = 64
# Window-disabled sentinel: plain int, not a jnp constant (module-level
# jax arrays are rejected as pallas closure constants).
_FULL = FULL_WINDOW


def ragged_attn_enabled() -> bool:
    """Serving gate for the one-dispatch ragged step (default OFF until
    the chip session validates it). Requires the base Pallas gate — off
    TPU the engine's ragged path still runs, but through the XLA gather
    reference (the kernel itself is exercised under the interpreter only
    in tests). The engine reads this ONCE per Engine.__init__ and caches
    it, so flipping the env mid-run cannot recompile the serving jits
    (xlint rule 17)."""
    return os.environ.get("XLLM_RAGGED_ATTN", "0") == "1"


def _kernel(qstart_ref, lens_ref, pt_ref, win_ref, q_ref, kp_ref, vp_ref,
            sk_ref, o_ref, m_ref, l_ref, acc_ref, *, page_size: int,
            q_block: int, num_kv_steps: int, logits_soft_cap: float,
            scale: float, has_sinks: bool, layered: bool = False):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    s = pl.program_id(2)

    # q arrives PRE-relaid as [Hkv, QB*G, D] (the caller does the 4D
    # transpose in XLA where it is free — in-kernel 4D transposes are a
    # Mosaic lowering hazard on v5e).
    g = q_ref.shape[3] // q_block
    q_start = qstart_ref[b]
    length = lens_ref[b]
    w = win_ref[0]
    w_eff = jnp.where(w > 0, w, _FULL)

    @pl.when(s == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Every kv step is a pool step (write-then-attend): global position
    # of this block's first kv token.
    base = s * page_size
    # Query rows of this block sit at global positions q_start + qi*QB + t
    # (padded rows past ``length`` produce garbage the engine never
    # reads — sampling selects the last valid row downstream).
    q_lo = q_start + qi * q_block

    # A kv step is live while some (q, kv) pair survives all three masks:
    # the source bound (kv < q_start + length), causality (kv ≤ some q in
    # the block), and the window (block's last kv above the FIRST query
    # row's window floor). Decode rows (length = 1) keep only the steps
    # covering [max(0, q_start − W), q_start] — the rest skip.
    in_win = base + page_size - 1 > q_lo - w_eff
    live = (base < q_start + length) & (base <= q_lo + q_block - 1) & in_win

    @pl.when(live)
    def _fold():
        kp_blk = kp_ref[0, 0] if layered else kp_ref[0]
        vp_blk = vp_ref[0, 0] if layered else vp_ref[0]
        kb = kp_blk.astype(jnp.float32)                      # [ps, Hkv, D]
        vb = vp_blk.astype(jnp.float32)
        qt = q_ref[0, 0].astype(jnp.float32)                 # [Hkv, QB*G, D]
        kt = jnp.transpose(kb, (1, 0, 2))                    # [Hkv, ps, D]
        vt = jnp.transpose(vb, (1, 0, 2))
        # [Hkv, QB*G, D] x [Hkv, ps, D] -> [Hkv, QB*G, ps]
        logits = jax.lax.dot_general(
            qt, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        if logits_soft_cap > 0.0:
            logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)

        # Positions: kv along ps, queries along QB (replicated over G).
        kv_pos = base + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, g, page_size), 2)
        q_pos = q_lo + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, g, page_size), 0)
        # Compare against the scalar THRESHOLD, not boolean vectors: i1
        # vector selects are unlegalizable for Mosaic (v5e AOT probe).
        src_ok = kv_pos < q_start + length
        mask3 = (src_ok & (kv_pos <= q_pos)
                 & (kv_pos > q_pos - w_eff)).reshape(
            1, q_block * g, page_size)                       # [1, QB*G, ps]

        logits = jnp.where(mask3, logits, _NEG_INF)
        m_prev = m_ref[:]                                    # [Hkv, QB*G, 1]
        blk_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_max)
        prob = jnp.exp(logits - m_new)
        prob = jnp.where(mask3, prob, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(prob, axis=-1,
                                             keepdims=True)
        # [Hkv, QB*G, ps] x [Hkv, ps, D] -> [Hkv, QB*G, D]
        pv = jax.lax.dot_general(
            prob, vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = m_new

    @pl.when(s == num_kv_steps - 1)
    def _finalize():
        m_fin = m_ref[:]
        l_fin = l_ref[:]
        acc_fin = acc_ref[:]
        if has_sinks:
            # GPT-OSS sinks: one per-head logit joins the denominator and
            # its probability mass is dropped — a final single-position
            # rescale of the accumulator.
            sk = sk_ref[:].astype(jnp.float32)               # [Hkv,QB*G,1]
            m_sk = jnp.maximum(m_fin, sk)
            corr = jnp.exp(m_fin - m_sk)
            l_fin = l_fin * corr + jnp.exp(sk - m_sk)
            acc_fin = acc_fin * corr
        # Clamp: a fully-masked row (length = 0 padding) has l == 0; its
        # output is garbage the engine never reads, but must stay finite.
        denom = jnp.maximum(l_fin, 1e-30)
        o_ref[0, 0] = (acc_fin / denom).astype(o_ref.dtype)


def _kernel_layered(qstart_ref, lens_ref, pt_ref, win_ref, lyr_ref,
                    *rest, **kw):
    """Layered-pool entry: the 5th scalar-prefetch ref (layer) is
    consumed by the BLOCK INDEX MAPS only."""
    return _kernel(qstart_ref, lens_ref, pt_ref, win_ref, *rest,
                   layered=True, **kw)


def ragged_paged_attention_pallas(q: jnp.ndarray, k_pages: jnp.ndarray,
                                  v_pages: jnp.ndarray,
                                  page_table: jnp.ndarray,
                                  q_start: jnp.ndarray,
                                  lengths: jnp.ndarray,
                                  q_block: Optional[int] = None,
                                  interpret: bool = None,
                                  sliding_window=0,
                                  logits_soft_cap: float = 0.0,
                                  scale=None,
                                  sinks=None,
                                  layer=None) -> jnp.ndarray:
    """q: [B, T, Hq, D] — the ragged batch's new tokens, row i holding
    ``lengths[i]`` real rows (prefill window or a single decode token)
    left-aligned in the T bucket, already roped; k/v_pages:
    [P, ps, Hkv, D] — or, with ``layer`` (traced int32 scalar), the FULL
    stacked [L, P, ps, Hkv, D] pools; page_table: [B, MP]; q_start: [B]
    cached prefix length (tokens already in the pool BEFORE this batch's
    new tokens — for a decode row, len(tokens) − 1); lengths: [B] true
    new-token count (1 for decode rows, 0 for padding rows). The new
    tokens' K/V must ALREADY be in the pool (write-then-attend) — there
    is no fresh-block stream and no T-page alignment requirement, so
    decode rows may start mid-page. ``sliding_window`` is a static int OR
    a traced int32 scalar; ``logits_soft_cap``/``scale`` static floats;
    ``sinks`` an optional [Hq] array. ``interpret=None`` → Pallas
    interpreter off TPU, Mosaic on TPU. Returns [B, T, Hq, D]."""
    if interpret is None:
        from xllm_service_tpu.ops import pallas
        interpret = pallas.default_interpret()
    if q_block is None:
        q_block = _QBLOCK_DEFAULT
    win = jnp.asarray(sliding_window, jnp.int32).reshape(1)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _impl(q, k_pages, v_pages, page_table, q_start, lengths, win,
                 sinks, layer, q_block=q_block,
                 logits_soft_cap=float(logits_soft_cap),
                 scale=float(scale), interpret=interpret)


@functools.partial(jax.jit, static_argnames=("q_block", "logits_soft_cap",
                                             "scale", "interpret"))
def _impl(q, k_pages, v_pages, page_table, q_start, lengths, win, sinks,
          layer=None, *, q_block: int, logits_soft_cap: float,
          scale: float, interpret: bool):
    B, T, Hq, D = q.shape
    layered = layer is not None
    if layered:
        _, _, page_size, Hkv, _ = k_pages.shape
    else:
        _, page_size, Hkv, _ = k_pages.shape
    MP = page_table.shape[1]
    # Largest block ≤ q_block that tiles T exactly (T is an engine bucket,
    # not necessarily a page multiple — decode-only mixes use T = 1).
    QB = math.gcd(T, min(q_block, T))
    nQ = T // QB
    G = Hq // Hkv
    has_sinks = sinks is not None

    # One set of index maps for both arities: the layered form appends
    # the layer prefetch ref, which only pool_idx consumes (*_ swallows
    # it elsewhere).
    def fixed_idx(b, qi, s, qstart, lens, pt, w, *_):
        return (0, 0, 0)

    def q_idx(b, qi, s, qstart, lens, pt, w, *_):
        return (b, qi, 0, 0, 0)

    if layered:
        def pool_idx(b, qi, s, qstart, lens, pt, w, l):
            return (l[0], pt[b, s], 0, 0, 0)

        pool_block = (1, 1, page_size, Hkv, D)
        n_prefetch = 5
    else:
        def pool_idx(b, qi, s, qstart, lens, pt, w):
            return (pt[b, s], 0, 0, 0)

        pool_block = (1, page_size, Hkv, D)
        n_prefetch = 4

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,  # q_start, lens, pt, win[, layer]
        grid=(B, nQ, MP),
        in_specs=[
            pl.BlockSpec((1, 1, Hkv, QB * G, D), q_idx),
            pl.BlockSpec(pool_block, pool_idx),
            pl.BlockSpec(pool_block, pool_idx),
            pl.BlockSpec((Hkv, QB * G, 1), fixed_idx),
        ],
        out_specs=pl.BlockSpec((1, 1, Hkv, QB * G, D), q_idx),
        scratch_shapes=[
            pltpu.VMEM((Hkv, QB * G, 1), jnp.float32),   # running max
            pltpu.VMEM((Hkv, QB * G, 1), jnp.float32),   # running denom
            pltpu.VMEM((Hkv, QB * G, D), jnp.float32),   # accumulator
        ],
    )
    # q PRE-relaid to the kernel's [Hkv, QB*G, D] block layout (and the
    # output un-relaid below) in XLA, where the transposes fuse for free.
    q6 = q.reshape(B, nQ, QB, Hkv, G, D).transpose(0, 1, 3, 2, 4, 5) \
        .reshape(B, nQ, Hkv, QB * G, D)
    if has_sinks:
        sk3 = jnp.broadcast_to(
            sinks.astype(jnp.float32).reshape(Hkv, 1, G),
            (Hkv, QB, G)).reshape(Hkv, QB * G, 1)
    else:
        sk3 = jnp.zeros((Hkv, QB * G, 1), jnp.float32)
    body = _kernel_layered if layered else _kernel
    out = pl.pallas_call(
        functools.partial(body,
                          page_size=page_size, q_block=QB,
                          num_kv_steps=MP,
                          logits_soft_cap=logits_soft_cap, scale=scale,
                          has_sinks=has_sinks),
        out_shape=jax.ShapeDtypeStruct((B, nQ, Hkv, QB * G, D), q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(q_start.astype(jnp.int32), lengths.astype(jnp.int32),
      page_table, win,
      *((layer.reshape(1).astype(jnp.int32),) if layered else ()),
      q6, k_pages, v_pages, sk3)
    out = out.reshape(B, nQ, Hkv, QB, G, D).transpose(0, 1, 3, 2, 4, 5)
    return out.reshape(B, T, Hq, D)
