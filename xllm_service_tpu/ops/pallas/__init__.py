"""Fused Pallas TPU kernels for the serving hot path.

``ops/attention.py`` holds the XLA reference implementations (gather →
attend); these kernels replace them where it pays: paged decode attention
reads KV pages HBM→VMEM directly via a scalar-prefetched page table, so
the per-layer, per-step dense gather of the whole page table disappears
(half the HBM traffic of gather-then-attend, and no [B, S, Hkv, D]
materialization).

Selection: ``enabled()`` — on for TPU backends, off elsewhere, overridable
with XLLM_PALLAS=0/1. On CPU the kernels still run under the Pallas
interpreter for tests (``interpret=True``).
"""

import os

import jax


def enabled() -> bool:
    env = os.environ.get("XLLM_PALLAS", "").strip()
    if env in ("0", "false", "no"):
        return False
    if env in ("1", "true", "yes"):
        return True
    return _on_tpu()


def _on_tpu() -> bool:
    try:
        # devices()[0].platform is "tpu" even when the backend registers
        # under another name (e.g. the tunneled "axon" plugin).
        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001 — backend init failure → reference path
        return False


def mla_kernel_enabled() -> bool:
    """Opt-in gate for routing absorbed-MLA decode (Hkv=1, D=r+rope —
    e.g. 576 for DeepSeek, not 128-lane-aligned) through the paged
    decode kernel. Off by default until the MLA-shaped AOT compile probe
    (tools/kernel_compile_probes.py) clears Mosaic on hardware; the XLA
    gather reference serves MLA otherwise."""
    return os.environ.get("XLLM_PALLAS_MLA", "0") == "1" and enabled()


def default_interpret() -> bool:
    """Kernel ``interpret=None`` resolution, shared by every kernel: run
    under the Pallas interpreter anywhere but a real TPU (so XLLM_PALLAS=1
    on CPU exercises kernel paths in tests instead of crashing in
    Mosaic). ``XLLM_PALLAS_INTERPRET=0`` forces REAL Mosaic lowering
    regardless of the runtime platform — required by the offline v5e
    AOT checks (tools/aot_engine_check.py), whose runtime backend is the
    pinned CPU while the compile target is the libtpu topology (without
    the override every kernel silently lowers as interpreter ops and
    the 'TPU' program under analysis contains no Mosaic at all)."""
    env = os.environ.get("XLLM_PALLAS_INTERPRET", "").strip()
    if env in ("0", "false", "no"):
        return False
    if env in ("1", "true", "yes"):
        return True
    return not _on_tpu()


from xllm_service_tpu.ops.pallas.paged_attention import (  # noqa: E402,F401
    paged_decode_attention_pallas)
from xllm_service_tpu.ops.pallas.prefill_attention import (  # noqa: E402,F401
    paged_prefill_attention_pallas, prefill_kernel_enabled)
from xllm_service_tpu.ops.pallas.ragged_attention import (  # noqa: E402,F401
    ragged_attn_enabled, ragged_paged_attention_pallas)
