"""Pallas TPU kernel: single-token GQA attention over the paged KV cache.

Replaces the reference XLA path (ops/attention.py ``paged_decode_attention``)
which gathers every referenced page into a dense [B, S, Hkv, D] tensor
before attending — 2× the HBM traffic and a full materialization per layer
per decode step. Here each batch program streams its sequence's pages
HBM→VMEM via a **scalar-prefetched page table** (the BlockSpec index map
reads ``page_table[b, p]`` before the kernel body runs, so the pipeline
DMAs exactly the right page), folding each page into a flash-style
online-softmax accumulator in VMEM scratch.

Grid: (B, max_pages), pages fastest → the scratch accumulator carries
across the page walk of one batch row (standard TPU flash pattern). Each
block is a whole page with all KV heads ([ps, Hkv, D] — Pallas TPU wants
the trailing two block dims full or (8,128)-aligned, so heads stay in the
block and the GQA grouping happens in-kernel). NULL pages (id 0) and
positions ≥ context_len are masked; fully out-of-range pages skip compute
via ``pl.when`` (their DMA lands on page 0 and is discarded).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from xllm_service_tpu.ops.pallas._compat import (
    CompilerParams as _CompilerParams, HBM as _HBM)

_NEG_INF = -1e30



def _transpose_free_default() -> bool:
    """Transpose-free fold: contract the K/V page blocks in their native
    [ps, Hkv, D] layout by batching the dot_generals over Hkv *in place*
    (rhs batch dim at position 1) instead of materializing a transposed
    [Hkv, ps, D] copy in VMEM per grid cell. Numerically identical
    (interpret-mode bit-exact); gated until Mosaic lowering is validated
    on hardware. Read per call, like the sibling XLLM_PALLAS gate, so a
    runtime toggle (bench retry loops, test fixtures) takes effect."""
    return os.environ.get("XLLM_PALLAS_DECODE_V2", "0") == "1"


def _row_kernel_default() -> bool:
    """Whole-row decode kernel (grid (B,), double-buffered page DMA)
    instead of one grid cell per (batch, page). The (B, pages) grid pays
    per-cell overhead on B*MP tiny cells per layer per step — at the
    bench shape (B=64, MP=8, 16 layers) that is 8192 cell invocations a
    step, which dwarfs the actual attention FLOPs at decode. The row
    kernel walks a sequence's pages inside ONE cell with its own
    double-buffered HBM→VMEM copies, cutting cell count 8x and bounding
    the page walk at the sequence's true page count (the grid version
    visits all MP cells; `pl.when` skips compute but not the cell).
    Gated off until validated on hardware (XLLM_PALLAS_DECODE_V3=1);
    read per call like the sibling gates so runtime toggles work."""
    return os.environ.get("XLLM_PALLAS_DECODE_V3", "0") == "1"


# Window sentinel: larger than any context. A plain int — module-level
# jnp constants would be captured as pallas closure constants, which
# pallas_call rejects; the shared definition documents the <= 2^30
# int32-safety bound.
from xllm_service_tpu.ops.attention import FULL_WINDOW as _FULL


def _kernel(ctx_ref, pt_ref, win_ref, q_ref, k_ref, v_ref, kc_ref, vc_ref,
            sk_ref, o_ref, m_ref, l_ref, acc_ref, *, page_size: int,
            pages_per_seq: int, num_kv_heads: int, has_current: bool,
            transpose_free: bool, logits_soft_cap: float, scale: float,
            has_sinks: bool, layered: bool = False):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ctx = ctx_ref[b]
    page_start = p * page_size
    w = win_ref[0]
    w_eff = jnp.where(w > 0, w, _FULL)
    # The query's logical position: with the current token held
    # in-registers the cache holds [0, ctx) and the query sits at ctx;
    # without it, ctx INcludes the query token (position ctx − 1). The
    # window keeps cache slot j > q_pos − W (slot j holds position j).
    q_pos = ctx if has_current else ctx - 1
    win_floor = q_pos - w_eff

    @pl.when((page_start < ctx) & (page_start + page_size - 1 > win_floor))
    def _fold():
        hq, d = q_ref.shape[1], q_ref.shape[2]
        g = hq // num_kv_heads
        q = q_ref[0].astype(jnp.float32)                     # [Hq, D]
        qg = q.reshape(num_kv_heads, g, d)                   # [Hkv, G, D]
        # ``layered``: the pool rides FULL as [L, P, ps, Hkv, D] and the
        # block is [1, 1, ps, Hkv, D] (the round-5 fix for the per-layer
        # 134 MB slice materialization feeding this custom call).
        k = (k_ref[0, 0] if layered else k_ref[0]).astype(jnp.float32)
        v = (v_ref[0, 0] if layered else v_ref[0]).astype(jnp.float32)
        if transpose_free:
            # Batch Hkv where it lives: [Hkv,G,D] x [ps,Hkv,D] -> [Hkv,G,ps]
            logits = jax.lax.dot_general(
                qg, k, (((2,), (2,)), ((0,), (1,))),
                preferred_element_type=jnp.float32) * scale
        else:
            kt = jnp.transpose(k, (1, 0, 2))                 # [Hkv, ps, D]
            # Batched over Hkv: [Hkv, G, D] x [Hkv, ps, D] -> [Hkv, G, ps]
            logits = jax.lax.dot_general(
                qg, kt, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32) * scale
        logits = logits.reshape(hq, page_size)               # [Hq, ps]
        if logits_soft_cap > 0.0:
            logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
        pos = page_start + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        mask = (pos < ctx) & (pos > win_floor)               # [1, ps]
        logits = jnp.where(mask, logits, _NEG_INF)
        m_prev = m_ref[:]                                    # [Hq, 1]
        blk_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_max)
        prob = jnp.exp(logits - m_new)
        prob = jnp.where(mask, prob, 0.0)                    # [Hq, ps]
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(prob, axis=-1,
                                             keepdims=True)
        if transpose_free:
            # [Hkv, G, ps] x [ps, Hkv, D] -> [Hkv, G, D]
            pv = jax.lax.dot_general(
                prob.reshape(num_kv_heads, g, page_size), v,
                (((2,), (0,)), ((0,), (1,))),
                preferred_element_type=jnp.float32)
        else:
            vt = jnp.transpose(v, (1, 0, 2))
            # [Hkv, G, ps] x [Hkv, ps, D] -> [Hkv, G, D]
            pv = jax.lax.dot_general(
                prob.reshape(num_kv_heads, g, page_size), vt,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv.reshape(hq, d)
        m_ref[:] = m_new

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        m_fin = m_ref[:]
        l_fin = l_ref[:]
        acc_fin = acc_ref[:]
        if has_current:
            # Fold the current token's K/V (held in-registers, not yet in
            # the pool) as a final always-valid single-position block
            # (soft-capped like any cache logit; inside its own window).
            hq, d = q_ref.shape[1], q_ref.shape[2]
            g = hq // num_kv_heads
            q = q_ref[0].astype(jnp.float32)
            qg = q.reshape(num_kv_heads, g, d)
            kc = kc_ref[0].astype(jnp.float32)               # [Hkv, D]
            vc = vc_ref[0].astype(jnp.float32)
            lc = jnp.sum(qg * kc[:, None, :], axis=-1) * scale  # [Hkv, G]
            lc = lc.reshape(hq, 1)
            if logits_soft_cap > 0.0:
                lc = logits_soft_cap * jnp.tanh(lc / logits_soft_cap)
            m_new = jnp.maximum(m_fin, lc)
            corr = jnp.exp(m_fin - m_new)
            pc = jnp.exp(lc - m_new)                         # [Hq, 1]
            l_fin = l_fin * corr + pc
            vc_full = jnp.broadcast_to(
                vc[:, None, :], (num_kv_heads, g, d)).reshape(hq, d)
            acc_fin = acc_fin * corr + pc * vc_full
            m_fin = m_new
        if has_sinks:
            # GPT-OSS sinks: the per-head logit joins the denominator
            # only (never capped, never scaled — reference semantics,
            # ops/attention.py paged_decode_attention_current).
            sk = sk_ref[:].astype(jnp.float32)               # [Hq, 1]
            m_sk = jnp.maximum(m_fin, sk)
            corr = jnp.exp(m_fin - m_sk)
            l_fin = l_fin * corr + jnp.exp(sk - m_sk)
            acc_fin = acc_fin * corr
        denom = jnp.maximum(l_fin, 1e-30)
        o_ref[0] = (acc_fin / denom).astype(o_ref.dtype)


def _row_kernel(ctx_ref, pt_ref, qw_ref, k_hbm, v_hbm, kc_ref, vc_ref,
                o_ref, k_buf, v_buf, sems, *, page_size: int,
                has_current: bool):
    """One grid cell = one batch row's whole page walk.

    K/V pools stay in HBM (memory_space=HBM, no automatic pipeline);
    the kernel issues its own async copies, page p+1 in flight while
    page p folds into the online-softmax accumulator. The loop runs
    ceil(ctx/ps) iterations — a short sequence in a wide table does not
    visit dead pages. Accumulators are fori_loop carries (f32 values,
    not scratch refs).

    GQA is expressed BLOCK-DIAGONALLY: the caller pre-expands the query
    to ``q_wide [Hq, Hkv*D]`` (zeros outside each row's own kv-head
    slice) and the pools arrive flattened to ``[P, ps, Hkv*D]``, so both
    dots are plain 2D matmuls and the output is ``o_wide [Hq, Hkv*D]``
    (each row's result lives in its kv-head's lane slice, selected
    outside). This wastes Hkv× MXU flops on zero blocks — irrelevant
    next to decode's weight reads — and is what v5e Mosaic actually
    lowers: per-head shapes need D=64-aligned HBM slices ("must be
    aligned to tiling (128)") or vector reshapes like (ps, 512)->(ps,
    8, 64) ("Not Implemented: tpu.reshape"), both of which fail."""
    b = pl.program_id(0)
    ctx = ctx_ref[b]
    npages = (ctx + page_size - 1) // page_size

    hq, w = qw_ref.shape[1], qw_ref.shape[2]
    qw = qw_ref[0].astype(jnp.float32)                       # [Hq, W]

    def k_dma(slot, p):
        return pltpu.make_async_copy(k_hbm.at[pt_ref[b, p]],
                                     k_buf.at[slot], sems.at[slot, 0])

    def v_dma(slot, p):
        return pltpu.make_async_copy(v_hbm.at[pt_ref[b, p]],
                                     v_buf.at[slot], sems.at[slot, 1])

    @pl.when(npages > 0)
    def _prime():
        k_dma(0, 0).start()
        v_dma(0, 0).start()

    def fold(p, carry):
        m, l, acc = carry
        slot = jax.lax.rem(p, 2)

        @pl.when(p + 1 < npages)
        def _prefetch_next():
            nxt = jax.lax.rem(p + 1, 2)
            k_dma(nxt, p + 1).start()
            v_dma(nxt, p + 1).start()

        k_dma(slot, p).wait()
        v_dma(slot, p).wait()
        k = k_buf[slot].astype(jnp.float32)                  # [ps, W]
        v = v_buf[slot].astype(jnp.float32)
        # [Hq, W] x [ps, W] -> [Hq, ps]; block-diagonal zeros in qw keep
        # each query head inside its own kv head's D-slice.
        logits = jax.lax.dot_general(
            qw, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        mask = pos < ctx
        logits = jnp.where(mask, logits, _NEG_INF)
        blk_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, blk_max)
        prob = jnp.where(mask, jnp.exp(logits - m_new), 0.0)  # [Hq, ps]
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(prob, axis=-1, keepdims=True)
        # [Hq, ps] x [ps, W] -> [Hq, W]; row hq's useful lanes are its
        # kv head's slice, the rest carry other heads' values and are
        # dropped by the caller's diagonal selection.
        pv = jax.lax.dot_general(
            prob, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc * corr + pv

    m0 = jnp.full((hq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((hq, 1), jnp.float32)
    acc0 = jnp.zeros((hq, w), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, npages, fold, (m0, l0, acc0))

    if has_current:
        # The current token's K/V (in-registers, not yet in the pool) as
        # a final always-valid single-position block.
        kc = kc_ref[0].astype(jnp.float32)                   # [1, W]
        vc = vc_ref[0].astype(jnp.float32)
        lc = jax.lax.dot_general(
            qw, kc, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [Hq, 1]
        m_new = jnp.maximum(m, lc)
        corr = jnp.exp(m - m_new)
        pc = jnp.exp(lc - m_new)
        l = l * corr + pc
        acc = acc * corr + pc * vc
    o_ref[0] = acc / jnp.maximum(l, 1e-30)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_decode_attention_row_impl(q: jnp.ndarray, k_pages: jnp.ndarray,
                                     v_pages: jnp.ndarray,
                                     page_table: jnp.ndarray,
                                     context_lens: jnp.ndarray,
                                     k_cur: jnp.ndarray = None,
                                     v_cur: jnp.ndarray = None,
                                     interpret: bool = False) -> jnp.ndarray:
    B, Hq, D = q.shape
    _, page_size, Hkv, _ = k_pages.shape
    G = Hq // Hkv
    W = Hkv * D
    has_current = k_cur is not None
    if not has_current:
        k_cur = jnp.zeros((B, Hkv, D), q.dtype)
        v_cur = jnp.zeros((B, Hkv, D), q.dtype)

    # Pre-scale ONCE here instead of scaling page logits in the kernel.
    scale = 1.0 / (D ** 0.5)
    eye = jnp.eye(Hkv, dtype=q.dtype)                        # [Hkv, Hkv]
    # q [B, Hkv, G, D] -> block-diagonal q_wide [B, Hq, Hkv*D].
    q_wide = (q.astype(jnp.float32) * scale).astype(q.dtype)
    q_wide = (q_wide.reshape(B, Hkv, G, 1, D)
              * eye[:, None, :, None]).reshape(B, Hq, W)
    k_flat = k_pages.reshape(-1, page_size, W)
    v_flat = v_pages.reshape(-1, page_size, W)
    kc_flat = k_cur.reshape(B, 1, W)
    vc_flat = v_cur.reshape(B, 1, W)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,               # context_lens, page_table
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, W), lambda b, ctx, pt: (b, 0, 0)),
            pl.BlockSpec(memory_space=_HBM),    # whole K pool
            pl.BlockSpec(memory_space=_HBM),    # whole V pool
            pl.BlockSpec((1, 1, W), lambda b, ctx, pt: (b, 0, 0)),
            pl.BlockSpec((1, 1, W), lambda b, ctx, pt: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hq, W), lambda b, ctx, pt: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, page_size, W), k_pages.dtype),
            pltpu.VMEM((2, page_size, W), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    o_wide = pl.pallas_call(
        functools.partial(_row_kernel, page_size=page_size,
                          has_current=has_current),
        out_shape=jax.ShapeDtypeStruct((B, Hq, W), jnp.float32),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(context_lens, page_table, q_wide, k_flat, v_flat, kc_flat, vc_flat)
    # Diagonal selection: row hq keeps its own kv head's D-slice.
    o = jnp.einsum("bhgkd,hk->bhgd",
                   o_wide.reshape(B, Hkv, G, Hkv, D),
                   eye.astype(jnp.float32))
    return o.reshape(B, Hq, D).astype(q.dtype)


def _wide_default() -> bool:
    """Wide block-diagonal variant of the (B, pages) kernel
    (XLLM_PALLAS_DECODE_V5): same grid, but queries arrive pre-expanded
    to [Hq, Hkv*D] (zeros outside each row's kv-head slice) against
    FLAT [P, ps, Hkv*D] pools, so both dots are plain 2D and the cell
    body has ZERO relayouts — no per-cell [ps, Hkv, D] -> [Hkv, ps, D]
    transpose (a VMEM relayout paid B*MP*layers times per step). Wastes
    Hkv x MXU flops on zero blocks, irrelevant at decode. The same
    trick that made V3 lowerable; here it attacks per-cell cost
    instead of cell count (V4's axis)."""
    return os.environ.get("XLLM_PALLAS_DECODE_V5", "0") == "1"


def _widen_q(q: jnp.ndarray, num_kv_heads: int) -> jnp.ndarray:
    """[B, Hq, D] -> block-diagonal [B, Hq, Hkv*D] (pre-scaled by the
    caller if desired): row hq's kv-head slice holds its query vector,
    all other lanes zero."""
    B, Hq, D = q.shape
    G = Hq // num_kv_heads
    eye = jnp.eye(num_kv_heads, dtype=q.dtype)
    return (q.reshape(B, num_kv_heads, G, 1, D)
            * eye[:, None, :, None]).reshape(B, Hq, num_kv_heads * D)


def _select_diag(o_wide: jnp.ndarray, num_kv_heads: int) -> jnp.ndarray:
    """[B, Hq, Hkv*D] f32 -> [B, Hq, D]: row hq keeps its own kv head's
    D-slice."""
    B, Hq, W = o_wide.shape
    G = Hq // num_kv_heads
    D = W // num_kv_heads
    eye = jnp.eye(num_kv_heads, dtype=jnp.float32)
    return jnp.einsum(
        "bhgkd,hk->bhgd",
        o_wide.reshape(B, num_kv_heads, G, num_kv_heads, D),
        eye).reshape(B, Hq, D)


def _wide_kernel(ctx_ref, pt_ref, qw_ref, k_ref, v_ref, kc_ref, vc_ref,
                 o_ref, m_ref, l_ref, acc_ref, *, page_size: int,
                 pages_per_seq: int, has_current: bool):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ctx = ctx_ref[b]
    page_start = p * page_size

    @pl.when(page_start < ctx)
    def _fold():
        qw = qw_ref[0].astype(jnp.float32)                   # [Hq, W]
        k = k_ref[0].astype(jnp.float32)                     # [ps, W]
        v = v_ref[0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            qw, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [Hq, ps]
        pos = page_start + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        mask = pos < ctx
        logits = jnp.where(mask, logits, _NEG_INF)
        m_prev = m_ref[:]
        blk_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_max)
        prob = jnp.where(mask, jnp.exp(logits - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(prob, axis=-1,
                                             keepdims=True)
        pv = jax.lax.dot_general(
            prob, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [Hq, W]
        acc_ref[:] = acc_ref[:] * corr + pv
        m_ref[:] = m_new

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        m_fin = m_ref[:]
        l_fin = l_ref[:]
        acc_fin = acc_ref[:]
        if has_current:
            qw = qw_ref[0].astype(jnp.float32)
            kc = kc_ref[0].astype(jnp.float32)               # [1, W]
            vc = vc_ref[0].astype(jnp.float32)
            lc = jax.lax.dot_general(
                qw, kc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)          # [Hq, 1]
            m_new = jnp.maximum(m_fin, lc)
            corr = jnp.exp(m_fin - m_new)
            pc = jnp.exp(lc - m_new)
            l_fin = l_fin * corr + pc
            acc_fin = acc_fin * corr + pc * vc
        o_ref[0] = acc_fin / jnp.maximum(l_fin, 1e-30)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _paged_decode_attention_wide_impl(q: jnp.ndarray,
                                      k_pages: jnp.ndarray,
                                      v_pages: jnp.ndarray,
                                      page_table: jnp.ndarray,
                                      context_lens: jnp.ndarray,
                                      k_cur: jnp.ndarray = None,
                                      v_cur: jnp.ndarray = None,
                                      interpret: bool = False
                                      ) -> jnp.ndarray:
    B, Hq, D = q.shape
    _, page_size, Hkv, _ = k_pages.shape
    MP = page_table.shape[1]
    W = Hkv * D
    has_current = k_cur is not None
    if not has_current:
        k_cur = jnp.zeros((B, Hkv, D), q.dtype)
        v_cur = jnp.zeros((B, Hkv, D), q.dtype)
    scale = 1.0 / (D ** 0.5)
    q_wide = _widen_q((q.astype(jnp.float32) * scale).astype(q.dtype),
                      Hkv)
    k_flat = k_pages.reshape(-1, page_size, W)
    v_flat = v_pages.reshape(-1, page_size, W)
    kc_flat = k_cur.reshape(B, 1, W)
    vc_flat = v_cur.reshape(B, 1, W)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, MP),
        in_specs=[
            pl.BlockSpec((1, Hq, W), lambda b, p, ctx, pt: (b, 0, 0)),
            pl.BlockSpec((1, page_size, W),
                         lambda b, p, ctx, pt: (pt[b, p], 0, 0)),
            pl.BlockSpec((1, page_size, W),
                         lambda b, p, ctx, pt: (pt[b, p], 0, 0)),
            pl.BlockSpec((1, 1, W), lambda b, p, ctx, pt: (b, 0, 0)),
            pl.BlockSpec((1, 1, W), lambda b, p, ctx, pt: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hq, W),
                               lambda b, p, ctx, pt: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hq, 1), jnp.float32),
            pltpu.VMEM((Hq, 1), jnp.float32),
            pltpu.VMEM((Hq, W), jnp.float32),
        ],
    )
    o_wide = pl.pallas_call(
        functools.partial(_wide_kernel, page_size=page_size,
                          pages_per_seq=MP, has_current=has_current),
        out_shape=jax.ShapeDtypeStruct((B, Hq, W), jnp.float32),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(context_lens, page_table, q_wide, k_flat, v_flat, kc_flat,
      vc_flat)
    return _select_diag(o_wide, Hkv).astype(q.dtype)


def _multirow_default() -> int:
    """Rows per grid cell for the multi-row kernel (0 = off). The
    (B, pages) kernel's cost at decode is dominated by CELL COUNT
    (B x MP x layers tiny invocations per step — ~8k at the bench
    shape), not attention FLOPs; V3 cut cells to B but serialized the
    page walk behind manual DMAs and lost. V4 keeps the AUTOMATIC
    BlockSpec pipeline (the only page-fetch form Mosaic accepts for
    D=64 pools — manual DMA needs 128-lane-aligned slices) and simply
    processes XLLM_PALLAS_DECODE_V4 rows per cell: the pool is passed
    that many times with per-row page-table index maps, so the pipeline
    still overlaps all fetches while the cell count drops RB-fold."""
    try:
        return int(os.environ.get("XLLM_PALLAS_DECODE_V4", "0"))
    except ValueError:
        return 0


def _mr_kernel(ctx_ref, pt_ref, q_ref, *refs, page_size: int,
               num_kv_heads: int, rows: int, pages_per_seq: int,
               has_current: bool):
    k_refs = refs[:rows]
    v_refs = refs[rows:2 * rows]
    kc_ref, vc_ref, o_ref, m_ref, l_ref, acc_ref = refs[2 * rows:]
    i = pl.program_id(0)
    p = pl.program_id(1)
    hq, d = q_ref.shape[1], q_ref.shape[2]
    g = hq // num_kv_heads
    row0 = i * rows
    ctxs = jnp.stack([ctx_ref[row0 + r] for r in range(rows)])   # [RB]
    scale = 1.0 / (d ** 0.5)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    page_start = p * page_size

    @pl.when(page_start < jnp.max(ctxs))
    def _fold():
        q = q_ref[...].astype(jnp.float32)                # [RB, Hq, D]
        qg = q.reshape(rows * num_kv_heads, g, d)
        k = jnp.concatenate([r[...] for r in k_refs], 0)  # [RB, ps, Hkv, D]
        v = jnp.concatenate([r[...] for r in v_refs], 0)
        kt = jnp.transpose(k.astype(jnp.float32), (0, 2, 1, 3)) \
            .reshape(rows * num_kv_heads, page_size, d)
        vt = jnp.transpose(v.astype(jnp.float32), (0, 2, 1, 3)) \
            .reshape(rows * num_kv_heads, page_size, d)
        # [RB*Hkv, G, D] x [RB*Hkv, ps, D] -> [RB*Hkv, G, ps]; batch dim
        # at index 0 on both sides (the only form v5e Mosaic lowers).
        logits = jax.lax.dot_general(
            qg, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        logits = logits.reshape(rows, hq, page_size)
        pos = page_start + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)                 # [1, ps]
        # Per-row scalar compares, stacked: reshaping the [RB] ctx
        # vector to [RB,1,1] is a Mosaic-unlowerable shape cast
        # ("tpu.reshape vector<8xi32> -> vector<8x1x1xi32>" — offline
        # v5e AOT probe); scalar-vs-vector broadcasts are fine and RB
        # is static.
        mask = jnp.stack([pos < ctx_ref[row0 + r]
                          for r in range(rows)])          # [RB, 1, ps]
        logits = jnp.where(mask, logits, _NEG_INF)
        m_prev = m_ref[...]                               # [RB, Hq, 1]
        blk_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_max)
        prob = jnp.exp(logits - m_new)
        prob = jnp.where(mask, prob, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * corr + jnp.sum(prob, axis=-1,
                                                 keepdims=True)
        pv = jax.lax.dot_general(
            prob.reshape(rows * num_kv_heads, g, page_size), vt,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * corr \
            + pv.reshape(rows, hq, d)
        m_ref[...] = m_new

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        m_fin = m_ref[...]
        l_fin = l_ref[...]
        acc_fin = acc_ref[...]
        if has_current:
            q = q_ref[...].astype(jnp.float32)
            qg4 = q.reshape(rows, num_kv_heads, g, d)
            kc = kc_ref[...].astype(jnp.float32)          # [RB, Hkv, D]
            vc = vc_ref[...].astype(jnp.float32)
            lc = jnp.sum(qg4 * kc[:, :, None, :], -1) * scale
            lc = lc.reshape(rows, hq, 1)
            m_new = jnp.maximum(m_fin, lc)
            corr = jnp.exp(m_fin - m_new)
            pc = jnp.exp(lc - m_new)
            l_fin = l_fin * corr + pc
            vc_full = jnp.broadcast_to(
                vc[:, :, None, :],
                (rows, num_kv_heads, g, d)).reshape(rows, hq, d)
            acc_fin = acc_fin * corr + pc * vc_full
        denom = jnp.maximum(l_fin, 1e-30)
        o_ref[...] = (acc_fin / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("rows", "interpret"))
def _paged_decode_attention_mr_impl(q: jnp.ndarray, k_pages: jnp.ndarray,
                                    v_pages: jnp.ndarray,
                                    page_table: jnp.ndarray,
                                    context_lens: jnp.ndarray,
                                    k_cur: jnp.ndarray = None,
                                    v_cur: jnp.ndarray = None,
                                    rows: int = 8,
                                    interpret: bool = False
                                    ) -> jnp.ndarray:
    B, Hq, D = q.shape
    _, page_size, Hkv, _ = k_pages.shape
    MP = page_table.shape[1]
    has_current = k_cur is not None
    if not has_current:
        k_cur = jnp.zeros((B, Hkv, D), q.dtype)
        v_cur = jnp.zeros((B, Hkv, D), q.dtype)
    RB = max(1, min(rows, B))
    pad = (-B) % RB
    if pad:
        q = jnp.pad(q, ((0, pad), (0, 0), (0, 0)))
        k_cur = jnp.pad(k_cur, ((0, pad), (0, 0), (0, 0)))
        v_cur = jnp.pad(v_cur, ((0, pad), (0, 0), (0, 0)))
        page_table = jnp.pad(page_table, ((0, pad), (0, 0)))
        context_lens = jnp.pad(context_lens, (0, pad))
    Bp = B + pad

    def k_idx(r):
        return lambda i, p, ctx, pt: (pt[i * RB + r, p], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,               # context_lens, page_table
        grid=(Bp // RB, MP),
        in_specs=[
            pl.BlockSpec((RB, Hq, D), lambda i, p, ctx, pt: (i, 0, 0)),
            *[pl.BlockSpec((1, page_size, Hkv, D), k_idx(r))
              for r in range(RB)],
            *[pl.BlockSpec((1, page_size, Hkv, D), k_idx(r))
              for r in range(RB)],
            pl.BlockSpec((RB, Hkv, D), lambda i, p, ctx, pt: (i, 0, 0)),
            pl.BlockSpec((RB, Hkv, D), lambda i, p, ctx, pt: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((RB, Hq, D),
                               lambda i, p, ctx, pt: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((RB, Hq, 1), jnp.float32),
            pltpu.VMEM((RB, Hq, 1), jnp.float32),
            pltpu.VMEM((RB, Hq, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_mr_kernel, page_size=page_size,
                          num_kv_heads=Hkv, rows=RB, pages_per_seq=MP,
                          has_current=has_current),
        out_shape=jax.ShapeDtypeStruct((Bp, Hq, D), q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(context_lens, page_table, q,
      *([k_pages] * RB), *([v_pages] * RB), k_cur, v_cur)
    return out[:B]


def paged_decode_attention_pallas(q: jnp.ndarray, k_pages: jnp.ndarray,
                                  v_pages: jnp.ndarray,
                                  page_table: jnp.ndarray,
                                  context_lens: jnp.ndarray,
                                  k_cur: jnp.ndarray = None,
                                  v_cur: jnp.ndarray = None,
                                  interpret: bool = None,
                                  transpose_free: bool = None,
                                  sliding_window=0,
                                  logits_soft_cap: float = 0.0,
                                  scale=None,
                                  sinks=None,
                                  layer=None) -> jnp.ndarray:
    """q: [B, Hq, D]; k/v_pages: [P, ps, Hkv, D]; page_table: [B, MP];
    context_lens: [B] valid cache tokens. With ``k_cur``/``v_cur``
    [B, Hkv, D], the current (not-yet-written) token is folded as a final
    block — the contract of ``paged_decode_attention_current``. Returns
    [B, Hq, D].

    ``sliding_window`` is a static int OR a traced int32 scalar (per-layer
    window vectors riding the layer scan — Gemma-2/3, GPT-OSS); 0
    disables. ``logits_soft_cap``/``scale`` static floats (Gemma);
    ``sinks`` an optional [Hq] array (GPT-OSS). Model deltas are
    implemented by the base (V1) kernel only — calls carrying any of them
    route there regardless of the V3/V4/V5 experiment gates.

    ``transpose_free=None`` resolves the XLLM_PALLAS_DECODE_V2 env var
    HERE, outside the jit cache, so runtime toggles take effect (the
    sibling XLLM_PALLAS gate has the same call-time semantics).
    ``interpret=None`` → Pallas interpreter off TPU (XLLM_PALLAS=1 on CPU
    exercises the kernel path in tests instead of crashing in Mosaic)."""
    if transpose_free is None:
        transpose_free = _transpose_free_default()
    if interpret is None:
        from xllm_service_tpu.ops import pallas
        interpret = pallas.default_interpret()
    plain = (isinstance(sliding_window, int) and sliding_window == 0
             and logits_soft_cap == 0.0 and scale is None
             and sinks is None)
    if plain and layer is not None and (
            _wide_default() or _multirow_default() > 1
            or _row_kernel_default()):
        # Experiment-variant A/B with the layered serving path: the
        # V3/V4/V5 kernels take per-layer pools, so slice here (the
        # materialization cost is the experiment's to measure — without
        # this the env knobs would silently no-op from serving).
        k_pages = jax.lax.dynamic_index_in_dim(
            k_pages, layer, axis=0, keepdims=False)
        v_pages = jax.lax.dynamic_index_in_dim(
            v_pages, layer, axis=0, keepdims=False)
        layer = None
    plain = plain and layer is None
    if plain:
        if _wide_default():
            return _paged_decode_attention_wide_impl(
                q, k_pages, v_pages, page_table, context_lens, k_cur,
                v_cur, interpret=interpret)
        mr = _multirow_default()
        if mr > 1:
            return _paged_decode_attention_mr_impl(
                q, k_pages, v_pages, page_table, context_lens, k_cur,
                v_cur, rows=mr, interpret=interpret)
        if _row_kernel_default():
            return _paged_decode_attention_row_impl(
                q, k_pages, v_pages, page_table, context_lens, k_cur,
                v_cur, interpret=interpret)
    win = jnp.asarray(sliding_window, jnp.int32).reshape(1)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    return _paged_decode_attention_impl(
        q, k_pages, v_pages, page_table, context_lens, k_cur, v_cur, win,
        sinks, interpret=interpret, transpose_free=transpose_free,
        logits_soft_cap=float(logits_soft_cap), scale=float(scale),
        layer=layer)


def _kernel_layered(ctx_ref, pt_ref, win_ref, lyr_ref, *rest, **kw):
    """Layered-pool entry: the 4th scalar-prefetch ref (layer) is
    consumed by the BLOCK INDEX MAPS only — the body never reads it."""
    return _kernel(ctx_ref, pt_ref, win_ref, *rest, **kw)


@functools.partial(jax.jit,
                   static_argnames=("interpret", "transpose_free",
                                    "logits_soft_cap", "scale"))
def _paged_decode_attention_impl(q: jnp.ndarray, k_pages: jnp.ndarray,
                                 v_pages: jnp.ndarray,
                                 page_table: jnp.ndarray,
                                 context_lens: jnp.ndarray,
                                 k_cur: jnp.ndarray = None,
                                 v_cur: jnp.ndarray = None,
                                 win: jnp.ndarray = None,
                                 sinks: jnp.ndarray = None,
                                 interpret: bool = False,
                                 transpose_free: bool = False,
                                 logits_soft_cap: float = 0.0,
                                 scale: float = None,
                                 layer: jnp.ndarray = None) -> jnp.ndarray:
    B, Hq, D = q.shape
    layered = layer is not None
    if layered:
        _, _, page_size, Hkv, _ = k_pages.shape
    else:
        _, page_size, Hkv, _ = k_pages.shape
    MP = page_table.shape[1]
    has_current = k_cur is not None
    if not has_current:
        k_cur = jnp.zeros((B, Hkv, D), q.dtype)
        v_cur = jnp.zeros((B, Hkv, D), q.dtype)
    if win is None:
        win = jnp.zeros((1,), jnp.int32)
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    has_sinks = sinks is not None
    sk2 = (sinks.astype(jnp.float32).reshape(Hq, 1) if has_sinks
           else jnp.zeros((Hq, 1), jnp.float32))

    if layered:
        # Pool blocks index (layer, page) straight out of the FULL
        # [L, P, ps, Hkv, D] pool — no per-layer slice exists for XLA
        # to materialize (134 MB x layers x 2 pools per decode step).
        lyr = layer.reshape(1).astype(jnp.int32)
        pool_spec = pl.BlockSpec(
            (1, 1, page_size, Hkv, D),
            lambda b, p, ctx, pt, w, l: (l[0], pt[b, p], 0, 0, 0))
        n_prefetch = 4
        def small(ix):
            return lambda b, p, ctx, pt, w, l: ix(b)
    else:
        pool_spec = pl.BlockSpec(
            (1, page_size, Hkv, D),
            lambda b, p, ctx, pt, w: (pt[b, p], 0, 0, 0))
        n_prefetch = 3
        def small(ix):
            return lambda b, p, ctx, pt, w: ix(b)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,  # ctx, page_table, win[, layer]
        grid=(B, MP),
        in_specs=[
            pl.BlockSpec((1, Hq, D), small(lambda b: (b, 0, 0))),
            pool_spec,
            pool_spec,
            pl.BlockSpec((1, Hkv, D), small(lambda b: (b, 0, 0))),
            pl.BlockSpec((1, Hkv, D), small(lambda b: (b, 0, 0))),
            pl.BlockSpec((Hq, 1), small(lambda b: (0, 0))),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), small(lambda b: (b, 0, 0))),
        scratch_shapes=[
            pltpu.VMEM((Hq, 1), jnp.float32),    # running max
            pltpu.VMEM((Hq, 1), jnp.float32),    # running denom
            pltpu.VMEM((Hq, D), jnp.float32),    # output accumulator
        ],
    )
    prefetch = (context_lens, page_table, win) + (
        (lyr,) if layered else ())
    out = pl.pallas_call(
        functools.partial(_kernel_layered if layered else _kernel,
                          page_size=page_size, pages_per_seq=MP,
                          num_kv_heads=Hkv, has_current=has_current,
                          transpose_free=transpose_free,
                          logits_soft_cap=logits_soft_cap, scale=scale,
                          has_sinks=has_sinks, layered=layered),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(*prefetch, q, k_pages, v_pages, k_cur, v_cur, sk2)
    return out
