"""Pallas TPU kernel: single-token GQA attention over the paged KV cache.

Replaces the reference XLA path (ops/attention.py ``paged_decode_attention``)
which gathers every referenced page into a dense [B, S, Hkv, D] tensor
before attending — 2× the HBM traffic and a full materialization per layer
per decode step. Here each batch program streams its sequence's pages
HBM→VMEM via a **scalar-prefetched page table** (the BlockSpec index map
reads ``page_table[b, p]`` before the kernel body runs, so the pipeline
DMAs exactly the right page), folding each page into a flash-style
online-softmax accumulator in VMEM scratch.

Grid: (B, max_pages), pages fastest → the scratch accumulator carries
across the page walk of one batch row (standard TPU flash pattern). Each
block is a whole page with all KV heads ([ps, Hkv, D] — Pallas TPU wants
the trailing two block dims full or (8,128)-aligned, so heads stay in the
block and the GQA grouping happens in-kernel). NULL pages (id 0) and
positions ≥ context_len are masked; fully out-of-range pages skip compute
via ``pl.when`` (their DMA lands on page 0 and is discarded).

The V2–V5 experiment variants (transpose-free fold, whole-row manual-DMA
walk, multi-row cells, wide block-diagonal) were deleted when the ragged
kernel (ops/pallas/ragged_attention.py) subsumed the mixed-step decode
path — none of them beat this base kernel on hardware, and their flag
matrix fragmented the bench slots and xlint pins (docs/PERF_NOTES.md
keeps the post-mortems).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from xllm_service_tpu.ops.pallas._compat import (
    CompilerParams as _CompilerParams)

_NEG_INF = -1e30

# Window sentinel: larger than any context. A plain int — module-level
# jnp constants would be captured as pallas closure constants, which
# pallas_call rejects; the shared definition documents the <= 2^30
# int32-safety bound.
from xllm_service_tpu.ops.attention import FULL_WINDOW as _FULL


def _kernel(ctx_ref, pt_ref, win_ref, q_ref, k_ref, v_ref, kc_ref, vc_ref,
            sk_ref, o_ref, m_ref, l_ref, acc_ref, *, page_size: int,
            pages_per_seq: int, num_kv_heads: int, has_current: bool,
            logits_soft_cap: float, scale: float, has_sinks: bool,
            layered: bool = False):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ctx = ctx_ref[b]
    page_start = p * page_size
    w = win_ref[0]
    w_eff = jnp.where(w > 0, w, _FULL)
    # The query's logical position: with the current token held
    # in-registers the cache holds [0, ctx) and the query sits at ctx;
    # without it, ctx INcludes the query token (position ctx − 1). The
    # window keeps cache slot j > q_pos − W (slot j holds position j).
    q_pos = ctx if has_current else ctx - 1
    win_floor = q_pos - w_eff

    @pl.when((page_start < ctx) & (page_start + page_size - 1 > win_floor))
    def _fold():
        hq, d = q_ref.shape[1], q_ref.shape[2]
        g = hq // num_kv_heads
        q = q_ref[0].astype(jnp.float32)                     # [Hq, D]
        qg = q.reshape(num_kv_heads, g, d)                   # [Hkv, G, D]
        # ``layered``: the pool rides FULL as [L, P, ps, Hkv, D] and the
        # block is [1, 1, ps, Hkv, D] (the round-5 fix for the per-layer
        # 134 MB slice materialization feeding this custom call).
        k = (k_ref[0, 0] if layered else k_ref[0]).astype(jnp.float32)
        v = (v_ref[0, 0] if layered else v_ref[0]).astype(jnp.float32)
        kt = jnp.transpose(k, (1, 0, 2))                     # [Hkv, ps, D]
        # Batched over Hkv: [Hkv, G, D] x [Hkv, ps, D] -> [Hkv, G, ps]
        logits = jax.lax.dot_general(
            qg, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale
        logits = logits.reshape(hq, page_size)               # [Hq, ps]
        if logits_soft_cap > 0.0:
            logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
        pos = page_start + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        mask = (pos < ctx) & (pos > win_floor)               # [1, ps]
        logits = jnp.where(mask, logits, _NEG_INF)
        m_prev = m_ref[:]                                    # [Hq, 1]
        blk_max = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, blk_max)
        prob = jnp.exp(logits - m_new)
        prob = jnp.where(mask, prob, 0.0)                    # [Hq, ps]
        corr = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * corr + jnp.sum(prob, axis=-1,
                                             keepdims=True)
        vt = jnp.transpose(v, (1, 0, 2))
        # [Hkv, G, ps] x [Hkv, ps, D] -> [Hkv, G, D]
        pv = jax.lax.dot_general(
            prob.reshape(num_kv_heads, g, page_size), vt,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * corr + pv.reshape(hq, d)
        m_ref[:] = m_new

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        m_fin = m_ref[:]
        l_fin = l_ref[:]
        acc_fin = acc_ref[:]
        if has_current:
            # Fold the current token's K/V (held in-registers, not yet in
            # the pool) as a final always-valid single-position block
            # (soft-capped like any cache logit; inside its own window).
            hq, d = q_ref.shape[1], q_ref.shape[2]
            g = hq // num_kv_heads
            q = q_ref[0].astype(jnp.float32)
            qg = q.reshape(num_kv_heads, g, d)
            kc = kc_ref[0].astype(jnp.float32)               # [Hkv, D]
            vc = vc_ref[0].astype(jnp.float32)
            lc = jnp.sum(qg * kc[:, None, :], axis=-1) * scale  # [Hkv, G]
            lc = lc.reshape(hq, 1)
            if logits_soft_cap > 0.0:
                lc = logits_soft_cap * jnp.tanh(lc / logits_soft_cap)
            m_new = jnp.maximum(m_fin, lc)
            corr = jnp.exp(m_fin - m_new)
            pc = jnp.exp(lc - m_new)                         # [Hq, 1]
            l_fin = l_fin * corr + pc
            vc_full = jnp.broadcast_to(
                vc[:, None, :], (num_kv_heads, g, d)).reshape(hq, d)
            acc_fin = acc_fin * corr + pc * vc_full
            m_fin = m_new
        if has_sinks:
            # GPT-OSS sinks: the per-head logit joins the denominator
            # only (never capped, never scaled — reference semantics,
            # ops/attention.py paged_decode_attention_current).
            sk = sk_ref[:].astype(jnp.float32)               # [Hq, 1]
            m_sk = jnp.maximum(m_fin, sk)
            corr = jnp.exp(m_fin - m_sk)
            l_fin = l_fin * corr + jnp.exp(sk - m_sk)
            acc_fin = acc_fin * corr
        denom = jnp.maximum(l_fin, 1e-30)
        o_ref[0] = (acc_fin / denom).astype(o_ref.dtype)


def paged_decode_attention_pallas(q: jnp.ndarray, k_pages: jnp.ndarray,
                                  v_pages: jnp.ndarray,
                                  page_table: jnp.ndarray,
                                  context_lens: jnp.ndarray,
                                  k_cur: jnp.ndarray = None,
                                  v_cur: jnp.ndarray = None,
                                  interpret: bool = None,
                                  sliding_window=0,
                                  logits_soft_cap: float = 0.0,
                                  scale=None,
                                  sinks=None,
                                  layer=None) -> jnp.ndarray:
    """q: [B, Hq, D]; k/v_pages: [P, ps, Hkv, D]; page_table: [B, MP];
    context_lens: [B] valid cache tokens. With ``k_cur``/``v_cur``
    [B, Hkv, D], the current (not-yet-written) token is folded as a final
    block — the contract of ``paged_decode_attention_current``. Returns
    [B, Hq, D].

    ``sliding_window`` is a static int OR a traced int32 scalar (per-layer
    window vectors riding the layer scan — Gemma-2/3, GPT-OSS); 0
    disables. ``logits_soft_cap``/``scale`` static floats (Gemma);
    ``sinks`` an optional [Hq] array (GPT-OSS).

    ``interpret=None`` → Pallas interpreter off TPU (XLLM_PALLAS=1 on CPU
    exercises the kernel path in tests instead of crashing in Mosaic)."""
    if interpret is None:
        from xllm_service_tpu.ops import pallas
        interpret = pallas.default_interpret()
    win = jnp.asarray(sliding_window, jnp.int32).reshape(1)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    return _paged_decode_attention_impl(
        q, k_pages, v_pages, page_table, context_lens, k_cur, v_cur, win,
        sinks, interpret=interpret,
        logits_soft_cap=float(logits_soft_cap), scale=float(scale),
        layer=layer)


def _kernel_layered(ctx_ref, pt_ref, win_ref, lyr_ref, *rest, **kw):
    """Layered-pool entry: the 4th scalar-prefetch ref (layer) is
    consumed by the BLOCK INDEX MAPS only — the body never reads it."""
    return _kernel(ctx_ref, pt_ref, win_ref, *rest, **kw)


@functools.partial(jax.jit,
                   static_argnames=("interpret", "logits_soft_cap",
                                    "scale"))
def _paged_decode_attention_impl(q: jnp.ndarray, k_pages: jnp.ndarray,
                                 v_pages: jnp.ndarray,
                                 page_table: jnp.ndarray,
                                 context_lens: jnp.ndarray,
                                 k_cur: jnp.ndarray = None,
                                 v_cur: jnp.ndarray = None,
                                 win: jnp.ndarray = None,
                                 sinks: jnp.ndarray = None,
                                 interpret: bool = False,
                                 logits_soft_cap: float = 0.0,
                                 scale: float = None,
                                 layer: jnp.ndarray = None) -> jnp.ndarray:
    B, Hq, D = q.shape
    layered = layer is not None
    if layered:
        _, _, page_size, Hkv, _ = k_pages.shape
    else:
        _, page_size, Hkv, _ = k_pages.shape
    MP = page_table.shape[1]
    has_current = k_cur is not None
    if not has_current:
        k_cur = jnp.zeros((B, Hkv, D), q.dtype)
        v_cur = jnp.zeros((B, Hkv, D), q.dtype)
    if win is None:
        win = jnp.zeros((1,), jnp.int32)
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    has_sinks = sinks is not None
    sk2 = (sinks.astype(jnp.float32).reshape(Hq, 1) if has_sinks
           else jnp.zeros((Hq, 1), jnp.float32))

    if layered:
        # Pool blocks index (layer, page) straight out of the FULL
        # [L, P, ps, Hkv, D] pool — no per-layer slice exists for XLA
        # to materialize (134 MB x layers x 2 pools per decode step).
        lyr = layer.reshape(1).astype(jnp.int32)
        pool_spec = pl.BlockSpec(
            (1, 1, page_size, Hkv, D),
            lambda b, p, ctx, pt, w, l: (l[0], pt[b, p], 0, 0, 0))
        n_prefetch = 4
        def small(ix):
            return lambda b, p, ctx, pt, w, l: ix(b)
    else:
        pool_spec = pl.BlockSpec(
            (1, page_size, Hkv, D),
            lambda b, p, ctx, pt, w: (pt[b, p], 0, 0, 0))
        n_prefetch = 3
        def small(ix):
            return lambda b, p, ctx, pt, w: ix(b)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,  # ctx, page_table, win[, layer]
        grid=(B, MP),
        in_specs=[
            pl.BlockSpec((1, Hq, D), small(lambda b: (b, 0, 0))),
            pool_spec,
            pool_spec,
            pl.BlockSpec((1, Hkv, D), small(lambda b: (b, 0, 0))),
            pl.BlockSpec((1, Hkv, D), small(lambda b: (b, 0, 0))),
            pl.BlockSpec((Hq, 1), small(lambda b: (0, 0))),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), small(lambda b: (b, 0, 0))),
        scratch_shapes=[
            pltpu.VMEM((Hq, 1), jnp.float32),    # running max
            pltpu.VMEM((Hq, 1), jnp.float32),    # running denom
            pltpu.VMEM((Hq, D), jnp.float32),    # output accumulator
        ],
    )
    prefetch = (context_lens, page_table, win) + (
        (lyr,) if layered else ())
    out = pl.pallas_call(
        functools.partial(_kernel_layered if layered else _kernel,
                          page_size=page_size, pages_per_seq=MP,
                          num_kv_heads=Hkv, has_current=has_current,
                          logits_soft_cap=logits_soft_cap, scale=scale,
                          has_sinks=has_sinks, layered=layered),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
        grid_spec=grid_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(*prefetch, q, k_pages, v_pages, k_cur, v_cur, sk2)
    return out
