"""Pallas TPU kernel: in-place decode KV write (the scatter replacement).

THE round-5 decode conviction (found offline via the local-libtpu AOT
harness, tools/aot_engine_check.py): inside the fused decode burst the
XLA scatter that writes one token's K/V per sequence cannot be proven
in-place — the pool is also read by the nested layer-scan — so XLA
copies BOTH pools around the scatter EVERY STEP: 2 x 2.1 GB of pure
copy traffic per decoded token at the bench shape, ~10.5 ms/step at
HBM roofline, the bulk of the measured 23.8 ms TPOT that three rounds
of kernel A/Bs on the attention side never explained.

This kernel declares the aliasing XLA cannot infer
(``input_output_aliases``) so the pools never move, and updates ONE
8-slot tile per row through Pallas's own block pipeline (manual DMA
slices reject a 64-wide trailing dim; pipelined blocks with FULL
trailing dims are legal — the scalar-prefetched slot drives the block
index maps, the decode-attention kernel's own pattern). Per grid cell:
fetch the row's old [L, 1, 8, Hkv, D] tile, mask-select the new
[L, Hkv, D] row in at ``slot % 8``, write the tile back — an identity
write when the row is inactive/NULL (mask empty), so dropped rows
write back exactly the bytes they read and no pl.when is needed on the
write-back path. ~128 KB per row per pool vs 2.1 GB of copy.

Correctness of the tile RMW: page_size is a multiple of 8 everywhere
the engine runs (8/64/128), so a tile never straddles a page boundary,
and two batch rows never share a page — tiles are disjoint across grid
cells even before Mosaic's sequential-cell guarantee. Dropped rows
target page 0 (the engine's NULL page) with an identity write.

Semantics match ``ops/attention.write_decode_kv_all_layers`` exactly:
inactive rows and NULL/out-of-range pages write nothing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from xllm_service_tpu.ops.pallas._compat import (
    CompilerParams as _CompilerParams)

# Sentinel slot for rows whose write must be dropped (inactive, NULL
# page, position beyond the table): the index maps send them to page 0
# tile 0 and the kernel's mask makes the write-back an identity.
_DROP = -1


def _kernel(slot_ref, kn_ref, vn_ref, ko_in_ref, vo_in_ref,
            ko_ref, vo_ref, *, page_size: int):
    b = pl.program_id(0)
    slot = slot_ref[b]
    within = jnp.maximum(slot, 0) % page_size
    off = within % 8
    live = slot >= 0
    # The iota mask carries FULL trailing (Hkv, D) dims: a (.., 1, 1)
    # mask would need a vector broadcast in both sublanes and lanes,
    # which this toolchain's Mosaic does not implement.
    hkv, d = ko_ref.shape[3], ko_ref.shape[4]
    row_mask = (jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, 8, hkv, d), 2) == off) & live

    # Select in f32: this toolchain's Mosaic lowers 32-bit vector
    # selects only ("Only 32-bit select supported" on bf16 operands);
    # the conversion is VMEM-local and the kernel is memory-bound.
    ko_ref[...] = jnp.where(
        row_mask, kn_ref[0][:, None, None].astype(jnp.float32),
        ko_in_ref[...].astype(jnp.float32)).astype(ko_ref.dtype)
    vo_ref[...] = jnp.where(
        row_mask, vn_ref[0][:, None, None].astype(jnp.float32),
        vo_in_ref[...].astype(jnp.float32)).astype(vo_ref.dtype)


def paged_kv_update(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                    k_new: jnp.ndarray, v_new: jnp.ndarray,
                    page_table: jnp.ndarray, positions: jnp.ndarray,
                    active: jnp.ndarray, *, interpret: bool = None):
    """In-place write of one decode token's K/V for all layers.

    k_pages/v_pages: [L, P, ps, Hkv, D] (DONATED through the caller's
    jit — the kernel aliases them to its outputs); k_new/v_new:
    [L, B, Hkv, D]; page_table [B, MP]; positions/active [B].
    Returns the updated (k_pages, v_pages)."""
    if interpret is None:
        from xllm_service_tpu.ops import pallas
        interpret = pallas.default_interpret()
    L, P, ps, Hkv, D = k_pages.shape
    B = k_new.shape[1]

    page_idx = positions // ps
    in_range = (page_idx < page_table.shape[1]) & active
    page = jnp.where(
        in_range,
        jnp.take_along_axis(page_table,
                            jnp.minimum(page_idx, page_table.shape[1] - 1)
                            [:, None], axis=1)[:, 0],
        0)
    slot = jnp.where(in_range & (page > 0),
                     page * ps + positions % ps,
                     _DROP).astype(jnp.int32)

    # New rows ride batch-major so each grid cell's block is a legal
    # full-trailing-dims (1, L, Hkv, D) spec.
    kn = jnp.transpose(k_new, (1, 0, 2, 3))
    vn = jnp.transpose(v_new, (1, 0, 2, 3))

    def tile_idx(b, slot_ref):
        s = jnp.maximum(slot_ref[b], 0)
        return (0, s // ps, (s % ps) // 8, 0, 0)

    pool_spec = pl.BlockSpec((L, 1, 8, Hkv, D), tile_idx)
    new_spec = pl.BlockSpec((1, L, Hkv, D),
                            lambda b, slot_ref: (b, 0, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,                    # slot
        grid=(B,),
        in_specs=[new_spec, new_spec, pool_spec, pool_spec],
        out_specs=[pool_spec, pool_spec],
    )
    ko, vo = pl.pallas_call(
        functools.partial(_kernel, page_size=ps),
        out_shape=[jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                   jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype)],
        grid_spec=grid_spec,
        # flat operand order INCLUDING the scalar prefetch: 0=slot
        # 1=k_new 2=v_new 3=k_pool 4=v_pool -> outputs 0/1. THE point
        # of the kernel: declared in-place, so the burst loop stops
        # copying 4.3 GB of pool per step.
        input_output_aliases={3: 0, 4: 1},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(slot, kn, vn, k_pages, v_pages)
    return (ko, vo)


def _kernel_layer(slot_ref, lyr_ref, kn_ref, vn_ref, ko_in_ref, vo_in_ref,
                  ko_ref, vo_ref, *, page_size: int):
    """Single-layer decode write (write-then-attend layer body): the
    traced layer index rides as a scalar-prefetch operand consumed by
    the block index maps, so the tile RMW lands straight in the FULL
    [L, P, ps, Hkv, D] pool — no per-layer slice exists, and the
    aliased write is the pool's first consumer inside the layer scan."""
    b = pl.program_id(0)
    slot = slot_ref[b]
    off = (jnp.maximum(slot, 0) % page_size) % 8
    live = slot >= 0
    hkv, d = ko_ref.shape[3], ko_ref.shape[4]
    row_mask = (jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, 8, hkv, d), 2) == off) & live
    ko_ref[...] = jnp.where(
        row_mask, kn_ref[0][None, None, None].astype(jnp.float32),
        ko_in_ref[...].astype(jnp.float32)).astype(ko_ref.dtype)
    vo_ref[...] = jnp.where(
        row_mask, vn_ref[0][None, None, None].astype(jnp.float32),
        vo_in_ref[...].astype(jnp.float32)).astype(vo_ref.dtype)


def paged_kv_update_layer(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                          k_new: jnp.ndarray, v_new: jnp.ndarray,
                          page_table: jnp.ndarray, positions: jnp.ndarray,
                          active: jnp.ndarray, layer: jnp.ndarray, *,
                          interpret: bool = None):
    """In-place write of one decode token's K/V for ONE (traced) layer.

    The write-then-attend sibling of ``paged_kv_update``: the layer scan
    carries the full pools and each layer body writes its own fresh row
    BEFORE attending, so the attention kernel reads everything —
    including the current token — from the pool. k_pages/v_pages:
    [L, P, ps, Hkv, D] (aliased to the outputs); k_new/v_new:
    [B, Hkv, D]; layer: traced int32 scalar. Semantics per row match
    ``paged_kv_update`` exactly (inactive/NULL/off-table rows drop)."""
    if interpret is None:
        from xllm_service_tpu.ops import pallas
        interpret = pallas.default_interpret()
    L, P, ps, Hkv, D = k_pages.shape
    B = k_new.shape[0]

    page_idx = positions // ps
    in_range = (page_idx < page_table.shape[1]) & active
    page = jnp.where(
        in_range,
        jnp.take_along_axis(page_table,
                            jnp.minimum(page_idx, page_table.shape[1] - 1)
                            [:, None], axis=1)[:, 0],
        0)
    slot = jnp.where(in_range & (page > 0),
                     page * ps + positions % ps,
                     _DROP).astype(jnp.int32)
    lyr = jnp.asarray(layer, jnp.int32).reshape(1)

    def tile_idx(b, slot_ref, lyr_ref):
        s = jnp.maximum(slot_ref[b], 0)
        return (lyr_ref[0], s // ps, (s % ps) // 8, 0, 0)

    pool_spec = pl.BlockSpec((1, 1, 8, Hkv, D), tile_idx)
    new_spec = pl.BlockSpec((1, Hkv, D),
                            lambda b, slot_ref, lyr_ref: (b, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                    # slot, layer
        grid=(B,),
        in_specs=[new_spec, new_spec, pool_spec, pool_spec],
        out_specs=[pool_spec, pool_spec],
    )
    ko, vo = pl.pallas_call(
        functools.partial(_kernel_layer, page_size=ps),
        out_shape=[jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                   jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype)],
        grid_spec=grid_spec,
        # flat operands incl. prefetch: 0=slot 1=layer 2=k_new 3=v_new
        # 4=k_pool 5=v_pool -> outputs 0/1. Declared in-place so the
        # pool never moves while it rides the layer scan as a carry.
        input_output_aliases={4: 0, 5: 1},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(slot, lyr, k_new, v_new, k_pages, v_pages)
    return (ko, vo)


def _prefill_kernel(pagemap_ref, valid_ref, kn_ref, vn_ref,
                    kp_in_ref, vp_in_ref, ko_ref, vo_ref, *,
                    page_size: int):
    """Grid (L, B, nW): write window-page ``w`` of row ``b`` into its
    mapped pool page for layer ``l``. Valid token count ``valid_ref[b,w]``
    masks the tail partial page (and 0 = dropped/NULL → identity)."""
    b = pl.program_id(1)
    w = pl.program_id(2)
    n_valid = valid_ref[b, w]
    hkv, d = ko_ref.shape[3], ko_ref.shape[4]
    tok_mask = (jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, page_size, hkv, d), 2) < n_valid)
    ko_ref[...] = jnp.where(tok_mask, kn_ref[0].astype(jnp.float32),
                            kp_in_ref[...].astype(jnp.float32)
                            ).astype(ko_ref.dtype)
    vo_ref[...] = jnp.where(tok_mask, vn_ref[0].astype(jnp.float32),
                            vp_in_ref[...].astype(jnp.float32)
                            ).astype(vo_ref.dtype)


def paged_prefill_kv_update(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                            k_new: jnp.ndarray, v_new: jnp.ndarray,
                            page_table: jnp.ndarray,
                            start_pos: jnp.ndarray,
                            lengths: jnp.ndarray, *,
                            interpret: bool = None):
    """In-place prefill KV write: the window's fresh rows
    [L, B, T, Hkv, D] land in their mapped pool pages with declared
    aliasing — the XLA scatter otherwise copies a full pool around the
    write at every prefill call (the decode conviction's sibling).

    Requires page-aligned window starts (``start_pos % ps == 0`` —
    engine invariant: prefix-cache grants are whole pages and mid-prompt
    chunked-prefill windows are full page-multiple buckets, only the
    FINAL chunk is ragged; the caller's static gate covers this via
    T % ps == 0), and EXCLUSIVE page ownership per row (the allocator
    invariant): the tail of a partially-valid page is identity-written
    from its old bytes, which would clobber a co-owner's rows if pages
    were ever shared."""
    if interpret is None:
        from xllm_service_tpu.ops import pallas
        interpret = pallas.default_interpret()
    L, P, ps, Hkv, D = k_pages.shape
    B, T = k_new.shape[1], k_new.shape[2]
    nW = T // ps

    # Per (b, w): target page id (NULL/out-of-table → identity write on
    # page 0) and valid token count within the page window.
    w_idx = jnp.arange(nW, dtype=jnp.int32)[None, :]            # [1,nW]
    page_idx = (start_pos[:, None] // ps) + w_idx               # [B,nW]
    in_table = page_idx < page_table.shape[1]
    page = jnp.where(
        in_table,
        jnp.take_along_axis(
            page_table, jnp.minimum(page_idx, page_table.shape[1] - 1),
            axis=1),
        0)
    n_valid = jnp.clip(lengths[:, None] - w_idx * ps, 0, ps)
    n_valid = jnp.where(in_table & (page > 0), n_valid, 0)
    pagemap = page.astype(jnp.int32)
    n_valid = n_valid.astype(jnp.int32)

    pool_spec = pl.BlockSpec(
        (1, 1, ps, Hkv, D),
        lambda l, b, w, pm, nv: (l, pm[b, w], 0, 0, 0))
    new_spec = pl.BlockSpec(
        (1, 1, 1, ps, Hkv, D),
        lambda l, b, w, pm, nv: (l, b, w, 0, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                 # pagemap, n_valid
        grid=(L, B, nW),
        in_specs=[new_spec, new_spec, pool_spec, pool_spec],
        out_specs=[pool_spec, pool_spec],
    )
    kn = k_new.reshape(L, B, nW, ps, Hkv, D)
    vn = v_new.reshape(L, B, nW, ps, Hkv, D)
    ko, vo = pl.pallas_call(
        functools.partial(_prefill_kernel, page_size=ps),
        out_shape=[jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                   jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype)],
        grid_spec=grid_spec,
        # flat operands incl. prefetch: 0=pagemap 1=n_valid 2=k_new
        # 3=v_new 4=k_pool 5=v_pool -> outputs 0/1.
        input_output_aliases={4: 0, 5: 1},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(pagemap, n_valid, kn, vn, k_pages, v_pages)
    return (ko, vo)


def _prefill_kernel_layer(pagemap_ref, valid_ref, lyr_ref, kn_ref, vn_ref,
                          kp_in_ref, vp_in_ref, ko_ref, vo_ref, *,
                          page_size: int):
    """Grid (B, nW): single-layer prefill page write at a traced layer
    index (the write-then-attend layer body's writer). Same masking as
    ``_prefill_kernel``; the layer scalar is consumed by the block index
    maps only."""
    b = pl.program_id(0)
    w = pl.program_id(1)
    n_valid = valid_ref[b, w]
    hkv, d = ko_ref.shape[3], ko_ref.shape[4]
    tok_mask = (jax.lax.broadcasted_iota(
        jnp.int32, (1, 1, page_size, hkv, d), 2) < n_valid)
    ko_ref[...] = jnp.where(tok_mask, kn_ref[...].astype(jnp.float32),
                            kp_in_ref[...].astype(jnp.float32)
                            ).astype(ko_ref.dtype)
    vo_ref[...] = jnp.where(tok_mask, vn_ref[...].astype(jnp.float32),
                            vp_in_ref[...].astype(jnp.float32)
                            ).astype(vo_ref.dtype)


def paged_prefill_kv_update_layer(k_pages: jnp.ndarray,
                                  v_pages: jnp.ndarray,
                                  k_new: jnp.ndarray, v_new: jnp.ndarray,
                                  page_table: jnp.ndarray,
                                  start_pos: jnp.ndarray,
                                  lengths: jnp.ndarray,
                                  layer: jnp.ndarray, *,
                                  interpret: bool = None):
    """In-place prefill window write for ONE (traced) layer — the
    write-then-attend sibling of ``paged_prefill_kv_update``. The pool
    rides the layer scan as a carry; each layer writes its own fresh
    window [B, T, Hkv, D] into the FULL [L, P, ps, Hkv, D] pools BEFORE
    its attention kernel reads the window back through the page table.
    The write covers the not-yet-attended window, not just committed
    tokens. Requires page-aligned window starts and T % ps == 0 (same
    invariants and drop semantics as ``paged_prefill_kv_update``)."""
    if interpret is None:
        from xllm_service_tpu.ops import pallas
        interpret = pallas.default_interpret()
    L, P, ps, Hkv, D = k_pages.shape
    B, T = k_new.shape[0], k_new.shape[1]
    nW = T // ps

    w_idx = jnp.arange(nW, dtype=jnp.int32)[None, :]            # [1,nW]
    page_idx = (start_pos[:, None] // ps) + w_idx               # [B,nW]
    in_table = page_idx < page_table.shape[1]
    page = jnp.where(
        in_table,
        jnp.take_along_axis(
            page_table, jnp.minimum(page_idx, page_table.shape[1] - 1),
            axis=1),
        0)
    n_valid = jnp.clip(lengths[:, None] - w_idx * ps, 0, ps)
    n_valid = jnp.where(in_table & (page > 0), n_valid, 0)
    pagemap = page.astype(jnp.int32)
    n_valid = n_valid.astype(jnp.int32)
    lyr = jnp.asarray(layer, jnp.int32).reshape(1)

    pool_spec = pl.BlockSpec(
        (1, 1, ps, Hkv, D),
        lambda b, w, pm, nv, ly: (ly[0], pm[b, w], 0, 0, 0))
    new_spec = pl.BlockSpec(
        (1, 1, ps, Hkv, D),
        lambda b, w, pm, nv, ly: (b, w, 0, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,                 # pagemap, n_valid, layer
        grid=(B, nW),
        in_specs=[new_spec, new_spec, pool_spec, pool_spec],
        out_specs=[pool_spec, pool_spec],
    )
    kn = k_new.reshape(B, nW, ps, Hkv, D)
    vn = v_new.reshape(B, nW, ps, Hkv, D)
    ko, vo = pl.pallas_call(
        functools.partial(_prefill_kernel_layer, page_size=ps),
        out_shape=[jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                   jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype)],
        grid_spec=grid_spec,
        # flat operands incl. prefetch: 0=pagemap 1=n_valid 2=layer
        # 3=k_new 4=v_new 5=k_pool 6=v_pool -> outputs 0/1.
        input_output_aliases={5: 0, 6: 1},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(pagemap, n_valid, lyr, kn, vn, k_pages, v_pages)
    return (ko, vo)
