"""Pallas TPU API compatibility.

The kernels target the current pallas API (``pltpu.CompilerParams``);
older jax releases (<= 0.4.x, including the pinned toolchain image)
ship the same class as ``pltpu.TPUCompilerParams``. One alias here so
every kernel module compiles against either — without it the whole
Pallas surface (and every interpret-mode test) dies at call time with
AttributeError on the older API.
"""

from jax.experimental.pallas import tpu as _pltpu

CompilerParams = getattr(_pltpu, "CompilerParams", None) \
    or _pltpu.TPUCompilerParams

# ``pltpu.HBM`` (newer name) == ``TPUMemorySpace.ANY`` on the older
# API: "leave the operand in HBM, the kernel DMAs it itself" (the V3
# row kernel's manual double-buffered page fetch).
HBM = getattr(_pltpu, "HBM", None) or _pltpu.TPUMemorySpace.ANY


def shard_map_unchecked():
    """The shard_map entry point with replication checking off, across
    both API generations: current jax ships ``jax.shard_map`` with
    ``check_vma=``; the pinned 0.4.x toolchain ships
    ``jax.experimental.shard_map.shard_map`` with ``check_rep=``.
    Returns a callable with the usual (f, mesh=..., in_specs=...,
    out_specs=...) signature."""
    import functools

    import jax
    if hasattr(jax, "shard_map"):
        return functools.partial(jax.shard_map, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm  # noqa: E501 — the one sanctioned spelling site
    return functools.partial(_sm, check_rep=False)
