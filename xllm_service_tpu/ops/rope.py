"""Rotary position embeddings (Llama/Qwen "neox" half-rotation layout).

Cos/sin tables are computed on the fly from integer positions rather than
precomputed-and-gathered: a handful of VPU transcendentals fuses into the
attention prologue under XLA, while a [max_len, dim] table gather costs HBM
bandwidth — the scarcer resource on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_cos_sin(positions: jnp.ndarray, head_dim: int, theta: float,
                 dtype=jnp.float32):
    """cos/sin for integer ``positions`` (any shape), returned with a trailing
    ``head_dim/2`` axis, always in float32 for accuracy at long context."""
    half = head_dim // 2
    freq = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * freq  # [..., half]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotate ``x`` of shape [..., seq, heads, head_dim] by per-token
    ``positions`` of shape [..., seq]. Half-rotation (GPT-NeoX/Llama) layout:
    the first half of head_dim pairs with the second half."""
    head_dim = x.shape[-1]
    cos, sin = rope_cos_sin(positions, head_dim, theta)  # [..., seq, half]
    cos = cos[..., None, :]  # broadcast over heads: [..., seq, 1, half]
    sin = sin[..., None, :]
    x1 = x[..., : head_dim // 2].astype(jnp.float32)
    x2 = x[..., head_dim // 2:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
