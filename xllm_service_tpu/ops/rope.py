"""Rotary position embeddings (Llama/Qwen "neox" half-rotation layout).

Cos/sin tables are computed on the fly from integer positions rather than
precomputed-and-gathered: a handful of VPU transcendentals fuses into the
attention prologue under XLA, while a [max_len, dim] table gather costs HBM
bandwidth — the scarcer resource on TPU.

Frequency scaling: Llama-3.1/3.2 checkpoints ship
``config.json:rope_scaling = {rope_type: "llama3", factor, low_freq_factor,
high_freq_factor, original_max_position_embeddings}`` — long-context
extension by stretching low-frequency bands while keeping high-frequency
(local-order) bands intact. ``scaling`` here is the hashable tuple form
``("llama3", factor, low, high, orig_ctx)`` carried by ModelConfig (static
under jit, so the branch below is trace-time).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax.numpy as jnp

RopeScaling = Tuple  # ("llama3", f, lo, hi, orig) | ("linear", f, 0, 0, 0)
#   | ("mrope", (s_t, s_h, s_w))
#   | ("yarn", factor, beta_fast, beta_slow, orig, attn_factor,
#             truncate, mscale_all_dim)


def rope_inv_freq(head_dim: int, theta: float,
                  scaling: Optional[RopeScaling] = None) -> jnp.ndarray:
    """Per-band inverse frequencies [head_dim/2], with checkpoint scaling
    applied. float32 throughout — bf16 frequencies destroy long-context
    phase accuracy."""
    half = head_dim // 2
    inv = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    if scaling is None:
        return inv
    kind = scaling[0]
    if kind == "llama3":
        _, factor, low_f, high_f, orig = scaling
        wavelen = 2.0 * jnp.pi / inv
        # Three bands by wavelength vs the original training context:
        # short waves untouched, long waves fully slowed by `factor`,
        # in-between smoothly interpolated.
        smooth = (orig / wavelen - low_f) / (high_f - low_f)
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = (1.0 - smooth) * inv / factor + smooth * inv
        return jnp.where(wavelen > orig / low_f, inv / factor,
                         jnp.where(wavelen < orig / high_f, inv, scaled))
    if kind == "linear":
        return inv / float(scaling[1])
    if kind == "mrope":
        return inv          # sections select streams; bands unscaled
    if kind == "yarn":
        # NTK-by-parts blend (YaRN §3.2): band b's "rotations" over the
        # original context = orig / wavelength; bands doing more than
        # beta_fast rotations keep the raw frequency (extrapolation),
        # fewer than beta_slow interpolate by 1/factor, a linear ramp
        # mixes in between. The cos/sin attention factor is applied in
        # rope_cos_sin (this function returns frequencies only).
        (_, factor, beta_fast, beta_slow, orig, _attn,
         truncate) = scaling[:7]

        def correction_dim(rot):
            return (head_dim * math.log(orig / (rot * 2 * math.pi))
                    / (2 * math.log(theta)))

        low = correction_dim(beta_fast)
        high = correction_dim(beta_slow)
        if truncate:
            low, high = math.floor(low), math.ceil(high)
        low = max(low, 0.0)
        high = min(high, head_dim - 1.0)
        if low == high:
            high += 0.001
        ramp = jnp.clip(
            (jnp.arange(half, dtype=jnp.float32) - low) / (high - low),
            0.0, 1.0)
        extrap_w = 1.0 - ramp
        return inv / factor * (1.0 - extrap_w) + inv * extrap_w
    raise NotImplementedError(
        f"rope_scaling type {kind!r} not supported — refusing to load a "
        f"checkpoint whose positions would be silently mis-rotated")


def rope_cos_sin(positions: jnp.ndarray, head_dim: int, theta: float,
                 dtype=jnp.float32,
                 scaling: Optional[RopeScaling] = None):
    """cos/sin for integer ``positions`` (any shape), returned with a trailing
    ``head_dim/2`` axis, always in float32 for accuracy at long context."""
    freq = rope_inv_freq(head_dim, theta, scaling)
    angles = positions.astype(jnp.float32)[..., None] * freq  # [..., half]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    if scaling is not None and scaling[0] == "yarn":
        # YaRN's attention-temperature factor rides the cos/sin tables
        # (HF: cos = emb.cos() * attention_scaling).
        attn = scaling[5]
        cos, sin = cos * attn, sin * attn
    return cos.astype(dtype), sin.astype(dtype)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float,
               scaling: Optional[RopeScaling] = None) -> jnp.ndarray:
    """Rotate ``x`` of shape [..., seq, heads, head_dim] by per-token
    ``positions`` of shape [..., seq]. Half-rotation (GPT-NeoX/Llama) layout:
    the first half of head_dim pairs with the second half."""
    head_dim = x.shape[-1]
    cos, sin = rope_cos_sin(positions, head_dim, theta, scaling=scaling)
    return _rotate(x, cos, sin)


def _rotate(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray):
    head_dim = x.shape[-1]
    if x.ndim == cos.ndim + 1:   # head axis present: [..., seq, H, dim]
        cos = cos[..., None, :]  # broadcast over heads: [..., seq, 1, half]
        sin = sin[..., None, :]
    x1 = x[..., : head_dim // 2].astype(jnp.float32)
    x2 = x[..., head_dim // 2:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def apply_rope_interleaved(x: jnp.ndarray, positions: jnp.ndarray,
                           theta: float,
                           scaling: Optional[RopeScaling] = None
                           ) -> jnp.ndarray:
    """Adjacent-pair ("GPT-J" / complex) rotation: pairs (x[2i], x[2i+1])
    rotate by angle_i — DeepSeek-V2's convention for its rope sub-head
    (modeling_deepseek_v2.apply_rotary_emb views the last dim as complex
    pairs), vs the half-rotation layout everywhere else. ``x`` is
    [..., seq, dim] or [..., seq, heads, dim]; ``positions`` [..., seq]."""
    dim = x.shape[-1]
    cos, sin = rope_cos_sin(positions, dim, theta, scaling=scaling)
    if x.ndim == cos.ndim + 1:            # head axis present
        cos = cos[..., None, :]
        sin = sin[..., None, :]
    x1 = x[..., 0::2].astype(jnp.float32)
    x2 = x[..., 1::2].astype(jnp.float32)
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def apply_mrope(x: jnp.ndarray, positions3: jnp.ndarray, theta: float,
                sections: Tuple[int, ...]) -> jnp.ndarray:
    """Qwen2-VL multimodal rope: three position streams (temporal /
    height / width), each owning a contiguous SECTION of the frequency
    bands. ``positions3`` is [..., 3, seq]; ``sections`` (s_t, s_h, s_w)
    sums to head_dim // 2. Text tokens carry equal streams, which makes
    this exactly standard rope for them (HF apply_multimodal_rotary_
    pos_emb semantics — the duplicated-emb split with i % 3 selection
    reduces to a per-section stream choice on the half axis)."""
    head_dim = x.shape[-1]
    freq = rope_inv_freq(head_dim, theta)               # [half]
    ang = positions3.astype(jnp.float32)[..., None] * freq  # [..,3,seq,half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    parts_c, parts_s = [], []
    off = 0
    for i, s in enumerate(sections):
        parts_c.append(cos[..., i, :, off:off + s])
        parts_s.append(sin[..., i, :, off:off + s])
        off += s
    return _rotate(x, jnp.concatenate(parts_c, -1),
                   jnp.concatenate(parts_s, -1))


def apply_rope_dynamic(x: jnp.ndarray, positions: jnp.ndarray,
                       theta, factor) -> jnp.ndarray:
    """Half-rotation rope where ``theta`` and ``factor`` (linear
    position-interpolation divisor) may be TRACED per-layer scalars —
    Gemma-3's local layers rotate with their own base and no scaling
    while global layers use the long-context base, selected per layer
    inside the scan."""
    head_dim = x.shape[-1]
    half = head_dim // 2
    inv = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions.astype(jnp.float32)[..., None] * (inv / factor)
    return _rotate(x, jnp.cos(angles), jnp.sin(angles))


def rope_for(cfg_scaling, x: jnp.ndarray, positions: jnp.ndarray,
             theta: float, positions3: Optional[jnp.ndarray] = None
             ) -> jnp.ndarray:
    """Model-level dispatch: standard/scaled rope for 1-D positions,
    mrope when the config carries sections. With mrope but no explicit
    3-D positions (pure-text requests), streams are the broadcast 1-D
    positions — identical to standard rope by construction."""
    if cfg_scaling is not None and cfg_scaling[0] == "mrope":
        if positions3 is None:
            positions3 = jnp.broadcast_to(
                positions[..., None, :],
                positions.shape[:-1] + (3, positions.shape[-1]))
        return apply_mrope(x, positions3, theta, cfg_scaling[1])
    return apply_rope(x, positions, theta, cfg_scaling)
