"""Rotary position embeddings (Llama/Qwen "neox" half-rotation layout).

Cos/sin tables are computed on the fly from integer positions rather than
precomputed-and-gathered: a handful of VPU transcendentals fuses into the
attention prologue under XLA, while a [max_len, dim] table gather costs HBM
bandwidth — the scarcer resource on TPU.

Frequency scaling: Llama-3.1/3.2 checkpoints ship
``config.json:rope_scaling = {rope_type: "llama3", factor, low_freq_factor,
high_freq_factor, original_max_position_embeddings}`` — long-context
extension by stretching low-frequency bands while keeping high-frequency
(local-order) bands intact. ``scaling`` here is the hashable tuple form
``("llama3", factor, low, high, orig_ctx)`` carried by ModelConfig (static
under jit, so the branch below is trace-time).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

RopeScaling = Tuple[str, float, float, float, int]


def rope_inv_freq(head_dim: int, theta: float,
                  scaling: Optional[RopeScaling] = None) -> jnp.ndarray:
    """Per-band inverse frequencies [head_dim/2], with checkpoint scaling
    applied. float32 throughout — bf16 frequencies destroy long-context
    phase accuracy."""
    half = head_dim // 2
    inv = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    if scaling is None:
        return inv
    kind = scaling[0]
    if kind == "llama3":
        _, factor, low_f, high_f, orig = scaling
        wavelen = 2.0 * jnp.pi / inv
        # Three bands by wavelength vs the original training context:
        # short waves untouched, long waves fully slowed by `factor`,
        # in-between smoothly interpolated.
        smooth = (orig / wavelen - low_f) / (high_f - low_f)
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = (1.0 - smooth) * inv / factor + smooth * inv
        return jnp.where(wavelen > orig / low_f, inv / factor,
                         jnp.where(wavelen < orig / high_f, inv, scaled))
    if kind == "linear":
        return inv / float(scaling[1])
    raise NotImplementedError(
        f"rope_scaling type {kind!r} not supported — refusing to load a "
        f"checkpoint whose positions would be silently mis-rotated")


def rope_cos_sin(positions: jnp.ndarray, head_dim: int, theta: float,
                 dtype=jnp.float32,
                 scaling: Optional[RopeScaling] = None):
    """cos/sin for integer ``positions`` (any shape), returned with a trailing
    ``head_dim/2`` axis, always in float32 for accuracy at long context."""
    freq = rope_inv_freq(head_dim, theta, scaling)
    angles = positions.astype(jnp.float32)[..., None] * freq  # [..., half]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float,
               scaling: Optional[RopeScaling] = None) -> jnp.ndarray:
    """Rotate ``x`` of shape [..., seq, heads, head_dim] by per-token
    ``positions`` of shape [..., seq]. Half-rotation (GPT-NeoX/Llama) layout:
    the first half of head_dim pairs with the second half."""
    head_dim = x.shape[-1]
    cos, sin = rope_cos_sin(positions, head_dim, theta, scaling=scaling)
    cos = cos[..., None, :]  # broadcast over heads: [..., seq, 1, half]
    sin = sin[..., None, :]
    x1 = x[..., : head_dim // 2].astype(jnp.float32)
    x2 = x[..., head_dim // 2:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
