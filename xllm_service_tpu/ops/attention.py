"""Attention over a paged KV cache — the core of the worker engine.

The KV cache is a pool of fixed-size pages per layer:
``k_pages, v_pages : [num_pages, page_size, num_kv_heads, head_dim]``.
A sequence owns an ordered list of page ids (its *page table*), so HBM is
allocated in page_size-token granules with no per-sequence max-length
reservation — the TPU-native equivalent of the engine-side paged KV cache the
reference assumes (SURVEY.md §5.7; block_size flag global_gflags.cpp:87-89).

Page id 0 is the NULL page: writes targeting it are dropped and reads from it
are masked out. The allocator (engine/kv_cache.py) never hands out page 0.

All functions are static-shaped and jit-safe. GQA is expressed by grouping
query heads over KV heads ([B, Hkv, G, D]) so the einsums keep the MXU busy
without materializing repeated KV. Softmax runs in float32 on the VPU.

These are the XLA reference implementations; ``ops/pallas/`` holds the fused
TPU kernels that replace the gather-then-attend pattern on the hot path.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

NULL_PAGE = 0
_NEG_INF = -1e30

# Sentinel window for full-attention layers when windows ride the layer
# scan as traced per-layer values (Gemma-2/3, GPT-OSS alternation):
# larger than any context, so the window mask is a no-op. The ONE shared
# definition — the Pallas kernels and models/transformer.py import it.
# MUST stay <= 2^30: the kernels compute q_pos - window in int32, and a
# larger sentinel would wrap negative-to-positive and mask every kv
# position on full-attention layers.
FULL_WINDOW = 1 << 30


def _win_off(w) -> bool:
    """Trace-time check: is the sliding window statically disabled?
    ``w`` is either a static python int (0 = full attention) or a traced
    int32 scalar (per-layer windows — Gemma-2's alternating local/global
    layers ride the layer scan as xs, with full layers carrying a
    larger-than-any-context sentinel)."""
    return isinstance(w, int) and w == 0


def _attn_scale(D: int, scale) -> jnp.ndarray:
    """Default 1/sqrt(head_dim); Gemma-2 overrides with
    query_pre_attn_scalar**-0.5."""
    if scale is None:
        return 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    return jnp.asarray(scale, jnp.float32)


def _flat_kv_index(page_table: jnp.ndarray, positions: jnp.ndarray,
                   page_size: int, num_slots: int,
                   valid: jnp.ndarray) -> jnp.ndarray:
    """Map logical token ``positions`` [B, T] to flat slot indices into the
    pool viewed as [num_pages * page_size, ...]. Invalid tokens map to
    ``num_slots`` — a *positive* out-of-bounds sentinel that
    scatter-with-mode=drop discards. (-1 would NOT work: JAX normalizes
    negative indices before the bounds check, so -1 silently aliases the
    last slot of the pool.)"""
    page_idx = positions // page_size                      # [B, T]
    slot = positions % page_size
    # A position past the table's capacity must be dropped, not clamped —
    # take_along_axis would otherwise silently alias the last table entry.
    in_table = page_idx < page_table.shape[1]
    page_id = jnp.take_along_axis(
        page_table, jnp.minimum(page_idx, page_table.shape[1] - 1), axis=1)
    flat = page_id * page_size + slot
    flat = jnp.where(valid & in_table & (page_id != NULL_PAGE), flat,
                     num_slots)
    return flat


def write_prefill_kv(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                     k: jnp.ndarray, v: jnp.ndarray,
                     page_table: jnp.ndarray, start_pos: jnp.ndarray,
                     lengths: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter freshly-computed prefill K/V [B, T, Hkv, D] into the page pool.

    Token t of sequence b lands at logical position ``start_pos[b] + t`` (a
    nonzero start_pos is a prefix-cache hit: the first start_pos tokens were
    already resident). Tokens with ``t >= lengths[b]`` (padding) are dropped.
    """
    B, T = k.shape[0], k.shape[1]
    page_size = k_pages.shape[1]
    num_slots = k_pages.shape[0] * page_size
    t = jnp.arange(T, dtype=jnp.int32)[None, :]            # [1, T]
    positions = start_pos[:, None] + t                      # [B, T]
    valid = t < lengths[:, None]
    flat = _flat_kv_index(page_table, positions, page_size, num_slots,
                          valid)                            # [B, T]

    pool_shape = (-1,) + k_pages.shape[2:]
    k_flat = k_pages.reshape(pool_shape)
    v_flat = v_pages.reshape(pool_shape)
    idx = flat.reshape(-1)
    k_flat = k_flat.at[idx].set(k.reshape((B * T,) + k.shape[2:]), mode="drop")
    v_flat = v_flat.at[idx].set(v.reshape((B * T,) + v.shape[2:]), mode="drop")
    return k_flat.reshape(k_pages.shape), v_flat.reshape(v_pages.shape)


def write_decode_kv(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                    k: jnp.ndarray, v: jnp.ndarray,
                    page_table: jnp.ndarray,
                    positions: jnp.ndarray,
                    active: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter one decode-step K/V [B, Hkv, D] at per-sequence ``positions``
    [B]. Inactive batch slots are dropped."""
    page_size = k_pages.shape[1]
    num_slots = k_pages.shape[0] * page_size
    flat = _flat_kv_index(page_table, positions[:, None], page_size,
                          num_slots, active[:, None])[:, 0]  # [B]
    pool_shape = (-1,) + k_pages.shape[2:]
    k_flat = k_pages.reshape(pool_shape).at[flat].set(k, mode="drop")
    v_flat = v_pages.reshape(pool_shape).at[flat].set(v, mode="drop")
    return k_flat.reshape(k_pages.shape), v_flat.reshape(v_pages.shape)


def _kv_update_kernel_enabled() -> bool:
    """Gate for the Pallas in-place KV writers
    (ops/pallas/kv_update.py): unset follows the base XLLM_PALLAS
    semantics (on wherever the Pallas kernels are on);
    XLLM_PALLAS_KV=0 switches the writers off on their own;
    XLLM_PALLAS_KV=1 FORCES them on even with XLLM_PALLAS=0 — the
    aliased writers lower on Mosaic toolchains whose attention-kernel
    relayouts do not, and XLA-attention + Pallas-writers is a
    legitimate serving mix (it is what the write-then-attend copy
    census compiles, tools/aot_copy_census.py). The XLA scatter the
    writers replace copies BOTH pools around every decode step inside
    the fused burst (~8.6 GB/step at the bench shape) — the round-5
    offline-AOT conviction."""
    import os
    env = os.environ.get("XLLM_PALLAS_KV", "").strip()
    if env in ("0", "false", "no"):
        return False
    if env in ("1", "true", "yes"):
        return True
    from xllm_service_tpu.ops import pallas
    return pallas.enabled()


def write_decode_kv_all_layers(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                               k_new: jnp.ndarray, v_new: jnp.ndarray,
                               page_table: jnp.ndarray,
                               positions: jnp.ndarray,
                               active: jnp.ndarray
                               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Write ONE decode token's K/V for ALL layers in a single scatter.

    k_pages: [L, P, ps, Hkv, D]; k_new: [L, B, Hkv, D] (per-layer scan ys).
    This exists so the layer scan never carries the pool as stacked ys —
    which would rewrite the entire pool in HBM every decode step (measured
    ~13 ms/step per GB of pool). One donated scatter after the scan is
    in-place."""
    L, _, ps_, Hkv_, D_ = k_pages.shape
    # Kernel eligibility: page tiles must exist (ps % 8) and the
    # per-cell VMEM footprint must fit comfortably (4 pool-tile blocks +
    # 2 new-row blocks, double-buffered — deep/wide models fall back to
    # the XLA scatter rather than failing Mosaic allocation).
    tile_bytes = L * 8 * Hkv_ * D_ * k_pages.dtype.itemsize
    row_bytes = L * Hkv_ * D_ * k_new.dtype.itemsize
    footprint = 2 * (4 * tile_bytes + 2 * row_bytes)
    # The MLA latent shape (Hkv=1, D=576) is INCLUDED: unlike the
    # math-heavy MLA attention kernel (still behind XLLM_PALLAS_MLA),
    # both writers are pure block-pipelined memory ops with
    # full-trailing-dims blocks, and BOTH Mosaic-compile at the latent
    # geometry in the offline v5e probe matrix
    # (docs/AOT_VERDICTS_r5.txt: 'KV UPDATE @ MLA latent' and
    # 'PREFILL KV UPDATE @ MLA latent'), with interpret parity pinned
    # at an unaligned-minor latent geometry in the ops suite.
    if _kv_update_kernel_enabled() and ps_ % 8 == 0 \
            and footprint < 6 * 2 ** 20:
        from xllm_service_tpu.ops.pallas.kv_update import paged_kv_update
        return paged_kv_update(k_pages, v_pages, k_new, v_new,
                               page_table, positions, active)
    return write_decode_kv_all_layers_xla(
        k_pages, v_pages, k_new, v_new, page_table, positions, active)


def write_decode_kv_all_layers_xla(k_pages, v_pages, k_new, v_new,
                                   page_table, positions, active
                                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The raw XLA scatter (kernel-free reference) — the gate's
    fallback, and the A/B baseline the budget table pins by name."""
    L = k_pages.shape[0]
    page_size = k_pages.shape[2]
    num_slots = k_pages.shape[1] * page_size
    flat = _flat_kv_index(page_table, positions[:, None], page_size,
                          num_slots, active[:, None])[:, 0]     # [B]
    pool_shape = (L, -1) + k_pages.shape[3:]
    k_flat = k_pages.reshape(pool_shape).at[:, flat].set(
        k_new, mode="drop")
    v_flat = v_pages.reshape(pool_shape).at[:, flat].set(
        v_new, mode="drop")
    return (k_flat.reshape(k_pages.shape), v_flat.reshape(v_pages.shape))


def write_prefill_kv_all_layers(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                                k_new: jnp.ndarray, v_new: jnp.ndarray,
                                page_table: jnp.ndarray,
                                start_pos: jnp.ndarray,
                                lengths: jnp.ndarray,
                                page_aligned_starts: bool = True
                                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Prefill counterpart: k_new [L, B, T, Hkv, D] → one scatter — or,
    on the Pallas path, the in-place page-granular write kernel (the
    XLA scatter copies a full pool around the write per prefill call;
    the decode conviction's sibling). Kernel eligibility is static:
    T % ps == 0 (bucketed windows) and page-aligned window starts,
    which the engine guarantees whenever its prefill buckets are
    page-multiples (chunked-prefill starts advance by bucket sizes;
    prefix-cache grants are whole pages)."""
    T_, ps2 = k_new.shape[2], k_pages.shape[2]
    _, _, _, Hkv2, D2 = k_pages.shape
    # Per-cell VMEM: 6 page blocks (4 pool + 2 new), double-buffered —
    # the same comfort threshold as the decode gate, falling back to
    # the scatter instead of failing Mosaic allocation. MLA latent
    # pools included (see the decode gate's note).
    cell_bytes = 2 * 6 * ps2 * Hkv2 * D2 * k_pages.dtype.itemsize
    if _kv_update_kernel_enabled() and page_aligned_starts \
            and T_ % ps2 == 0 and ps2 % 8 == 0 \
            and cell_bytes < 6 * 2 ** 20:
        from xllm_service_tpu.ops.pallas.kv_update import (
            paged_prefill_kv_update)
        return paged_prefill_kv_update(k_pages, v_pages, k_new, v_new,
                                       page_table, start_pos, lengths)
    return write_prefill_kv_all_layers_xla(
        k_pages, v_pages, k_new, v_new, page_table, start_pos, lengths)


def write_prefill_kv_all_layers_xla(k_pages, v_pages, k_new, v_new,
                                    page_table, start_pos, lengths
                                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The raw XLA prefill scatter (kernel-free reference)."""
    L, B, T = k_new.shape[0], k_new.shape[1], k_new.shape[2]
    page_size = k_pages.shape[2]
    num_slots = k_pages.shape[1] * page_size
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    positions = start_pos[:, None] + t
    valid = t < lengths[:, None]
    flat = _flat_kv_index(page_table, positions, page_size, num_slots,
                          valid).reshape(-1)                    # [B*T]
    pool_shape = (L, -1) + k_pages.shape[3:]
    new_shape = (L, B * T) + k_new.shape[3:]
    k_flat = k_pages.reshape(pool_shape).at[:, flat].set(
        k_new.reshape(new_shape), mode="drop")
    v_flat = v_pages.reshape(pool_shape).at[:, flat].set(
        v_new.reshape(new_shape), mode="drop")
    return (k_flat.reshape(k_pages.shape), v_flat.reshape(v_pages.shape))


def write_decode_kv_layer(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                          k_new: jnp.ndarray, v_new: jnp.ndarray,
                          page_table: jnp.ndarray,
                          positions: jnp.ndarray, active: jnp.ndarray,
                          layer) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Write ONE decode token's K/V for ONE (traced) layer into the FULL
    [L, P, ps, Hkv, D] pools — the write-then-attend layer-body writer.

    The pool rides the layer scan as a CARRY: each layer writes its
    fresh row first (the aliased Pallas kernel is the pool's first
    consumer, so XLA needs no defensive copy), then attention reads
    everything — including the current token — from the pool.
    k_new/v_new: [B, Hkv, D]; ``layer``: traced int32 scalar."""
    _, _, ps_, Hkv_, D_ = k_pages.shape
    if _kv_update_kernel_enabled() and ps_ % 8 == 0:
        from xllm_service_tpu.ops.pallas.kv_update import (
            paged_kv_update_layer)
        return paged_kv_update_layer(k_pages, v_pages, k_new, v_new,
                                     page_table, positions, active, layer)
    return write_decode_kv_layer_xla(k_pages, v_pages, k_new, v_new,
                                     page_table, positions, active, layer)


def write_decode_kv_layer_xla(k_pages, v_pages, k_new, v_new, page_table,
                              positions, active, layer
                              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """XLA reference for the single-layer decode write (scatter at a
    traced layer index) — the kernel-free fallback and test oracle."""
    L = k_pages.shape[0]
    page_size = k_pages.shape[2]
    num_slots = k_pages.shape[1] * page_size
    flat = _flat_kv_index(page_table, positions[:, None], page_size,
                          num_slots, active[:, None])[:, 0]     # [B]
    pool_shape = (L, -1) + k_pages.shape[3:]
    lyr = jnp.asarray(layer, jnp.int32)
    k_flat = k_pages.reshape(pool_shape).at[lyr, flat].set(
        k_new, mode="drop")
    v_flat = v_pages.reshape(pool_shape).at[lyr, flat].set(
        v_new, mode="drop")
    return (k_flat.reshape(k_pages.shape), v_flat.reshape(v_pages.shape))


def write_prefill_kv_layer(k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                           k_new: jnp.ndarray, v_new: jnp.ndarray,
                           page_table: jnp.ndarray,
                           start_pos: jnp.ndarray, lengths: jnp.ndarray,
                           layer, page_aligned_starts: bool = True
                           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Prefill counterpart of ``write_decode_kv_layer``: one layer's
    fresh window [B, T, Hkv, D] lands in the full pools BEFORE that
    layer's attention reads the window back through the page table
    (write-then-attend). The write covers the not-yet-attended window,
    not just committed tokens. Kernel eligibility mirrors
    ``write_prefill_kv_all_layers`` (page-aligned starts, T % ps == 0);
    otherwise the XLA scatter at a traced layer index."""
    T_, ps2 = k_new.shape[1], k_pages.shape[2]
    if _kv_update_kernel_enabled() and page_aligned_starts \
            and T_ % ps2 == 0 and ps2 % 8 == 0:
        from xllm_service_tpu.ops.pallas.kv_update import (
            paged_prefill_kv_update_layer)
        return paged_prefill_kv_update_layer(
            k_pages, v_pages, k_new, v_new, page_table, start_pos,
            lengths, layer)
    return write_prefill_kv_layer_xla(k_pages, v_pages, k_new, v_new,
                                      page_table, start_pos, lengths,
                                      layer)


def write_prefill_kv_layer_xla(k_pages, v_pages, k_new, v_new,
                               page_table, start_pos, lengths, layer
                               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """XLA reference for the single-layer prefill window write."""
    L = k_pages.shape[0]
    B, T = k_new.shape[0], k_new.shape[1]
    page_size = k_pages.shape[2]
    num_slots = k_pages.shape[1] * page_size
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    positions = start_pos[:, None] + t
    valid = t < lengths[:, None]
    flat = _flat_kv_index(page_table, positions, page_size, num_slots,
                          valid).reshape(-1)                    # [B*T]
    pool_shape = (L, -1) + k_pages.shape[3:]
    new_shape = (B * T,) + k_new.shape[2:]
    lyr = jnp.asarray(layer, jnp.int32)
    k_flat = k_pages.reshape(pool_shape).at[lyr, flat].set(
        k_new.reshape(new_shape), mode="drop")
    v_flat = v_pages.reshape(pool_shape).at[lyr, flat].set(
        v_new.reshape(new_shape), mode="drop")
    return (k_flat.reshape(k_pages.shape), v_flat.reshape(v_pages.shape))


def overlay_fresh_kv(k_all: jnp.ndarray, k_fresh: jnp.ndarray,
                     start_pos: jnp.ndarray) -> jnp.ndarray:
    """Overlay this step's fresh K/V [B, T, H, D] onto the gathered cache
    view [B, S, H, D] at per-sequence offsets (prefill attends against
    cache + fresh without the fresh tokens having been written yet)."""
    return jax.vmap(
        lambda arr, upd, s: jax.lax.dynamic_update_slice(
            arr, upd, (s, 0, 0)))(k_all, k_fresh, start_pos)


def gather_pages(pages: jnp.ndarray, page_table: jnp.ndarray) -> jnp.ndarray:
    """Gather a sequence's pages into [B, max_pages * page_size, Hkv, D]."""
    g = pages[page_table]                                   # [B, MP, page, H, D]
    B, MP, PS = g.shape[0], g.shape[1], g.shape[2]
    return g.reshape(B, MP * PS, *g.shape[3:])


def _group_heads(q: jnp.ndarray, num_kv_heads: int) -> jnp.ndarray:
    """[..., Hq, D] → [..., Hkv, G, D]."""
    *lead, hq, d = q.shape
    return q.reshape(*lead, num_kv_heads, hq // num_kv_heads, d)


def mha_prefill(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                kv_lengths: jnp.ndarray, q_start: jnp.ndarray,
                logits_soft_cap: float = 0.0,
                sliding_window=0, scale=None,
                sinks=None) -> jnp.ndarray:
    """Causal GQA attention for prefill.

    q: [B, T, Hq, D] — the new tokens, at global positions q_start[b] + t.
    k/v: [B, S, Hkv, D] with S >= T — cached prefix (prefix-cache hit)
      concatenated with the fresh tokens; kv position j is global position j.
    kv_lengths: [B] — valid kv length per sequence (= q_start + true T).
    ``sliding_window`` W > 0 (static) restricts each query to the last W
    key positions including itself (HF semantics: kv_pos > q_pos − W), the
    Mistral-v0.1 / Phi-3 mask.
    Returns [B, T, Hq, D].
    """
    B, T, Hq, D = q.shape
    Hkv = k.shape[2]
    S = k.shape[1]
    qg = _group_heads(q, Hkv)                               # [B, T, Hkv, G, D]
    logits = jnp.einsum("bthgd,bshd->bhgts", qg, k,
                        preferred_element_type=jnp.float32) \
        * _attn_scale(D, scale)
    if logits_soft_cap > 0.0:
        logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
    q_pos = q_start[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # [B, T]
    kv_pos = jnp.arange(S, dtype=jnp.int32)[None, :]                    # [1, S]
    causal = kv_pos[:, None, :] <= q_pos[:, :, None]                    # [B, T, S]
    in_range = kv_pos < kv_lengths[:, None]                             # [B, S]
    mask = causal & in_range[:, None, :]                                # [B, T, S]
    if not _win_off(sliding_window):
        mask &= kv_pos[:, None, :] > q_pos[:, :, None] - sliding_window
    logits = jnp.where(mask[:, None, None, :, :], logits, _NEG_INF)
    if sinks is not None:
        # GPT-OSS attention sinks: one learned per-head logit joins the
        # softmax denominator, then its probability is dropped — an
        # always-on "null token" that soaks attention mass.
        sk = jnp.broadcast_to(
            sinks.astype(jnp.float32).reshape(Hkv, -1)[None, :, :, None,
                                                       None],
            logits.shape[:-1] + (1,))
        logits = jnp.concatenate([logits, sk], axis=-1)
    p = jax.nn.softmax(logits, axis=-1)
    if sinks is not None:
        p = p[..., :-1]
    out = jnp.einsum("bhgts,bshd->bthgd", p.astype(v.dtype), v)
    return out.reshape(B, T, Hq, D)


def flash_fold(o: jnp.ndarray, m: jnp.ndarray, l: jnp.ndarray,
               qg: jnp.ndarray, kb: jnp.ndarray, vb: jnp.ndarray,
               mask: jnp.ndarray, scale,
               logits_soft_cap: float = 0.0):
    """Fold one KV block into a running online-softmax accumulator.

    qg [B, T, Hkv, G, D]; kb/vb [B, S, Hkv, D]; ``mask`` broadcastable to
    [B, T, Hkv, G, S]; carry o [B, T, Hkv, G, D], m/l [B, T, Hkv, G] all
    fp32. The flash numerics (running max, exp-rescale, masked-row zeroing)
    live here and ONLY here — shared by the chunked prefill path below and
    ring attention (parallel/ring.py)."""
    logits = jnp.einsum("bthgd,bshd->bthgs", qg, kb,
                        preferred_element_type=jnp.float32) * scale
    if logits_soft_cap > 0.0:
        logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
    logits = jnp.where(mask, logits, _NEG_INF)
    blk_max = jnp.max(logits, axis=-1)                    # [B, T, Hkv, G]
    m_new = jnp.maximum(m, blk_max)
    # exp of fully-masked rows must contribute zero, not exp(-inf - -inf).
    p = jnp.exp(logits - m_new[..., None])
    p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bthgs,bshd->bthgd", p, vb.astype(jnp.float32))
    return o_new, m_new, l_new


def flash_finalize(o: jnp.ndarray, l: jnp.ndarray) -> jnp.ndarray:
    """[B, T, Hkv, G, D] accumulator / denom → normalized output."""
    return o / jnp.maximum(l[..., None], 1e-30)


def mha_prefill_chunked(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        kv_lengths: jnp.ndarray, q_start: jnp.ndarray,
                        logits_soft_cap: float = 0.0,
                        chunk_size: int = 512,
                        sliding_window=0, scale=None,
                        sinks=None) -> jnp.ndarray:
    """Flash-style causal GQA prefill: O(T · chunk) logits memory.

    Same contract as ``mha_prefill`` but instead of materializing the full
    [B, Hkv, G, T, S] score tensor it scans KV in ``chunk_size`` blocks,
    folding each into an online-softmax accumulator (running max / denom /
    weighted sum, all fp32). Peak intermediate memory is O(B·T·chunk)
    regardless of S, so long-context prefill no longer scales quadratically
    in HBM. Chunks entirely above the causal diagonal are skipped via
    ``lax.cond`` — the scan still visits them but runs no MXU work.

    Addresses round-1 weakness: ``mha_prefill`` was O(T·S) memory and
    dominated TTFT at long context (VERDICT.md weak #5).
    """
    B, T, Hq, D = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv
    if S <= chunk_size:
        return mha_prefill(q, k, v, kv_lengths, q_start, logits_soft_cap,
                           sliding_window, scale, sinks)

    nC = (S + chunk_size - 1) // chunk_size
    pad = nC * chunk_size - S
    if pad:
        # Padded slots sit past every kv_length, so the in-range mask
        # already discards them.
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    # [nC, B, C, Hkv, D] so scan slices chunks along the leading axis.
    kc = k.reshape(B, nC, chunk_size, Hkv, D).transpose(1, 0, 2, 3, 4)
    vc = v.reshape(B, nC, chunk_size, Hkv, D).transpose(1, 0, 2, 3, 4)

    qg = _group_heads(q, Hkv).astype(jnp.float32)           # [B,T,Hkv,G,D]
    scale = _attn_scale(D, scale)
    q_pos = q_start[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # [B,T]
    # Highest query position in the batch: chunks starting beyond it are
    # fully masked for every row and can skip their compute. With a
    # sliding window, chunks entirely below every row's window (kv_pos <=
    # min(q_start) − W for all slots) skip likewise — long-context SWA
    # prefill then does O(T·W) attention work, not O(T·S).
    max_q_pos = jnp.max(q_pos)
    min_q_pos = jnp.min(q_pos[:, 0])

    o0 = jnp.zeros((B, T, Hkv, G, D), jnp.float32)
    if sinks is None:
        m0 = jnp.full((B, T, Hkv, G), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, T, Hkv, G), jnp.float32)
    else:
        # A sink IS a flash-accumulator seed: running max starts at the
        # sink logit with denominator exp(sink - sink) = 1 and zero
        # numerator — the online softmax then carries the sink's
        # denominator share exactly.
        m0 = jnp.broadcast_to(
            sinks.astype(jnp.float32).reshape(Hkv, G)[None, None],
            (B, T, Hkv, G))
        l0 = jnp.ones((B, T, Hkv, G), jnp.float32)

    def fold(carry, idx):
        o, m, l = carry
        kb, vb = kc[idx], vc[idx]
        base = idx * chunk_size

        def compute(_):
            k_pos = base + jnp.arange(chunk_size, dtype=jnp.int32)  # [C]
            causal = k_pos[None, None, :] <= q_pos[:, :, None]      # [B,T,C]
            in_range = k_pos[None, :] < kv_lengths[:, None]         # [B,C]
            btc = causal & in_range[:, None, :]
            if not _win_off(sliding_window):
                btc &= k_pos[None, None, :] > (q_pos[:, :, None]
                                               - sliding_window)
            mask = btc[:, :, None, None, :]
            return flash_fold(o, m, l, qg, kb, vb, mask, scale,
                              logits_soft_cap)

        relevant = base <= max_q_pos
        if not _win_off(sliding_window):
            relevant &= base + chunk_size - 1 > min_q_pos - sliding_window
        o, m, l = jax.lax.cond(relevant, compute,
                               lambda _: (o, m, l), None)
        return (o, m, l), None

    (o, m, l), _ = jax.lax.scan(fold, (o0, m0, l0),
                                jnp.arange(nC, dtype=jnp.int32))
    out = flash_finalize(o, l)
    return out.reshape(B, T, Hq, D).astype(q.dtype)


def mha_prefill_auto(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     kv_lengths: jnp.ndarray, q_start: jnp.ndarray,
                     logits_soft_cap: float = 0.0,
                     sliding_window=0, scale=None,
                     sinks=None) -> jnp.ndarray:
    """Trace-time dispatch for prefill attention, by SCORE-TENSOR BYTES
    (4·B·Hq·T·S), not sequence length alone: at the batched-prefill
    bench shape (B=64, T=128, S=512) an S-only cutoff picked the dense
    path whose [B, Hkv, G, T, S] fp32 scores are ~0.5 GB *per layer* —
    ~52 GB of HBM traffic per prefill call (measured via XLA
    cost_analysis, round 3). Past 64 MB of scores the chunked
    online-softmax path runs, with the chunk sized so one fold's score
    block stays ~VMEM-friendly while never dropping below 128
    positions (the fp32 lane tile)."""
    B, T, Hq = q.shape[0], q.shape[1], q.shape[2]
    S = k.shape[1]
    score_bytes = 4 * B * Hq * T * S
    if score_bytes <= 64 * 1024 * 1024:
        return mha_prefill(q, k, v, kv_lengths, q_start, logits_soft_cap,
                           sliding_window, scale, sinks)
    per_pos = 4 * B * Hq * T                 # score bytes per kv position
    chunk = (32 * 1024 * 1024) // max(per_pos, 1)
    chunk = max(128, min(1024, (chunk // 128) * 128))
    return mha_prefill_chunked(q, k, v, kv_lengths, q_start,
                               logits_soft_cap, chunk_size=chunk,
                               sliding_window=sliding_window, scale=scale,
                               sinks=sinks)


def paged_decode_attention_current(q: jnp.ndarray, k_pages: jnp.ndarray,
                                   v_pages: jnp.ndarray,
                                   page_table: jnp.ndarray,
                                   cache_lens: jnp.ndarray,
                                   k_cur: jnp.ndarray, v_cur: jnp.ndarray,
                                   logits_soft_cap: float = 0.0,
                                   sliding_window=0,
                                   scale=None, sinks=None) -> jnp.ndarray:
    """Decode attention over the cache PLUS the current token's K/V held
    in-registers (XLA reference path).

    The hot-loop restructure that motivates this: writing the current
    token's KV into the pool before attending forces the per-layer scan to
    emit a full pool copy as stacked ys (a whole-pool HBM rewrite per
    decode step). Keeping the current token out of the pool lets layers
    read the cache as scan xs and defer all writes to one donated scatter
    after the layer scan.

    q: [B, Hq, D]; k_cur/v_cur: [B, Hkv, D]; cache_lens: [B] valid tokens
    already in the cache (EXcluding the current token). Returns [B, Hq, D].
    """
    B, Hq, D = q.shape
    Hkv = k_cur.shape[1]
    k = gather_pages(k_pages, page_table)                   # [B, S, Hkv, D]
    v = gather_pages(v_pages, page_table)
    k = jnp.concatenate([k, k_cur[:, None]], axis=1)        # [B, S+1, ...]
    v = jnp.concatenate([v, v_cur[:, None]], axis=1)
    qg = _group_heads(q, Hkv)
    scale = _attn_scale(D, scale)
    logits = jnp.einsum("bhgd,bshd->bhgs", qg, k,
                        preferred_element_type=jnp.float32) * scale
    if logits_soft_cap > 0.0:
        logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
    S1 = k.shape[1]
    pos = jnp.arange(S1, dtype=jnp.int32)[None, :]
    # Cache positions < cache_lens valid; the appended slot (index S1-1)
    # is the current token, always valid (with W > 0 it sits at logical
    # position cache_lens, trivially inside its own window). Cache slot j
    # holds logical position j, so the window keeps j > cache_lens − W.
    in_cache = pos < cache_lens[:, None]
    if not _win_off(sliding_window):
        in_cache &= pos > cache_lens[:, None] - sliding_window
    mask = in_cache | (pos == S1 - 1)
    logits = jnp.where(mask[:, None, None, :], logits, _NEG_INF)
    if sinks is not None:
        sk = jnp.broadcast_to(
            sinks.astype(jnp.float32).reshape(Hkv, -1)[None, :, :, None],
            logits.shape[:-1] + (1,))
        logits = jnp.concatenate([logits, sk], axis=-1)
    p = jax.nn.softmax(logits, axis=-1)
    if sinks is not None:
        p = p[..., :-1]
    out = jnp.einsum("bhgs,bshd->bhgd", p.astype(v.dtype), v)
    return out.reshape(B, Hq, D)


def paged_decode_attention_current_auto(q, k_pages, v_pages, page_table,
                                        cache_lens, k_cur, v_cur,
                                        logits_soft_cap: float = 0.0,
                                        sliding_window=0, scale=None,
                                        sinks=None, layer=None):
    """Trace-time dispatch for the current-token variant. The base (V1)
    Pallas kernel implements the full model-delta surface — windowed
    masks (static or traced per-layer), Gemma soft-cap and scale
    overrides, GPT-OSS sinks — so SWA families ride the kernel path too
    (round-4 verdict item 3).

    ``layer`` (traced int32 scalar) + FULL 5D pools routes the kernel's
    page DMAs straight into [L, P, ps, Hkv, D] — no per-layer pool
    slice for XLA to materialize (134 MB x 2 pools x layers per decode
    step, the round-5 offline-AOT conviction). The XLA fallback slices
    locally (its gather fuses; nothing materializes)."""
    from xllm_service_tpu.ops import pallas
    if pallas.enabled():
        return pallas.paged_decode_attention_pallas(
            q, k_pages, v_pages, page_table, cache_lens,
            k_cur=k_cur, v_cur=v_cur, sliding_window=sliding_window,
            logits_soft_cap=logits_soft_cap, scale=scale, sinks=sinks,
            layer=layer)
    if layer is not None:
        k_pages = jax.lax.dynamic_index_in_dim(
            k_pages, layer, axis=0, keepdims=False)
        v_pages = jax.lax.dynamic_index_in_dim(
            v_pages, layer, axis=0, keepdims=False)
    return paged_decode_attention_current(
        q, k_pages, v_pages, page_table, cache_lens, k_cur, v_cur,
        logits_soft_cap, sliding_window, scale, sinks)


def paged_decode_attention(q: jnp.ndarray, k_pages: jnp.ndarray,
                           v_pages: jnp.ndarray, page_table: jnp.ndarray,
                           context_lens: jnp.ndarray,
                           logits_soft_cap: float = 0.0,
                           sliding_window=0, scale=None,
                           sinks=None) -> jnp.ndarray:
    """Single-token GQA attention against the paged cache (XLA reference path).

    q: [B, Hq, D]; page_table: [B, max_pages]; context_lens: [B] (number of
    valid kv tokens, including the token written this step). Returns [B, Hq, D].
    """
    B, Hq, D = q.shape
    k = gather_pages(k_pages, page_table)                   # [B, S, Hkv, D]
    v = gather_pages(v_pages, page_table)
    Hkv = k.shape[2]
    qg = _group_heads(q, Hkv)                               # [B, Hkv, G, D]
    scale = _attn_scale(D, scale)
    logits = jnp.einsum("bhgd,bshd->bhgs", qg, k,
                        preferred_element_type=jnp.float32) * scale
    if logits_soft_cap > 0.0:
        logits = logits_soft_cap * jnp.tanh(logits / logits_soft_cap)
    S = k.shape[1]
    pos = jnp.arange(S, dtype=jnp.int32)[None, :]
    mask = pos < context_lens[:, None]
    if not _win_off(sliding_window):
        # context_lens INcludes the current token (query position is
        # context_lens − 1): keep j > (context_lens − 1) − W.
        mask &= pos > context_lens[:, None] - 1 - sliding_window
    logits = jnp.where(mask[:, None, None, :], logits, _NEG_INF)
    if sinks is not None:
        # GPT-OSS sinks: concat-column-then-drop, the same reference
        # semantics as paged_decode_attention_current.
        sk = jnp.broadcast_to(
            sinks.astype(jnp.float32).reshape(Hkv, -1)[None, :, :, None],
            logits.shape[:-1] + (1,))
        logits = jnp.concatenate([logits, sk], axis=-1)
    p = jax.nn.softmax(logits, axis=-1)
    if sinks is not None:
        p = p[..., :-1]
    out = jnp.einsum("bhgs,bshd->bhgd", p.astype(v.dtype), v)
    return out.reshape(B, Hq, D)


def paged_decode_attention_auto(q, k_pages, v_pages, page_table,
                                context_lens, logits_soft_cap: float = 0.0,
                                sliding_window=0, scale=None, sinks=None,
                                layer=None):
    """Write-then-attend decode dispatch: the current token's K/V is
    already IN the pool (written by the layer body's aliased writer), so
    ``context_lens`` INCLUDES it and there is no ``k_cur``/``v_cur``
    plumbing. The Pallas kernel path reads the full 5D pools at a traced
    ``layer``; the XLA fallback slices locally (its gather fuses)."""
    from xllm_service_tpu.ops import pallas
    if pallas.enabled():
        return pallas.paged_decode_attention_pallas(
            q, k_pages, v_pages, page_table, context_lens,
            k_cur=None, v_cur=None, sliding_window=sliding_window,
            logits_soft_cap=logits_soft_cap, scale=scale, sinks=sinks,
            layer=layer)
    if layer is not None:
        k_pages = jax.lax.dynamic_index_in_dim(
            k_pages, layer, axis=0, keepdims=False)
        v_pages = jax.lax.dynamic_index_in_dim(
            v_pages, layer, axis=0, keepdims=False)
    return paged_decode_attention(
        q, k_pages, v_pages, page_table, context_lens, logits_soft_cap,
        sliding_window, scale, sinks)
