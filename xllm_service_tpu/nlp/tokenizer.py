"""Tokenizer subsystem: one interface, four backends.

Rebuild of the reference's ``tokenizer/`` (SURVEY.md §2 #13). The reference
factory picks between an HF-tokenizers Rust FFI, a re2-based tiktoken BPE,
and vendored sentencepiece (tokenizer_factory.cpp:9-33); here:

- ``HFTokenizer`` — ``tokenizer.json`` via the ``tokenizers`` package (the
  same Rust core the reference binds through its own FFI shim).
- ``TiktokenTokenizer`` — tiktoken-format rank files, re-implemented: ranks
  loaded from base64 lines, greedy BPE merge by rank, ``regex``-based
  pretokenization (the reference's re2 pattern, tiktoken_tokenizer.cpp).
- ``SentencePieceTokenizer`` — via the ``sentencepiece`` package when
  installed (gated import; absent in this image).
- ``ByteTokenizer`` — UTF-8 byte-level fallback with reserved specials; no
  model assets required (tests, demos, loadgen).

All are stateless after construction → trivially shareable across threads
(the reference clones per-thread instead, scheduler.cpp:192-195; these
backends are immutable so sharing is safe without clones).

Incremental streaming detokenization (``IncrementalDecoder``) handles the
multi-byte/UTF-8 boundary problem: bytes of a partially decoded character
are withheld until complete.
"""

from __future__ import annotations

import abc
import base64
import functools
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple


class Tokenizer(abc.ABC):
    """Reference ``tokenizer/tokenizer.h:28-47``."""

    @abc.abstractmethod
    def encode(self, text: str) -> List[int]: ...

    @abc.abstractmethod
    def decode(self, ids: Sequence[int],
               skip_special_tokens: bool = True) -> str: ...

    @property
    @abc.abstractmethod
    def vocab_size(self) -> int: ...

    @property
    def eos_token_ids(self) -> Tuple[int, ...]:
        return ()

    @property
    def bos_token_id(self) -> Optional[int]:
        return None


class ByteTokenizer(Tokenizer):
    """UTF-8 bytes + reserved special ids. id = byte + 3;
    0=pad, 1=bos, 2=eos."""

    PAD, BOS, EOS = 0, 1, 2
    _OFFSET = 3

    def __init__(self, add_bos: bool = False) -> None:
        self.add_bos = add_bos

    def encode(self, text: str) -> List[int]:
        ids = [b + self._OFFSET for b in text.encode("utf-8")]
        return [self.BOS] + ids if self.add_bos else ids

    def decode(self, ids: Sequence[int],
               skip_special_tokens: bool = True) -> str:
        # Ids outside [OFFSET, OFFSET+256) — specials or out-of-range
        # samples from a larger model vocab — are dropped.
        data = bytes(i - self._OFFSET for i in ids
                     if self._OFFSET <= i < self._OFFSET + 256)
        return data.decode("utf-8", errors="replace")

    @property
    def vocab_size(self) -> int:
        return 256 + self._OFFSET

    @property
    def eos_token_ids(self) -> Tuple[int, ...]:
        return (self.EOS,)

    @property
    def bos_token_id(self) -> Optional[int]:
        return self.BOS


class HFTokenizer(Tokenizer):
    """``tokenizer.json`` via the HF ``tokenizers`` Rust core."""

    def __init__(self, path: str,
                 eos_ids: Tuple[int, ...] = ()) -> None:
        from tokenizers import Tokenizer as _T
        self._tok = _T.from_file(path)
        self._eos = eos_ids

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text).ids

    def decode(self, ids: Sequence[int],
               skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids),
                                skip_special_tokens=skip_special_tokens)

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    @property
    def eos_token_ids(self) -> Tuple[int, ...]:
        return self._eos


# cl100k-style pretokenization pattern (tiktoken's public pattern; the
# reference compiles the same class of pattern into re2,
# tiktoken_tokenizer.cpp).
_TIKTOKEN_PAT = (
    r"""'(?i:[sdmt]|ll|ve|re)|[^\r\n\p{L}\p{N}]?+\p{L}+|\p{N}{1,3}|"""
    r""" ?[^\s\p{L}\p{N}]++[\r\n]*|\s*[\r\n]|\s+(?!\S)|\s+""")


class TiktokenTokenizer(Tokenizer):
    """tiktoken-format BPE: file of ``<base64 token> <rank>`` lines."""

    def __init__(self, path: str, pattern: str = _TIKTOKEN_PAT,
                 special_tokens: Optional[Dict[str, int]] = None) -> None:
        import regex
        self._pat = regex.compile(pattern)
        self._ranks: Dict[bytes, int] = {}
        with open(path, "rb") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                tok_b64, rank = line.split()
                self._ranks[base64.b64decode(tok_b64)] = int(rank)
        self._id_to_bytes = {v: k for k, v in self._ranks.items()}
        self._special = dict(special_tokens or {})
        for name, sid in self._special.items():
            self._id_to_bytes[sid] = name.encode("utf-8")
        self._max_id = max(self._id_to_bytes) + 1

    def _bpe(self, piece: bytes) -> List[int]:
        if piece in self._ranks:
            return [self._ranks[piece]]
        parts: List[bytes] = [bytes([b]) for b in piece]
        while len(parts) > 1:
            best_rank, best_i = None, -1
            for i in range(len(parts) - 1):
                r = self._ranks.get(parts[i] + parts[i + 1])
                if r is not None and (best_rank is None or r < best_rank):
                    best_rank, best_i = r, i
            if best_rank is None:
                break
            parts[best_i:best_i + 2] = [parts[best_i] + parts[best_i + 1]]
        out = []
        for p in parts:
            r = self._ranks.get(p)
            if r is None:
                # Unknown byte sequence: fall back to per-byte ranks where
                # they exist; skip otherwise.
                out.extend(self._ranks[bytes([b])] for b in p
                           if bytes([b]) in self._ranks)
            else:
                out.append(r)
        return out

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        for m in self._pat.finditer(text):
            ids.extend(self._bpe(m.group().encode("utf-8")))
        return ids

    def decode(self, ids: Sequence[int],
               skip_special_tokens: bool = True) -> str:
        special_ids = set(self._special.values())
        buf = b""
        for i in ids:
            if skip_special_tokens and i in special_ids:
                continue
            buf += self._id_to_bytes.get(i, b"")
        return buf.decode("utf-8", errors="replace")

    @property
    def vocab_size(self) -> int:
        return self._max_id

    @property
    def eos_token_ids(self) -> Tuple[int, ...]:
        return tuple(sid for name, sid in self._special.items()
                     if "end" in name.lower() or "eot" in name.lower())


class SentencePieceTokenizer(Tokenizer):
    """``tokenizer.model`` via the sentencepiece package (optional)."""

    def __init__(self, path: str) -> None:
        try:
            import sentencepiece as spm
        except ImportError as e:  # pragma: no cover - absent in this image
            raise RuntimeError(
                "sentencepiece is not installed; convert the model to "
                "tokenizer.json or install sentencepiece") from e
        self._sp = spm.SentencePieceProcessor(model_file=path)

    def encode(self, text: str) -> List[int]:
        return list(self._sp.encode(text))

    def decode(self, ids: Sequence[int],
               skip_special_tokens: bool = True) -> str:
        return self._sp.decode(list(ids))

    @property
    def vocab_size(self) -> int:
        return self._sp.vocab_size()

    @property
    def eos_token_ids(self) -> Tuple[int, ...]:
        return (self._sp.eos_id(),) if self._sp.eos_id() >= 0 else ()


class TokenizerFactory:
    """File-sniffing factory (reference tokenizer_factory.cpp:9-33):
    ``tokenizer.json`` → HF; ``*.tiktoken`` → tiktoken;
    ``tokenizer.model`` → sentencepiece; nothing → byte-level."""

    @staticmethod
    @functools.lru_cache(maxsize=8)
    def create_tokenizer(model_dir: str = "") -> Tokenizer:
        if not model_dir:
            return ByteTokenizer()
        hf = os.path.join(model_dir, "tokenizer.json")
        if os.path.exists(hf):
            eos = _eos_from_config(model_dir)
            return HFTokenizer(hf, eos)
        for fname in sorted(os.listdir(model_dir)):
            if fname.endswith(".tiktoken"):
                return TiktokenTokenizer(os.path.join(model_dir, fname))
        sp = os.path.join(model_dir, "tokenizer.model")
        if os.path.exists(sp):
            return SentencePieceTokenizer(sp)
        return ByteTokenizer()


def _eos_from_config(model_dir: str) -> Tuple[int, ...]:
    """eos ids from config.json / generation_config.json
    (reference tokenizer_args.cpp:30-72 reads tokenizer_config.json)."""
    for fname in ("generation_config.json", "config.json"):
        path = os.path.join(model_dir, fname)
        if not os.path.exists(path):
            continue
        try:
            with open(path, "r", encoding="utf-8") as f:
                cfg = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        eos = cfg.get("eos_token_id")
        if eos is None:
            continue
        return tuple(eos) if isinstance(eos, list) else (int(eos),)
    return ()


class IncrementalDecoder:
    """Streaming detokenizer for one sequence: feeds token ids, emits only
    complete UTF-8 text (held-back bytes flushed once the char completes).

    Decodes a bounded trailing window, not the whole accumulated id list:
    ``_prefix`` marks the window start and advances on every successful
    emit, so per-token cost stays O(window) instead of O(generated) — the
    per-token host hot path must not be quadratic in generation length."""

    _CONTEXT_TOKENS = 4

    def __init__(self, tokenizer: Tokenizer) -> None:
        self._tok = tokenizer
        self._ids: List[int] = []
        self._prefix = 0             # window start (context for BPE joins)
        self._win_emitted = 0        # chars of decode(window) already out

    def feed(self, new_ids: Sequence[int]) -> str:
        self._ids.extend(new_ids)
        full = self._tok.decode(self._ids[self._prefix:])
        # A trailing replacement char usually means a split multi-byte
        # sequence: hold it back until the char completes; anything before
        # it is final and emitted now.
        safe = len(full)
        while safe > 0 and full[safe - 1] == "�":
            safe -= 1
        delta = full[self._win_emitted:safe] \
            if safe > self._win_emitted else ""
        self._win_emitted = max(self._win_emitted, safe)
        if safe == len(full):
            # Window fully emitted: slide it forward, keeping a few tokens
            # of context (boundary-marker tokenizers like SentencePiece
            # mis-decode a word-start token with no left context).
            new_prefix = max(len(self._ids) - self._CONTEXT_TOKENS, 0)
            if new_prefix > self._prefix:
                self._prefix = new_prefix
                self._win_emitted = len(
                    self._tok.decode(self._ids[self._prefix:]))
        return delta

    def flush(self) -> str:
        full = self._tok.decode(self._ids[self._prefix:])
        delta = full[self._win_emitted:]
        self._prefix = len(self._ids)
        self._win_emitted = 0
        return delta
