"""NLP preprocessing: tokenizer backends + chat templating (reference
layer E — ``tokenizer/`` and ``chat_template/``, SURVEY.md §1)."""

from xllm_service_tpu.nlp.tokenizer import (  # noqa: F401
    ByteTokenizer, Tokenizer, TokenizerFactory)
from xllm_service_tpu.nlp.chat_template import ChatTemplate  # noqa: F401
