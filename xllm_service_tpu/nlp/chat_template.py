"""Chat templating: jinja templates with tools + multimodal content.

Rebuild of ``chat_template/jinja_chat_template.{h,cpp}`` (SURVEY.md §2
#15): applies the model's ``chat_template.jinja`` (or the template string
from ``tokenizer_config.json``) to an OpenAI ``messages`` array, with a
``tools`` array and multimodal content-part flattening (image parts become
placeholder tokens for the EPD encode stage, jinja_chat_template.cpp:
26-120). Uses the jinja2 package (the reference vendors minja, a C++
jinja); a ChatML default covers models that ship no template.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_CHATML_TEMPLATE = (
    "{% for message in messages %}"
    "{{ '<|im_start|>' + message['role'] + '\n' + message['content'] "
    "+ '<|im_end|>' + '\n' }}"
    "{% endfor %}"
    "{% if add_generation_prompt %}"
    "{{ '<|im_start|>assistant\n' }}"
    "{% endif %}")

IMAGE_PLACEHOLDER = "<|image_pad|>"
VIDEO_PLACEHOLDER = "<|video_pad|>"


def _flatten_content(content: Any) -> Tuple[str, List[Dict[str, Any]]]:
    """OpenAI content parts → (flat text with placeholders, mm_inputs)."""
    if isinstance(content, str):
        return content, []
    if not isinstance(content, list):
        return str(content), []
    text_parts: List[str] = []
    mm_inputs: List[Dict[str, Any]] = []
    for part in content:
        ptype = part.get("type", "text")
        if ptype == "text":
            text_parts.append(part.get("text", ""))
        elif ptype in ("image_url", "image"):
            url = part.get("image_url", {})
            url = url.get("url", "") if isinstance(url, dict) else str(url)
            mm_inputs.append({"type": "image", "data": url})
            text_parts.append(IMAGE_PLACEHOLDER)
        elif ptype in ("video_url", "video"):
            url = part.get("video_url", {})
            url = url.get("url", "") if isinstance(url, dict) else str(url)
            mm_inputs.append({"type": "video", "data": url})
            text_parts.append(VIDEO_PLACEHOLDER)
    return "".join(text_parts), mm_inputs


class ChatTemplate:
    def __init__(self, template: Optional[str] = None,
                 bos_token: str = "", eos_token: str = "") -> None:
        import jinja2
        self._env = jinja2.Environment(
            loader=jinja2.BaseLoader(),
            undefined=jinja2.ChainableUndefined,
            trim_blocks=True, lstrip_blocks=True)
        self._env.globals["raise_exception"] = _raise_exception
        self._env.filters["tojson"] = lambda v, **kw: json.dumps(v, **kw)
        self._template = self._env.from_string(
            template or DEFAULT_CHATML_TEMPLATE)
        self.bos_token = bos_token
        self.eos_token = eos_token

    @classmethod
    def from_model_dir(cls, model_dir: str) -> "ChatTemplate":
        """Load ``chat_template.jinja`` or the ``chat_template`` field of
        ``tokenizer_config.json`` (reference tokenizer_args.cpp:30-72)."""
        template = None
        bos = eos = ""
        if model_dir:
            jinja_path = os.path.join(model_dir, "chat_template.jinja")
            if os.path.exists(jinja_path):
                with open(jinja_path, "r", encoding="utf-8") as f:
                    template = f.read()
            cfg_path = os.path.join(model_dir, "tokenizer_config.json")
            if os.path.exists(cfg_path):
                with open(cfg_path, "r", encoding="utf-8") as f:
                    cfg = json.load(f)
                template = template or cfg.get("chat_template")
                bos = _token_str(cfg.get("bos_token"))
                eos = _token_str(cfg.get("eos_token"))
        return cls(template, bos, eos)

    def apply(self, messages: List[Dict[str, Any]],
              tools: Optional[List[Dict[str, Any]]] = None,
              add_generation_prompt: bool = True
              ) -> Tuple[str, List[Dict[str, Any]]]:
        """messages (+tools) → (prompt string, multimodal inputs)
        (reference JinjaChatTemplate::apply, jinja_chat_template.h:66-85)."""
        mm_inputs: List[Dict[str, Any]] = []
        flat_messages = []
        for msg in messages:
            text, mm = _flatten_content(msg.get("content", ""))
            mm_inputs.extend(mm)
            out = dict(msg)
            out["content"] = text
            flat_messages.append(out)
        prompt = self._template.render(
            messages=flat_messages,
            tools=tools or None,
            add_generation_prompt=add_generation_prompt,
            bos_token=self.bos_token,
            eos_token=self.eos_token)
        return prompt, mm_inputs


def _token_str(v: Any) -> str:
    if isinstance(v, dict):
        return v.get("content", "")
    return v or ""


def _raise_exception(message: str) -> None:
    raise ValueError(f"chat template error: {message}")
