"""xllm-service-tpu: a TPU-native LLM serving-orchestration framework.

A ground-up rebuild of the capabilities of ``czynb666/xllm-service`` for
Google TPUs: an OpenAI-compatible front door and cluster scheduler
(``service/``) orchestrating JAX/XLA/Pallas worker engines (``runtime/``,
``models/``, ``ops/``, ``parallel/``) with prefill/decode disaggregation,
a cluster-wide prefix KV-cache index, SLO-aware routing, and multi-model
sleep/wakeup — plus the net-new TPU engine the reference delegated to
NPU-side xLLM.
"""

__version__ = "0.1.0"

from xllm_service_tpu.config import (  # noqa: F401
    EngineConfig,
    InstanceType,
    LoadBalancePolicyType,
    ModelConfig,
    ServiceOptions,
)
