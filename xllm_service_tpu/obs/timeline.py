"""Cluster-merged Perfetto/chrome-trace export.

``GET /admin/timeline?seconds=N`` on the master merges three evidence
streams into one chrome://tracing- and Perfetto-loadable JSON document:

- **service-plane request spans** (obs/spans.py): each request becomes
  a track of "X" duration slices, one per consecutive stage pair
  (received→admitted→scheduled→…), on the master process;
- **hot-path section slices** (obs/profiler.py event tail): the PR-18
  section timers, one track per thread, on whichever plane recorded
  them;
- **worker step records** (obs/steptrace.py): one "engine" track per
  worker instance with an "X" slice per engine iteration, per-phase
  child slices laid out inside it (sequential placement — the ledger
  carries durations, not offsets, so sub-slices are attribution, not
  exact timing), plus counter tracks ("C") for KV usage and batch
  occupancy sampled at every step.

Flow events ("s"/"t"/"f", one flow id per request id) stitch a request
from its ``received`` stage on the master through the engine steps that
carried it on a worker — the artifact the PD-migration/sharded-serving
ROADMAP items will be debugged with.

Determinism is part of the contract (tier-1 pins it byte-for-byte):
instances sort by name, pids/tids/flow-ids are assigned in sorted
order, timestamps are integer microseconds relative to the earliest
event, and ``render()`` serializes with sorted keys and fixed
separators. Two builds over the same inputs are identical bytes.

``CHROME_PHASES`` is the CLOSED catalog of chrome-trace "ph" values
this exporter may emit — xlint rule ``steptrace-schema`` pins every
``{"ph": ...}`` literal in the tree to it, so a typo'd phase can't
silently produce an unloadable trace.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

# The closed chrome-trace event-phase catalog: X = complete slice,
# M = metadata (process/thread names), C = counter sample, s/t/f =
# flow start/step/finish, i = instant.
CHROME_PHASES: Tuple[str, ...] = ("X", "M", "C", "s", "t", "f", "i")

# Pid of the master process track; workers are assigned 2.. in sorted
# instance-name order.
MASTER_PID = 1


def _us(t_wall: float, t0: float) -> int:
    return max(0, int(round((t_wall - t0) * 1e6)))


def _meta(pid: int, tid: int, what: str, name: str) -> Dict[str, Any]:
    return {"ph": "M", "pid": pid, "tid": tid, "name": what,
            "args": {"name": name}}


def build_timeline(*, service_id: str,
                   spans: List[Dict[str, Any]],
                   sections: List[Dict[str, Any]],
                   workers: Dict[str, Dict[str, Any]],
                   window_s: float = 60.0,
                   master_counters: Optional[Dict[str, float]] = None
                   ) -> Dict[str, Any]:
    """Merge spans + section slices + worker step records into one
    chrome-trace dict. ``spans`` is SpanStore.tail() output;
    ``sections`` is profiler.recent_events() (master-side);
    ``workers`` maps instance name → {"steps": [...], "sections":
    [...]} (each worker's ring pull or heartbeat book). ``window_s``
    clips everything older than the newest event minus the window."""
    # ---- collect every wall timestamp first: t0 anchors the trace.
    walls: List[float] = []
    for span in spans:
        for ev in span.get("events", []):
            walls.append(float(ev.get("t_wall", 0.0)))
    for ev in sections:
        walls.append(float(ev.get("t_wall", 0.0)))
    for wname in workers:
        for rec in workers[wname].get("steps", []):
            walls.append(float(rec.get("t_wall", 0.0)))
        for ev in workers[wname].get("sections", []):
            walls.append(float(ev.get("t_wall", 0.0)))
    walls = [w for w in walls if w > 0.0]
    if not walls:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "metadata": {"service_id": service_id, "window_s":
                             window_s, "instances": []}}
    newest = max(walls)
    horizon = newest - window_s
    t0 = min(w for w in walls if w >= horizon)

    events: List[Dict[str, Any]] = []
    instance_names = sorted(workers)
    pids = {name: MASTER_PID + 1 + i
            for i, name in enumerate(instance_names)}

    # ---- process/thread metadata tracks ------------------------------
    events.append(_meta(MASTER_PID, 0, "process_name",
                        f"service:{service_id}"))
    events.append(_meta(MASTER_PID, 1, "thread_name", "requests"))
    events.append(_meta(MASTER_PID, 2, "thread_name", "hotpath"))
    for name in instance_names:
        events.append(_meta(pids[name], 0, "process_name",
                            f"worker:{name}"))
        events.append(_meta(pids[name], 1, "thread_name", "engine"))
        events.append(_meta(pids[name], 2, "thread_name", "hotpath"))

    # ---- flow ids: one per request id BOTH planes saw inside the
    # window (a span with service stages AND ≥1 step that carried it) —
    # so every emitted flow is complete (one "s" … one "f") by
    # construction, the invariant tools/trace_view.py enforces. A
    # span-only rid (steps evicted/not pulled) gets slices, no flow.
    step_rids = set()
    for name in instance_names:
        for rec in workers[name].get("steps", []):
            if float(rec.get("t_wall", 0.0)) >= horizon:
                step_rids.update(rec.get("members") or ())
    svc_rids = {
        span.get("request_id", "") for span in spans
        if span.get("request_id")
        and any(e.get("plane") == "service"
                and float(e.get("t_wall", 0.0)) >= horizon
                for e in span.get("events", []))}
    rids = sorted(svc_rids & step_rids)
    flow_ids = {rid: i + 1 for i, rid in enumerate(rids)}

    # ---- service-plane spans → per-request stage slices + flow "s" ---
    for span in sorted(spans, key=lambda s: s.get("request_id", "")):
        rid = span.get("request_id", "")
        evs = [e for e in span.get("events", [])
               if float(e.get("t_wall", 0.0)) >= horizon]
        evs.sort(key=lambda e: (float(e.get("t_wall", 0.0)),
                                str(e.get("stage", ""))))
        svc = [e for e in evs if e.get("plane") == "service"]
        for a, b in zip(svc, svc[1:]):
            ts = _us(float(a["t_wall"]), t0)
            dur = max(1, _us(float(b["t_wall"]), t0) - ts)
            events.append({
                "ph": "X", "pid": MASTER_PID, "tid": 1,
                "ts": ts, "dur": dur,
                "name": f"{a.get('stage')}→{b.get('stage')}",
                "cat": "span", "args": {"request_id": rid}})
        if svc and rid in flow_ids:
            # Flow start rides the first service-plane stage slice.
            events.append({
                "ph": "s", "pid": MASTER_PID, "tid": 1,
                "ts": _us(float(svc[0]["t_wall"]), t0),
                "name": "request", "cat": "flow",
                "id": flow_ids[rid], "args": {"request_id": rid}})
        # Worker-plane stages merged into the span ring (heartbeats)
        # land on that worker's engine track as instants.
        for e in evs:
            if e.get("plane") != "worker":
                continue
            src = e.get("source", "")
            pid = pids.get(src)
            if pid is None:
                continue
            events.append({
                "ph": "i", "pid": pid, "tid": 1,
                "ts": _us(float(e["t_wall"]), t0),
                "name": f"{rid}:{e.get('stage')}", "cat": "span",
                "s": "t", "args": {"request_id": rid}})

    # ---- hot-path section slices (master + per-worker tails) ---------
    def _section_events(tail: List[Dict[str, Any]], pid: int) -> None:
        for ev in tail:
            wall = float(ev.get("t_wall", 0.0))
            if wall < horizon:
                continue
            dur_ms = float(ev.get("dur_ms", 0.0))
            ts = _us(wall - dur_ms / 1000.0, t0)
            events.append({
                "ph": "X", "pid": pid, "tid": 2, "ts": ts,
                "dur": max(1, int(round(dur_ms * 1000.0))),
                "name": str(ev.get("name", "")), "cat": "hotpath",
                "args": {"thread": str(ev.get("thread", ""))}})

    _section_events(sections, MASTER_PID)
    for name in instance_names:
        _section_events(workers[name].get("sections", []), pids[name])

    # ---- worker step records → engine slices, phase sub-slices,
    #      counter tracks, and flow "t"/"f" stitches -------------------
    finished_flow: Dict[str, Tuple[int, int]] = {}
    for name in instance_names:
        pid = pids[name]
        recs = [r for r in workers[name].get("steps", [])
                if float(r.get("t_wall", 0.0)) >= horizon]
        recs.sort(key=lambda r: int(r.get("seq", 0)))
        for rec in recs:
            step_ms = float(rec.get("step_ms", 0.0))
            end = float(rec.get("t_wall", 0.0))
            ts = _us(end - step_ms / 1000.0, t0)
            dur = max(1, int(round(step_ms * 1000.0)))
            args = {k: rec.get(k) for k in
                    ("seq", "kind", "model", "prefill_tokens",
                     "decode_tokens", "attn_dispatches", "ragged",
                     "mfu", "bound", "debt_ms")
                    if k in rec}
            events.append({
                "ph": "X", "pid": pid, "tid": 1, "ts": ts,
                "dur": dur, "name": f"step:{rec.get('kind', '?')}",
                "cat": "step", "args": args})
            # Phase sub-slices: sequential within the parent, clamped
            # to its duration (durations, not offsets — attribution).
            cursor = ts
            budget = ts + dur
            for phase in sorted(rec.get("phases", {})):
                ms = float(rec["phases"][phase])
                if ms <= 0.0 or cursor >= budget:
                    continue
                sub = min(max(1, int(round(ms * 1000.0))),
                          budget - cursor)
                events.append({
                    "ph": "X", "pid": pid, "tid": 1, "ts": cursor,
                    "dur": sub, "name": phase, "cat": "phase",
                    "args": {"ms": round(ms, 3)}})
                cursor += sub
            # Counter samples at every step: ≥1 counter track per
            # worker (KV usage + live batch occupancy).
            if "kv_usage" in rec:
                events.append({
                    "ph": "C", "pid": pid, "tid": 0, "ts": ts,
                    "name": "kv_usage",
                    "args": {"kv_usage":
                             round(float(rec["kv_usage"]), 4)}})
            members = rec.get("members") or ()
            events.append({
                "ph": "C", "pid": pid, "tid": 0, "ts": ts,
                "name": "batch", "args": {"running": len(members)}})
            # Flow stitches: a step that carried a known request id
            # gets a "t" riding its slice; the LAST such step per rid
            # is upgraded to the flow finish below.
            for rid in sorted(members):
                if rid in flow_ids:
                    finished_flow[rid] = (pid, ts)
                    events.append({
                        "ph": "t", "pid": pid, "tid": 1, "ts": ts,
                        "name": "request", "cat": "flow",
                        "id": flow_ids[rid],
                        "args": {"request_id": rid}})
    if master_counters:
        for cname in sorted(master_counters):
            events.append({
                "ph": "C", "pid": MASTER_PID, "tid": 0,
                "ts": _us(newest, t0), "name": cname,
                "args": {cname: master_counters[cname]}})
    for rid in sorted(finished_flow):
        pid, ts = finished_flow[rid]
        events.append({
            "ph": "f", "pid": pid, "tid": 1, "ts": ts, "bp": "e",
            "name": "request", "cat": "flow", "id": flow_ids[rid],
            "args": {"request_id": rid}})

    # Deterministic event order: chrome-trace consumers don't require
    # sorting, but byte-stability does.
    events.sort(key=lambda e: (int(e.get("ts", -1)),
                               int(e.get("pid", 0)),
                               int(e.get("tid", 0)),
                               str(e.get("ph", "")),
                               str(e.get("name", ""))))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {
            "service_id": service_id,
            "window_s": window_s,
            "instances": instance_names,
        },
    }


def render(trace: Dict[str, Any]) -> str:
    """Canonical byte-stable serialization (sorted keys, fixed
    separators) — what /admin/timeline returns and what the merge-
    determinism test pins byte-for-byte."""
    return json.dumps(trace, sort_keys=True, separators=(",", ":"))
