"""SLO engine + anomaly detector: turn raw telemetry into judgment.

PR 3 made latency measurable; this module answers "are we meeting the
SLO right now" and "is an instance misbehaving" from those same
measurements — no new instrumentation, only judgment over snapshots of
what the registry already records.

## SLO engine

Each objective is "``objective`` fraction of requests must be good",
where good means a latency sample under ``threshold_ms`` (ttft / e2e /
queue_wait) or a request that did not error (availability). The engine
keeps a time-stamped history of cumulative (good, total) counts and
evaluates each objective over two windows — a FAST window (~5 min;
pages quickly, noisy) and a SLOW window (~1 h; pages slowly, confident)
— as error-budget BURN RATES:

    bad_fraction(window) = 1 - good/total          (over the window delta)
    burn_rate(window)    = bad_fraction / (1 - objective)

burn 1.0 = consuming budget exactly as fast as the objective allows;
a breach opens when BOTH windows burn ≥ ``burn_open`` (the standard
multi-window guard: the fast window confirms it is happening NOW, the
slow window confirms it is not a blip) and closes when the fast window
drops back under ``burn_close``. Open/close transitions land in the
event log (``slo_breach_{open,close}``); current state is exported as
``xllm_slo_{attainment,burn_rate,breach}`` gauges and served at
``GET /admin/slo``.

Thresholds/windows come from ``XLLM_SLO_*`` env knobs (docs/FLAGS.md);
snapshots are produced by an injected callable so the engine itself
stays dependency-free and clock-injectable (unit tests drive it with a
fake clock and synthetic traffic).

## Anomaly detector (the watchdog's brain)

``AnomalyDetector.observe()`` consumes per-instance signals the service
plane already has — heartbeat age vs. deadline, the worker-shipped
recent ``xllm_worker_step_ms`` p99 vs. a rolling (EWMA) per-instance
baseline, KV-pool utilization — and maintains open anomalies per
(type, instance), emitting ``anomaly_{open,close}`` events and
exporting ``xllm_anomaly_active{type,instance}``. Signal GATHERING
happens in the service watchdog thread (http_service.py) outside any
obs lock; this class only judges.

Lock ranks (utils/locks.py table): ``obs.slo`` 78, ``obs.watchdog`` 79
— both may emit events (rank 80) and touch the registry (rank 93)
while held.
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from xllm_service_tpu.obs.events import EventLog
from xllm_service_tpu.utils.locks import make_lock

# Snapshot: objective name → (good_count, total_count), both cumulative.
Snapshot = Dict[str, Tuple[float, float]]

DEFAULT_TTFT_MS = 1000.0        # mirrors ServiceOptions.target_ttft_ms
DEFAULT_E2E_MS = 30000.0
DEFAULT_QUEUE_WAIT_MS = 5000.0
DEFAULT_ENCODE_MS = 2000.0      # EPD per-call vision-encode bound
DEFAULT_OBJECTIVE = 0.99        # 99% of requests good
DEFAULT_AVAILABILITY = 0.999
DEFAULT_FAST_WINDOW_S = 300.0
DEFAULT_SLOW_WINDOW_S = 3600.0
DEFAULT_TICK_S = 5.0


def _env_f(raw: Optional[str], default: float) -> float:
    """Parse an env value already read at the call site (the reads stay
    literal ``os.environ.get("XLLM_...")`` calls so the flag-registry
    xlint rule sees every one of them)."""
    try:
        return float(raw)
    except (TypeError, ValueError):
        return default


@dataclasses.dataclass
class SloObjective:
    """One SLO: ``objective`` fraction of requests must be good."""

    name: str                   # "ttft" | "e2e" | "queue_wait" | "availability"
    objective: float            # target good fraction in (0, 1)
    threshold_ms: float = 0.0   # latency bound (0 for availability)


@dataclasses.dataclass
class SloConfig:
    objectives: List[SloObjective]
    fast_window_s: float = DEFAULT_FAST_WINDOW_S
    slow_window_s: float = DEFAULT_SLOW_WINDOW_S
    tick_s: float = DEFAULT_TICK_S
    burn_open: float = 1.0      # breach opens at/above this burn (both windows)
    burn_close: float = 1.0     # breach closes under this (fast window)

    @classmethod
    def from_env(cls, default_ttft_ms: float = DEFAULT_TTFT_MS
                 ) -> "SloConfig":
        """Build from ``XLLM_SLO_*`` knobs (docs/FLAGS.md). The TTFT
        threshold defaults to the routing layer's ``target_ttft_ms`` so
        the SLO the scheduler routes FOR is the SLO the engine judges
        AGAINST unless an operator splits them on purpose."""
        obj = _env_f(os.environ.get("XLLM_SLO_OBJECTIVE"),
                     DEFAULT_OBJECTIVE)
        return cls(
            objectives=[
                SloObjective("ttft", obj,
                             _env_f(os.environ.get("XLLM_SLO_TTFT_MS"),
                                    default_ttft_ms)),
                SloObjective("e2e", obj,
                             _env_f(os.environ.get("XLLM_SLO_E2E_MS"),
                                    DEFAULT_E2E_MS)),
                SloObjective("queue_wait", obj,
                             _env_f(os.environ.get(
                                 "XLLM_SLO_QUEUE_WAIT_MS"),
                                 DEFAULT_QUEUE_WAIT_MS)),
                # EPD encode latency (docs/EPD.md): judged from the
                # per-call tower durations workers ship in heartbeats
                # (xllm_service_encode_ms). No encode traffic → no
                # samples → the objective is vacuously green.
                SloObjective("encode", obj,
                             _env_f(os.environ.get(
                                 "XLLM_SLO_ENCODE_MS"),
                                 DEFAULT_ENCODE_MS)),
                SloObjective("availability",
                             _env_f(os.environ.get(
                                 "XLLM_SLO_AVAILABILITY"),
                                 DEFAULT_AVAILABILITY)),
            ],
            fast_window_s=_env_f(
                os.environ.get("XLLM_SLO_FAST_WINDOW_S"),
                DEFAULT_FAST_WINDOW_S),
            slow_window_s=_env_f(
                os.environ.get("XLLM_SLO_SLOW_WINDOW_S"),
                DEFAULT_SLOW_WINDOW_S),
            tick_s=_env_f(os.environ.get("XLLM_SLO_TICK_S"),
                          DEFAULT_TICK_S),
        )


class SloEngine:
    """Multi-window burn-rate evaluation over cumulative-count snapshots."""

    def __init__(self, config: SloConfig,
                 snapshot_fn: Callable[[], Snapshot],
                 events: Optional[EventLog] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.config = config
        self.snapshot_fn = snapshot_fn
        self.events = events
        self.clock = clock
        self._lock = make_lock("obs.slo", 78)
        # [(t_mono, snapshot)] oldest first; trimmed to one entry past
        # the slow window so every window always has a baseline.
        self._history: List[Tuple[float, Snapshot]] = []
        self._breach: Dict[str, bool] = {}
        self._breach_since: Dict[str, float] = {}
        self._last_state: Dict[str, Any] = {}
        # Baseline snapshot at construction: traffic that lands before
        # the first tick still deltas against zero, not against itself.
        self._history.append((self.clock(), snapshot_fn()))

    # ------------------------------------------------------------------
    def tick(self) -> Dict[str, Any]:
        """Take a snapshot (rate-limited to ~tick_s/2 so /admin/slo
        polls don't flood the history), re-evaluate every objective, and
        run breach open/close transitions. Returns the fresh state."""
        now = self.clock()
        snap = self.snapshot_fn()
        transitions: List[Tuple[str, str, Dict[str, Any]]] = []
        with self._lock:
            if now - self._history[-1][0] >= self.config.tick_s / 2.0:
                self._history.append((now, snap))
                # Keep exactly one snapshot older than the slow window as
                # its baseline; drop the rest.
                horizon = now - self.config.slow_window_s
                while len(self._history) >= 2 \
                        and self._history[1][0] <= horizon:
                    self._history.pop(0)
            state = self._evaluate_locked(now, snap)
            for name, obj_state in state["objectives"].items():
                fast = obj_state["windows"]["fast"]
                slow = obj_state["windows"]["slow"]
                was = self._breach.get(name, False)
                opens = (not was
                         and fast["total"] > 0
                         and fast["burn_rate"] >= self.config.burn_open
                         and slow["burn_rate"] >= self.config.burn_open)
                closes = (was
                          and fast["burn_rate"] < self.config.burn_close)
                if opens:
                    self._breach[name] = True
                    self._breach_since[name] = now
                    transitions.append(("open", name, {
                        "fast_burn": fast["burn_rate"],
                        "slow_burn": slow["burn_rate"],
                        "fast_attainment": fast["attainment"],
                        "threshold_ms": obj_state["threshold_ms"],
                        "target": obj_state["objective"]}))
                elif closes:
                    self._breach[name] = False
                    dur = now - self._breach_since.pop(name, now)
                    transitions.append(("close", name, {
                        "fast_burn": fast["burn_rate"],
                        "breach_duration_s": round(dur, 3)}))
                obj_state["breach"] = self._breach.get(name, False)
                since = self._breach_since.get(name)
                if obj_state["breach"] and since is not None:
                    obj_state["breach_age_s"] = round(now - since, 3)
            state["breached"] = sorted(
                n for n, b in self._breach.items() if b)
            self._last_state = state
        if self.events is not None:
            # Literal emit sites: the event-catalog xlint rule verifies
            # every emitted type against the closed taxonomy statically.
            for kind, name, attrs in transitions:
                if kind == "open":
                    self.events.emit("slo_breach_open", objective=name,
                                     **attrs)
                else:
                    self.events.emit("slo_breach_close", objective=name,
                                     **attrs)
        return state

    def _evaluate_locked(self, now: float, snap: Snapshot
                         ) -> Dict[str, Any]:
        windows = (("fast", self.config.fast_window_s),
                   ("slow", self.config.slow_window_s))
        objectives: Dict[str, Any] = {}
        for obj in self.config.objectives:
            cur_good, cur_total = snap.get(obj.name, (0.0, 0.0))
            win_state: Dict[str, Any] = {}
            for wname, wsecs in windows:
                base = self._baseline_locked(now - wsecs)
                b_good, b_total = base.get(obj.name, (0.0, 0.0))
                total = max(cur_total - b_total, 0.0)
                good = min(max(cur_good - b_good, 0.0), total)
                if total > 0:
                    attainment = good / total
                else:
                    attainment = 1.0        # no traffic burns no budget
                budget = max(1.0 - obj.objective, 1e-9)
                burn = (1.0 - attainment) / budget
                win_state[wname] = {
                    "window_s": wsecs,
                    "total": round(total, 3),
                    "attainment": round(attainment, 6),
                    "burn_rate": round(burn, 4),
                }
            objectives[obj.name] = {
                "objective": obj.objective,
                "threshold_ms": obj.threshold_ms,
                "total_seen": round(cur_total, 3),
                "attainment_total": round(
                    cur_good / cur_total, 6) if cur_total > 0 else 1.0,
                "windows": win_state,
            }
        return {"objectives": objectives,
                "fast_window_s": self.config.fast_window_s,
                "slow_window_s": self.config.slow_window_s,
                "tick_s": self.config.tick_s,
                "burn_open": self.config.burn_open,
                "burn_close": self.config.burn_close}

    def _baseline_locked(self, t: float) -> Snapshot:
        """Last snapshot at/before ``t`` (the window baseline); the
        oldest snapshot when history doesn't reach back that far —
        short uptimes evaluate over what exists, not over nothing."""
        base = self._history[0][1]
        for ts, snap in self._history:
            if ts <= t:
                base = snap
            else:
                break
        return base

    # ------------------------------------------------------------------
    def state(self) -> Dict[str, Any]:
        """Last evaluated state (tick() to refresh)."""
        with self._lock:
            if not self._last_state:
                # Never ticked: evaluate in place without mutating.
                now = self.clock()
                state = self._evaluate_locked(now, self._history[-1][1])
                for name, obj_state in state["objectives"].items():
                    obj_state["breach"] = self._breach.get(name, False)
                state["breached"] = sorted(
                    n for n, b in self._breach.items() if b)
                return state
            return dict(self._last_state)

    def export(self, registry) -> None:
        """Scrape-time mirror into ``xllm_slo_*`` gauges."""
        state = self.state()
        g_att = registry.gauge(
            "xllm_slo_attainment",
            "fast-window good-request fraction per SLO objective",
            labelnames=("objective",))
        g_burn = registry.gauge(
            "xllm_slo_burn_rate",
            "error-budget burn rate per objective and window "
            "(1.0 = burning exactly at the objective's rate)",
            labelnames=("objective", "window"))
        g_breach = registry.gauge(
            "xllm_slo_breach",
            "1 while the objective's multi-window breach is open",
            labelnames=("objective",))
        for name, obj_state in state.get("objectives", {}).items():
            g_att.set(obj_state["windows"]["fast"]["attainment"],
                      objective=name)
            for wname, w in obj_state["windows"].items():
                g_burn.set(w["burn_rate"], objective=name, window=wname)
            g_breach.set(1 if obj_state.get("breach") else 0,
                         objective=name)


# ---------------------------------------------------------------------------
# Anomaly detection (the watchdog's judgment)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class InstanceSignal:
    """One instance's health signals for a single watchdog tick, gathered
    by the service plane from state it already tracks."""

    name: str
    heartbeat_age_s: float
    heartbeat_deadline_s: float
    step_ms_p99: Optional[float] = None     # recent, worker-shipped
    kv_usage: float = 0.0                   # [0, 1]
    # Heartbeat-carried LoadMetrics.engine_alive: 0 once the worker's
    # engine fault breaker let its loop die (docs/ROBUSTNESS.md).
    engine_alive: int = 1


ANOMALY_TYPES = ("heartbeat_gap", "step_ms_regression", "kv_saturation",
                 "engine_dead")


class AnomalyDetector:
    """Per-instance anomaly state machine over watchdog signals."""

    def __init__(self, events: Optional[EventLog] = None,
                 step_factor: Optional[float] = None,
                 kv_sat: Optional[float] = None,
                 ewma_alpha: float = 0.3,
                 min_baseline_samples: int = 3) -> None:
        self.events = events
        # p99 regression threshold: current > factor × rolling baseline.
        self.step_factor = step_factor if step_factor is not None else \
            _env_f(os.environ.get("XLLM_WATCHDOG_STEP_FACTOR"), 3.0)
        self.kv_sat = kv_sat if kv_sat is not None else \
            _env_f(os.environ.get("XLLM_WATCHDOG_KV_SAT"), 0.95)
        self.ewma_alpha = ewma_alpha
        self.min_baseline_samples = min_baseline_samples
        self._lock = make_lock("obs.watchdog", 79)
        # (type, instance) → {"since": t_wall, "value": ..., ...}
        self._active: Dict[Tuple[str, str], Dict[str, Any]] = {}
        # instance → (ewma_p99, n_samples)
        self._baseline: Dict[str, Tuple[float, int]] = {}

    def observe(self, signals: List[InstanceSignal]) -> None:
        transitions: List[Tuple[str, str, str, Dict[str, Any]]] = []
        with self._lock:
            seen = set()
            for sig in signals:
                seen.add(sig.name)
                self._judge_locked(sig, transitions)
            # Instances gone from the cluster close their anomalies and
            # drop their baselines (a future same-name instance is a new
            # instance, not a recovered one).
            for (atype, name) in [k for k in self._active
                                  if k[1] not in seen]:
                self._close_locked(atype, name,
                                   {"reason": "instance_removed"},
                                   transitions)
            for name in [n for n in self._baseline if n not in seen]:
                del self._baseline[name]
        if self.events is not None:
            # Literal emit sites (event-catalog xlint rule).
            for kind, atype, name, attrs in transitions:
                if kind == "open":
                    self.events.emit("anomaly_open", anomaly=atype,
                                     instance=name, **attrs)
                else:
                    self.events.emit("anomaly_close", anomaly=atype,
                                     instance=name, **attrs)

    def _judge_locked(self, sig: InstanceSignal, transitions) -> None:
        # Heartbeat gap vs. deadline.
        self._set_locked(
            "heartbeat_gap", sig.name,
            open_=sig.heartbeat_age_s > sig.heartbeat_deadline_s,
            attrs={"age_s": round(sig.heartbeat_age_s, 3),
                   "deadline_s": sig.heartbeat_deadline_s},
            transitions=transitions)
        # KV-pool saturation.
        self._set_locked(
            "kv_saturation", sig.name,
            open_=sig.kv_usage >= self.kv_sat,
            attrs={"kv_usage": round(sig.kv_usage, 4),
                   "threshold": self.kv_sat},
            transitions=transitions)
        # Dead engine loop (the fault breaker opened): the worker still
        # heartbeats — store keepalive continues — but serves nothing;
        # without this signal the gap between thread_crashed and lease
        # expiry is invisible to the service plane.
        self._set_locked(
            "engine_dead", sig.name,
            open_=sig.engine_alive == 0,
            attrs={"engine_alive": sig.engine_alive},
            transitions=transitions)
        # Step-time p99 regression vs. the rolling baseline. The
        # baseline only learns from non-anomalous samples — folding the
        # regression in would normalize it away.
        p99 = sig.step_ms_p99
        if p99 is None or p99 <= 0 or not math.isfinite(p99):
            return
        base, n = self._baseline.get(sig.name, (0.0, 0))
        warmed = n >= self.min_baseline_samples
        regressed = warmed and p99 > self.step_factor * base
        self._set_locked(
            "step_ms_regression", sig.name, open_=regressed,
            attrs={"step_ms_p99": round(p99, 3),
                   "baseline_ms": round(base, 3),
                   "factor": self.step_factor},
            transitions=transitions)
        if not regressed:
            new = p99 if n == 0 else \
                (1 - self.ewma_alpha) * base + self.ewma_alpha * p99
            self._baseline[sig.name] = (new, n + 1)

    def _set_locked(self, atype: str, name: str, open_: bool,
                    attrs: Dict[str, Any], transitions) -> None:
        key = (atype, name)
        if open_ and key not in self._active:
            self._active[key] = {"since": time.time(), **attrs}
            transitions.append(("open", atype, name, attrs))
        elif not open_ and key in self._active:
            self._close_locked(atype, name, attrs, transitions)
        elif open_:
            self._active[key].update(attrs)     # refresh live values

    def _close_locked(self, atype: str, name: str,
                      attrs: Dict[str, Any], transitions) -> None:
        rec = self._active.pop((atype, name), None)
        dur = time.time() - rec["since"] if rec else 0.0
        transitions.append(("close", atype, name,
                            dict(attrs, duration_s=round(dur, 3))))

    # ------------------------------------------------------------------
    def active(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"type": atype, "instance": name, **dict(rec)}
                    for (atype, name), rec in sorted(self._active.items())]

    def export(self, registry) -> None:
        """Scrape-time rebuild of ``xllm_anomaly_active{type,instance}``
        (cleared each scrape so closed anomalies drop out)."""
        g = registry.gauge(
            "xllm_anomaly_active",
            "1 per open watchdog anomaly", labelnames=("type", "instance"))
        g.clear()
        for rec in self.active():
            g.set(1, type=rec["type"], instance=rec["instance"])
