"""Structured cluster event log: what happened to the cluster, when.

The judgment layer's memory. Metrics answer "how much"; spans answer
"where did THIS request's time go"; this log answers "what did the
CLUSTER do in the 30 seconds before that request failed" — instance
lifecycle, role flips, master elections, redispatches, SLO breaches and
watchdog anomalies, in one bounded, ordered, queryable ring.

Design rules:

- CLOSED taxonomy. ``EVENT_TYPES`` below is the complete catalogue;
  ``emit()`` rejects anything else at runtime, and the ``event-catalog``
  xlint rule rejects it statically at every ``*.emit("<type>", ...)``
  call site (tools/xlint/rules.py). An event type nobody declared is an
  event type no dashboard, alert, or post-mortem tool knows to look for.
- Bounded and always on: a ring of ``capacity`` events (size it with
  ``XLLM_EVENT_RING`` at the call site that builds the log); the oldest
  events drop, with a drop counter so truncation is visible.
- Dependency-free and thread-safe; rank ``obs.events`` in the
  utils/locks.py table — ``emit`` never calls out, so it is safe under
  every serving-path lock (instance books, scheduler registry).

Queried at ``GET /admin/events?since=<seq>`` on the service plane and
snapshotted whole into ``GET /admin/debug_bundle``; per-type totals are
mirrored into the registry as ``xllm_events_total{type}`` at scrape
time (the scrape-time-mirror pattern, obs/metrics.py docstring).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional

from xllm_service_tpu.obs import profiler
from xllm_service_tpu.utils.locks import make_lock


def _deep_copy(v: Any) -> Any:
    """Deep-enough copy (dict/list/tuple of JSON-ish values) for the
    read side — same rationale as spans._deep_copy."""
    if isinstance(v, dict):
        return {k: _deep_copy(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_deep_copy(x) for x in v]
    return v


# The complete event taxonomy (docs/OBSERVABILITY.md documents each).
# Adding a type means adding it HERE (the event-catalog xlint rule pins
# every emit site to this tuple) and documenting it.
EVENT_TYPES = (
    "instance_join",        # worker key seen in the store (pending)
    "instance_confirm",     # registration complete: instance routable
    "instance_remove",      # lease expiry / store DELETE cleanup
    "role_flip",            # dynamic PD role change
    "master_elected",       # this replica won/took over the election
    "master_lease_lost",    # this replica's lease expired under it
    "redispatch",           # request re-routed after a worker refusal
    "slo_breach_open",      # an SLO objective's burn rate crossed open
    "slo_breach_close",     # ... and recovered
    "anomaly_open",         # watchdog opened a per-instance anomaly
    "anomaly_close",        # ... and it cleared
    "request_recovered",    # mid-stream failover resumed a request
    "recovery_failed",      # ... or exhausted its retry budget
    "failpoint_tripped",    # an armed fault-injection site fired
    "cache_digest_mismatch",  # worker's block hashing diverges from the
                              # service's — its prefix digests are
                              # quarantined (docs/KV_CACHE.md)
    "thread_crashed",       # an uncaught exception escaped a supervised
                            # thread root (utils/threads.py spawn);
                            # attrs say whether it restarted
    "store_outage_open",    # the coordination-store guard declared the
                            # store down (service/store_guard.py);
                            # degraded-mode serving begins
    "store_outage_close",   # the store healed; leases/registrations
                            # re-establish and the books resync
    "master_demoted",       # this replica stopped being master: a
                            # higher-epoch master exists (fenced
                            # split-brain) or re-election was lost
    "encode_fallback",      # a routed encode stage was not served by
                            # its chosen instance — rerouted to a
                            # survivor or degraded to local encode
                            # (attrs: reason, from, to)
    "engine_fault",         # a worker contained a device-plane step
                            # fault and blamed this request — one poison
                            # strike (attrs: service_request_id,
                            # instance, verdict, strikes)
    "request_quarantined",  # the poison ledger hit XLLM_POISON_STRIKES:
                            # request failed to the client, its prompt
                            # digest quarantined for XLLM_POISON_TTL_S
                            # (attrs: service_request_id, digest,
                            # strikes, ttl_s)
)

DEFAULT_CAPACITY = 1024


class EventLog:
    """Bounded, ordered, thread-safe structured event ring."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.capacity = max(1, int(capacity))
        self._lock = make_lock("obs.events", 80)
        self._ring: "collections.deque[Dict[str, Any]]" = \
            collections.deque(maxlen=self.capacity)
        self._seq = 0
        self._counts: Dict[str, int] = {t: 0 for t in EVENT_TYPES}
        self._dropped = 0

    def emit(self, type: str, **attrs: Any) -> int:
        """Append one event; returns its sequence number. ``type`` MUST
        be in ``EVENT_TYPES`` (closed taxonomy — see module doc)."""
        if type not in EVENT_TYPES:
            raise ValueError(
                f"event type {type!r} is not in the obs/events.py "
                f"catalog {EVENT_TYPES}")
        with profiler.section("event.emit"):
            with self._lock:
                self._seq += 1
                if len(self._ring) == self.capacity:
                    self._dropped += 1
                self._ring.append({"seq": self._seq, "type": type,
                                   "t_wall": time.time(),
                                   "attrs": attrs})
                self._counts[type] += 1
                return self._seq

    # -- querying -------------------------------------------------------
    def since(self, seq: int = 0,
              limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Events with sequence number > ``seq``, oldest first — the
        OLDEST ``limit`` matches, so a poller resuming from the last
        seq it saw walks the whole ring page by page (newest-first
        truncation would permanently skip stored events the cursor can
        never reach). A reader that fell behind the RING sees a gap in
        seq numbers — that IS the signal that events were dropped, not
        silently papered over."""
        with self._lock:
            # Deep-enough copies: emit() keeps the caller's attrs dict
            # by reference, and attr VALUES can be dicts/lists a caller
            # still mutates — shallow dict(e["attrs"]) leaves those
            # shared with the live ring mid-render.
            out = [dict(e, attrs=_deep_copy(e["attrs"]))
                   for e in self._ring if e["seq"] > seq]
        if limit is not None and len(out) > limit:
            out = out[:limit]
        return out

    def counts(self) -> Dict[str, int]:
        """Per-type emitted totals since boot (NOT ring occupancy) —
        the ``xllm_events_total{type}`` scrape-time mirror source."""
        with self._lock:
            return dict(self._counts)

    @property
    def latest_seq(self) -> int:
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        """Events evicted from the ring (visible truncation)."""
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
