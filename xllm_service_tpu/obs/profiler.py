"""Service-plane self-profiling: the master watching itself.

Every scale item left in ROADMAP.md lands on a single Python master
whose own cost had never been measured — the relay parses every SSE
frame, ``schedule()`` runs a prefix-walk plus multi-policy audit per
request, and spans/events/metrics all take locks on the hot path. This
module is the always-on accounting layer that makes that cost a metric
instead of a guess:

- **Hot-path sections**: a CLOSED catalog (``SECTIONS``) of named timed
  regions recorded via ``with profiler.section("schedule"):`` into
  per-thread books (no shared lock on the record path) and mirrored at
  scrape time into ``xllm_service_hotpath_ms{section}`` histograms plus
  ``xllm_service_hotpath_ops_total{section}`` counters. The catalog is
  machine-checked: xlint rule ``hotpath-section-catalog`` pins every
  ``section("<name>")`` literal in the tree to this tuple, exactly like
  the event-type and failpoint catalogs.
- **Lock contention**: ``utils/locks.py`` samples 1-in-N acquisitions
  (``XLLM_LOCK_PROFILE_SAMPLE``) into its own book; ``flush_metrics``
  mirrors it here as ``xllm_lock_wait_ms{lock,rank}`` /
  ``xllm_lock_contended_total{lock}`` (locks.py never imports obs).
- **Per-thread-root CPU**: supervised threads register their native tid
  under their root name (utils/threads.py calls
  ``register_thread_root``); scrape-time reads of
  ``/proc/self/task/<tid>/stat`` utime+stime become
  ``xllm_thread_cpu_seconds_total{root}``. ``time.thread_time_ns`` only
  measures the *calling* thread, so /proc is the only way to account
  someone else's CPU.
- **Self-gauges**: RSS, process CPU% (delta between scrapes), live
  thread count, and GC pauses via ``gc.callbacks`` →
  ``xllm_gc_pause_ms`` + ``xllm_gc_collections_total{generation}``.
- **Stack sampler**: ``sample_stacks(seconds)`` drives
  ``sys._current_frames`` at a fixed rate and returns collapsed-stack /
  top-function tables — served by ``GET /admin/profile?seconds=N`` and
  embedded in ``/admin/debug_bundle``.

``XLLM_HOTPATH_PROFILE`` (default ON, read at import per the hot-path
flag discipline) gates the section timers; everything else is
scrape-time-only cost. With the flag off, ``section()`` returns one
shared no-op context manager — the disabled path is a dict lookup and
an attribute load, nothing else.

State is process-global on purpose: one serving process hosts one
plane, and the co-located test harness tolerates shared books because
every series is labelled. Books only grow (a dead thread's totals are
retained), keeping the mirrored counters monotonic.
"""

from __future__ import annotations

import gc
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from xllm_service_tpu.utils import locks as _locks

# ---------------------------------------------------------------------------
# The closed section catalog. xlint rule `hotpath-section-catalog` pins
# every profiler.section("<name>") literal in the tree to this tuple —
# add the name HERE first, with a comment saying what the section spans.
# ---------------------------------------------------------------------------
SECTIONS: Tuple[str, ...] = (
    "schedule",       # Scheduler.schedule(): policy walk + audit + plan
    "relay.frame",    # per-SSE-frame ledger work in _recoverable_relay
    "span.write",     # SpanStore.record(): one stage write
    "event.emit",     # EventLog.emit(): one cluster event
    "store.call",     # one coordination-store RPC from the master loop
    "sse.assemble",   # building one outbound SSE frame from a delta
    "tokenize",       # chat-template apply + tokenizer encode
)

_SECTION_SET = frozenset(SECTIONS)

# Section bucket edges (ms): hot-path units of work are typically
# 10 µs – 10 ms on the master; the default latency buckets would fold
# everything into their first bucket.
HOTPATH_BUCKETS_MS: Tuple[float, ...] = (
    0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0,
    10.0, 20.0, 50.0, 100.0, 500.0, 2000.0)

GC_PAUSE_BUCKETS_MS: Tuple[float, ...] = (
    0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0,
    200.0, 500.0, 1000.0)


def _enabled_from_env() -> bool:
    return os.environ.get("XLLM_HOTPATH_PROFILE", "1").strip() not in (
        "0", "false", "no")


ENABLED = _enabled_from_env()


def _events_enabled_from_env() -> bool:
    # The timed-event tail (per-section slices for the merged timeline,
    # obs/timeline.py) rides the step-trace flag: XLLM_STEPTRACE=0
    # turns the per-exit wall-clock read + tail append off together
    # with the worker's step recorder. Read once at import, like every
    # hot-path flag.
    return ENABLED and os.environ.get(
        "XLLM_STEPTRACE", "1").strip() not in ("0", "false", "no")


EVENTS_ENABLED = _events_enabled_from_env()

# Per-thread bounded tail of timed section events (newest EVENT_TAIL
# per thread) — the raw material for the timeline's hotpath tracks.
EVENT_TAIL = 256

try:
    _CLK_TCK = float(os.sysconf("SC_CLK_TCK"))
except (AttributeError, ValueError, OSError):
    _CLK_TCK = 100.0

try:
    _PAGE_SIZE = float(os.sysconf("SC_PAGE_SIZE"))
except (AttributeError, ValueError, OSError):
    _PAGE_SIZE = 4096.0


# ---------------------------------------------------------------------------
# Section books: one dict per thread, registered once in a global list.
# The record path touches only thread-local state — no shared lock.
# ---------------------------------------------------------------------------

class _Sect:
    __slots__ = ("counts", "sum_ms", "ops")

    def __init__(self) -> None:
        self.counts = [0] * len(HOTPATH_BUCKETS_MS)
        self.sum_ms = 0.0
        self.ops = 0


_tls = threading.local()
_all_books: List[Dict[str, _Sect]] = []
# Raw threading.Lock: guards the book list only, never calls out, and
# stays invisible to the rank checker (the profiler sits under locks.py
# in the import graph).
_books_lock = threading.Lock()


def _thread_book() -> Dict[str, _Sect]:
    book = getattr(_tls, "book", None)
    if book is None:
        book = _tls.book = {}
        with _books_lock:
            _all_books.append(book)
    return book


# (thread name, bounded deque of (section, t_wall_end, dur_ms)) — one
# tail per thread, registered like the books. Appends are thread-local;
# readers copy under _books_lock.
_all_event_tails: List[Tuple[str, Any]] = []


def _thread_events():
    tail = getattr(_tls, "events", None)
    if tail is None:
        import collections
        tail = _tls.events = collections.deque(maxlen=EVENT_TAIL)
        with _books_lock:
            _all_event_tails.append(
                (threading.current_thread().name, tail))
    return tail


class _NullSection:
    __slots__ = ()

    def __enter__(self) -> "_NullSection":
        return self

    def __exit__(self, *exc: object) -> bool:
        return False


_NULL = _NullSection()


class _Timer:
    __slots__ = ("name", "t0")

    def __init__(self, name: str) -> None:
        self.name = name
        self.t0 = 0.0

    def __enter__(self) -> "_Timer":
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: object) -> bool:
        dt_ms = (time.perf_counter() - self.t0) * 1000.0
        book = _thread_book()
        s = book.get(self.name)
        if s is None:
            s = book[self.name] = _Sect()
        for i, edge in enumerate(HOTPATH_BUCKETS_MS):
            if dt_ms <= edge:
                s.counts[i] += 1
                break
        s.sum_ms += dt_ms
        s.ops += 1
        if EVENTS_ENABLED:
            _thread_events().append((self.name, time.time(), dt_ms))
        return False


def section(name: str):
    """Context manager timing one hot-path section. ``name`` MUST be a
    member of the closed ``SECTIONS`` catalog (enforced here at runtime
    and by xlint rule ``hotpath-section-catalog`` statically)."""
    if name not in _SECTION_SET:
        raise ValueError(
            f"unknown hot-path section {name!r} — add it to "
            f"profiler.SECTIONS first (closed catalog)")
    if not ENABLED:
        return _NULL
    return _Timer(name)


def section_snapshot() -> Dict[str, Dict[str, Any]]:
    """Merged per-section totals across every thread book:
    ``{name: {ops, sum_ms, counts}}`` (counts align with
    HOTPATH_BUCKETS_MS; overflow samples count in ops only)."""
    with _books_lock:
        books = list(_all_books)
    merged: Dict[str, Dict[str, Any]] = {}
    for book in books:
        for name, s in list(book.items()):
            m = merged.get(name)
            if m is None:
                m = merged[name] = {
                    "ops": 0, "sum_ms": 0.0,
                    "counts": [0] * len(HOTPATH_BUCKETS_MS)}
            m["ops"] += s.ops
            m["sum_ms"] += s.sum_ms
            for i, c in enumerate(s.counts):
                m["counts"][i] += c
    return merged


def reset_sections() -> None:
    """Test helper: forget every thread book (process-global state)."""
    with _books_lock:
        _all_books.clear()
        _all_event_tails.clear()
    _tls.book = None
    _tls.events = None


def recent_events(window_s: float = 0.0,
                  limit: int = 2048) -> List[Dict[str, Any]]:
    """Merged copy of every thread's timed-event tail, oldest-first:
    ``[{name, t_wall, dur_ms, thread}]`` — the timeline's hotpath
    tracks. ``window_s`` clips to the newest event minus the window."""
    with _books_lock:
        tails = [(tname, list(tail))
                 for tname, tail in _all_event_tails]
    out: List[Dict[str, Any]] = []
    for tname, tail in tails:
        for name, t_wall, dur_ms in tail:
            out.append({"name": name, "t_wall": t_wall,
                        "dur_ms": dur_ms, "thread": tname})
    out.sort(key=lambda e: (e["t_wall"], e["thread"], e["name"]))
    if window_s > 0 and out:
        horizon = out[-1]["t_wall"] - window_s
        out = [e for e in out if e["t_wall"] >= horizon]
    return out[-limit:]


# ---------------------------------------------------------------------------
# Per-thread-root CPU accounting (/proc/self/task/<tid>/stat)
# ---------------------------------------------------------------------------

_roots_lock = threading.Lock()
_root_tids: Dict[str, set] = {}       # root -> live native tids
_tid_cpu_last: Dict[int, float] = {}  # tid -> last observed cpu seconds
_root_retired: Dict[str, float] = {}  # cpu seconds of exited threads


def register_thread_root(root: str) -> None:
    """Called from the supervised-thread wrapper (utils/threads.py) at
    thread start: binds this thread's native tid to its root name so
    scrape-time /proc reads can attribute CPU per root."""
    try:
        tid = threading.get_native_id()
    except Exception:  # noqa: BLE001 — attribution is best-effort: on a
        return         # platform with no native tids the root simply
                       # reports no CPU series, never fails to start
    with _roots_lock:
        _root_tids.setdefault(root, set()).add(tid)


def _read_tid_cpu_s(tid: int) -> Optional[float]:
    try:
        with open(f"/proc/self/task/{tid}/stat", "rb") as f:
            data = f.read()
    except OSError:
        return None
    # comm may contain spaces/parens — fields resume after the LAST ')'.
    rest = data.rsplit(b")", 1)[-1].split()
    try:
        return (int(rest[11]) + int(rest[12])) / _CLK_TCK
    except (IndexError, ValueError):
        return None


def thread_cpu_snapshot() -> Dict[str, float]:
    """Cumulative CPU seconds per supervised root (live threads read
    from /proc; exited threads keep their last-known contribution, so
    the series stays monotonic)."""
    with _roots_lock:
        out: Dict[str, float] = {}
        for root, tids in _root_tids.items():
            live = 0.0
            for tid in list(tids):
                cur = _read_tid_cpu_s(tid)
                if cur is None:
                    # Thread exited: retire its last-known total.
                    _root_retired[root] = (
                        _root_retired.get(root, 0.0)
                        + _tid_cpu_last.pop(tid, 0.0))
                    tids.discard(tid)
                    continue
                _tid_cpu_last[tid] = cur
                live += cur
            out[root] = _root_retired.get(root, 0.0) + live
        for root, retired in _root_retired.items():
            out.setdefault(root, retired)
    return out


# ---------------------------------------------------------------------------
# Process self stats (/proc/self) + GC pause hook
# ---------------------------------------------------------------------------

def _proc_self_cpu_s() -> Optional[float]:
    try:
        with open("/proc/self/stat", "rb") as f:
            data = f.read()
    except OSError:
        return None
    rest = data.rsplit(b")", 1)[-1].split()
    try:
        return (int(rest[11]) + int(rest[12])) / _CLK_TCK
    except (IndexError, ValueError):
        return None


def process_rss_bytes() -> Optional[float]:
    try:
        with open("/proc/self/statm", "rb") as f:
            fields = f.read().split()
        return float(int(fields[1])) * _PAGE_SIZE
    except (OSError, IndexError, ValueError):
        return None


_cpu_lock = threading.Lock()
_cpu_last: Optional[Tuple[float, float]] = None  # (wall_s, cpu_s)


def process_cpu_percent() -> Optional[float]:
    """CPU% of this process over the window since the previous call
    (scrape-to-scrape delta). None on the first call or off-Linux."""
    global _cpu_last
    cpu = _proc_self_cpu_s()
    if cpu is None:
        return None
    now = time.monotonic()
    with _cpu_lock:
        last = _cpu_last
        _cpu_last = (now, cpu)
    if last is None or now <= last[0]:
        return None
    return 100.0 * (cpu - last[1]) / (now - last[0])


_gc_lock = threading.Lock()
_gc_t0: Optional[float] = None
_gc_pause_counts = [0] * len(GC_PAUSE_BUCKETS_MS)
_gc_pause_sum_ms = 0.0
_gc_pause_total = 0
_gc_collections: Dict[int, int] = {}
_gc_hook_installed = False


def _gc_callback(phase: str, info: Dict[str, Any]) -> None:
    # CPython GC is stop-the-world and non-reentrant, so one module
    # slot for the start time is enough.
    global _gc_t0, _gc_pause_sum_ms, _gc_pause_total
    if phase == "start":
        _gc_t0 = time.perf_counter()
        return
    if phase != "stop" or _gc_t0 is None:
        return
    dt_ms = (time.perf_counter() - _gc_t0) * 1000.0
    _gc_t0 = None
    gen = int(info.get("generation", -1))
    with _gc_lock:
        for i, edge in enumerate(GC_PAUSE_BUCKETS_MS):
            if dt_ms <= edge:
                _gc_pause_counts[i] += 1
                break
        _gc_pause_sum_ms += dt_ms
        _gc_pause_total += 1
        _gc_collections[gen] = _gc_collections.get(gen, 0) + 1


def install_gc_hook() -> None:
    global _gc_hook_installed
    with _gc_lock:
        if _gc_hook_installed:
            return
        _gc_hook_installed = True
    gc.callbacks.append(_gc_callback)


def gc_snapshot() -> Dict[str, Any]:
    with _gc_lock:
        return {
            "pause_counts": list(_gc_pause_counts),
            "pause_sum_ms": _gc_pause_sum_ms,
            "pause_total": _gc_pause_total,
            "collections": dict(_gc_collections),
        }


if ENABLED:
    install_gc_hook()


# ---------------------------------------------------------------------------
# Scrape-time flush: mirror every book into a Registry
# ---------------------------------------------------------------------------

def flush_metrics(registry) -> None:
    """Refresh the profiler's families in ``registry`` from the live
    books — the scrape-time-mirror pattern (``Counter.set_total`` /
    ``Histogram.set_counts``), called from each plane's /metrics
    handler. The registry never caches stale copies of state the books
    own."""
    hot_h = registry.histogram(
        "xllm_service_hotpath_ms",
        "per-section hot-path time (profiler catalog)",
        labelnames=("section",), buckets=HOTPATH_BUCKETS_MS)
    hot_c = registry.counter(
        "xllm_service_hotpath_ops_total",
        "per-section hot-path operations", labelnames=("section",))
    for name, m in section_snapshot().items():
        hot_h.set_counts(m["counts"], m["sum_ms"], total=m["ops"],
                         section=name)
        hot_c.set_total(m["ops"], section=name)

    contention = _locks.contention_snapshot()
    if contention:
        wait_h = registry.histogram(
            "xllm_lock_wait_ms",
            "sampled lock acquisition wait time "
            "(XLLM_LOCK_PROFILE_SAMPLE)",
            labelnames=("lock", "rank"),
            buckets=_locks.LOCK_WAIT_BUCKETS_MS)
        cont_c = registry.counter(
            "xllm_lock_contended_total",
            "sampled acquisitions that had to block",
            labelnames=("lock",))
        samp_c = registry.counter(
            "xllm_lock_sampled_total",
            "acquisitions sampled by the contention profiler",
            labelnames=("lock",))
        for name, b in contention.items():
            wait_h.set_counts(b["wait_counts"], b["wait_sum_ms"],
                              total=b["sampled"], lock=name,
                              rank=b["rank"])
            cont_c.set_total(b["contended"], lock=name)
            samp_c.set_total(b["sampled"], lock=name)

    cpu_c = registry.counter(
        "xllm_thread_cpu_seconds_total",
        "cumulative CPU seconds per supervised thread root",
        labelnames=("root",))
    for root, secs in thread_cpu_snapshot().items():
        cpu_c.set_total(secs, root=root)

    rss = process_rss_bytes()
    if rss is not None:
        registry.gauge("xllm_process_rss_bytes",
                       "resident set size").set(rss)
    pct = process_cpu_percent()
    if pct is not None:
        registry.gauge(
            "xllm_process_cpu_percent",
            "process CPU percent over the previous scrape window"
        ).set(pct)
    registry.gauge("xllm_process_threads",
                   "live thread count").set(threading.active_count())

    g = gc_snapshot()
    registry.histogram(
        "xllm_gc_pause_ms", "GC stop-the-world pause time",
        buckets=GC_PAUSE_BUCKETS_MS).set_counts(
            g["pause_counts"], g["pause_sum_ms"],
            total=g["pause_total"])
    gc_c = registry.counter("xllm_gc_collections_total",
                            "GC runs per generation",
                            labelnames=("generation",))
    for gen, n in g["collections"].items():
        gc_c.set_total(n, generation=gen)


# ---------------------------------------------------------------------------
# Snapshot (for /admin/profile and the debug bundle) + stack sampler
# ---------------------------------------------------------------------------

def _quantiles_from_counts(counts: List[int], total: int,
                           edges: Tuple[float, ...],
                           qs: Tuple[float, ...] = (0.5, 0.99)
                           ) -> Dict[str, Optional[float]]:
    from xllm_service_tpu.obs.expfmt import quantile_from_buckets
    if total <= 0:
        return {f"p{int(q * 100)}": None for q in qs}
    bs: List[Tuple[float, float]] = []
    cum = 0
    for edge, c in zip(edges, counts):
        cum += c
        bs.append((edge, float(cum)))
    bs.append((float("inf"), float(total)))
    return {f"p{int(q * 100)}": quantile_from_buckets(bs, q)
            for q in qs}


def snapshot() -> Dict[str, Any]:
    """The live section/contention/self tables as one JSON-ready dict —
    what /admin/profile returns alongside the sampled stacks and what
    the debug bundle embeds."""
    sections: Dict[str, Any] = {}
    for name, m in sorted(section_snapshot().items()):
        row = {"ops": m["ops"], "sum_ms": round(m["sum_ms"], 3)}
        row.update({
            k: (round(v, 4) if v is not None else None)
            for k, v in _quantiles_from_counts(
                m["counts"], m["ops"], HOTPATH_BUCKETS_MS).items()})
        sections[name] = row
    lock_rows: Dict[str, Any] = {}
    for name, b in sorted(_locks.contention_snapshot().items()):
        row = {"rank": b["rank"], "sampled": b["sampled"],
               "contended": b["contended"],
               "wait_sum_ms": round(b["wait_sum_ms"], 3)}
        row.update({
            k: (round(v, 4) if v is not None else None)
            for k, v in _quantiles_from_counts(
                b["wait_counts"], b["sampled"],
                _locks.LOCK_WAIT_BUCKETS_MS).items()})
        lock_rows[name] = row
    g = gc_snapshot()
    return {
        "enabled": ENABLED,
        "lock_profile_sample": _locks.PROFILE_SAMPLE,
        "sections": sections,
        "locks": lock_rows,
        "thread_cpu_s": {r: round(v, 3) for r, v in
                         sorted(thread_cpu_snapshot().items())},
        "self": {
            "rss_bytes": process_rss_bytes(),
            "threads": threading.active_count(),
            "gc_collections": {str(k): v for k, v in
                               sorted(g["collections"].items())},
            "gc_pause_total": g["pause_total"],
            "gc_pause_sum_ms": round(g["pause_sum_ms"], 3),
        },
    }


def sample_stacks(seconds: float = 2.0, hz: float = 50.0,
                  top: int = 30) -> Dict[str, Any]:
    """On-demand wall-clock stack sampler: polls
    ``sys._current_frames`` at ``hz`` for ``seconds``, aggregating
    collapsed stacks (root;...;leaf) and leaf functions. The sampling
    thread excludes itself. Cost is borne only while a sampling request
    is in flight — nothing runs between requests."""
    seconds = max(0.05, min(float(seconds), 60.0))
    hz = max(1.0, min(float(hz), 250.0))
    interval = 1.0 / hz
    me = threading.get_ident()
    stack_counts: Dict[str, int] = {}
    func_counts: Dict[str, int] = {}
    samples = 0
    threads_seen = 0
    deadline = time.monotonic() + seconds
    while True:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            names: List[str] = []
            f = frame
            depth = 0
            while f is not None and depth < 64:
                code = f.f_code
                names.append(
                    f"{code.co_name} "
                    f"({os.path.basename(code.co_filename)}"
                    f":{f.f_lineno})")
                f = f.f_back
                depth += 1
            if not names:
                continue
            threads_seen += 1
            collapsed = ";".join(reversed(names))
            stack_counts[collapsed] = stack_counts.get(collapsed, 0) + 1
            leaf = names[0]
            func_counts[leaf] = func_counts.get(leaf, 0) + 1
        samples += 1
        if time.monotonic() >= deadline:
            break
        time.sleep(interval)
    def _top(d: Dict[str, int], key: str) -> List[Dict[str, Any]]:
        rows = sorted(d.items(), key=lambda kv: (-kv[1], kv[0]))[:top]
        return [{key: k, "count": v,
                 "share": round(v / max(1, threads_seen), 4)}
                for k, v in rows]
    return {
        "seconds": seconds,
        "hz": hz,
        "samples": samples,
        "thread_samples": threads_seen,
        "top_functions": _top(func_counts, "function"),
        "stacks": _top(stack_counts, "stack"),
    }
