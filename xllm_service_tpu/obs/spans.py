"""Per-request span timelines: where did this request's latency go?

One span per ``service_request_id``: an ordered list of stage events,
each stamped with the recording process's monotonic clock (interval
arithmetic within a plane) and wall clock (cross-plane ordering — the
service and worker monotonic clocks share no epoch). The service plane
records received → admitted → scheduled → dispatched → first_token →
finished; the worker records its own received → scheduled →
first_token → finished under the SAME correlation id (propagated as the
``x-xllm-request-id`` header on the forwarded request) and ships
finished spans back on the heartbeat path, where the service merges
them in with ``plane="worker"``. The merged timeline is queryable at
``GET /admin/trace/<request_id>`` on the service plane.

Storage is a bounded ring: the oldest span is evicted when ``capacity``
is exceeded, so tracing is always on without growing without bound
(size the ring via ``XLLM_SPAN_RING`` at the call site that builds the
store). Thread-safe; rank ``obs.spans`` in the utils/locks.py table.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional

from xllm_service_tpu.obs import profiler
from xllm_service_tpu.utils.locks import make_lock


def _deep_copy(v: Any) -> Any:
    """Deep-enough copy for span/event payloads (dict/list/tuple of
    JSON-ish values). The read side copies; writers stay cheap. Shallow
    ``dict(...)`` is NOT enough: ``merge_remote`` nests per-plane attr
    dicts (and remote events can carry dict/list attr values) that
    would stay shared with the live span and mutate mid-render."""
    if isinstance(v, dict):
        return {k: _deep_copy(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_deep_copy(x) for x in v]
    return v


# Canonical service-plane stage order (docs/OBSERVABILITY.md); extra
# stages (e.g. "redispatch"/"redispatched") may interleave — the first
# occurrence per (stage, plane) wins (see record()).
SERVICE_STAGES = ("received", "admitted", "scheduled", "dispatched",
                  "first_token", "finished")
# "encoded" appears only on multimodal requests: the prefill worker
# records it once the EPD encode stage resolved (attrs say whether a
# remote ENCODE instance, a cache hit, or local fallback produced the
# embeddings — docs/EPD.md). "faulted" appears only on requests the
# engine-step fault boundary blamed and evicted (docs/ROBUSTNESS.md
# device-plane fault contract).
WORKER_STAGES = ("received", "encoded", "scheduled", "first_token",
                 "faulted", "finished")

DEFAULT_CAPACITY = 2048

# The correlation header the service stamps on every forwarded request;
# the worker tags its span stages with this id (defined here, not in
# http_service, so the worker doesn't import the whole service plane
# for one constant).
REQUEST_ID_HEADER = "x-xllm-request-id"


class SpanStore:
    """Ring buffer of span timelines keyed by correlation id."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.capacity = max(1, int(capacity))
        self._lock = make_lock("obs.spans", 94)
        # rid → {"request_id", "attrs", "events": [event...]}; insertion
        # order is eviction order.
        self._spans: "collections.OrderedDict[str, Dict[str, Any]]" = \
            collections.OrderedDict()
        # drain_finished queue. Always ⊆ the ring's keys (eviction
        # discards the mark too) so a plane that never drains — the
        # service, which drains nothing; only workers export — stays
        # bounded by ``capacity`` instead of leaking one id per request.
        self._finished: set = set()
        # Eviction visibility: a counter (exported as
        # ``xllm_span_evictions_total`` on both planes) plus a small
        # tombstone ring of evicted rids, so ``GET /admin/trace/<id>``
        # can answer "this id existed but fell off the ring" (HTTP 410)
        # instead of an indistinguishable 404.
        self._evictions = 0
        self._tombstones: "collections.deque[str]" = collections.deque(
            maxlen=max(64, self.capacity // 8))
        self._tombstone_set: set = set()

    # -- recording ------------------------------------------------------
    def _evict_overflow_locked(self) -> None:
        while len(self._spans) > self.capacity:
            old_rid, _old = self._spans.popitem(last=False)
            self._finished.discard(old_rid)
            self._evictions += 1
            if len(self._tombstones) == self._tombstones.maxlen:
                dead = self._tombstones.popleft()
                self._tombstone_set.discard(dead)
            self._tombstones.append(old_rid)
            self._tombstone_set.add(old_rid)

    def _span_locked(self, rid: str) -> Dict[str, Any]:
        span = self._spans.get(rid)
        if span is None:
            span = {"request_id": rid, "attrs": {}, "events": []}
            self._spans[rid] = span
            # A tombstoned rid coming back to life is live again, not
            # evicted (e.g. a worker requeue landing after an eviction).
            self._revive_tombstone_locked(rid)
            self._evict_overflow_locked()
        return span

    def _revive_tombstone_locked(self, rid: str) -> None:
        """Clear a tombstone for an rid that is live again — from BOTH
        structures: a stale deque copy left behind would, on its
        eventual popleft, discard the set entry backing a NEWER
        tombstone of the same rid."""
        if rid in self._tombstone_set:
            self._tombstone_set.discard(rid)
            try:
                self._tombstones.remove(rid)
            except ValueError:
                pass

    def annotate(self, rid: str, **attrs: Any) -> None:
        with self._lock:
            self._span_locked(rid)["attrs"].update(attrs)

    def record(self, rid: str, stage: str, plane: str = "service",
               t_mono: Optional[float] = None,
               t_wall: Optional[float] = None, **attrs: Any) -> None:
        """Record one stage event. Idempotent per (stage, plane): retry
        paths (redispatch, on_close backstops) may reach the same stage
        twice, and the FIRST occurrence is the truthful timestamp."""
        event = {"stage": stage, "plane": plane,
                 "t_mono": time.monotonic() if t_mono is None else t_mono,
                 "t_wall": time.time() if t_wall is None else t_wall}
        event.update(attrs)
        with profiler.section("span.write"):
            with self._lock:
                span = self._span_locked(rid)
                if any(e["stage"] == stage and e["plane"] == plane
                       for e in span["events"]):
                    return
                span["events"].append(event)
                if stage == "finished":
                    self._finished.add(rid)

    def merge_remote(self, rid: str, plane: str,
                     events: List[Dict[str, Any]],
                     source: str = "",
                     attrs: Optional[Dict[str, Any]] = None) -> None:
        """Fold another plane's exported events into this store (the
        heartbeat merge path). Remote monotonic stamps are meaningful
        only relative to each other; the wall stamps order them against
        local stages. Remote attrs land under ``attrs[<plane>]`` so the
        worker's view (e.g. the correlation header it actually read)
        never clobbers local keys."""
        with self._lock:
            span = self._span_locked(rid)
            if attrs:
                span["attrs"].setdefault(plane, {}).update(attrs)
            for e in events:
                ev = dict(e)
                ev["plane"] = plane
                if source:
                    ev.setdefault("source", source)
                if any(x["stage"] == ev.get("stage")
                       and x["plane"] == plane
                       and x.get("source") == ev.get("source")
                       for x in span["events"]):
                    continue
                span["events"].append(ev)

    # -- querying -------------------------------------------------------
    def get(self, rid: str) -> Optional[Dict[str, Any]]:
        """A deep-enough copy of one span, events sorted by wall clock
        (cross-plane safe; stable for same-stamp events)."""
        with self._lock:
            span = self._spans.get(rid)
            if span is None:
                return None
            events = [_deep_copy(e) for e in span["events"]]
            attrs = _deep_copy(span["attrs"])
        events.sort(key=lambda e: e.get("t_wall", 0.0))
        return {"request_id": rid, "attrs": attrs, "events": events}

    def interval_ms(self, rid: str, a: str, b: str,
                    plane: str = "service") -> Optional[float]:
        """Monotonic-clock interval between two stages recorded by the
        SAME plane (None when either is missing)."""
        with self._lock:
            span = self._spans.get(rid)
            if span is None:
                return None
            ts = {e["stage"]: e["t_mono"] for e in span["events"]
                  if e["plane"] == plane and "t_mono" in e}
        if a not in ts or b not in ts:
            return None
        return 1000.0 * (ts[b] - ts[a])

    def eviction_count(self) -> int:
        """Spans dropped by ring overflow since construction (the
        ``xllm_span_evictions_total`` scrape-time mirror source)."""
        with self._lock:
            return self._evictions

    def was_evicted(self, rid: str) -> bool:
        """True when ``rid`` once held a span that the ring evicted (and
        it has not been re-created since). Bounded memory: only the most
        recent evictions are remembered — beyond the tombstone ring an
        evicted id degrades back to an honest 404."""
        with self._lock:
            return rid in self._tombstone_set and rid not in self._spans

    def tail(self, n: int, finished_only: bool = False
             ) -> List[Dict[str, Any]]:
        """Deep-enough copies of the newest ``n`` spans (insertion
        order), optionally only those that reached ``finished`` on some
        plane — the debug bundle's recent-request evidence. Copies are
        taken UNDER the lock (like ``get``): live spans mutate
        concurrently, and the incident-debug path must not 500 on a
        dict-changed-during-iteration race."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for span in reversed(self._spans.values()):
                if finished_only and not any(
                        e.get("stage") == "finished"
                        for e in span["events"]):
                    continue
                out.append({"request_id": span["request_id"],
                            "attrs": _deep_copy(span["attrs"]),
                            "events": [_deep_copy(e)
                                       for e in span["events"]]})
                if len(out) >= n:
                    break
        out.reverse()
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    # -- worker-side export (heartbeat path) ----------------------------
    def drain_finished(self) -> List[Dict[str, Any]]:
        """Pop every span that reached ``finished`` since the last
        drain, removing them from the ring (the exporter owns them now).
        On a failed ship, hand the batch back via ``requeue``."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            rids, self._finished = sorted(self._finished), set()
            for rid in rids:
                span = self._spans.pop(rid, None)
                if span is not None:
                    out.append({"request_id": rid,
                                "attrs": _deep_copy(span["attrs"]),
                                "events": [_deep_copy(e)
                                           for e in span["events"]]})
        return out

    def requeue(self, drained: List[Dict[str, Any]]) -> None:
        """Return an undeliverable drained batch so the next heartbeat
        retries it (ring bounds still apply)."""
        with self._lock:
            for rec in drained:
                rid = rec["request_id"]
                if rid in self._spans:
                    continue
                self._spans[rid] = {
                    "request_id": rid,
                    "attrs": _deep_copy(rec.get("attrs", {})),
                    "events": [_deep_copy(e)
                               for e in rec.get("events", [])]}
                self._revive_tombstone_locked(rid)
                self._finished.add(rid)
                self._evict_overflow_locked()
