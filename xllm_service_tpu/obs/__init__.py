"""obs — the observability core: metrics registry + request spans.

Dependency-free (stdlib only), thread-safe, shared by both planes:

- ``metrics``: Counter / Gauge / Histogram families with label sets,
  log-spaced latency buckets, Prometheus text exposition. Every
  ``/metrics`` line in this repo renders through a ``Registry``
  (enforced by the ``metrics-registry`` xlint rule).
- ``expfmt``: the read side — exposition parsing, structural histogram
  validation (tier-1 tests), and ``histogram_quantile`` (bench.py's
  latency percentiles).
- ``spans``: per-request stage timelines in a bounded ring, merged
  across the service/worker boundary by correlation id and served at
  ``GET /admin/trace/<request_id>``.
- ``events``: the bounded structured cluster event log (closed
  taxonomy, ``event-catalog`` xlint rule) behind ``GET /admin/events``.
- ``failpoints``: deterministic fault injection — a closed catalog of
  named failure sites (``failpoint-catalog`` xlint rule), armed via
  ``XLLM_FAILPOINTS`` / ``POST /admin/failpoint``; the chaos tests'
  lever (docs/ROBUSTNESS.md).
- ``slo``: the judgment layer — multi-window SLO burn-rate engine and
  the watchdog's anomaly detector, behind ``GET /admin/slo`` and the
  ``xllm_slo_*`` / ``xllm_anomaly_active`` series.
- ``profiler``: the master watching itself — closed-catalog hot-path
  section timers (``hotpath-section-catalog`` xlint rule),
  lock-contention mirrors, per-thread-root CPU, self-gauges, and the
  ``GET /admin/profile`` stack sampler.

See docs/OBSERVABILITY.md for the full series and stage catalogue.
"""

from xllm_service_tpu.obs.events import (           # noqa: F401
    EVENT_TYPES, EventLog)
from xllm_service_tpu.obs.failpoints import (       # noqa: F401
    FAILPOINTS, Failpoints)
from xllm_service_tpu.obs.expfmt import (           # noqa: F401
    fraction_le_from_buckets, histogram_fraction_le, histogram_quantile,
    parse_exposition, validate_exposition)
from xllm_service_tpu.obs.metrics import (          # noqa: F401
    DEFAULT_LATENCY_BUCKETS_MS, Counter, Gauge, Histogram, Registry,
    default_registry)
from xllm_service_tpu.obs.profiler import (         # noqa: F401
    HOTPATH_BUCKETS_MS, SECTIONS)
from xllm_service_tpu.obs import profiler           # noqa: F401
from xllm_service_tpu.obs.slo import (              # noqa: F401
    AnomalyDetector, InstanceSignal, SloConfig, SloEngine, SloObjective)
from xllm_service_tpu.obs.spans import (            # noqa: F401
    REQUEST_ID_HEADER, SERVICE_STAGES, WORKER_STAGES, SpanStore)
