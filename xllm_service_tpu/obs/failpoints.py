"""Deterministic fault injection: a closed catalog of named failure
sites threaded through the worker and the service.

The recovery subsystem (docs/ROBUSTNESS.md) is only as trustworthy as
the failures it was proven against — and SIGKILL-under-load chaos tests
are slow and nondeterministic. Failpoints make the interesting failure
modes *injectable*: each named site calls ``failpoints.fire("<name>")``
on its hot path (a dict lookup when nothing is armed), and an armed
failpoint fires deterministically (count/threshold modes carry no
randomness, so a test that arms ``worker.die_after_n_tokens=after:6``
gets a worker that dies after exactly six dispatched tokens, every
run).

Design rules (mirroring obs/events.py):

- CLOSED catalog. ``FAILPOINTS`` below is the complete list;
  ``arm()``/``fire()`` reject anything else at runtime, and the
  ``failpoint-catalog`` xlint rule rejects unknown or non-literal
  names statically at every ``*.fire("<name>")`` call site. A failure
  site nobody declared is a failure mode no chaos test knows to arm.
- Armed via the ``XLLM_FAILPOINTS`` env at construction (spec grammar
  below) and at runtime via ``POST /admin/failpoint`` on either plane.
- Every trip is visible: ``xllm_failpoints_tripped_total{name}`` in the
  constructing plane's registry, and a ``failpoint_tripped`` event when
  the plane has an event log (the service plane; workers have metrics
  only).

Spec grammar (comma-separated entries)::

    name=always[:value]     fire every time (value: site-specific arg,
                            e.g. worker.slow_response_ms=always:250)
    name=count:N[:value]    fire the first N times, then auto-disarm
    name=after:N            fire ONCE when the cumulative units passed
                            to fire(..., n=...) reach N, then disarm
                            (die-after-N-tokens)
    name=prob:P[:value]     fire with probability P (load tests only —
                            the deterministic modes are for CI)
    name=off                explicit no-op (override an env arming)

Thread-safe; rank ``obs.failpoints`` in the utils/locks.py table (the
lock guards arming state only and never calls out).
"""

from __future__ import annotations

import os
import random
from typing import Any, Dict, Optional

from xllm_service_tpu.utils.locks import make_lock

# The complete failpoint catalog (docs/ROBUSTNESS.md documents each
# site's semantics). Adding a site means adding it HERE (the
# failpoint-catalog xlint rule pins every fire() call to this tuple)
# and documenting it.
FAILPOINTS = (
    "worker.drop_heartbeats",    # skip store keepalive + master beat
    "worker.refuse_generate",    # 503 every new generate (refusal class)
    "worker.hang_rpc",           # block a generate handler (value: s)
    "worker.die_after_n_tokens",  # simulate process death mid-stream
    "worker.slow_response_ms",   # delay a generate handler (value: ms)
    "worker.fail_kv_transfer",   # PD migration transport failure
    "worker.fail_kv_fetch",      # cross-worker cached-block fetch fails
                                 # (requester side) — prefill recomputes
                                 # from token zero, correctness intact
    "service.fail_redispatch",   # service refuses to pick an alternate
    "worker.crash_heartbeat",    # raise OUTSIDE the heartbeat loop's
                                 # try — an injected thread crash, for
                                 # proving the supervised restart path
                                 # (utils/threads.py, docs/ROBUSTNESS.md)
    "store.fail_rpc",            # every coordination-store call raises
                                 # (service/store_guard.py — one-plane
                                 # store outage, deterministic)
    "store.hang",                # a store call blocks for the armed
                                 # value (s) then times out — the
                                 # deadline'd-guard slow-outage shape
    "store.partition",           # store calls raise AND incoming watch
                                 # events are suppressed — a full
                                 # network partition from the store
                                 # (lease expiry invisible, exactly like
                                 # a real blackout)
    "worker.fail_encode",        # /encode raises on the encode worker —
                                 # the requester walks its fallback
                                 # chain (survivor reroute, then local
                                 # encode), never a client error
    "worker.hang_encode",        # /encode blocks for the armed value
                                 # (s) — exercises the
                                 # XLLM_ENCODE_TIMEOUT_S deadline path
    "worker.fault_step",         # raise inside the engine step fault
                                 # boundary — a device-plane step fault
                                 # (count/after/prob choose which step)
    "worker.fault_step_req",     # raise only while a MARKED request is
                                 # in the step's batch (value: prompt
                                 # substring to mark; no value marks
                                 # all) — the poison-pill simulator
)

_MODES = ("always", "count", "after", "prob", "off")


class Failpoints:
    """Per-plane armed-failpoint registry (one per Worker/HttpService —
    the co-located test harness arms one in-process worker without
    touching its twin)."""

    def __init__(self, events=None, obs=None,
                 env: Optional[str] = None) -> None:
        self._lock = make_lock("obs.failpoints", 75)
        self.events = events
        self.obs = obs
        # name → {"mode", "n", "value", "fired", "units"}
        self._armed: Dict[str, Dict[str, Any]] = {}
        self._trips: Dict[str, int] = {name: 0 for name in FAILPOINTS}
        spec = os.environ.get("XLLM_FAILPOINTS", "") if env is None \
            else env
        if spec:
            self.arm_from_spec(spec)

    # -- arming ---------------------------------------------------------
    @staticmethod
    def _check_name(name: str) -> None:
        if name not in FAILPOINTS:
            raise ValueError(
                f"failpoint {name!r} is not in the obs/failpoints.py "
                f"catalog {FAILPOINTS}")

    def arm(self, name: str, mode: str = "always", n: float = 0,
            value: Any = None) -> None:
        """Arm one failpoint. ``n`` is the count (mode=count), the unit
        threshold (mode=after), or the probability (mode=prob)."""
        self._check_name(name)
        if mode not in _MODES:
            raise ValueError(f"failpoint mode {mode!r} not in {_MODES}")
        with self._lock:
            if mode == "off":
                self._armed.pop(name, None)
                return
            self._armed[name] = {"mode": mode, "n": float(n),
                                 "value": value, "fired": 0, "units": 0.0}

    def disarm(self, name: str) -> None:
        self._check_name(name)
        with self._lock:
            self._armed.pop(name, None)

    def arm_from_spec(self, spec: str) -> None:
        """Parse the ``XLLM_FAILPOINTS`` grammar (module docstring) —
        also the body format of ``POST /admin/failpoint`` ``{"spec"}``."""
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            name, sep, rest = entry.partition("=")
            if not sep:
                raise ValueError(
                    f"failpoint spec entry {entry!r}: expected "
                    f"name=mode[:arg[:value]]")
            parts = rest.split(":")
            mode = parts[0] or "always"
            n = 0.0
            value: Any = None
            if mode in ("count", "after", "prob"):
                if len(parts) < 2:
                    raise ValueError(
                        f"failpoint {name}: mode {mode!r} needs an "
                        f"argument (e.g. {mode}:3)")
                n = float(parts[1])
                if len(parts) > 2:
                    value = float(parts[2])
            elif len(parts) > 1:
                value = float(parts[1])
            self.arm(name.strip(), mode=mode, n=n, value=value)

    def arm_from_body(self, body: Dict[str, Any]) -> None:
        """The ``POST /admin/failpoint`` body contract, shared by both
        planes' handlers (``{"spec": "<grammar>"}`` or
        ``{"name", "mode", "n", "value"}``). Raises ValueError/TypeError
        on bad input — handlers map to HTTP 400."""
        if body.get("spec"):
            self.arm_from_spec(str(body["spec"]))
        else:
            self.arm(str(body.get("name", "")),
                     mode=str(body.get("mode", "always")),
                     n=float(body.get("n", 0) or 0),
                     value=body.get("value"))

    # -- firing ---------------------------------------------------------
    def fire(self, name: str, n: float = 1) -> Optional[Any]:
        """One pass through a failure site. Returns the armed value (or
        ``True`` when none was set) when the failpoint trips, else
        ``None``. ``n`` is the unit weight of this pass (token count
        for ``after``-mode sites)."""
        self._check_name(name)
        if name not in self._armed:
            # Unlocked fast path: disarmed sites (production — fire()
            # runs per engine step) cost one dict probe, no mutex. The
            # race with a concurrent arm() is benign: a just-armed
            # point fires on the next pass.
            return None
        with self._lock:
            spec = self._armed.get(name)
            if spec is None:
                return None
            mode = spec["mode"]
            if mode == "count":
                if spec["fired"] >= spec["n"]:
                    self._armed.pop(name, None)
                    return None
            elif mode == "after":
                spec["units"] += n
                if spec["units"] < spec["n"]:
                    return None
                self._armed.pop(name, None)   # fires exactly once
            elif mode == "prob":
                if random.random() >= spec["n"]:
                    return None
            spec["fired"] += 1
            self._trips[name] += 1
            value = spec["value"]
        self._note_trip(name)
        return value if value is not None else True

    def _note_trip(self, name: str) -> None:
        """Visibility, outside the arming lock: the registry counter
        and (service plane) a cluster event."""
        if self.obs is not None:
            self.obs.counter(
                "xllm_failpoints_tripped_total",
                "armed failure-injection sites tripped, by name "
                "(obs/failpoints.py catalog)",
                labelnames=("name",)).inc(name=name)
        if self.events is not None:
            self.events.emit("failpoint_tripped", name=name)

    def armed_value(self, name: str) -> Optional[Any]:
        """Non-firing peek at an armed site: the armed value (``True``
        when none was set), ``None`` when disarmed. For sites whose
        *setup* needs the arming (worker.fault_step_req marks requests
        at admission) without consuming the fire budget."""
        self._check_name(name)
        if name not in self._armed:
            return None                 # same benign race as fire()
        with self._lock:
            spec = self._armed.get(name)
            if spec is None:
                return None
            value = spec["value"]
        return value if value is not None else True

    # -- querying -------------------------------------------------------
    def trips(self, name: str) -> int:
        self._check_name(name)
        with self._lock:
            return self._trips[name]

    def state(self) -> Dict[str, Any]:
        """The ``GET /admin/failpoints`` body: what is armed (mode /
        remaining budget / value) and per-name lifetime trip counts."""
        with self._lock:
            armed = {name: dict(spec)
                     for name, spec in self._armed.items()}
            trips = {name: count for name, count in self._trips.items()
                     if count}
        return {"catalog": list(FAILPOINTS), "armed": armed,
                "trips": trips}
