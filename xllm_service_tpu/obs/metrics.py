"""Metrics core: Counter / Gauge / Histogram families with label sets.

The single place this repo turns numbers into Prometheus text exposition.
Every ``/metrics`` line on both planes renders through a ``Registry``
(enforced by the ``metrics-registry`` xlint rule: hand-rolled
``name{...} value`` f-strings outside ``xllm_service_tpu/obs/`` are
findings), so series names, label escaping, and histogram consistency
(``_bucket`` cumulative/monotone, ``_count`` == the ``+Inf`` bucket,
``_sum`` present) are structural properties instead of per-call-site
conventions. Dependency-free (stdlib only) and thread-safe: one lock per
registry, rank ``obs.registry`` in the utils/locks.py table — registry
methods never call out, so it nests safely under every serving-path
lock.

Two kinds of write path coexist deliberately:

- live instrumentation (``Counter.inc`` / ``Histogram.observe``) for
  values that are events — request counts, latency samples;
- scrape-time mirroring (``Counter.set_total`` / ``Gauge.set``) for
  totals another subsystem already owns (engine phase ledgers, the
  keep-alive pool counters, per-instance load) — the ``/metrics``
  handler refreshes them from the live objects, then renders, so the
  registry never caches stale copies of state it doesn't own.

Deployment note: one serving process hosts one plane, so a plane's
registry is process-global there. The test harness co-locates several
masters/workers in one process; each plane instance therefore OWNS its
registry (``Worker.obs`` / ``HttpService.obs``) to keep attribution
per-instance, and ``default_registry()`` serves single-plane callers
(bench.py).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from xllm_service_tpu.utils.locks import make_lock

# Log-spaced latency buckets (milliseconds): 1-2-5 per decade from 1 ms
# to 2 minutes. Wide enough for tunneled-TPU TTFTs (minutes-scale
# compiles land in +Inf, which is itself a signal) and fine enough that
# p50/p90/p99 interpolation stays meaningful at CPU-test speeds.
DEFAULT_LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
    1000.0, 2000.0, 5000.0, 10000.0, 20000.0, 50000.0, 120000.0)

_NAME_OK = "abcdefghijklmnopqrstuvwxyz" \
           "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:"


def _fmt(v: float) -> str:
    """Prometheus sample value: integers without a trailing .0 (existing
    series like ``xllm_service_instances 1`` are grepped as substrings by
    tests and ops scripts), shortest-repr floats otherwise. NaN renders
    as ``NaN`` (valid exposition) — one NaN sample (e.g. a heartbeat
    shipping a NaN load value through JSON) must poison its own series,
    not 500 every future /metrics render via ``int(nan)``."""
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


class _Family:
    """One metric family: a name, a fixed labelname tuple, and a value
    per label set. Subclasses define the value semantics."""

    kind = "untyped"

    def __init__(self, registry: "Registry", name: str, help: str = "",
                 labelnames: Sequence[str] = ()) -> None:
        if not name or any(c not in _NAME_OK for c in name) \
                or name[0].isdigit():
            raise ValueError(f"bad metric name {name!r}")
        self._registry = registry
        self._lock = registry._lock
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Dict[str, Any]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def _label_str(self, key: Tuple[str, ...],
                   extra: Tuple[Tuple[str, str], ...] = ()) -> str:
        pairs = list(zip(self.labelnames, key)) + list(extra)
        if not pairs:
            return ""
        inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
        return "{" + inner + "}"

    def clear(self) -> None:
        """Drop every label set (scrape-time rebuilders: per-instance
        gauges whose members come and go with the cluster)."""
        with self._lock:
            self._series.clear()

    def remove(self, **labels: Any) -> None:
        with self._lock:
            self._series.pop(self._key(labels), None)

    def render(self, out: List[str]) -> None:
        raise NotImplementedError


class Counter(_Family):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counter increment {amount} < 0")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def set_total(self, total: float, **labels: Any) -> None:
        """Scrape-time mirror of a monotonic total another object owns
        (engine phase ledger, keep-alive pool counters). The caller is
        responsible for monotonicity — this is a refresh, not an event."""
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(total)

    def value(self, **labels: Any) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))

    def render(self, out: List[str]) -> None:
        with self._lock:
            items = sorted(self._series.items())
        for key, v in items:
            out.append(f"{self.name}{self._label_str(key)} {_fmt(v)}")


class Gauge(_Family):
    kind = "gauge"

    def set(self, value: float, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: Any) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: Any) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))

    def render(self, out: List[str]) -> None:
        with self._lock:
            items = sorted(self._series.items())
        for key, v in items:
            out.append(f"{self.name}{self._label_str(key)} {_fmt(v)}")


class _HistogramSeries:
    __slots__ = ("counts", "total", "sum")

    def __init__(self, n_buckets: int) -> None:
        self.counts = [0] * n_buckets   # per-bucket (non-cumulative)
        self.total = 0
        self.sum = 0.0


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, registry: "Registry", name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None) -> None:
        super().__init__(registry, name, help, labelnames)
        if "le" in self.labelnames:
            raise ValueError(f"{name}: 'le' is reserved for buckets")
        bs = tuple(float(b) for b in
                   (buckets or DEFAULT_LATENCY_BUCKETS_MS))
        if list(bs) != sorted(bs) or len(set(bs)) != len(bs):
            raise ValueError(f"{name}: buckets must strictly increase")
        self.buckets = bs

    def observe(self, value: float, **labels: Any) -> None:
        key = self._key(labels)
        v = float(value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistogramSeries(len(self.buckets))
            for i, edge in enumerate(self.buckets):
                if v <= edge:
                    s.counts[i] += 1
                    break
            s.total += 1
            s.sum += v

    def count(self, **labels: Any) -> int:
        with self._lock:
            s = self._series.get(self._key(labels))
            return s.total if s is not None else 0

    def set_counts(self, counts: Sequence[int], sum_value: float,
                   total: Optional[int] = None, **labels: Any) -> None:
        """Scrape-time mirror of a full bucket distribution another
        object owns (the profiler's section books, the lock-contention
        wait books) — the histogram analogue of ``Counter.set_total``.
        ``counts`` are per-bucket (non-cumulative) and must match this
        family's bucket count; ``total`` covers overflow samples past
        the last finite edge (defaults to ``sum(counts)``); the caller
        owns monotonicity."""
        if len(counts) != len(self.buckets):
            raise ValueError(
                f"{self.name}: set_counts got {len(counts)} buckets, "
                f"family has {len(self.buckets)}")
        key = self._key(labels)
        n_total = int(sum(counts)) if total is None else int(total)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistogramSeries(len(self.buckets))
            s.counts = [int(c) for c in counts]
            s.total = n_total
            s.sum = float(sum_value)

    def cumulative(self, **labels: Any
                   ) -> Optional[List[Tuple[float, float]]]:
        """Snapshot of one label set's cumulative bucket counts as
        ``[(le, cum), ...]`` ending with the ``+Inf`` bucket — the exact
        shape ``expfmt``'s bucket arithmetic consumes, so the SLO
        engine's window deltas and a scraped dashboard read the SAME
        numbers. None when the series has never been observed."""
        with self._lock:
            s = self._series.get(self._key(labels))
            if s is None:
                return None
            counts = list(s.counts)
            total = s.total
        bs: List[Tuple[float, float]] = []
        cum = 0
        for edge, c in zip(self.buckets, counts):
            cum += c
            bs.append((edge, float(cum)))
        bs.append((math.inf, float(total)))
        return bs

    def quantile(self, q: float, **labels: Any) -> Optional[float]:
        """Estimated q-quantile of one label set — the same
        ``le``-bucket interpolation the scrape side runs
        (``expfmt.quantile_from_buckets``: one copy of the arithmetic,
        so in-memory and scraped quantiles cannot drift). None with no
        observations; samples past the last finite edge clamp to it."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        from xllm_service_tpu.obs.expfmt import quantile_from_buckets
        bs = self.cumulative(**labels)
        if bs is None or bs[-1][1] == 0:
            return None
        return quantile_from_buckets(bs, q)

    def render(self, out: List[str]) -> None:
        with self._lock:
            items = [(k, list(s.counts), s.total, s.sum)
                     for k, s in sorted(self._series.items())]
        for key, counts, total, ssum in items:
            cum = 0
            for edge, c in zip(self.buckets, counts):
                cum += c
                out.append(
                    f"{self.name}_bucket"
                    f"{self._label_str(key, (('le', _fmt(edge)),))} "
                    f"{cum}")
            out.append(
                f"{self.name}_bucket"
                f"{self._label_str(key, (('le', '+Inf'),))} {total}")
            out.append(f"{self.name}_sum{self._label_str(key)} "
                       f"{_fmt(ssum)}")
            out.append(f"{self.name}_count{self._label_str(key)} {total}")


class Registry:
    """A named, ordered set of metric families sharing one lock.

    Get-or-create accessors are idempotent (same name → same family) and
    raise on a kind or labelname conflict, so two call sites can't
    silently fork one series into incompatible shapes."""

    def __init__(self) -> None:
        self._lock = make_lock("obs.registry", 93)
        self._families: Dict[str, _Family] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kwargs) -> _Family:
        with self._lock:
            fam = self._families.get(name)
        if fam is not None:
            if not isinstance(fam, cls) or \
                    fam.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} re-declared as {cls.kind} "
                    f"labels={tuple(labelnames)} (was {fam.kind} "
                    f"labels={fam.labelnames})")
            return fam
        fam = cls(self, name, help, labelnames, **kwargs)
        with self._lock:
            return self._families.setdefault(name, fam)

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        fam = self._get_or_create(Histogram, name, help, labelnames,
                                  buckets=buckets)
        if buckets is not None and fam.buckets != tuple(
                float(b) for b in buckets):
            # The kind/labelname checks already refuse silent series
            # forks; differing bucket edges are the same class of bug.
            raise ValueError(
                f"histogram {name!r} re-declared with buckets "
                f"{tuple(buckets)} (was {fam.buckets})")
        return fam

    def render(self) -> str:
        """Prometheus text exposition (version 0.0.4): ``# HELP`` /
        ``# TYPE`` headers per family, then its samples."""
        with self._lock:
            fams = list(self._families.values())
        out: List[str] = []
        for fam in fams:
            if fam.help:
                out.append(f"# HELP {fam.name} {fam.help}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            fam.render(out)
        return "\n".join(out) + "\n"


_DEFAULT: Optional[Registry] = None


def default_registry() -> Registry:
    """The process-default registry for single-plane processes (bench.py
    and ad-hoc tools). Plane objects own their registries — see module
    docstring."""
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = Registry()
    return _DEFAULT
