"""Device-plane step flight recorder + roofline/MFU attribution.

The service plane got self-profiling in the hotpath-section catalog
(obs/profiler.py); the device plane still reported only aggregate
histograms — the ROADMAP's "unattributed ~17 ms/step decode debt" had
no per-step evidence trail. This module is that trail:

- **Step records**: every engine iteration appends one fixed-schema
  record (``STEP_FIELDS`` — a CLOSED catalog, machine-checked by xlint
  rule ``steptrace-schema`` exactly like the event/failpoint/section
  catalogs) into a bounded ring. The record carries the step kind, the
  per-phase ms delta from the engine's phase ledger (device_wait /
  host_copy splits included), the batch token mix, ragged/split
  dispatch counts, the speculation outcome delta, KV-page/cache
  deltas, and the request-id membership of the step.
- **Roofline attribution**: at warmup the engine captures
  ``.lower().compile().cost_analysis()`` FLOPs/bytes per compiled
  variant of each jitted program (``Engine.roofline``); this module
  owns the peak table (``XLLM_PEAK_FLOPS`` / ``XLLM_PEAK_BW_GBPS``,
  with device-kind defaults) and turns (ledger, roofline) into per-step
  achieved FLOP/s, MFU, a compute-vs-memory-bound verdict, and the
  decode-debt ms (measured wall − modeled roofline time) the
  PERF_NOTES decode_budget runbook used to hand-compute.
- **Shipping**: the worker exposes the ring on ``GET /admin/steptrace``
  and ships a bounded tail on every heartbeat (sequence-baseline
  committed only on a delivered beat, so an undelivered tail is
  re-shipped — same discipline as the step-p99 bucket baseline); the
  master's ``StepBooks`` holds the last records per instance for the
  cluster-merged ``/admin/timeline`` export (obs/timeline.py).

``XLLM_STEPTRACE`` (default ON) and ``XLLM_STEPTRACE_RING`` (default
512) are read ONCE at import per the hot-path flag discipline; with the
flag off the recording path is a single ``if st.enabled:`` branch at
the call site — no record dict is ever built.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

from xllm_service_tpu.utils.locks import make_lock

# ---------------------------------------------------------------------------
# The closed step-record schema. xlint rule `steptrace-schema` pins every
# steptrace.record(<field>=...) keyword in the tree to this tuple — add
# the field HERE first, with a comment saying what it carries.
# ---------------------------------------------------------------------------
STEP_FIELDS: Tuple[str, ...] = (
    "seq",              # per-worker monotone step index (recorder-assigned)
    "t_wall",           # wall-clock END of the step (seconds, time.time)
    "model",            # model the iteration served
    "kind",             # prefill | decode | mixed | fault
    "step_ms",          # host wall time of the whole iteration
    "prefill_tokens",   # prompt tokens computed this step
    "decode_tokens",    # tokens sampled this step
    "prefill_windows",  # scheduled prefill window sizes (tuple of ints)
    "decode_deferred",  # prefill-first step deferred live decodes (bool)
    "ragged",           # served by the one-dispatch ragged program (bool)
    "attn_dispatches",  # attention-bearing device dispatches this step
    "members",          # request ids in the step's batch (tuple)
    "phases",           # {phase: ms} DELTA of the engine ledger this step
    "spec",             # {dispatches,hits,rollbacks} speculation delta
    "kv_usage",         # KV page pool utilization [0,1] after the step
    "pages_delta",      # free-page delta across the step (+freed/-taken)
    "cache_hit_tokens", # prefix-cache hit-token delta this step
    "flops",            # modeled useful FLOPs of the step (roofline)
    "bytes",            # modeled bytes moved by the step (roofline)
    "mfu",              # achieved FLOP/s over the peak, this step
    "bound",            # roofline verdict: compute | memory | unknown
    "debt_ms",          # measured step ms − modeled roofline ms
)

_FIELD_SET = frozenset(STEP_FIELDS)


def _enabled_from_env() -> bool:
    return os.environ.get("XLLM_STEPTRACE", "1").strip() not in (
        "0", "false", "no")


def _ring_from_env() -> int:
    try:
        return max(16, int(os.environ.get("XLLM_STEPTRACE_RING", "512")))
    except ValueError:
        return 512


ENABLED = _enabled_from_env()
RING = _ring_from_env()

# Configurable peaks for the roofline model, read ONCE at import (hot-
# path flag discipline). 0 = auto: resolve from the device kind at
# engine attach time (the bench's public-spec table), with a deliberate
# CPU fallback so MFU/debt stay finite (and obviously modeled) on the
# CPU tier-1 harness.
try:
    PEAK_FLOPS_OVERRIDE = float(os.environ.get("XLLM_PEAK_FLOPS", "0"))
except ValueError:
    PEAK_FLOPS_OVERRIDE = 0.0
try:
    PEAK_BW_GBPS_OVERRIDE = float(
        os.environ.get("XLLM_PEAK_BW_GBPS", "0"))
except ValueError:
    PEAK_BW_GBPS_OVERRIDE = 0.0

# Dense bf16 peak FLOP/s and HBM GB/s per chip, by device_kind
# substring (public specs; same family table as bench.py's headline
# MFU). The CPU row is a deliberately round placeholder so the tier-1
# harness exercises the full arithmetic with visibly-modeled numbers.
_CHIP_PEAKS: Tuple[Tuple[str, float, float], ...] = (
    ("v6", 918e12, 1640.0),      # Trillium / v6e
    ("v5p", 459e12, 2765.0),
    ("v5", 197e12, 819.0),       # v5e
    ("v4", 275e12, 1228.0),
    ("v3", 123e12, 900.0),
    ("v2", 45e12, 700.0),
    ("cpu", 1e11, 50.0),
)


def peaks_for(device_kind: str) -> Tuple[float, float]:
    """(peak FLOP/s, peak bytes/s) for a device kind — env overrides
    first, then the public-spec table, then the CPU placeholder row."""
    flops = PEAK_FLOPS_OVERRIDE
    bw = PEAK_BW_GBPS_OVERRIDE * 1e9
    if flops > 0 and bw > 0:
        return flops, bw
    kind = (device_kind or "").lower()
    t_flops, t_bw = _CHIP_PEAKS[-1][1], _CHIP_PEAKS[-1][2]
    for tag, f, b in _CHIP_PEAKS:
        if tag in kind:
            t_flops, t_bw = f, b
            break
    return (flops if flops > 0 else t_flops,
            bw if bw > 0 else t_bw * 1e9)


class StepTrace:
    """Bounded per-worker ring of step records.

    ``record()`` assigns the monotone ``seq`` and validates field names
    against the closed catalog; readers get copies. The ring is shared
    between the engine-loop writer and the HTTP/heartbeat readers, so
    every access is under one low-rank lock — the writer takes it once
    per engine iteration, which is noise next to a device dispatch."""

    def __init__(self, enabled: Optional[bool] = None,
                 ring: Optional[int] = None) -> None:
        self.enabled = ENABLED if enabled is None else bool(enabled)
        self.capacity = RING if ring is None else max(16, int(ring))
        self._ring: Deque[Dict[str, Any]] = collections.deque(
            maxlen=self.capacity)
        self._seq = 0
        self._lock = make_lock("obs.steptrace", 85)

    def record(self, **fields: Any) -> int:
        """Append one step record; returns its ``seq``. Unknown field
        names raise — the schema is closed (xlint rule
        ``steptrace-schema`` enforces the same statically)."""
        unknown = set(fields) - _FIELD_SET
        if unknown:
            raise ValueError(
                f"unknown step-record fields {sorted(unknown)!r} — add "
                f"them to steptrace.STEP_FIELDS first (closed schema)")
        with self._lock:
            self._seq += 1
            fields["seq"] = self._seq
            fields.setdefault("t_wall", time.time())
            self._ring.append(fields)
            return self._seq

    def tail(self, n: int = 0, since_seq: int = 0,
             window_s: float = 0.0) -> List[Dict[str, Any]]:
        """Copies of the newest records, oldest-first — optionally only
        those with ``seq > since_seq`` (the heartbeat tail) and/or
        within ``window_s`` of the newest record (the timeline pull)."""
        with self._lock:
            recs = [dict(r) for r in self._ring]
        if since_seq > 0:
            recs = [r for r in recs if r.get("seq", 0) > since_seq]
        if window_s > 0 and recs:
            horizon = recs[-1].get("t_wall", 0.0) - window_s
            recs = [r for r in recs if r.get("t_wall", 0.0) >= horizon]
        if n > 0:
            recs = recs[-n:]
        return recs

    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


class StepBooks:
    """Master-side per-instance step-record books, fed by heartbeat
    tails (``Heartbeat.steps``) — the fallback source for the merged
    timeline when a worker's ``/admin/steptrace`` pull fails. Bounded
    per instance; an instance's book is replaced record-by-record in
    seq order (re-shipped tails dedupe on seq)."""

    def __init__(self, per_instance: int = 256) -> None:
        self._cap = per_instance
        self._books: Dict[str, Deque[Dict[str, Any]]] = {}
        self._lock = make_lock("obs.stepbooks", 86)

    def ingest(self, instance: str, records: List[Dict[str, Any]]) -> None:
        if not records:
            return
        with self._lock:
            book = self._books.get(instance)
            if book is None:
                book = self._books[instance] = collections.deque(
                    maxlen=self._cap)
            have = {r.get("seq") for r in book}
            for r in records:
                if isinstance(r, dict) and r.get("seq") not in have:
                    book.append(r)

    def tail(self, instance: str, n: int = 0) -> List[Dict[str, Any]]:
        with self._lock:
            book = self._books.get(instance)
            recs = [dict(r) for r in book] if book else []
        recs.sort(key=lambda r: r.get("seq", 0))
        return recs[-n:] if n > 0 else recs

    def instances(self) -> List[str]:
        with self._lock:
            return sorted(self._books)


# ---------------------------------------------------------------------------
# Roofline arithmetic: (engine roofline table, step ledger) → modeled
# step cost, MFU, bound verdict, and decode debt.
# ---------------------------------------------------------------------------

def _median_variant(variants: Dict[str, Dict[str, float]]
                    ) -> Optional[Dict[str, float]]:
    rows = [v for v in variants.values()
            if v.get("flops", 0.0) > 0.0]
    if not rows:
        return None
    rows.sort(key=lambda v: v["flops"])
    return rows[len(rows) // 2]


def _nearest_prefill_variant(variants: Dict[str, Dict[str, float]],
                             tokens: int) -> Optional[Dict[str, float]]:
    """The captured prefill/ragged variant whose batch token count
    (B*T, parsed from the ``B{B}xT{T}x...`` key) is nearest the step's
    actual prompt-token load — the modeled cost scales from it."""
    best = None
    best_d = None
    for key, v in variants.items():
        if v.get("flops", 0.0) <= 0.0:
            continue
        toks = v.get("tokens", 0.0)
        if toks <= 0:
            continue
        d = abs(toks - tokens)
        if best_d is None or d < best_d:
            best, best_d = v, d
    return best


def estimate_step(roofline: Dict[str, Dict[str, Dict[str, float]]],
                  *, kind: str, prefill_tokens: int, decode_tokens: int,
                  batch_size: int, decode_steps: int,
                  ragged: bool) -> Dict[str, float]:
    """Modeled device cost of one engine iteration from the warmup-
    captured cost_analysis table: total FLOPs/bytes, and which side of
    the roofline the dominant program sits on. Scaling is explicit and
    documented as a MODEL: prefill cost scales linearly in prompt
    tokens from the nearest captured variant; decode cost is per-burst
    (a decode dispatch runs the full padded batch, so dead rows are
    paid — that is the point of the debt number)."""
    flops = 0.0
    bytes_ = 0.0
    if prefill_tokens > 0:
        prog = "ragged" if ragged else "prefill"
        variants = roofline.get(prog) or roofline.get("prefill") or {}
        v = _nearest_prefill_variant(variants, prefill_tokens)
        if v is not None:
            scale = prefill_tokens / max(v.get("tokens", 1.0), 1.0)
            flops += v["flops"] * scale
            bytes_ += v.get("bytes", 0.0) * scale
    if decode_tokens > 0 and not (ragged and kind == "mixed"):
        variants = (roofline.get("decode_multi")
                    or roofline.get("decode") or {})
        v = _median_variant(variants)
        if v is not None:
            per_burst = max(batch_size, 1) * max(decode_steps, 1)
            bursts = max(1, -(-decode_tokens // per_burst))
            flops += v["flops"] * bursts
            bytes_ += v.get("bytes", 0.0) * bursts
    return {"flops": flops, "bytes": bytes_}


def attribute_step(roofline: Dict[str, Dict[str, Dict[str, float]]],
                   *, kind: str, step_ms: float, prefill_tokens: int,
                   decode_tokens: int, batch_size: int,
                   decode_steps: int, ragged: bool,
                   peak_flops: float, peak_bytes_s: float
                   ) -> Dict[str, Any]:
    """The per-step roofline verdict the flight recorder embeds:
    modeled flops/bytes, MFU (achieved FLOP/s over peak), compute-vs-
    memory-bound, and the debt — measured wall ms minus the modeled
    roofline floor max(flops/peak_flops, bytes/peak_bw)."""
    cost = estimate_step(
        roofline, kind=kind, prefill_tokens=prefill_tokens,
        decode_tokens=decode_tokens, batch_size=batch_size,
        decode_steps=decode_steps, ragged=ragged)
    flops, bytes_ = cost["flops"], cost["bytes"]
    step_s = max(step_ms, 1e-6) / 1000.0
    mfu = (flops / step_s / peak_flops) if peak_flops > 0 else 0.0
    t_compute = flops / peak_flops if peak_flops > 0 else 0.0
    t_memory = bytes_ / peak_bytes_s if peak_bytes_s > 0 else 0.0
    if flops <= 0.0 and bytes_ <= 0.0:
        bound = "unknown"
    elif t_compute >= t_memory:
        bound = "compute"
    else:
        bound = "memory"
    modeled_ms = 1000.0 * max(t_compute, t_memory)
    return {
        "flops": flops,
        "bytes": bytes_,
        "mfu": round(mfu, 6),
        "bound": bound,
        "debt_ms": round(step_ms - modeled_ms, 3),
    }


def roofline_table(roofline: Dict[str, Dict[str, Dict[str, float]]],
                   peak_flops: float, peak_bytes_s: float
                   ) -> List[Dict[str, Any]]:
    """Flattened per-(program, variant) roofline rows for the debug
    bundle and /admin/steptrace: arithmetic intensity vs the machine's
    ridge point decides the bound verdict per compiled program."""
    ridge = (peak_flops / peak_bytes_s) if peak_bytes_s > 0 else 0.0
    rows: List[Dict[str, Any]] = []
    for prog in sorted(roofline):
        for key in sorted(roofline[prog]):
            v = roofline[prog][key]
            fl = v.get("flops", 0.0)
            by = v.get("bytes", 0.0)
            intensity = fl / by if by > 0 else 0.0
            rows.append({
                "program": prog, "variant": key,
                "flops": fl, "bytes": by,
                "intensity": round(intensity, 3),
                "bound": ("unknown" if fl <= 0 and by <= 0 else
                          "compute" if intensity >= ridge else
                          "memory"),
            })
    return rows


def flush_metrics(registry, model: str, roofline, last_mfu: float,
                  last_debt_ms: float, device_kind: str = "") -> None:
    """Scrape-time mirror of the roofline attribution into a worker
    Registry: per-program/variant FLOPs+bytes gauges (cost_analysis-
    derived numerators — never hardcoded) and the last step's MFU and
    decode-debt. Same set_total/set pattern as profiler.flush_metrics."""
    g_mfu = registry.gauge(
        "xllm_worker_step_mfu",
        "model FLOP utilization of the last engine step (modeled "
        "roofline FLOPs over wall time over the configured peak — "
        "XLLM_PEAK_FLOPS)", labelnames=("model",))
    g_mfu.set(last_mfu, model=model)
    registry.gauge(
        "xllm_worker_step_debt_ms",
        "last step's wall ms minus its modeled roofline floor "
        "(the unattributed decode debt, now attributed)",
        labelnames=("model",)).set(last_debt_ms, model=model)
    g_fl = registry.gauge(
        "xllm_worker_program_flops",
        "cost_analysis FLOPs per compiled program variant "
        "(captured at warmup)",
        labelnames=("model", "program", "variant"))
    g_by = registry.gauge(
        "xllm_worker_program_bytes",
        "cost_analysis bytes accessed per compiled program variant",
        labelnames=("model", "program", "variant"))
    for prog, variants in (roofline or {}).items():
        for key, v in variants.items():
            g_fl.set(v.get("flops", 0.0), model=model, program=prog,
                     variant=key)
            g_by.set(v.get("bytes", 0.0), model=model, program=prog,
                     variant=key)
    peak_flops, _ = peaks_for(device_kind)
    registry.gauge(
        "xllm_worker_peak_flops",
        "peak FLOP/s the MFU series is normalized by "
        "(XLLM_PEAK_FLOPS or the device-kind table)").set(peak_flops)
