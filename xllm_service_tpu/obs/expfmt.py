"""Prometheus text-exposition parsing + structural validation.

The read side of the registry: tests point ``validate_exposition`` at
both planes' ``/metrics`` bodies (every line must parse; histograms must
be internally consistent), and bench.py scrapes its latency percentiles
out of rendered histogram text with ``histogram_quantile`` — the same
arithmetic a Prometheus server would run, so the numbers a dashboard
shows and the numbers BENCH_*.json records cannot drift apart.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    rf"^({_NAME_RE})(?:\{{(.*)\}})?\s+(\S+)(?:\s+(-?\d+))?$")
_LABEL_RE = re.compile(
    rf'({_NAME_RE})="((?:[^"\\]|\\.)*)"\s*(,|$)')
_COMMENT_RE = re.compile(
    rf"^#\s+(HELP|TYPE)\s+({_NAME_RE})(?:\s+(.*))?$")

Sample = Tuple[str, Dict[str, str], float]


def _unescape(v: str) -> str:
    """Left-to-right scan, one escape at a time — sequential
    str.replace passes mangle a literal backslash followed by ``n``
    (``\\\\n`` would lose its backslash to the ``\\n`` pass first)."""
    out: List[str] = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _parse_value(s: str) -> float:
    if s in ("+Inf", "Inf"):
        return math.inf
    if s == "-Inf":
        return -math.inf
    if s == "NaN":
        return math.nan
    return float(s)       # raises ValueError on garbage


def _parse_labels(raw: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    pos = 0
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            raise ValueError(f"bad label pair at {raw[pos:pos + 30]!r}")
        labels[m.group(1)] = _unescape(m.group(2))
        pos = m.end()
    return labels


def parse_exposition(text: str
                     ) -> Tuple[List[Sample], Dict[str, str], List[str]]:
    """→ (samples, {family: declared type}, errors). Never raises:
    unparseable lines become error strings so a validator can report all
    of them at once."""
    samples: List[Sample] = []
    types: Dict[str, str] = {}
    errors: List[str] = []
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = _COMMENT_RE.match(line)
            if m is None:
                errors.append(f"line {i}: malformed comment {line!r}")
            elif m.group(1) == "TYPE":
                types[m.group(2)] = (m.group(3) or "").strip()
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {i}: unparseable sample {line!r}")
            continue
        name, rawlabels, rawvalue = m.group(1), m.group(2), m.group(3)
        try:
            labels = _parse_labels(rawlabels) if rawlabels else {}
        except ValueError as e:
            errors.append(f"line {i}: {e}")
            continue
        try:
            value = _parse_value(rawvalue)
        except ValueError:
            errors.append(f"line {i}: bad value {rawvalue!r}")
            continue
        samples.append((name, labels, value))
    return samples, types, errors


def _series_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, v) for k, v in labels.items() if k != "le"))


def _histogram_families(samples: List[Sample],
                        types: Dict[str, str]) -> List[str]:
    fams = {n for n, t in types.items() if t == "histogram"}
    # Untyped expositions: infer from the _bucket suffix.
    for name, labels, _v in samples:
        if name.endswith("_bucket") and "le" in labels:
            fams.add(name[:-len("_bucket")])
    return sorted(fams)


def validate_exposition(text: str) -> List[str]:
    """Structural checks beyond line grammar: for every histogram
    family+label set, buckets are cumulative-monotone in ascending
    ``le``, a ``+Inf`` bucket exists and equals ``_count``, and ``_sum``
    is present. Returns all violations (empty == valid)."""
    samples, types, errors = parse_exposition(text)
    for fam in _histogram_families(samples, types):
        buckets: Dict[Tuple, List[Tuple[float, float]]] = {}
        counts: Dict[Tuple, float] = {}
        sums: Dict[Tuple, float] = {}
        for name, labels, value in samples:
            if name == fam + "_bucket" and "le" in labels:
                try:
                    le = _parse_value(labels["le"])
                except ValueError:
                    errors.append(f"{fam}: bad le {labels['le']!r}")
                    continue
                buckets.setdefault(_series_key(labels), []) \
                    .append((le, value))
            elif name == fam + "_count":
                counts[_series_key(labels)] = value
            elif name == fam + "_sum":
                sums[_series_key(labels)] = value
        for key, bs in buckets.items():
            tag = f"{fam}{dict(key)}"
            bs.sort(key=lambda p: p[0])
            cum = [v for _le, v in bs]
            if any(b > a for a, b in zip(cum[1:], cum)):
                errors.append(f"{tag}: bucket counts not monotone: {cum}")
            if not bs or not math.isinf(bs[-1][0]):
                errors.append(f"{tag}: no +Inf bucket")
            elif key not in counts:
                errors.append(f"{tag}: missing _count")
            elif counts[key] != bs[-1][1]:
                errors.append(
                    f"{tag}: _count {counts[key]} != +Inf bucket "
                    f"{bs[-1][1]}")
            if key not in sums:
                errors.append(f"{tag}: missing _sum")
        for key in counts:
            if key not in buckets:
                errors.append(f"{fam}{dict(key)}: _count with no "
                              f"buckets")
    return errors


def quantile_from_buckets(bs: List[Tuple[float, float]], q: float
                          ) -> Optional[float]:
    """The one copy of the ``le``-bucket interpolation Prometheus's
    ``histogram_quantile`` uses: ``bs`` is ``[(le, cumulative_count)]``
    sorted ascending, ending with the ``+Inf`` bucket. Samples past the
    last finite edge clamp to it; an empty series is None. Shared by
    ``Histogram.quantile`` (in-memory) and ``histogram_quantile``
    (scraped) so the two paths cannot drift."""
    if not bs or bs[-1][1] <= 0:
        return None
    total = bs[-1][1]
    rank = q * total
    prev_edge, prev_cum = 0.0, 0.0
    for le, cum in bs:
        if cum >= rank:
            in_bucket = cum - prev_cum
            if math.isinf(le):
                return prev_edge       # clamp to last finite edge
            frac = (rank - prev_cum) / in_bucket if in_bucket else 0.0
            return prev_edge + (le - prev_edge) * frac
        prev_edge, prev_cum = le, cum
    return prev_edge


def fraction_le_from_buckets(bs: List[Tuple[float, float]],
                             threshold: float) -> Optional[float]:
    """Fraction of observations ≤ ``threshold`` — the inverse of
    ``quantile_from_buckets``, with the same linear interpolation inside
    the containing bucket. ``bs`` is ``[(le, cumulative_count)]`` sorted
    ascending, ending with ``+Inf``. Mass in the ``+Inf`` bucket counts
    as ABOVE any finite threshold (the conservative reading). None on an
    empty series. This is the one copy of the SLO-attainment arithmetic:
    the live engine (obs/slo.py) and bench.py's scraped
    ``slo_*_attainment`` fields both run it."""
    if not bs or bs[-1][1] <= 0:
        return None
    total = bs[-1][1]
    prev_edge, prev_cum = 0.0, 0.0
    for le, cum in bs:
        if threshold <= le:
            if math.isinf(le):
                return prev_cum / total
            in_bucket = cum - prev_cum
            width = le - prev_edge
            frac = (threshold - prev_edge) / width if width > 0 else 1.0
            return (prev_cum + in_bucket * frac) / total
        prev_edge, prev_cum = le, cum
    return 1.0


def _series_buckets(text_or_samples, family: str,
                    labels: Optional[Dict[str, str]]
                    ) -> List[Tuple[float, float]]:
    if isinstance(text_or_samples, str):
        samples, _types, _errors = parse_exposition(text_or_samples)
    else:
        samples = text_or_samples
    want = _series_key(labels or {})
    bs: List[Tuple[float, float]] = []
    for name, slabels, value in samples:
        if name == family + "_bucket" and "le" in slabels \
                and _series_key(slabels) == want:
            bs.append((_parse_value(slabels["le"]), value))
    bs.sort(key=lambda p: p[0])
    return bs


def histogram_fraction_le(text_or_samples, family: str, threshold: float,
                          labels: Optional[Dict[str, str]] = None
                          ) -> Optional[float]:
    """Fraction of one scraped histogram series' observations ≤
    ``threshold`` (SLO attainment against a latency target). Series
    selection matches ``histogram_quantile``; None when the series is
    absent or empty."""
    return fraction_le_from_buckets(
        _series_buckets(text_or_samples, family, labels), threshold)


def histogram_quantile(text_or_samples, family: str, q: float,
                       labels: Optional[Dict[str, str]] = None
                       ) -> Optional[float]:
    """Estimate the q-quantile of one scraped histogram series.
    ``labels`` selects the series (``le`` excluded); None matches only
    the unlabeled series. Returns None when the series is absent or
    empty."""
    return quantile_from_buckets(
        _series_buckets(text_or_samples, family, labels), q)
