"""Mid-stream request recovery: worker death becomes a resume, not an
error.

The reference claims "fast detection of instance error and automatic
rescheduling" but never implements re-dispatch (SURVEY.md §5.3); this
service used to redispatch only refusal-class failures (503 / refused
connection) *before* any work started — a worker dying mid-generation
cancelled every in-flight request. This module closes that gap
(docs/ROBUSTNESS.md) for both response topologies:

- **relay streaming**: the front door forwards the stream through a
  ledger-aware relay (the worker's ``"xllm"`` frame extension carries
  token ids, stripped before bytes reach the client). When the worker
  socket breaks mid-stream, the relay re-schedules onto a survivor,
  re-prefills prompt + delivered tokens as forced context, and splices
  the continuation into the still-open SSE stream.
- **RPC fan-in**: the scheduler's delivered-token ledger is fed by
  ``handle_generation``; when ``fail_requests_on_instance`` fires for
  a recoverable request, ``begin_rpc_resume`` re-dispatches the same
  forced-context resume to a survivor, whose pushes continue into the
  same per-request fan-in queue.

Exactly-once is by construction: the resume prompt IS the delivered
ledger, so the survivor only ever generates tokens the client has not
seen (no gap — the ledger is contiguous by frame order; no repeat —
forced tokens are prompt, never re-emitted), and a straggler push from
the deposed instance is dropped by the scheduler's source guard. At
``temperature=0`` the continuation is byte-identical to an unfailed
run (greedy decoding depends only on the forced context).

Recoverable = streaming relay or RPC topology, single choice (``n==1``,
no ``best_of`` pool), no ``echo``/``logprobs`` (their offsets/prompt
scores don't survive a re-prefill), no stop strings (a stop spanning
the failure boundary could over-generate), no multimodal inputs —
within a per-request resume budget (``XLLM_RECOVERY_RETRIES``).
Everything else keeps today's behavior: a prompt, countable error.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from xllm_service_tpu.config import ServiceOptions
from xllm_service_tpu.obs.spans import REQUEST_ID_HEADER
from xllm_service_tpu.service.instance_types import RequestPhase
from xllm_service_tpu.service.response_handler import SSE_DONE, sse_frame
from xllm_service_tpu.utils.locks import make_lock
from xllm_service_tpu.utils.retry import RetryPolicy
from xllm_service_tpu.utils.threads import spawn
from xllm_service_tpu.utils.types import (
    Request as SchedRequest, Routing, Usage)

logger = logging.getLogger(__name__)

# Zero-copy relay scan (the saturation sweep's spent finding,
# docs/PERF_NOTES.md service-plane round): when on, RelayLedger
# forwards plain mid-stream delta frames VERBATIM after a pure
# substring scan instead of json.loads + re-serialization per frame.
# Read once at import — hot-path flag discipline (docs/FLAGS.md).
RELAY_ZEROCOPY = os.environ.get(
    "XLLM_RELAY_ZEROCOPY", "").strip() in ("1", "true", "yes")


class RecoveryManager:
    """Per-service recovery policy + mechanics. Wired onto the
    scheduler by HttpService (``scheduler.recovery``), like
    spans/obs."""

    def __init__(self, opts: ServiceOptions, scheduler, spans, events,
                 obs, failpoints) -> None:
        self.opts = opts
        self.scheduler = scheduler
        self.spans = spans
        self.events = events
        self.obs = obs
        self.failpoints = failpoints
        self.enabled = os.environ.get("XLLM_RECOVERY", "1") != "0"
        # Per-request resume budget: how many times one request may be
        # failed over before it becomes a client-visible error.
        try:
            self.budget = int(
                os.environ.get("XLLM_RECOVERY_RETRIES", "") or 2)
        except ValueError:
            self.budget = 2
        self.retry = RetryPolicy.from_env()
        # Render both outcomes from boot so dashboards (and the chaos
        # tests' scrapes) see the series before the first failover.
        c = self._recoveries()
        c.inc(0.0, result="success")
        c.inc(0.0, result="failed")

    def _recoveries(self):
        return self.obs.counter(
            "xllm_request_recoveries_total",
            "mid-stream failovers by outcome (success = the stream "
            "resumed on a survivor; failed = budget/alternates "
            "exhausted and the client saw an error)",
            labelnames=("result",))

    # ------------------------------------------------------------------
    # Policy
    # ------------------------------------------------------------------
    def recoverable(self, req: SchedRequest) -> bool:
        """Whether this request's contract survives a forced-context
        re-prefill (module docstring). Callers additionally gate on
        topology (relay requires ``req.stream``)."""
        if not self.enabled or self.budget <= 0:
            return False
        sp = req.sampling
        return (sp.n == 1 and (sp.best_of or 1) <= 1 and not sp.echo
                and not sp.logprobs and not sp.stop
                and not req.mm_inputs)

    def arm(self, req: SchedRequest, fwd: Dict[str, Any], path: str,
            owner: str) -> Dict[str, Any]:
        """Attach a recovery context to the tracked request. For the
        relay topology this also switches the forward to the ledger
        extension (the worker emits token ids per frame; the relay
        strips them)."""
        if owner == "relay":
            fwd["ledger_tokens"] = True
        ctx: Dict[str, Any] = {
            "owner": owner, "fwd": fwd, "path": path,
            "budget": self.budget, "resumes": 0, "recovered": False,
            "resuming": False, "failed": set()}
        self.scheduler.arm_recovery(req.service_request_id, ctx)
        return ctx

    # ------------------------------------------------------------------
    # Shared mechanics
    # ------------------------------------------------------------------
    def resume_fwd(self, fwd: Dict[str, Any], req: SchedRequest,
                   delivered: List[int]) -> Dict[str, Any]:
        """The resume forward body: prompt + delivered ledger as forced
        context, completion budget reduced by what the client already
        has. The worker sees an ordinary request — the resume-accept
        path is its normal prefill."""
        fwd2 = dict(fwd)
        fwd2["token_ids"] = list(req.token_ids) + list(delivered)
        sp = dict(fwd.get("sampling") or req.sampling.to_json())
        sp["max_tokens"] = max(
            int(req.sampling.max_tokens) - len(delivered), 1)
        fwd2["sampling"] = sp
        return fwd2

    def reroute(self, req: SchedRequest, fwd: Dict[str, Any],
                exclude=()) -> Tuple[Optional[str], Optional[str]]:
        """Pick a surviving instance for ``req``, excluding every
        already-failed one, reversing the schedule bookkeeping of
        rejected candidates and of the instance the request is leaving.
        Rewrites ``fwd["routing"]`` and retargets the request registry.
        Returns ``(instance_name, address)`` or ``(None, None)``."""
        sched = self.scheduler
        mgr = sched.instance_mgr
        orig_routing = req.routing
        old = req.routing.prefill_name if req.routing else ""
        exclude = set(exclude)
        if old:
            exclude.add(old)
        if self.failpoints is not None and \
                self.failpoints.fire(
                    "service.fail_redispatch") is not None:
            return None, None
        n_prompt = len(req.token_ids)
        tries = min(8, max(2, len(mgr.names())))
        last_rejected = None
        for _ in range(tries):
            status, routing = sched.schedule(req)
            if not status.ok:
                # The scheduler's refusal (admission, model placement)
                # is authoritative — the pool fallback below is
                # model-blind and must not route around it.
                req.routing = orig_routing
                return None, None
            name = routing.prefill_name
            addr = mgr.address_of(name)
            if name in exclude or addr is None:
                # Rejected candidate (already failed, or gone between
                # schedule and address lookup): undo its SCHEDULE
                # increment and try the next alternate.
                mgr.update_request_metrics(
                    name, RequestPhase.UNSCHEDULE, n_prompt)
                exclude.add(name)
                if name == last_rejected:
                    # A deterministic policy (cache-aware / SLO-aware)
                    # returns the same winner every call — looping the
                    # remaining tries cannot help.
                    break
                last_rejected = name
                continue
            if routing.kv_fetch and \
                    routing.kv_fetch.get("holder") in exclude:
                # The freshly planned fetch elects an instance this
                # walk already failed AWAY from (typically the dead
                # worker, whose published prefix digests outlive it
                # until lease expiry): executing it would stall the
                # survivor's recovery TTFT on the fetch timeout before
                # the recompute fallback. Drop the plan, keep the
                # placement.
                routing.kv_fetch = None
            return self._adopt_routing(req, fwd, routing, old, n_prompt)
        # Policy fallback: a deterministic policy can keep electing an
        # excluded instance (e.g. the dead one still prefix-matches the
        # forced context best until its lease expires). Recovery must
        # not exhaust its budget on that — pick the least-loaded
        # survivor directly from the pool.
        pool = [n for n in mgr.prefill_instances()
                if n not in exclude and mgr.address_of(n) is not None]
        pool = mgr.filter_model_awake(pool, req.model)
        name = mgr.least_loaded_instance(pool) if pool else None
        if name is None:
            # Failed walk: schedule() left req.routing on the last
            # REJECTED candidate (whose SCHEDULE increment was already
            # undone). Restore the departing routing, or the caller's
            # next reroute attempt would compute old = that rejected
            # candidate and UNSCHEDULE it a second time (negative
            # ledger) while the real old instance's increment leaks.
            req.routing = orig_routing
            return None, None
        mgr.update_request_metrics(name, RequestPhase.SCHEDULE, n_prompt)
        routing = Routing(prefill_name=name, decode_name=name)
        req.routing = routing
        return self._adopt_routing(req, fwd, routing, old, n_prompt)

    def _adopt_routing(self, req: SchedRequest, fwd: Dict[str, Any],
                       routing, old: str, n_prompt: int
                       ) -> Tuple[str, str]:
        """Commit an accepted reroute: release the departed instance's
        schedule bookkeeping, retarget the registry, rewrite the
        forward body."""
        mgr = self.scheduler.instance_mgr
        if old:
            mgr.update_request_metrics(
                old, RequestPhase.UNSCHEDULE, n_prompt)
        self.scheduler.retarget_request(req.service_request_id, routing)
        fwd["routing"] = routing.to_json()
        return routing.prefill_name, mgr.address_of(routing.prefill_name)

    def note_success(self, req: SchedRequest, ctx: Dict[str, Any],
                     dead: str, to: str, delivered: int,
                     mode: str) -> None:
        ctx["recovered"] = True
        self._recoveries().inc(result="success")
        self.spans.record(req.service_request_id, "recovered",
                          from_instance=dead, to=to,
                          delivered_tokens=delivered)
        self.events.emit("request_recovered",
                         service_request_id=req.service_request_id,
                         from_instance=dead, to=to,
                         delivered=delivered, mode=mode)

    def note_failure(self, req: SchedRequest, dead: str, reason: str,
                     mode: str) -> None:
        self._recoveries().inc(result="failed")
        self.events.emit("recovery_failed",
                         service_request_id=req.service_request_id,
                         from_instance=dead, reason=reason, mode=mode)

    # ------------------------------------------------------------------
    # RPC-topology resume (driven by fail_requests_on_instance)
    # ------------------------------------------------------------------
    def begin_rpc_resume(self, tracked, dead: str) -> bool:
        """Claim one resume attempt for a tracked RPC-mode request and
        run it off-thread (the caller is the store's lease-expiry sweep
        — it must never block on worker HTTP). Returns False when the
        budget is exhausted (caller falls back to cancel)."""
        ctx = tracked.recovery
        if ctx is None or not self.enabled:
            return False
        with self.scheduler._req_lock:
            if ctx["resuming"]:
                return True         # a concurrent failure already claimed it
            if ctx["resumes"] >= ctx["budget"]:
                return False
            ctx["resuming"] = True
            ctx["resumes"] += 1
        spawn("recovery.resume_rpc", self._resume_rpc,
              args=(tracked, dead),
              thread_name=(f"recovery-"
                           f"{tracked.request.service_request_id}")
              ).start()
        return True

    def _resume_rpc(self, tracked, dead: str) -> None:
        req = tracked.request
        ctx = tracked.recovery
        srid = req.service_request_id
        ctx["failed"].add(dead)
        try:
            delivered = self.scheduler.resume_ledger(srid)
            if len(delivered) >= req.sampling.max_tokens:
                # Died between the last token and the finish delta:
                # the completion is already whole — close it out
                # locally instead of re-prefilling for zero tokens.
                self._synthesize_rpc_finish(tracked, delivered)
                self.note_success(req, ctx, dead, "(synthesized)",
                                  len(delivered), mode="rpc")
                return
            fwd2 = self.resume_fwd(ctx["fwd"], req, delivered)
            deadline = time.monotonic() + self.opts.request_timeout_s
            for attempt in range(self.retry.max_attempts):
                name, addr = self.reroute(req, fwd2, ctx["failed"])
                if name is None:
                    if not self.retry.sleep(attempt, deadline=deadline):
                        break
                    continue
                try:
                    from xllm_service_tpu.service.httpd import http_json
                    status, ack = http_json(
                        "POST", addr, ctx["path"], fwd2,
                        timeout=self.opts.request_timeout_s,
                        headers={REQUEST_ID_HEADER: srid})
                except Exception as e:  # noqa: BLE001 — survivor
                    # unreachable too: exclude it and try the next one
                    logger.warning("resume of %s on %s failed: %s",
                                   srid, name, e)
                    ctx["failed"].add(name)
                    if not self.retry.sleep(attempt, deadline=deadline):
                        break
                    continue
                if status != 200:
                    logger.warning("resume of %s on %s refused: %d %r",
                                   srid, name, status, ack)
                    ctx["failed"].add(name)
                    if not self.retry.sleep(attempt, deadline=deadline):
                        break
                    continue
                ctx["fwd"] = fwd2
                self.note_success(req, ctx, dead, name,
                                  len(delivered), mode="rpc")
                logger.info("recovered %s: %s -> %s (%d tokens "
                            "delivered)", srid, dead, name,
                            len(delivered))
                return
            # Exhausted: the client gets today's definite error.
            self.note_failure(req, dead, "no_surviving_instance",
                              mode="rpc")
            self.scheduler.count_failed("recovery_exhausted")
            self.scheduler.cancel_request(
                srid, f"instance {dead} died; recovery exhausted")
        except Exception:  # noqa: BLE001 — a resume bug must fail the
            # request cleanly, never strand the client without an answer
            logger.exception("rpc resume of %s crashed", srid)
            self.note_failure(req, dead, "resume_error", mode="rpc")
            self.scheduler.cancel_request(
                srid, f"instance {dead} died; recovery errored")
        finally:
            ctx["resuming"] = False

    def _synthesize_rpc_finish(self, tracked, delivered: List[int]
                               ) -> None:
        from xllm_service_tpu.utils.types import (
            FinishReason, RequestOutput, SequenceOutput)
        req = tracked.request
        out = RequestOutput(
            request_id=req.service_request_id,
            service_request_id=req.service_request_id,
            outputs=[SequenceOutput(index=0,
                                    finish_reason=FinishReason.LENGTH)],
            usage=Usage(prompt_tokens=len(req.token_ids),
                        completion_tokens=len(delivered)),
            finished=True)
        self.scheduler.handle_generation(out)


class RelayLedger:
    """Frame processor for one ledger-aware relay stream: parses each
    SSE payload, feeds token ids into the scheduler's delivered ledger,
    strips the ``"xllm"`` extension, and — after a resume — suppresses
    the survivor's duplicate role chunk, pins ``created`` to the
    original stream's value, and rewrites the usage chunk to the
    client-truthful counts."""

    def __init__(self, manager: RecoveryManager,
                 req: SchedRequest, is_chat: bool) -> None:
        self.manager = manager
        self.req = req
        self.is_chat = is_chat
        self.tokens_seen = 0     # every id that rode a frame (usage)
        self.content_frames = 0  # frames that delivered text/content
        self.usage_sent = False  # a usage chunk reached the client
        self.role_sent = False   # a role chunk reached the client
        self.done = False        # saw [DONE]
        self.finished = False    # saw a finish_reason chunk
        self.resumed = False
        self.created: Optional[int] = None
        self.template: Dict[str, Any] = {}

    def _zerocopy_ok(self, payload: str) -> bool:
        """True when ``payload`` is provably a plain mid-stream delta
        the ledger needs nothing from — every check is a substring scan
        against the deterministic ``sse_frame`` wire format
        (``json.dumps(obj, separators=(",", ":"))``), and ANY ambiguity
        answers False (the parsed path is always correct, just slower):

        - not resumed, and the first frame already captured the
          template/created (the first frame always parses);
        - no ``"xllm"`` ledger extension (nothing to strip or feed to
          ``note_delivered``);
        - no ``"usage"`` key (usage_sent tracking and the resumed-mode
          rewrite both need the parse);
        - exactly one ``"finish_reason"`` and it is ``null`` — every
          assembler delta carries ``"finish_reason":null`` (single
          choice: recoverable requires n==1), so a finish chunk never
          takes this path and ``finished`` stays truthful;
        - chat only: no ``"role"`` key (role chunks and ``role_sent``
          need the parse)."""
        return (not self.resumed
                and bool(self.template)
                and '"xllm"' not in payload
                and '"usage"' not in payload
                and payload.count('"finish_reason"') == 1
                and '"finish_reason":null' in payload
                and (not self.is_chat or '"role"' not in payload))

    def on_payload(self, payload: str) -> Tuple[Optional[bytes], int]:
        """One SSE payload in → (frame bytes to forward | None to
        suppress, number of NEW tokens it delivered)."""
        if payload.strip() == "[DONE]":
            self.done = True
            return SSE_DONE, 0
        if RELAY_ZEROCOPY and self._zerocopy_ok(payload):
            # Pure-delta frame: forward the worker's bytes verbatim
            # (the worker built them with the same sse_frame renderer,
            # so the client-visible shape is identical to the parsed
            # path). No ledger ext ⇒ no note_delivered; content-frame
            # detection below may only OVER-count, which fails a blind
            # resume clean instead of ever replaying content.
            if ('"content":""' if self.is_chat else '"text":""') \
                    not in payload:
                self.content_frames += 1
            return (b"data: " + payload.encode("utf-8") + b"\n\n"), 0
        try:
            obj = json.loads(payload)
        except ValueError:
            # Not a JSON chunk (defensive): forward verbatim.
            return (b"data: " + payload.encode("utf-8") + b"\n\n"), 0
        ext = obj.pop("xllm", None)
        n_new = 0
        has_text = False
        if isinstance(ext, dict) and ext.get("token_ids"):
            ids = [int(t) for t in ext["token_ids"]]
            n_new = len(ids)
            self.tokens_seen += n_new
            # Ledger semantics: only ids whose text this frame actually
            # DELIVERS are resumable-over; ids the detokenizer is still
            # holding back (empty delta) park as pending and are
            # regenerated by a resume (scheduler._ledger_append_locked).
            has_text = any(
                ((ch.get("delta") or {}).get("content") if self.is_chat
                 else ch.get("text"))
                for ch in obj.get("choices") or [])
            self.manager.scheduler.note_delivered(
                self.req.service_request_id, ids, has_text=has_text)
        if not self.template:
            self.template = {k: obj.get(k) for k in
                             ("id", "object", "model")}
            self.created = obj.get("created")
        choices = obj.get("choices") or []
        if any(((ch.get("delta") or {}).get("content") if self.is_chat
                else ch.get("text")) for ch in choices):
            self.content_frames += 1
        if not choices and isinstance(obj.get("usage"), dict):
            self.usage_sent = True
        if n_new and not has_text and \
                not isinstance(obj.get("usage"), dict) and not any(
                    ch.get("finish_reason") or
                    (self.is_chat and "role" in (ch.get("delta") or {}))
                    for ch in choices):
            # Held-back token(s) only: this frame existed to carry the
            # ledger extension just stripped (the assembler emits empty
            # deltas for UTF-8/stop holdbacks ONLY under emit_token_ids)
            # — forwarding its husk would give recoverable streams a
            # different client-visible shape than plain ones.
            return None, n_new
        if self.resumed:
            if self.created is not None and "created" in obj:
                obj["created"] = self.created
            if self.role_sent and self.is_chat and choices and \
                    not n_new and \
                    choices[0].get("delta") == {"role": "assistant"} \
                    and not choices[0].get("finish_reason"):
                # The survivor opens with a fresh role chunk; the
                # client already has one. (If the original worker died
                # before its role chunk ever reached the client, the
                # survivor's must pass through — a chat stream without
                # one is malformed.)
                return None, 0
            if not choices and isinstance(obj.get("usage"), dict):
                obj["usage"] = Usage(
                    prompt_tokens=len(self.req.token_ids),
                    completion_tokens=self.manager.scheduler
                    .delivered_total(
                        self.req.service_request_id)).to_json()
        for ch in choices:
            if ch.get("finish_reason"):
                self.finished = True
        if self.is_chat and any(
                "role" in (ch.get("delta") or {}) for ch in choices):
            self.role_sent = True
        return sse_frame(obj), n_new

    def _chunk_base(self) -> Dict[str, Any]:
        created = self.created if self.created is not None else \
            int(time.time())
        return {"id": self.template.get(
                    "id", self.req.service_request_id),
                "object": self.template.get(
                    "object", "chat.completion.chunk" if self.is_chat
                    else "text_completion"),
                "created": created,
                "model": self.template.get("model", self.req.model)}

    def _usage_frame(self, base: Dict[str, Any]) -> bytes:
        return sse_frame(dict(base, choices=[], usage=Usage(
            prompt_tokens=len(self.req.token_ids),
            completion_tokens=self.manager.scheduler.delivered_total(
                self.req.service_request_id)).to_json()))

    def close_finished(self, include_usage: bool) -> List[bytes]:
        """Close a stream whose worker died after the finish delta but
        before [DONE]: the completion is whole, but an include_usage
        client may still be owed its usage chunk — same death window
        as synthesize_finish, same client contract."""
        frames: List[bytes] = []
        if include_usage and not self.usage_sent:
            frames.append(self._usage_frame(self._chunk_base()))
        frames.append(SSE_DONE)
        self.done = True
        return frames

    def synthesize_finish(self, include_usage: bool) -> List[bytes]:
        """Close a stream whose worker died after the last token but
        before the finish delta: finish chunk (+ usage) + [DONE] from
        the captured template — no re-prefill for zero tokens."""
        base = self._chunk_base()
        if self.is_chat:
            finish = dict(base, choices=[{
                "index": 0, "delta": {}, "finish_reason": "length"}])
        else:
            finish = dict(base, choices=[{
                "index": 0, "text": "", "logprobs": None,
                "finish_reason": "length"}])
        frames = [sse_frame(finish)]
        if include_usage:
            frames.append(self._usage_frame(base))
        frames.append(SSE_DONE)
        self.finished = True
        self.done = True
        return frames


class PoisonLedger:
    """Cluster-wide strike ledger bounding a poison request's blast
    radius (docs/ROBUSTNESS.md, device-plane fault contract).

    Keyed by BOTH the service request id and the whole-prompt digest
    (``utils/hashing.prompt_digest``): each engine-fault blame from a
    worker's step fault boundary is one strike; at
    ``XLLM_POISON_STRIKES`` the request is failed to the client with
    the typed ``engine_fault`` error instead of re-scheduled, and the
    digest is quarantined for ``XLLM_POISON_TTL_S`` so an immediately
    retried identical prompt doesn't restart the rampage worker by
    worker. Pure state — events/metrics are emitted by the scheduler's
    ``note_engine_fault``, outside this lock."""

    MAX_ENTRIES = 4096      # strike-book bound; oldest entries drop

    def __init__(self, strikes: Optional[int] = None,
                 ttl_s: Optional[float] = None) -> None:
        self._lock = make_lock("service.poison", 11)
        if strikes is None:
            try:
                strikes = int(
                    os.environ.get("XLLM_POISON_STRIKES", "") or 2)
            except ValueError:
                strikes = 2
        if ttl_s is None:
            try:
                ttl_s = float(
                    os.environ.get("XLLM_POISON_TTL_S", "") or 300.0)
            except ValueError:
                ttl_s = 300.0
        self.max_strikes = max(1, strikes)
        self.ttl_s = ttl_s
        # srid-or-digest -> strikes (insertion-ordered for the bound).
        self._strikes: Dict[str, int] = {}
        self._quarantine: Dict[str, float] = {}   # digest -> expiry

    def strike(self, srid: str, digest: str) -> Tuple[int, bool]:
        """One engine-fault blame against a request. Returns
        ``(strikes, poisoned)``; when poisoned the digest enters
        quarantine."""
        now = time.monotonic()
        with self._lock:
            n = max(self._strikes.get(srid, 0),
                    self._strikes.get(digest, 0)) + 1
            for key in (srid, digest):
                self._strikes.pop(key, None)    # re-insert at the tail
                self._strikes[key] = n
            while len(self._strikes) > self.MAX_ENTRIES:
                self._strikes.pop(next(iter(self._strikes)))
            poisoned = n >= self.max_strikes
            if poisoned:
                self._quarantine[digest] = now + self.ttl_s
        return n, poisoned

    def quarantined(self, digest: str) -> bool:
        """Admission gate: True while ``digest`` is inside its
        quarantine TTL (expired entries clean up lazily, strikes
        included — a post-TTL retry starts from a clean slate)."""
        now = time.monotonic()
        with self._lock:
            expiry = self._quarantine.get(digest)
            if expiry is None:
                return False
            if now >= expiry:
                self._quarantine.pop(digest, None)
                self._strikes.pop(digest, None)
                return False
            return True

    def state(self) -> Dict[str, Any]:
        """Debug-bundle snapshot: live strike counts and quarantined
        digests with remaining TTL."""
        now = time.monotonic()
        with self._lock:
            return {
                "strikes": dict(self._strikes),
                "quarantined": {
                    d: round(exp - now, 3)
                    for d, exp in self._quarantine.items()
                    if exp > now},
                "max_strikes": self.max_strikes,
                "ttl_s": self.ttl_s}
