"""Minimal threaded HTTP substrate: routed server + JSON/SSE client helpers.

The reference runs two brpc servers (HTTP front door + worker RPC) and brpc
channels between processes (master.cpp:60-140, instance_mgr.cpp:523-551).
This module is the rebuild's equivalent transport: a stdlib-only threaded
HTTP/1.1 server with a route table and chunked/SSE streaming responses, and
client helpers for JSON calls and progressive SSE reads (the reference's
``ProgressiveReader``, http_service/service.cpp:113-143). All of this is
host-side CPU code on the TPU-VM — the data plane (tokens) is tiny compared
to the compute, so HTTP/JSON over DCN matches the reference's control-plane
role without vendoring an RPC stack.
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
import time
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Tuple)
from urllib.parse import parse_qs, urlparse

from xllm_service_tpu.utils.locks import make_lock
from xllm_service_tpu.utils.threads import spawn


class Request:
    def __init__(self, method: str, path: str, query: Dict[str, List[str]],
                 headers: Dict[str, str], body: bytes) -> None:
        self.method = method
        self.path = path
        self.query = query
        # HTTP header names are case-insensitive; normalize to lowercase
        # so lookups like headers.get("x-request-id") always hit.
        self.headers = {k.lower(): v for k, v in headers.items()}
        self.body = body

    def param(self, name: str, default: str = "") -> str:
        vals = self.query.get(name)
        return vals[0] if vals else default

    def json(self) -> Any:
        if not self.body:
            return {}
        return json.loads(self.body.decode("utf-8"))


class Response:
    """``body`` for buffered responses; ``stream`` (an iterator of byte
    chunks) for progressive/SSE responses — chunks are flushed as produced."""

    def __init__(self, status: int = 200, body: Optional[bytes] = None,
                 content_type: str = "application/json",
                 headers: Optional[Dict[str, str]] = None,
                 stream: Optional[Iterable[bytes]] = None,
                 on_close: Optional[Callable[[], None]] = None) -> None:
        self.status = status
        self.body = body if body is not None else b""
        self.content_type = content_type
        self.headers = headers or {}
        self.stream = stream
        # Invoked by the server EXACTLY when it is done with this
        # response — including when a stream body is never iterated
        # (failed header write): a never-STARTED generator's finally
        # does not run on close (PEP 342), so cleanup that must always
        # happen belongs here, not in the generator.
        self.on_close = on_close

    @classmethod
    def json(cls, obj: Any, status: int = 200) -> "Response":
        return cls(status=status,
                   body=json.dumps(obj).encode("utf-8"))

    @classmethod
    def error(cls, status: int, message: str,
              err_type: str = "invalid_request_error") -> "Response":
        """OpenAI-style error envelope."""
        return cls.json(
            {"error": {"message": message, "type": err_type, "code": status}},
            status=status)

    @classmethod
    def sse(cls, chunks: Iterable[bytes]) -> "Response":
        return cls(content_type="text/event-stream",
                   headers={"Cache-Control": "no-cache"}, stream=chunks)


Handler = Callable[[Request], Response]


class Admission:
    """Server-wide concurrent-request limit — the rebuild of brpc's
    ``max_concurrency`` backpressure (reference global_gflags.cpp:33-48,
    applied to both servers in master.cpp:60-140). Past the limit a new
    request gets an immediate 503 + Retry-After instead of an unbounded
    thread pile-up; a 503 is exactly the refusal class the service's
    re-dispatch path already handles, so worker-side overload shifts
    load instead of failing requests.

    ``limit`` may be an int, None (unlimited), or a zero-arg callable
    returning either — the callable form reads a live options object so
    ``/admin/flags`` hot-reload applies without a restart. A slot is
    held for the FULL handler lifetime including streaming, so long SSE
    responses count toward the limit (they hold a server thread)."""

    def __init__(self, limit=None) -> None:
        self._limit = limit
        self._active = 0
        self._lock = threading.Lock()
        self.rejected_total = 0

    def _current_limit(self) -> Optional[int]:
        lim = self._limit() if callable(self._limit) else self._limit
        return None if not lim or lim <= 0 else lim

    @property
    def active(self) -> int:
        return self._active

    def try_enter(self) -> bool:
        with self._lock:
            lim = self._current_limit()
            if lim is not None and self._active >= lim:
                self.rejected_total += 1
                return False
            self._active += 1
            return True

    def probe(self) -> bool:
        """Advisory admission check WITHOUT claiming a slot (counts a
        rejection). Used by the native front door to shed large-body
        uploads at header-complete time, before buffering the body; the
        authoritative ``try_enter`` still runs at dispatch."""
        with self._lock:
            lim = self._current_limit()
            if lim is not None and self._active >= lim:
                self.rejected_total += 1
                return False
            return True

    def leave(self) -> None:
        with self._lock:
            self._active -= 1


# Admission bites at REQUEST ENTRY (client-facing /v1/*), never on
# control-plane or continuation traffic:
# - liveness (heartbeats), observability, and the knobs to RAISE the
#   limit must not be starved by the congestion they diagnose;
# - /rpc/* carries workers' pushes for ALREADY-admitted requests
#   (generations fan-in) — shedding those doesn't reduce load, it
#   corrupts in-flight streams (tokens silently dropped).
# Servers with other continuation/control verbs extend this list
# (worker.py: /sleep, /kv/import, /encode, ...).
_ADMISSION_EXEMPT = ("/metrics", "/hello", "/admin/", "/rpc/")


class Router:
    """Exact-path and prefix routes per method."""

    def __init__(self) -> None:
        self._exact: Dict[Tuple[str, str], Handler] = {}
        self._prefix: List[Tuple[str, str, Handler]] = []

    def route(self, method: str, path: str, handler: Handler) -> None:
        self._exact[(method.upper(), path)] = handler

    def route_prefix(self, method: str, prefix: str,
                     handler: Handler) -> None:
        self._prefix.append((method.upper(), prefix, handler))

    def dispatch(self, req: Request) -> Response:
        h = self._exact.get((req.method, req.path))
        if h is None:
            for method, prefix, ph in self._prefix:
                if req.method == method and req.path.startswith(prefix):
                    h = ph
                    break
        if h is None:
            return Response.error(404, f"no route for {req.method} {req.path}")
        try:
            return h(req)
        except Exception as e:  # noqa: BLE001 — route errors become 500s
            import traceback
            traceback.print_exc()
            return Response.error(500, f"internal error: {e}",
                                  "internal_error")


class _RequestHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # Keep-alive + Nagle is poison: a small response segment can sit for
    # the ~40 ms delayed-ACK window before the next one flushes.
    disable_nagle_algorithm = True
    # Idle keep-alive connections must not pin their server thread
    # forever (ThreadingHTTPServer is thread-per-connection): close them
    # after this long with no next request. Clients evict pooled
    # connections well before this (see _ConnPool._MAX_IDLE_S), so a
    # reused client socket is never one the server already killed.
    timeout = 60.0
    router: Router       # set by server factory
    admission: Optional[Admission] = None      # set by server factory
    admission_exempt: Tuple[str, ...] = _ADMISSION_EXEMPT

    def log_message(self, fmt: str, *args: Any) -> None:  # quiet
        pass

    def _handle(self) -> None:
        parsed = urlparse(self.path)
        # Admission runs BEFORE the body read: a shed request must not
        # pay an unbounded (or slow-loris) upload on a server thread —
        # the reject path closes the connection instead of draining.
        admitted = (self.admission is None
                    or parsed.path.startswith(self.admission_exempt)
                    or self.admission.try_enter())
        if not admitted:
            self.close_connection = True
            try:
                self._write(Response(
                    status=503,
                    body=json.dumps({"error": {
                        "message": "server at max_concurrency",
                        "type": "overloaded_error",
                        "code": 503}}).encode("utf-8"),
                    headers={"Retry-After": "1", "Connection": "close"}))
            except (BrokenPipeError, ConnectionResetError):
                pass
            return
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        req = Request(self.command, parsed.path, parse_qs(parsed.query),
                      dict(self.headers.items()), body)
        try:
            resp = self.router.dispatch(req)
        except BaseException:
            if self.admission is not None \
                    and not parsed.path.startswith(self.admission_exempt):
                self.admission.leave()
            raise
        try:
            self._write(resp)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-stream
        finally:
            if self.admission is not None \
                    and not parsed.path.startswith(self.admission_exempt):
                self.admission.leave()
            # Run a STARTED stream generator's finally first, then the
            # response-level cleanup (covers the never-started case).
            if resp.stream is not None and hasattr(resp.stream, "close"):
                try:
                    resp.stream.close()
                except Exception:  # noqa: BLE001 — best-effort cleanup;
                    pass            # the response is already resolved
            if resp.on_close is not None:
                try:
                    resp.on_close()
                except Exception:  # noqa: BLE001 — a failing finish hook
                    pass            # must not poison this server thread

    def _write(self, resp: Response) -> None:
        self.send_response(resp.status)
        self.send_header("Content-Type", resp.content_type)
        for k, v in resp.headers.items():
            self.send_header(k, v)
        if resp.stream is not None:
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            for chunk in resp.stream:
                if not chunk:
                    continue
                # One write (one TCP segment under NODELAY) per frame —
                # size line + payload + CRLF as three writes tripled the
                # syscall count of every streamed token.
                self.wfile.write(b"".join(
                    (f"{len(chunk):X}\r\n".encode(), chunk, b"\r\n")))
                self.wfile.flush()
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        else:
            self.send_header("Content-Length", str(len(resp.body)))
            self.end_headers()
            if resp.body:
                self.wfile.write(resp.body)
                self.wfile.flush()

    do_GET = _handle
    do_POST = _handle
    do_PUT = _handle
    do_DELETE = _handle


class PyHttpServer:
    """Threaded HTTP server bound to (host, port); port 0 picks a free one.

    ``max_concurrency``: int / None / zero-arg callable — see
    ``Admission``. Control-plane paths (``_ADMISSION_EXEMPT``) bypass it."""

    def __init__(self, host: str, port: int, router: Router,
                 max_concurrency=None,
                 admission_exempt: Tuple[str, ...] = _ADMISSION_EXEMPT
                 ) -> None:
        self.admission = (Admission(max_concurrency)
                          if max_concurrency is not None else None)
        handler = type("BoundHandler", (_RequestHandler,),
                       {"router": router, "admission": self.admission,
                        "admission_exempt": tuple(admission_exempt)})
        self._srv = ThreadingHTTPServer((host, port), handler)
        self._srv.daemon_threads = True
        self.host = host
        self.port = self._srv.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "HttpServer":
        self._thread = spawn(
            "httpd.serve", self._srv.serve_forever,
            thread_name=f"httpd-{self.port}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def HttpServer(host: str, port: int, router: Router,  # noqa: N802
               max_concurrency=None,
               admission_exempt: Tuple[str, ...] = _ADMISSION_EXEMPT):
    """Server factory: the native epoll front door (csrc/xllm_httpd.cpp,
    the brpc-shaped event loop) when the library builds, else the
    pure-Python threaded server. ``XLLM_NATIVE_HTTPD=0`` forces Python.
    Both expose the same surface: ``start/stop/address/port/admission``."""
    try:
        from xllm_service_tpu.service.native_httpd import NativeHttpServer
        return NativeHttpServer(host, port, router,
                                max_concurrency=max_concurrency,
                                admission_exempt=admission_exempt)
    except (OSError, ImportError):
        # Library unavailable, module missing from a partial deployment,
        # or port-bind raced: the Python server's bind surfaces a genuine
        # port conflict identically.
        return PyHttpServer(host, port, router,
                            max_concurrency=max_concurrency,
                            admission_exempt=admission_exempt)


# ---------------------------------------------------------------------------
# Client helpers
# ---------------------------------------------------------------------------

class _NoDelayHTTPConnection(HTTPConnection):
    """TCP_NODELAY client connection — on a reused keep-alive socket the
    header and body writes are separate small segments, and with Nagle on
    the second waits out the peer's delayed-ACK timer (~40 ms p50 measured
    on the service bench)."""

    def connect(self) -> None:
        super().connect()
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


class _ConnPool:
    """Keep-alive HTTPConnection pool per address — the rebuild of the
    reference's per-instance brpc channel cache (instance_mgr.cpp:
    523-551). A fresh TCP connect per service→worker call costs a
    round-trip and a server thread spawn on every request; checked-out
    connections return here after a clean exchange instead.

    Staleness is handled by AVOIDANCE, not by blind retry (re-sending a
    non-idempotent POST could run an inference twice or repeat a CAS):
    pooled connections are discarded once idle longer than
    ``_MAX_IDLE_S``, well under the server's 60 s keep-alive timeout, so
    a reused socket is never one the peer already closed. Dead
    instances' sockets age out of the pool the same way (a periodic
    sweep piggybacks on ``put``)."""

    _MAX_IDLE_PER_ADDR = 8
    _MAX_IDLE_S = 20.0
    _SWEEP_INTERVAL_S = 5.0

    def __init__(self) -> None:
        # address -> [(conn, last_used_monotonic)]
        self._idle: Dict[str, List[Tuple[HTTPConnection, float]]] = {}
        self._lock = make_lock("httpd.connpool", 92)
        self._last_sweep = 0.0
        # Reuse counters (served at /metrics): a transport regression —
        # peers closing keep-alives early, the idle window mistuned, the
        # per-address cap too small under fan-out — shows up here as a
        # falling hit:miss ratio or climbing overflow before it shows up
        # as p50 latency in service_bench. Mutated under _lock.
        self.hits_total = 0        # get() satisfied from the pool
        self.misses_total = 0      # get() had to open a fresh TCP conn
        self.overflow_total = 0    # put() dropped a conn (addr cap full)
        self.expired_total = 0     # idle conns aged out (sweep or get)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits_total": self.hits_total,
                    "misses_total": self.misses_total,
                    "overflow_total": self.overflow_total,
                    "expired_total": self.expired_total,
                    "idle": sum(len(v) for v in self._idle.values())}

    def get(self, address: str, timeout: float
            ) -> Tuple[HTTPConnection, bool]:
        """→ (connection, reused)."""
        now = time.monotonic()
        stale: List[HTTPConnection] = []
        conn = None
        with self._lock:
            conns = self._idle.get(address)
            while conns:
                cand, last = conns.pop()
                if now - last <= self._MAX_IDLE_S:
                    conn = cand
                    break
                stale.append(cand)
            stale.extend(self._sweep_locked(now))
            self.expired_total += len(stale)
            if conn is not None:
                self.hits_total += 1
            else:
                self.misses_total += 1
        for c in stale:
            c.close()
        if conn is not None:
            conn.timeout = timeout
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            return conn, True
        return _NoDelayHTTPConnection(address, timeout=timeout), False

    def put(self, address: str, conn: HTTPConnection) -> None:
        now = time.monotonic()
        evicted: List[HTTPConnection] = []
        with self._lock:
            conns = self._idle.setdefault(address, [])
            if len(conns) < self._MAX_IDLE_PER_ADDR:
                conns.append((conn, now))
                conn = None
            else:
                self.overflow_total += 1
            swept = self._sweep_locked(now)
            self.expired_total += len(swept)
            evicted.extend(swept)
        if conn is not None:
            evicted.append(conn)
        for c in evicted:
            c.close()

    def _sweep_locked(self, now: float) -> List[HTTPConnection]:
        """Age out every address's idle conns (deregistered workers are
        never requested again — without this their sockets would sit in
        CLOSE_WAIT until process exit). Time-gated so any pool traffic,
        however light, triggers it; called with the lock held."""
        if now - self._last_sweep < self._SWEEP_INTERVAL_S:
            return []
        self._last_sweep = now
        evicted: List[HTTPConnection] = []
        for addr in list(self._idle):
            kept = [(c, t) for (c, t) in self._idle[addr]
                    if now - t <= self._MAX_IDLE_S]
            evicted.extend(c for (c, t) in self._idle[addr]
                           if now - t > self._MAX_IDLE_S)
            if kept:
                self._idle[addr] = kept
            else:
                del self._idle[addr]
        return evicted


_POOL = _ConnPool()


def conn_pool_stats() -> Dict[str, int]:
    """Process-wide keep-alive pool counters for /metrics exporters."""
    return _POOL.stats()


def flush_conn_pool_metrics(registry, plane: str) -> None:
    """Mirror the pool counters into an obs registry under the exporting
    plane's label (the pool is process-global; co-located planes export
    the same series under distinct labels instead of colliding). Shared
    by both planes' /metrics handlers so the series shapes can't drift."""
    for k, v in conn_pool_stats().items():
        name = f"xllm_http_conn_pool_{k}"
        if k.endswith("_total"):
            registry.counter(name, labelnames=("plane",)).set_total(
                v, plane=plane)
        else:
            registry.gauge(name, labelnames=("plane",)).set(
                v, plane=plane)

# Failures while SENDING on a reused socket — the request never reached
# the peer whole, so one fresh-connection retry cannot double-execute it.
_SEND_ERRORS = (http.client.CannotSendRequest, ConnectionResetError,
                BrokenPipeError, ConnectionAbortedError)


def http_json(method: str, address: str, path: str, obj: Any = None,
              timeout: float = 30.0,
              headers: Optional[Dict[str, str]] = None
              ) -> Tuple[int, Any]:
    """One JSON request to ``address`` ("host:port") over a pooled
    keep-alive connection. Returns (status, parsed-json-or-None)."""
    body = None if obj is None else json.dumps(obj).encode("utf-8")
    hdrs = {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    while True:
        conn, reused = _POOL.get(address, timeout)
        try:
            conn.request(method, path, body=body, headers=hdrs)
        except _SEND_ERRORS:
            conn.close()
            if reused:
                continue      # request never delivered — safe to retry
            raise
        except Exception:
            conn.close()
            raise
        try:
            resp = conn.getresponse()
            data = resp.read()
            parsed = json.loads(data.decode("utf-8")) if data else None
        except http.client.RemoteDisconnected:
            # Peer closed without ANY response. On a reused socket this
            # almost always means the peer restarted and the kernel RST'd
            # a dead connection the idle-age eviction missed — the new
            # process never saw the request, so retry once on a fresh
            # connection (urllib3's default for exactly this case). The
            # residual received-then-crashed-before-responding window is
            # the same one a fresh connection has.
            conn.close()
            if reused:
                continue
            raise
        except Exception:
            # Other response-phase failure: the peer may have executed
            # the request — no retry, surface it to the caller.
            conn.close()
            raise
        if resp.will_close:
            conn.close()
        else:
            _POOL.put(address, conn)
        return resp.status, parsed


def http_stream_status(method: str, address: str, path: str,
                       obj: Any = None, timeout: float = 600.0,
                       headers: Optional[Dict[str, str]] = None,
                       raw: Optional[bytes] = None
                       ) -> Tuple[int, Iterator[bytes]]:
    """Like ``http_stream`` but connects EAGERLY and returns
    (status, body-iterator) so callers can act on the status (e.g.
    re-dispatch a 503) before relaying any bytes. The caller must
    exhaust or close the iterator once it has been started; a non-200
    body should simply be drained (it is small)."""
    conn = _NoDelayHTTPConnection(address, timeout=timeout)
    try:
        if raw is not None:
            body = raw
            hdrs = {"Content-Type": "application/octet-stream"}
        else:
            body = None if obj is None else json.dumps(obj).encode("utf-8")
            hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        conn.request(method, path, body=body, headers=hdrs)
        resp = conn.getresponse()
    except Exception:
        conn.close()
        raise

    return resp.status, _StreamBody(resp, conn)


class _StreamBody:
    """Iterable response body that is ALSO closeable without having been
    iterated — closing a never-started generator cannot run its finally
    (PEP 342), but dropping the connection must always be possible."""

    def __init__(self, resp, conn) -> None:
        self._resp = resp
        self._conn = conn

    def __iter__(self) -> Iterator[bytes]:
        try:
            while True:
                chunk = self._resp.read1(65536)
                if not chunk:
                    return
                yield chunk
        finally:
            self._conn.close()

    def close(self) -> None:
        self._conn.close()


def http_stream(method: str, address: str, path: str, obj: Any = None,
                timeout: float = 600.0,
                headers: Optional[Dict[str, str]] = None,
                raw: Optional[bytes] = None
                ) -> Iterator[bytes]:
    """Progressive byte-chunk reader (reference CustomProgressiveReader,
    service.cpp:113-143): yields raw chunks as they arrive. ``raw`` sends
    an octet-stream body instead of JSON (KV migration payloads)."""
    _, body = http_stream_status(method, address, path, obj=obj,
                                 timeout=timeout, headers=headers, raw=raw)
    yield from body


def iter_sse_events(chunks: Iterable[bytes]) -> Iterator[str]:
    """Reassemble SSE ``data:`` payloads from a progressive byte stream."""
    buf = b""
    for chunk in chunks:
        buf += chunk
        while b"\n\n" in buf:
            event, buf = buf.split(b"\n\n", 1)
            for line in event.decode("utf-8").splitlines():
                if line.startswith("data: "):
                    yield line[len("data: "):]
                elif line.startswith("data:"):
                    yield line[len("data:"):]
