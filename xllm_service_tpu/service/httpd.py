"""Minimal threaded HTTP substrate: routed server + JSON/SSE client helpers.

The reference runs two brpc servers (HTTP front door + worker RPC) and brpc
channels between processes (master.cpp:60-140, instance_mgr.cpp:523-551).
This module is the rebuild's equivalent transport: a stdlib-only threaded
HTTP/1.1 server with a route table and chunked/SSE streaming responses, and
client helpers for JSON calls and progressive SSE reads (the reference's
``ProgressiveReader``, http_service/service.cpp:113-143). All of this is
host-side CPU code on the TPU-VM — the data plane (tokens) is tiny compared
to the compute, so HTTP/JSON over DCN matches the reference's control-plane
role without vendoring an RPC stack.
"""

from __future__ import annotations

import json
import socket
import threading
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Tuple)
from urllib.parse import parse_qs, urlparse


class Request:
    def __init__(self, method: str, path: str, query: Dict[str, List[str]],
                 headers: Dict[str, str], body: bytes) -> None:
        self.method = method
        self.path = path
        self.query = query
        # HTTP header names are case-insensitive; normalize to lowercase
        # so lookups like headers.get("x-request-id") always hit.
        self.headers = {k.lower(): v for k, v in headers.items()}
        self.body = body

    def param(self, name: str, default: str = "") -> str:
        vals = self.query.get(name)
        return vals[0] if vals else default

    def json(self) -> Any:
        if not self.body:
            return {}
        return json.loads(self.body.decode("utf-8"))


class Response:
    """``body`` for buffered responses; ``stream`` (an iterator of byte
    chunks) for progressive/SSE responses — chunks are flushed as produced."""

    def __init__(self, status: int = 200, body: Optional[bytes] = None,
                 content_type: str = "application/json",
                 headers: Optional[Dict[str, str]] = None,
                 stream: Optional[Iterable[bytes]] = None) -> None:
        self.status = status
        self.body = body if body is not None else b""
        self.content_type = content_type
        self.headers = headers or {}
        self.stream = stream

    @classmethod
    def json(cls, obj: Any, status: int = 200) -> "Response":
        return cls(status=status,
                   body=json.dumps(obj).encode("utf-8"))

    @classmethod
    def error(cls, status: int, message: str,
              err_type: str = "invalid_request_error") -> "Response":
        """OpenAI-style error envelope."""
        return cls.json(
            {"error": {"message": message, "type": err_type, "code": status}},
            status=status)

    @classmethod
    def sse(cls, chunks: Iterable[bytes]) -> "Response":
        return cls(content_type="text/event-stream",
                   headers={"Cache-Control": "no-cache"}, stream=chunks)


Handler = Callable[[Request], Response]


class Router:
    """Exact-path and prefix routes per method."""

    def __init__(self) -> None:
        self._exact: Dict[Tuple[str, str], Handler] = {}
        self._prefix: List[Tuple[str, str, Handler]] = []

    def route(self, method: str, path: str, handler: Handler) -> None:
        self._exact[(method.upper(), path)] = handler

    def route_prefix(self, method: str, prefix: str,
                     handler: Handler) -> None:
        self._prefix.append((method.upper(), prefix, handler))

    def dispatch(self, req: Request) -> Response:
        h = self._exact.get((req.method, req.path))
        if h is None:
            for method, prefix, ph in self._prefix:
                if req.method == method and req.path.startswith(prefix):
                    h = ph
                    break
        if h is None:
            return Response.error(404, f"no route for {req.method} {req.path}")
        try:
            return h(req)
        except Exception as e:  # noqa: BLE001 — route errors become 500s
            import traceback
            traceback.print_exc()
            return Response.error(500, f"internal error: {e}",
                                  "internal_error")


class _RequestHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    router: Router  # set by server factory

    def log_message(self, fmt: str, *args: Any) -> None:  # quiet
        pass

    def _handle(self) -> None:
        parsed = urlparse(self.path)
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        req = Request(self.command, parsed.path, parse_qs(parsed.query),
                      dict(self.headers.items()), body)
        resp = self.router.dispatch(req)
        try:
            self._write(resp)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-stream

    def _write(self, resp: Response) -> None:
        self.send_response(resp.status)
        self.send_header("Content-Type", resp.content_type)
        for k, v in resp.headers.items():
            self.send_header(k, v)
        if resp.stream is not None:
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            for chunk in resp.stream:
                if not chunk:
                    continue
                self.wfile.write(f"{len(chunk):X}\r\n".encode())
                self.wfile.write(chunk)
                self.wfile.write(b"\r\n")
                self.wfile.flush()
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        else:
            self.send_header("Content-Length", str(len(resp.body)))
            self.end_headers()
            if resp.body:
                self.wfile.write(resp.body)
                self.wfile.flush()

    do_GET = _handle
    do_POST = _handle
    do_PUT = _handle
    do_DELETE = _handle


class HttpServer:
    """Threaded HTTP server bound to (host, port); port 0 picks a free one."""

    def __init__(self, host: str, port: int, router: Router) -> None:
        handler = type("BoundHandler", (_RequestHandler,),
                       {"router": router})
        self._srv = ThreadingHTTPServer((host, port), handler)
        self._srv.daemon_threads = True
        self.host = host
        self.port = self._srv.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "HttpServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name=f"httpd-{self.port}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread:
            self._thread.join(timeout=5)


# ---------------------------------------------------------------------------
# Client helpers
# ---------------------------------------------------------------------------

def http_json(method: str, address: str, path: str, obj: Any = None,
              timeout: float = 30.0,
              headers: Optional[Dict[str, str]] = None
              ) -> Tuple[int, Any]:
    """One JSON request to ``address`` ("host:port"). Returns
    (status, parsed-json-or-None)."""
    conn = HTTPConnection(address, timeout=timeout)
    try:
        body = None if obj is None else json.dumps(obj).encode("utf-8")
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        conn.request(method, path, body=body, headers=hdrs)
        resp = conn.getresponse()
        data = resp.read()
        parsed = json.loads(data.decode("utf-8")) if data else None
        return resp.status, parsed
    finally:
        conn.close()


def http_stream(method: str, address: str, path: str, obj: Any = None,
                timeout: float = 600.0,
                headers: Optional[Dict[str, str]] = None,
                raw: Optional[bytes] = None
                ) -> Iterator[bytes]:
    """Progressive byte-chunk reader (reference CustomProgressiveReader,
    service.cpp:113-143): yields raw chunks as they arrive. ``raw`` sends
    an octet-stream body instead of JSON (KV migration payloads)."""
    conn = HTTPConnection(address, timeout=timeout)
    try:
        if raw is not None:
            body = raw
            hdrs = {"Content-Type": "application/octet-stream"}
        else:
            body = None if obj is None else json.dumps(obj).encode("utf-8")
            hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        conn.request(method, path, body=body, headers=hdrs)
        resp = conn.getresponse()
        if resp.status != 200:
            yield resp.read()
            return
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                return
            yield chunk
    finally:
        conn.close()


def iter_sse_events(chunks: Iterable[bytes]) -> Iterator[str]:
    """Reassemble SSE ``data:`` payloads from a progressive byte stream."""
    buf = b""
    for chunk in chunks:
        buf += chunk
        while b"\n\n" in buf:
            event, buf = buf.split(b"\n\n", 1)
            for line in event.decode("utf-8").splitlines():
                if line.startswith("data: "):
                    yield line[len("data: "):]
                elif line.startswith("data:"):
                    yield line[len("data:"):]
