"""Scheduler: per-request orchestration + cluster state + HA election.

Rebuild of ``scheduler/scheduler.{h,cpp}`` (SURVEY.md §2 #4, §3.2-3.5):

- ``schedule(request)``: chat template → tokenize → model heat → route
  (serverless awake/allocate for multi-model; the configured LB policy for
  the PD pair — composed, fixing the reference quirk where ``schedule()``
  bypasses ``lb_policy_``, scheduler.cpp:100-119 TODO, SURVEY.md §7.4);
- request registry keyed by ``service_request_id`` with per-request output
  callbacks (scheduler.cpp:197-302);
- token fan-in through N single-thread pools with per-request pinning so
  token order is preserved (scheduler.h:113-120, via
  ``utils.misc.OrderedFanInPools``);
- master election: ``compare_create`` on ``XLLM:SERVICE:MASTER`` with a TTL
  lease + keepalive; replicas watch the key and take over on expiry
  (scheduler.cpp:25-66, 158-175); the master uploads aggregated load
  metrics and the KV-cache index every ``master_upload_interval_s``
  (scheduler.cpp:138-146).
"""

from __future__ import annotations

import logging
import os
import threading


import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from xllm_service_tpu.config import ServiceOptions
from xllm_service_tpu.nlp.chat_template import ChatTemplate
from xllm_service_tpu.obs import profiler
from xllm_service_tpu.nlp.tokenizer import Tokenizer, TokenizerFactory
from xllm_service_tpu.service.coordination import (
    KEY_EPOCH_PREFIX, KEY_MASTER, KEY_MASTER_ADDR, CoordinationStore)
from xllm_service_tpu.service.instance_mgr import InstanceMgr
from xllm_service_tpu.service.store_guard import (
    EpochFencedError, StoreGuard)
from xllm_service_tpu.service.instance_types import (
    Heartbeat, RequestPhase)
from xllm_service_tpu.service.kvcache_mgr import GlobalKVCacheMgr
from xllm_service_tpu.service.lb_policy import create_policy
from xllm_service_tpu.service.recovery import PoisonLedger
from xllm_service_tpu.utils.hashing import prompt_digest
from xllm_service_tpu.utils.misc import OrderedFanInPools, short_uuid
from xllm_service_tpu.utils import threads
from xllm_service_tpu.utils.threads import spawn
from xllm_service_tpu.utils.types import (
    OutputCallback, Request, RequestOutput, Routing, Status, StatusCode)
from xllm_service_tpu.utils.locks import make_lock

logger = logging.getLogger(__name__)


def _env_float(raw: Optional[str], default: float) -> float:
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


class _TrackedRequest:
    __slots__ = ("request", "output_callback", "created",
                 "prefill_name", "decode_name", "prefill_done",
                 "num_generated", "delivered", "pending", "recovery")

    def __init__(self, request: Request,
                 output_callback: OutputCallback) -> None:
        self.request = request
        self.output_callback = output_callback
        self.created = time.monotonic()
        self.prefill_name = request.routing.prefill_name
        self.decode_name = request.routing.decode_name
        self.prefill_done = False
        self.num_generated = 0
        # Delivered-token ledger: token ids whose TEXT has reached the
        # client (choice 0), appended by handle_generation (RPC fan-in)
        # or note_delivered (ledger-aware relay). Mid-stream recovery
        # re-prefills prompt + this ledger as forced context, so the
        # continuation is exactly-once by construction
        # (docs/ROBUSTNESS.md). ``pending`` holds ids the detokenizer
        # is still holding back (UTF-8 / multi-token grapheme): their
        # text was NEVER sent, so on recovery they are left OUT of the
        # forced context and regenerated — counting them as delivered
        # would silently drop their text at the resume boundary.
        self.delivered: List[int] = []
        self.pending: List[int] = []
        # Recovery context (service/recovery.py arms it): owner
        # ("relay"|"rpc"), the rewritten forward body + path needed to
        # resume, the per-request resume budget, and progress flags.
        # None = not recoverable; fail_requests_on_instance cancels.
        self.recovery: Optional[Dict[str, Any]] = None


class Scheduler:
    def __init__(self, opts: ServiceOptions, store: CoordinationStore,
                 control=None,
                 model_memory_gb: Optional[Dict[str, float]] = None,
                 serverless_models: Optional[List[str]] = None,
                 events=None) -> None:
        self.opts = opts
        self.store = store
        self.service_id = f"service-{short_uuid(8)}"
        # Decision-attributable observability (all optional — standalone
        # schedulers in unit tests run without them): the cluster event
        # log (obs.EventLog, shared with InstanceMgr/HttpService), and
        # the service plane's span ring + registry, wired by Master
        # AFTER HttpService exists so routing audits land on the
        # request's span and in xllm_schedule_decisions_total.
        self.events = events
        self.spans = None
        self.obs = None
        # Mid-stream failover (service/recovery.py, wired by HttpService
        # post-construction like spans/obs): when set,
        # fail_requests_on_instance hands recoverable RPC-mode requests
        # to it instead of cancelling, and relay-owned recoverable
        # requests are left to their relay generator's own resume loop.
        self.recovery = None
        # Poison ledger (service/recovery.py PoisonLedger): cluster-wide
        # engine-fault strikes keyed by request id AND prompt digest.
        # note_engine_fault() is the single strike point for every
        # response topology (docs/ROBUSTNESS.md).
        self.poison = PoisonLedger()

        self.tokenizer: Tokenizer = TokenizerFactory.create_tokenizer(
            opts.tokenizer_path)
        self.chat_template = ChatTemplate.from_model_dir(opts.tokenizer_path)

        # --- leader election (scheduler.cpp:25-66) -----------------------
        # Election triple: the role flag plus the fenced epochs
        # (docs/ROBUSTNESS.md). ``epoch`` is the monotonic epoch THIS
        # replica minted when it last won an election (0 = never won);
        # ``_cluster_epoch`` is the highest epoch observed anywhere. A
        # master whose epoch trails the cluster's has been deposed and
        # must demote, never write.
        self._elect_mu = make_lock("scheduler.elect", 88)
        self.is_master = False       # guarded-by: scheduler.elect
        self.epoch = 0               # guarded-by: scheduler.elect
        self._cluster_epoch = 0      # guarded-by: scheduler.elect
        self._lease_id = store.lease_grant(
            max(3 * opts.heartbeat_interval_s, 3.0))
        won = store.compare_create(
            KEY_MASTER, self.service_id, self._lease_id)
        epoch = self._mint_epoch() if won else self._read_cluster_epoch()
        with self._elect_mu:
            self.is_master = won
            if won:
                self.epoch = epoch
            self._cluster_epoch = max(self._cluster_epoch, epoch)
        self._master_watch: Optional[int] = None  # guarded-by: scheduler.elect
        self._epoch_watch: Optional[int] = store.add_watch(
            KEY_EPOCH_PREFIX, self._on_epoch_event)
        if not won:
            self._master_watch = store.add_watch(
                KEY_MASTER, self._on_master_event)
        elif self.events is not None:
            self.events.emit("master_elected", service_id=self.service_id,
                             how="boot", epoch=epoch)
        # Store-guard integration (service/store_guard.py): fence every
        # master-authored write against a higher observed epoch, and
        # resync + maybe self-demote the moment an outage heals. Raw
        # stores (standalone schedulers in unit tests) skip both.
        if isinstance(store, StoreGuard):
            store.fence_check = self._fenced
            store.on_heal(self._on_store_heal)

        self.instance_mgr = InstanceMgr(
            opts, store, is_master=self.is_master, control=control,
            model_memory_gb=model_memory_gb,
            serverless_models=serverless_models, events=self.events)
        self.kvcache_mgr = GlobalKVCacheMgr(
            store, block_size=opts.block_size, seed=opts.murmur_hash3_seed,
            is_master=self.is_master)
        self.instance_mgr.on_removed = self._on_instance_removed
        self.lb_policy = create_policy(opts, self.instance_mgr,
                                       self.kvcache_mgr)

        # Fetch-vs-recompute cost model knobs (docs/KV_CACHE.md). Read
        # once at construction — the planner runs per request. Direct
        # os.environ reads with literal names so the flag-registry
        # xlint rule sees every one.
        self.kv_fetch_enabled = os.environ.get(
            "XLLM_KV_FETCH", "1").strip() not in ("0", "false", "no")
        # Fallbacks for the measured terms when no signal arrived yet:
        # per-pair bandwidth (GB/s; 1.0 ≈ the round-6 measured direct
        # migration rate) and prefill throughput (tok/s).
        self.kv_fetch_gbps_default = _env_float(
            os.environ.get("XLLM_KV_FETCH_GBPS"), 1.0)
        self.kv_fetch_toks_default = _env_float(
            os.environ.get("XLLM_KV_FETCH_TOKS"), 4000.0)
        # Fixed per-fetch overhead (handshake + scatter) and the minimum
        # fetched-block count worth that overhead.
        self.kv_fetch_overhead_ms = _env_float(
            os.environ.get("XLLM_KV_FETCH_OVERHEAD_MS"), 5.0)
        self.kv_fetch_min_blocks = int(_env_float(
            os.environ.get("XLLM_KV_FETCH_MIN_BLOCKS"), 1))

        self._addresses: Optional[Dict[str, str]] = None
        # Tracked-request registry: every mutation site (admission,
        # fan-in delivery, finish, recovery retarget) holds _req_lock.
        self._requests: Dict[str, _TrackedRequest] = {}  # guarded-by: scheduler.req
        self._req_lock = make_lock("scheduler.req", 10)
        self._pools = OrderedFanInPools(opts.num_output_pools)

        self._stop = threading.Event()
        # Supervised + restarted: the master keepalive loop IS the
        # replica's claim to the master lease — a crashed loop means a
        # spurious failover. events resolves lazily (the EventLog is
        # attached by Master post-construction).
        self._hb_thread = spawn(
            "scheduler.master_loop", self._master_loop,
            thread_name="scheduler-master-loop",
            restart=threads.RESTART_POLICY,
            events=lambda: self.events, stop=self._stop)
        self._hb_thread.start()

    # ------------------------------------------------------------------
    # Election / master loop
    # ------------------------------------------------------------------
    def _mint_epoch(self) -> int:
        """Mint the next monotonic master epoch: compare_create on
        ``XLLM:SERVICE:EPOCH:<n>`` (no lease — the ledger outlives every
        master) one past the highest existing entry. Loses the race →
        reads again and tries the next slot."""
        for _ in range(64):
            n = self._read_cluster_epoch() + 1
            if self.store.compare_create(KEY_EPOCH_PREFIX + str(n),
                                         self.service_id):
                return n
        raise RuntimeError("could not mint a master epoch in 64 tries "
                           "(epoch ledger churning?)")

    def _read_cluster_epoch(self) -> int:
        """Highest epoch in the store's ledger (0 when empty)."""
        best = 0
        for key in self.store.get_prefix(KEY_EPOCH_PREFIX):
            try:
                best = max(best, int(key[len(KEY_EPOCH_PREFIX):]))
            except ValueError:
                continue
        return best

    def current_epoch(self) -> int:
        """The epoch stamped on beat-acks and ``/rpc/config`` — workers
        reject acks that regress (runtime/worker.py)."""
        with self._elect_mu:
            return self.epoch if self.is_master else self._cluster_epoch

    def _fenced(self) -> bool:
        """Store-guard write fence: True = this replica believes it is
        master but a higher epoch exists → every write must be rejected
        (EpochFencedError) until it demotes."""
        with self._elect_mu:
            return self.is_master and self._cluster_epoch > self.epoch

    def _become_master(self, how: str) -> None:
        """Post-``compare_create``-win bookkeeping: mint the fencing
        epoch, then flip the role triple under the elect lock (store
        ops first, lock second — the lock never spans a store call)."""
        epoch = self._mint_epoch()
        with self._elect_mu:
            self.is_master = True
            self.epoch = epoch
            self._cluster_epoch = max(self._cluster_epoch, epoch)
            self.instance_mgr.is_master = True
            self.kvcache_mgr.is_master = True
        self._publish_addresses()
        if self.events is not None:
            self.events.emit("master_elected", service_id=self.service_id,
                             how=how, epoch=epoch)

    def _demote(self, how: str, cluster_epoch: Optional[int] = None) -> bool:
        """Stop being master (lost re-election, or fenced by a higher
        epoch). Returns False if we already weren't."""
        with self._elect_mu:
            if cluster_epoch is not None:
                self._cluster_epoch = max(self._cluster_epoch,
                                          cluster_epoch)
            if not self.is_master:
                return False
            my_epoch = self.epoch
            observed = self._cluster_epoch
            self.is_master = False
            self.instance_mgr.is_master = False
            self.kvcache_mgr.is_master = False
        if self.events is not None:
            self.events.emit("master_demoted", service_id=self.service_id,
                             how=how, epoch=my_epoch,
                             cluster_epoch=observed)
        logger.warning("%s demoted (%s): epoch %d vs cluster %d",
                       self.service_id, how, my_epoch, observed)
        try:
            self._ensure_master_watch()
        except Exception as e:  # noqa: BLE001 — store flapping; the next
            # heal/takeover path re-adds the watch
            logger.warning("re-adding master watch failed: %s", e)
        return True

    def _ensure_master_watch(self) -> None:
        """Re-add the KEY_MASTER vacancy watch if absent. The
        ``add_watch`` store call runs OUTSIDE scheduler.elect (store
        locks rank below it); the double-check under the lock cancels
        the loser when two demote paths race (epoch-watch thread vs
        master loop)."""
        with self._elect_mu:
            if self._master_watch is not None:
                return
        wid: Optional[int] = self.store.add_watch(
            KEY_MASTER, self._on_master_event)
        with self._elect_mu:
            if self._master_watch is None:
                self._master_watch = wid
                wid = None
        if wid is not None:
            try:
                self.store.cancel_watch(wid)
            except Exception:  # noqa: BLE001 — duplicate watch is benign
                pass

    def _on_epoch_event(self, event) -> None:
        """Epoch-ledger watch (all replicas): track the cluster's
        highest epoch; a master seeing a HIGHER one has been deposed
        (another replica won an election it couldn't see) and
        self-demotes instead of dual-serving."""
        ev_type, key, _value = event
        if ev_type != "PUT":
            return
        try:
            n = int(key[len(KEY_EPOCH_PREFIX):])
        except ValueError:
            return
        with self._elect_mu:
            self._cluster_epoch = max(self._cluster_epoch, n)
            deposed = self.is_master and self._cluster_epoch > self.epoch
        if deposed:
            self._demote(how="higher-epoch")

    def _on_store_heal(self) -> None:
        """Store-guard heal callback, run synchronously on the thread
        whose call healed the outage and BEFORE that call returns: a
        deposed master demotes before it can author a single stale
        write, and the instance books resync against what actually
        happened in the store while we were blind."""
        if self._stop.is_set():
            return
        try:
            cluster = self._read_cluster_epoch()
        except Exception as e:  # noqa: BLE001 — store flapping mid-heal;
            # the next successful call re-runs this path
            logger.warning("post-heal epoch read failed: %s", e)
            return
        with self._elect_mu:
            self._cluster_epoch = max(self._cluster_epoch, cluster)
            deposed = self.is_master and self._cluster_epoch > self.epoch
        if deposed:
            self._demote(how="healed-behind")
        try:
            self.instance_mgr.resync_from_store()
        except Exception as e:  # noqa: BLE001 — resync is re-runnable;
            # heartbeats keep the books converging meanwhile
            logger.warning("post-heal instance resync failed: %s", e)

    @property
    def degraded(self) -> bool:
        """True while the coordination store is DOWN and this replica is
        serving from the frozen last-known-good instance table."""
        return bool(getattr(self.store, "is_down", False))

    def store_health(self) -> int:
        """The ``xllm_store_health`` gauge value (2/1/0; raw stores
        report healthy)."""
        h = getattr(self.store, "health", None)
        return 2 if h is None else int(h)

    def _on_master_event(self, event) -> None:
        ev_type, _key, _value = event
        if ev_type != "DELETE" or self._stop.is_set():
            return
        # Master lease expired → try to take over (scheduler.cpp:158-175).
        try:
            won = self.store.compare_create(KEY_MASTER, self.service_id,
                                            self._lease_id)
            if won:
                self._become_master(how="takeover")
                logger.info("%s took over as master", self.service_id)
        except Exception as e:  # noqa: BLE001 — store outage mid-takeover;
            # the next master-key DELETE (or heal) retries the election
            logger.warning("master takeover attempt failed: %s", e)

    def announce(self, rpc_addr: str, http_addr: str) -> None:
        """Record this replica's reachable addresses; the current master
        publishes them under ``KEY_MASTER_ADDR`` (its lease) so workers
        retarget heartbeats/pushes after a takeover."""
        self._addresses = {"service_id": self.service_id,
                           "rpc": rpc_addr, "http": http_addr}
        if self.is_master:
            self._publish_addresses()

    def _publish_addresses(self) -> None:
        if getattr(self, "_addresses", None):
            try:
                # Epoch-stamped master-authored write: workers ignore an
                # advert regressing below the epoch they've acked.
                self.store.put_json(
                    KEY_MASTER_ADDR,
                    dict(self._addresses, epoch=self.current_epoch()),
                    self._lease_id)
            except Exception as e:  # noqa: BLE001 — store hiccup; retried
                logger.warning("publish master addr failed: %s", e)

    def _on_lease_lost(self) -> None:
        """Keepalive said the lease is gone (partition outlived the TTL):
        whatever we were, that identity is dead. Grant a fresh lease, try
        to win the (possibly vacant) election; otherwise demote — a stale
        master must NOT keep writing LOADMETRICS/CACHE alongside the
        takeover master (split-brain)."""
        if self.is_master and self.store.get(KEY_MASTER) == self.service_id:
            # Keepalive can return False on a transport blip (e.g. the
            # etcd gateway 502ing one call) while the lease is actually
            # alive. If we still own the master key, the lease has NOT
            # expired (expiry deletes the key) — don't self-demote over
            # one bad RPC; a genuine expiry shows up next tick as a
            # deleted/foreign key.
            return
        was_master = self.is_master
        if self.events is not None:
            self.events.emit("master_lease_lost",
                             service_id=self.service_id,
                             was_master=was_master)
        self._lease_id = self.store.lease_grant(
            max(3 * self.opts.heartbeat_interval_s, 3.0))
        if self.store.compare_create(KEY_MASTER, self.service_id,
                                     self._lease_id):
            # Winning mints a FRESH epoch even when we were master
            # before the expiry — any replica that took over in between
            # sits at a lower epoch now and fences itself out.
            self._become_master(how="re-elected")
            if was_master:
                logger.warning("%s lease expired but election was vacant; "
                               "re-elected with a fresh lease",
                               self.service_id)
        else:
            if not self._demote(how="lost-re-election"):
                # Already a replica (watch may have died with a store
                # reconnect) — just make sure we hear the next vacancy
                # (_demote re-adds it itself on a real demotion).
                self._ensure_master_watch()
            if was_master:
                logger.warning(
                    "%s demoted: lease expired and %s took over",
                    self.service_id, self.store.get(KEY_MASTER))

    def _degraded_tick(self) -> None:
        """One master-loop tick while the store is DOWN: keep serving
        from the frozen last-known-good table, with liveness judged by
        the direct worker→master heartbeats that still flow during a
        store-only outage. Only an instance that stopped BEATING for a
        full lease TTL is dropped — lease expiry is frozen and is not
        evidence of death (docs/ROBUSTNESS.md outage contract)."""
        if not self.is_master:
            return
        ttl = max(3 * self.opts.heartbeat_interval_s, 3.0)
        for name in self.instance_mgr.stale_instances(ttl):
            logger.warning("degraded mode: %s silent for > %.1fs of "
                           "direct beats, removing", name, ttl)
            self.instance_mgr.remove_instance(name)

    def _master_loop(self) -> None:
        """Keepalive + periodic state upload (scheduler.cpp:138-146)."""
        interval = self.opts.master_upload_interval_s
        while not self._stop.wait(interval):
            # The keepalive runs in its own try: an EXCEPTION means the
            # store is unreachable (outage — hold the role, freeze the
            # table, serve degraded), while a clean False means the
            # store is healthy and says the lease is dead (expiry —
            # re-run the election). Collapsing the two is how a store
            # hiccup used to turn into a spurious failover.
            try:
                with profiler.section("store.call"):
                    lease_alive = self.store.lease_keepalive(
                        self._lease_id)
            except Exception as e:  # noqa: BLE001 — outage; the guard
                # tracks health and fires the heal callback later
                logger.debug("keepalive unreachable (store outage?): %s", e)
                self._degraded_tick()
                continue
            try:
                if not lease_alive:
                    self._on_lease_lost()
                if self.instance_mgr.post_heal_resync_due():
                    # Settle window over: reconcile the DELETEs the
                    # post-heal deferral skipped (instance_mgr).
                    self.instance_mgr.resync_from_store(settle=False)
                if self.is_master:
                    self.instance_mgr.upload_load_metrics()
                    self.kvcache_mgr.upload_kvcache()
                    # Self-heal the address advertisement (lost store
                    # write, or the key expired with a previous lease).
                    if self._addresses is not None \
                            and self.store.get(KEY_MASTER_ADDR) is None:
                        self._publish_addresses()
            except EpochFencedError:
                # The guard refused a write because a higher epoch
                # exists: we are deposed — demote NOW, don't retry.
                self._demote(how="fenced-write")
            except Exception as e:  # noqa: BLE001 — store hiccup, retry next tick
                logger.warning("master loop error: %s", e)

    # ------------------------------------------------------------------
    # schedule (scheduler.cpp:70-131)
    # ------------------------------------------------------------------
    def preprocess(self, request: Request) -> None:
        """Chat template + tokenize (fills prompt/token_ids/mm_inputs)."""
        with profiler.section("tokenize"):
            if request.messages and not request.prompt:
                prompt, mm = self.chat_template.apply(request.messages)
                request.prompt = prompt
                if mm:
                    request.mm_inputs = mm
            if not request.token_ids and request.prompt:
                request.token_ids = self.tokenizer.encode(request.prompt)

    def schedule(self, request: Request) -> Tuple[Status, Routing]:
        with profiler.section("schedule"):
            return self._schedule_impl(request)

    def _schedule_impl(self, request: Request) -> Tuple[Status, Routing]:
        if not request.service_request_id:
            request.service_request_id = f"req-{short_uuid()}"
        try:
            self.preprocess(request)
        except Exception as e:  # noqa: BLE001 — bad template/input is a 400
            return Status(StatusCode.INVALID_ARGUMENT, str(e)), Routing()
        if not request.token_ids:
            return Status(StatusCode.INVALID_ARGUMENT,
                          "empty prompt"), Routing()
        # Poison-pill quarantine (docs/ROBUSTNESS.md, device-plane
        # fault contract): an identical prompt already crossed
        # XLLM_POISON_STRIKES engine-fault blames — refuse here, AFTER
        # preprocess (the digest is over the post-template token ids,
        # the same ids note_engine_fault strikes on), instead of
        # letting a retry restart the rampage worker by worker.
        if self.quarantined_digest(request.token_ids):
            return Status(StatusCode.INTERNAL,
                          "request quarantined: an identical prompt "
                          "repeatedly faulted the engine "
                          "(engine_fault, XLLM_POISON_TTL_S)"), Routing()

        if request.model:
            self.instance_mgr.update_model_heat(request.model)

        # Per-decision audit: the policy fills in the candidates it
        # considered, each candidate's score terms, and the winner;
        # _record_decision attaches it to the request's span and bumps
        # xllm_schedule_decisions_total{policy,reason}.
        audit: Dict[str, Any] = {}
        # Serverless multi-model path: the target must have the model awake
        # (scheduler.cpp:100-119 → instance_mgr.cpp:1087-1185).
        if request.model and self.instance_mgr.serverless_models:
            name = self.instance_mgr.get_awake_instance(request.model)
            how = "awake"
            if name is None:
                name = self.instance_mgr.allocate_instance_for_model(
                    request.model)
                how = "allocated"
            audit.update(policy="serverless", model=request.model,
                         reason=how if name else "no_instance",
                         prefill={"winner": name},
                         decode={"winner": name})
            if name is None:
                self._record_decision(request, audit)
                return Status(StatusCode.UNAVAILABLE,
                              f"no instance for model {request.model}"
                              ), Routing()
            routing = Routing(prefill_name=name, decode_name=name)
        else:
            prefill, decode = self.lb_policy.select_instances_pair(
                request.token_ids, audit=audit)
            if prefill is None:
                audit.setdefault("reason", "no_instance")
                self._record_decision(request, audit)
                return Status(StatusCode.UNAVAILABLE,
                              "no prefill instance available"), Routing()
            routing = Routing(prefill_name=prefill,
                              decode_name=decode or prefill)
        # Cross-worker cached-block fetch plan: when the placed prefill
        # target is not the (best) holder of this prompt's cached
        # prefix, decide fetch / partial-fetch / recompute on the
        # measured cost terms; the decision and both terms land in the
        # routing audit (attrs.schedule_decision) so wins are
        # attributed, not asserted.
        if not request.mm_inputs:
            routing.kv_fetch = self._plan_kv_fetch(
                request.token_ids, routing.prefill_name, audit,
                model=request.model)
        else:
            # EPD: cost-aware encode pick (queue depth + measured encode
            # ms + embed-cache hit credit from heartbeats — docs/EPD.md).
            # BEFORE _record_decision so the pick's terms land in the
            # schedule_decision audit like every other routing choice.
            from xllm_service_tpu.runtime.multimodal import image_digest
            # Same seed as the workers' embed caches — a seed mismatch
            # only mis-estimates cache hits, never correctness (the
            # worker re-digests with its own seed).
            digests = [image_digest(m, self.opts.murmur_hash3_seed)
                       for m in request.mm_inputs]
            enc, fallbacks = self.instance_mgr.select_encode_instance(
                digests, audit=audit)
            if enc:
                routing.encode_name = enc
                routing.encode_fallbacks = fallbacks
        self._record_decision(request, audit)

        request.routing = routing
        self.instance_mgr.update_request_metrics(
            routing.prefill_name, RequestPhase.SCHEDULE,
            len(request.token_ids))
        return Status(), routing

    # Tier-dependent effective-rate discount on the fetch term: HBM and
    # DRAM blocks stream at the measured wire rate (the holder gathers /
    # reads host RAM); SSD blocks pay the holder's disk read first.
    _FETCH_TIER_RATE = {"hbm": 1.0, "dram": 1.0, "ssd": 0.25}

    def _count_fetch_verdict(self, verdict: str) -> None:
        if self.obs is not None:
            self.obs.counter(
                "xllm_kv_fetch_decisions_total",
                "fetch-vs-recompute planner outcomes for prompts with a "
                "nonzero cluster prefix match (docs/KV_CACHE.md)",
                labelnames=("verdict",)).inc(verdict=verdict)

    def _plan_kv_fetch(self, token_ids: List[int], prefill_name: str,
                       audit: Dict[str, Any], model: str = ""
                       ) -> Optional[Dict[str, Any]]:
        """Fetch-vs-recompute cost model (NetKV-style bandwidth-aware
        choice; PAPERS.md 2606.03910): matched tokens ÷ measured prefill
        tok/s (recompute) vs matched bytes ÷ measured per-pair bandwidth
        (fetch), per block so a tier change mid-prefix can cut the fetch
        short (partial). Returns the Routing.kv_fetch plan, or None for
        recompute / local-hit / nothing-cached. Observe-only beyond the
        plan: the audit gains ``kv_fetch`` with the verdict and both
        cost terms. Reuses the cache-aware policy's index walk when the
        audit carries one (``_match_tiers``) — one prefix match per
        schedule(), not two."""
        if not self.kv_fetch_enabled or not prefill_name \
                or not token_ids:
            return None
        if not self.instance_mgr.digest_ok(prefill_name):
            # The TARGET's hashing is quarantined: any plan it executes
            # computes mismatched digests the holder can never serve —
            # a guaranteed 404 added to TTFT on every warm prompt.
            return None
        pre = audit.pop("_match_tiers", None)
        if pre is not None:
            matched, holders = pre
        else:
            matched, _scores, holders = \
                self.kvcache_mgr.match_prefix_tiers(token_ids)
        if not matched:
            return None         # cold prompt: no decision to attribute
        # The digest index is MODEL-BLIND (digests hash token ids only)
        # while KV bytes are model-specific: a holder is eligible only
        # when its PRIMARY model — the one whose engine feeds its cache
        # heartbeats — is the model this request runs (the target's
        # primary when the request names none). Same-shape fine-tunes
        # would otherwise swap KV silently.
        target_inst = self.instance_mgr.get(prefill_name)
        want_model = model or (
            target_inst.meta.models[0]
            if target_inst and target_inst.meta.models else "")
        if not want_model:
            return None
        # Liveness: a dead-but-lease-alive holder stalls the requester
        # for the whole fetch timeout (the mid-stream-recovery reroute
        # case) — on the master, whose heartbeat clock is live, skip
        # holders that stopped beating. Replicas learn load via the
        # master's uploads, not heartbeats, so their clock would lie.
        now = time.monotonic()
        stale_s = 3.0 * max(self.opts.heartbeat_interval_s, 0.1)
        local_blocks = len(holders.get(prefill_name, ()))
        best_name: Optional[str] = None
        best_tiers: List[str] = []
        for name, tiers in holders.items():
            if name == prefill_name or len(tiers) <= len(best_tiers):
                continue
            inst = self.instance_mgr.get(name)
            if inst is None or not inst.digest_compatible:
                continue
            if not (inst.meta.models
                    and inst.meta.models[0] == want_model):
                continue
            if self.is_master and now - inst.last_heartbeat > stale_s:
                continue
            best_name, best_tiers = name, list(tiers)
        bs = max(self.opts.block_size, 1)
        plan: Optional[Dict[str, Any]] = None
        terms: Dict[str, Any] = {
            "holder": best_name, "holder_blocks": len(best_tiers),
            "local_blocks": local_blocks, "matched_blocks": matched,
            "block_size": bs,
        }
        if best_name is None or len(best_tiers) <= local_blocks:
            verdict = "local" if local_blocks else "recompute"
            if verdict == "recompute":
                terms["reason"] = "no_remote_holder"
        else:
            holder_inst = self.instance_mgr.get(best_name)
            target_inst = self.instance_mgr.get(prefill_name)
            holder_addr = self.instance_mgr.address_of(best_name) or ""
            block_bytes = (holder_inst.meta.kv_block_bytes
                           if holder_inst else 0)
            # max() guards both terms: XLLM_KV_FETCH_GBPS=0 (or a
            # zeroed fallback) must degrade to an absurd fetch price —
            # i.e. verdict recompute — never a ZeroDivisionError inside
            # schedule().
            gbps = max((holder_inst.latency.kv_gbps
                        if holder_inst else 0.0)
                       or self.kv_fetch_gbps_default, 1e-9)
            tok_s = (target_inst.latency.prefill_tok_s
                     if target_inst else 0.0) or self.kv_fetch_toks_default
            recompute_ms_per_block = bs / max(tok_s, 1e-6) * 1e3
            terms.update(bandwidth_gbps=round(gbps, 3),
                         prefill_tok_s=round(tok_s, 1),
                         block_bytes=block_bytes)
            if not block_bytes or not holder_addr:
                verdict = "recompute"
                terms["reason"] = ("no_block_bytes" if not block_bytes
                                   else "holder_unreachable")
            else:
                # Walk the holder's surplus blocks; stop at the first
                # block whose (tier-discounted) fetch cost loses to
                # recomputing it.
                fetch_ms = 0.0
                n_fetch = 0
                for tier in best_tiers[local_blocks:]:
                    rate = self._FETCH_TIER_RATE.get(tier, 1.0)
                    blk_ms = block_bytes / (gbps * 1e9 * rate) * 1e3
                    if blk_ms >= recompute_ms_per_block:
                        break
                    fetch_ms += blk_ms
                    n_fetch += 1
                recompute_ms = n_fetch * recompute_ms_per_block
                terms.update(fetch_ms=round(
                    fetch_ms + self.kv_fetch_overhead_ms, 3),
                    recompute_ms=round(recompute_ms, 3))
                surplus = len(best_tiers) - local_blocks
                if n_fetch < self.kv_fetch_min_blocks or \
                        fetch_ms + self.kv_fetch_overhead_ms \
                        >= recompute_ms:
                    verdict = "recompute"
                    terms["reason"] = "fetch_loses"
                else:
                    verdict = "fetch" if n_fetch == surplus else "partial"
                    plan = {"holder": best_name,
                            "holder_addr": holder_addr,
                            "blocks": local_blocks + n_fetch,
                            "block_size": bs}
        terms["verdict"] = verdict
        audit["kv_fetch"] = terms
        self._count_fetch_verdict(verdict)
        return plan

    def _record_decision(self, request: Request,
                         audit: Dict[str, Any]) -> None:
        """Attach the routing audit to the request's span and aggregate
        the outcome. Observe-only: never influences the decision. A
        re-dispatch runs schedule() again and overwrites the span's
        ``schedule_decision`` with the decision that actually stuck (the
        ``redispatch`` stage event keeps the history)."""
        if not audit:
            return
        # Planner working state (popped there on the normal path; a
        # multimodal request skips the planner) — never span material.
        audit.pop("_match_tiers", None)
        if self.spans is not None:
            self.spans.annotate(request.service_request_id,
                                schedule_decision=audit)
        if self.obs is not None:
            self.obs.counter(
                "xllm_schedule_decisions_total",
                "routing decisions by policy and outcome",
                labelnames=("policy", "reason")).inc(
                policy=audit.get("policy", "unknown"),
                reason=audit.get("reason", "unknown"))

    # ------------------------------------------------------------------
    # Registry + token fan-in (scheduler.cpp:197-302, 329-372)
    # ------------------------------------------------------------------
    def record_new_request(self, request: Request,
                           output_callback: OutputCallback) -> None:
        tracked = _TrackedRequest(request, output_callback)
        with self._req_lock:
            self._requests[request.service_request_id] = tracked
        # Pin to a fan-in pool up front so ordering starts at token one.
        self._pools.pool_for(request.service_request_id)

    def handle_generation(self, out: RequestOutput,
                          source: str = "") -> None:
        """Per-token hot path: dispatch to the request's pinned pool.

        ``source`` is the pushing worker's name when the output arrived
        over the RPC fan-in — for recoverable requests it is the
        exactly-once guard: after a mid-stream resume retargets the
        request, a straggler push from the dead (or deposed) instance
        must not splice duplicate tokens into the stream."""
        srid = out.service_request_id or out.request_id
        with self._req_lock:
            tracked = self._requests.get(srid)
        if tracked is None:
            logger.debug("generation for unknown request %s", srid)
            return
        if tracked.recovery is not None and source and (
                source in tracked.recovery.get("failed", ())
                or source not in (tracked.prefill_name,
                                  tracked.decode_name)):
            # The failed-set check closes the pre-retarget window: a
            # resume marks the dead instance failed BEFORE snapshotting
            # the ledger, so a straggler push landing between snapshot
            # and retarget cannot be both delivered and regenerated.
            logger.warning("dropping %d stale output(s) for %s from "
                           "deposed instance %s",
                           len(out.outputs), srid, source)
            if self.obs is not None:
                self.obs.counter(
                    "xllm_stale_outputs_dropped_total",
                    "straggler generation pushes from deposed "
                    "instances dropped by the recovery source guard "
                    "(unit: pushes, not requests)").inc()
            return
        if out.status is not None \
                and out.status.code == StatusCode.INTERNAL \
                and (out.status.message or "").startswith("engine_fault"):
            # Device-plane fault verdict (worker fault boundary,
            # docs/ROBUSTNESS.md): strike the poison ledger. Below the
            # strike threshold an RPC-recoverable request is resumed on
            # a survivor instead of surfacing the fault; at the
            # threshold (or when not recoverable) the typed terminal
            # output falls through to the client.
            instance = source or tracked.decode_name \
                or tracked.prefill_name
            poisoned = self.note_engine_fault(
                srid, tracked.request.token_ids, instance,
                out.status.message)
            ctx = tracked.recovery
            if not poisoned and ctx is not None \
                    and self.recovery is not None \
                    and ctx.get("owner") == "rpc" \
                    and self.recovery.begin_rpc_resume(
                        tracked, instance):
                return
            self.count_failed("engine_fault")
        num_tokens = sum(len(s.token_ids) for s in out.outputs)
        if tracked.recovery is not None:
            with self._req_lock:
                for s in out.outputs:
                    if s.index == 0:
                        self._ledger_append_locked(
                            tracked, s.token_ids, bool(s.text))
                if out.usage is not None and \
                        tracked.recovery.get("recovered"):
                    # The resumed worker saw prompt + delivered tokens
                    # as its prompt and only the continuation as
                    # completion — restore the client-truthful counts.
                    out.usage.prompt_tokens = len(
                        tracked.request.token_ids)
                    out.usage.completion_tokens = (
                        len(tracked.delivered) + len(tracked.pending))
        tracked.num_generated += num_tokens
        decode_name = tracked.decode_name
        if decode_name:
            if not tracked.prefill_done:
                tracked.prefill_done = True
                self.instance_mgr.update_request_metrics(
                    tracked.prefill_name, RequestPhase.PREFILL_FINISH,
                    len(tracked.request.token_ids))
            self.instance_mgr.update_request_metrics(
                decode_name, RequestPhase.GENERATE, num_tokens)
        self._pools.submit(srid, lambda: self._deliver(tracked, out))

    def _deliver(self, tracked: _TrackedRequest,
                 out: RequestOutput) -> None:
        keep = True
        try:
            keep = tracked.output_callback(out)
        except Exception:  # noqa: BLE001 — client callback must not kill the pool
            keep = False
        if out.finished or out.cancelled or not keep:
            self.finish_request(
                tracked.request.service_request_id,
                cancelled=out.cancelled or not keep)

    def retarget_request(self, service_request_id: str,
                         routing: Routing) -> None:
        """Point a tracked request at its re-dispatched instances so
        finish/generation metrics drain the instance that actually does
        the work, not the one that refused it."""
        with self._req_lock:
            tracked = self._requests.get(service_request_id)
            if tracked is not None:
                tracked.prefill_name = routing.prefill_name
                tracked.decode_name = routing.decode_name

    def finish_request(self, service_request_id: str,
                       cancelled: bool = False) -> None:
        """Teardown (scheduler.cpp:304-327)."""
        with self._req_lock:
            tracked = self._requests.pop(service_request_id, None)
        if tracked is None:
            return
        self._pools.release(service_request_id)
        # Relay mode never sees per-token generations, so the SCHEDULE-phase
        # prefill increments must be drained here or the ledger grows
        # forever and starves the busiest instances under SLO routing.
        if not tracked.prefill_done and tracked.prefill_name:
            tracked.prefill_done = True
            self.instance_mgr.update_request_metrics(
                tracked.prefill_name, RequestPhase.PREFILL_FINISH,
                len(tracked.request.token_ids))
        phase = RequestPhase.CANCEL if cancelled \
            else RequestPhase.FINISH_DECODE
        name = tracked.decode_name or tracked.prefill_name
        if name:
            self.instance_mgr.update_request_metrics(
                name, phase, len(tracked.request.token_ids)
                + tracked.num_generated)

    def fail_requests_on_instance(self, instance: str) -> int:
        """Handle every tracked request routed to a dead instance.
        Recoverable requests (armed by service/recovery.py) are resumed
        mid-stream instead of cancelled: RPC-mode requests are handed to
        the recovery manager (re-prefill prompt + delivered ledger on a
        survivor), relay-owned requests are left alone (their relay
        generator sees the broken worker socket and runs its own resume
        loop). Everything else is cancelled promptly so clients get an
        error instead of hanging (the reference lacks both re-dispatch
        and recovery entirely, SURVEY.md §5.3)."""
        with self._req_lock:
            victims = [t for t in self._requests.values()
                       if instance in (t.prefill_name, t.decode_name)]
        for tracked in victims:
            ctx = tracked.recovery
            reason = "instance_died"
            if ctx is not None and self.recovery is not None:
                owner = ctx.get("owner")
                if owner == "relay":
                    continue
                if owner == "rpc":
                    if self.recovery.begin_rpc_resume(tracked, instance):
                        continue
                    # Resume budget exhausted: the client sees the
                    # error — that's the recoveries counter's "failed"
                    # contract, not a plain instance death.
                    self.recovery.note_failure(
                        tracked.request, instance, "budget_exhausted",
                        mode="rpc")
                    reason = "recovery_exhausted"
            self.count_failed(reason)
            self.cancel_request(
                tracked.request.service_request_id,
                f"instance {instance} died")
        return len(victims)

    def cancel_request(self, service_request_id: str,
                       message: str) -> None:
        """Deliver a terminal UNAVAILABLE output for one tracked request
        (the client's definite error; teardown follows through the
        normal _deliver → finish_request path)."""
        out = RequestOutput(
            request_id=service_request_id,
            service_request_id=service_request_id,
            status=Status(StatusCode.UNAVAILABLE, message),
            finished=True, cancelled=True)
        self.handle_generation(out)

    def count_failed(self, reason: str) -> None:
        """``xllm_requests_failed_total{reason}`` — failure modes stay
        countable before and after recovery (standalone schedulers run
        without a registry)."""
        if self.obs is not None:
            self.obs.counter(
                "xllm_requests_failed_total",
                "requests that hit a failure mode, by reason (a "
                "recovered request counts only under the recovery "
                "series, not here)",
                labelnames=("reason",)).inc(reason=reason)

    # ------------------------------------------------------------------
    # Poison-pill quarantine (docs/ROBUSTNESS.md device-plane faults)
    # ------------------------------------------------------------------
    def note_engine_fault(self, service_request_id: str,
                          token_ids: List[int], instance: str,
                          verdict: str) -> bool:
        """Record one engine-fault blame verdict against a request.

        Single strike point for every response topology (RPC push,
        relay stream, redispatch loop). Returns True when the request
        crossed ``XLLM_POISON_STRIKES`` and is now poisoned — callers
        must then fail it to the client instead of re-scheduling.
        Events/metrics are emitted outside the ledger lock."""
        digest = prompt_digest(token_ids, self.opts.murmur_hash3_seed)
        strikes, poisoned = self.poison.strike(
            service_request_id, digest)
        if self.events is not None:
            self.events.emit(
                "engine_fault", service_request_id=service_request_id,
                instance=instance, verdict=verdict, strikes=strikes)
        if poisoned:
            if self.obs is not None:
                self.obs.counter(
                    "xllm_requests_poisoned_total",
                    "requests failed to the client as poison pills "
                    "after repeated engine-fault blame verdicts "
                    "(strikes >= XLLM_POISON_STRIKES)").inc()
            if self.events is not None:
                self.events.emit(
                    "request_quarantined",
                    service_request_id=service_request_id,
                    digest=digest, strikes=strikes,
                    ttl_s=self.poison.ttl_s)
        return poisoned

    def quarantined_digest(self, token_ids: List[int]) -> bool:
        """True when the prompt's content digest is under quarantine —
        the admission gate refuses such requests outright for
        ``XLLM_POISON_TTL_S`` after a poisoning."""
        return self.poison.quarantined(
            prompt_digest(token_ids, self.opts.murmur_hash3_seed))

    # ------------------------------------------------------------------
    # Mid-stream recovery support (service/recovery.py drives these)
    # ------------------------------------------------------------------
    def arm_recovery(self, service_request_id: str,
                     ctx: Dict[str, Any]) -> None:
        """Attach a recovery context (owner/fwd/path/budget) to a
        tracked request — from then on handle_generation keeps its
        delivered-token ledger and fail_requests_on_instance recovers
        instead of cancelling."""
        with self._req_lock:
            tracked = self._requests.get(service_request_id)
            if tracked is not None:
                tracked.recovery = ctx

    @staticmethod
    def _ledger_append_locked(tracked: _TrackedRequest,
                              token_ids: List[int],
                              has_text: bool) -> None:
        """One delta into the delivered ledger. A delta WITH text
        flushes every held-back id first (the detokenizer's emitted
        text always covers the tokens it was holding); a delta without
        text parks its ids as pending — not yet client-visible, so not
        yet resumable-over."""
        if has_text:
            if tracked.pending:
                tracked.delivered.extend(tracked.pending)
                tracked.pending = []
            tracked.delivered.extend(token_ids)
        else:
            tracked.pending.extend(token_ids)

    def note_delivered(self, service_request_id: str,
                       token_ids: List[int],
                       has_text: bool = True) -> int:
        """Ledger append for the relay topology (the relay parses token
        ids out of the worker's ledger-extension frames). Returns the
        total delivered (text-flushed) count."""
        with self._req_lock:
            tracked = self._requests.get(service_request_id)
            if tracked is None:
                return 0
            self._ledger_append_locked(tracked, token_ids, has_text)
            return len(tracked.delivered)

    def delivered_snapshot(self, service_request_id: str) -> List[int]:
        with self._req_lock:
            tracked = self._requests.get(service_request_id)
            return list(tracked.delivered) if tracked is not None else []

    def resume_ledger(self, service_request_id: str) -> List[int]:
        """The forced-context snapshot for a resume: the delivered
        (text-flushed) ids. Pending held-back ids are ABANDONED — their
        text never reached the client, the survivor regenerates them —
        so they must not double-count when the continuation re-appends
        the same ids."""
        with self._req_lock:
            tracked = self._requests.get(service_request_id)
            if tracked is None:
                return []
            tracked.pending = []
            return list(tracked.delivered)

    def delivered_total(self, service_request_id: str) -> int:
        """Client-visible completion length so far: flushed + held ids
        (the usage-rewrite source for recovered streams)."""
        with self._req_lock:
            tracked = self._requests.get(service_request_id)
            if tracked is None:
                return 0
            return len(tracked.delivered) + len(tracked.pending)

    def recovery_ctx(self, service_request_id: str
                     ) -> Optional[Dict[str, Any]]:
        with self._req_lock:
            tracked = self._requests.get(service_request_id)
            return tracked.recovery if tracked is not None else None

    def num_tracked_requests(self, model: Optional[str] = None) -> int:
        """Tracked in-flight requests — optionally for one model (the
        bounded-admission per-model cap, http_service.py)."""
        with self._req_lock:
            if model is None:
                return len(self._requests)
            return sum(1 for t in self._requests.values()
                       if t.request.model == model)

    def tracked_requests_info(self) -> List[Dict[str, Any]]:
        """Flight-recorder view of the live request registry (the debug
        bundle's in-flight evidence): who is running where, for how
        long, and how far along."""
        now = time.monotonic()
        with self._req_lock:
            return [{"service_request_id": srid,
                     "age_s": round(now - t.created, 3),
                     "prefill": t.prefill_name,
                     "decode": t.decode_name,
                     "prefill_done": t.prefill_done,
                     "num_generated": t.num_generated,
                     "delivered_tokens": len(t.delivered),
                     "recovery": ({"owner": t.recovery.get("owner"),
                                   "resumes": t.recovery.get("resumes",
                                                             0),
                                   "recovered": t.recovery.get(
                                       "recovered", False)}
                                  if t.recovery is not None else None)}
                    for srid, t in self._requests.items()]

    def _on_instance_removed(self, name: str) -> None:
        self.kvcache_mgr.remove_instance(name)
        self.fail_requests_on_instance(name)

    # ------------------------------------------------------------------
    # Heartbeats (scheduler.cpp:148-156)
    # ------------------------------------------------------------------
    def handle_instance_heartbeat(self, hb: Heartbeat) -> bool:
        registered = self.instance_mgr.on_heartbeat(hb)
        if registered and hb.latency.encode_ms_samples \
                and self.obs is not None:
            # EPD encode SLO feed (docs/EPD.md): per-call tower
            # durations ride the beat; the service observes them into
            # the same histogram /metrics exports and the "encode"
            # objective judges (http_service._slo_snapshot).
            h = self.obs.histogram("xllm_service_encode_ms")
            for ms in hb.latency.encode_ms_samples[:64]:
                try:
                    h.observe(float(ms))
                except (TypeError, ValueError):
                    continue
        if registered and (hb.cache_stored or hb.cache_removed
                           or hb.cache_offloaded
                           or hb.cache_offloaded_ssd):
            if not self.instance_mgr.digest_ok(hb.name):
                # Quarantined block hashing (cache_digest_mismatch):
                # digests from this worker can never match service-side
                # digests — ingesting them would poison match scores.
                return registered
            self.kvcache_mgr.record_updated_kvcaches(
                hb.name,
                stored=[bytes.fromhex(h) for h in hb.cache_stored],
                removed=[bytes.fromhex(h) for h in hb.cache_removed],
                offloaded=[bytes.fromhex(h)
                           for h in hb.cache_offloaded],
                offloaded_ssd=[bytes.fromhex(h)
                               for h in hb.cache_offloaded_ssd])
        return registered

    # ------------------------------------------------------------------
    def pick_serving_instance(self) -> Optional[str]:
        """Direct instance pick for /v1/models and /metrics proxying —
        without a fake schedule() round-trip (fixes SURVEY.md §7.4 quirk)."""
        prefill, _ = self.instance_mgr.get_next_instance_pair()
        return prefill

    def stop(self) -> None:
        self._stop.set()
        self._hb_thread.join(timeout=5)
        self.instance_mgr.close()
        self.kvcache_mgr.close()
        for watch_id in (self._master_watch, self._epoch_watch):
            if watch_id is not None:
                try:
                    self.store.cancel_watch(watch_id)
                except Exception:  # noqa: BLE001 — store may already be gone
                    pass
        try:
            self.store.lease_revoke(self._lease_id)
        except Exception:  # noqa: BLE001 — store may already be gone
            pass
        self._pools.stop()
