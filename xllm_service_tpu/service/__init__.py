"""Service/orchestration layer: the TPU-native rebuild of the reference's
cluster front door (master, HTTP/RPC services, scheduler, managers, LB
policies, coordination plane — reference layers A-D, SURVEY.md §1)."""
