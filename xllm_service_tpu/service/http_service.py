"""OpenAI-compatible HTTP front door of the service.

Rebuild of ``http_service/service.{h,cpp}`` (SURVEY.md §2 #2): parses the
OpenAI request, schedules it, rewrites the body with ``service_request_id``
+ ``token_ids`` + ``routing`` (so the worker never re-tokenizes,
service.cpp:457-463), forwards to the chosen prefill worker, and returns
the response through one of the reference's two topologies
(rpc_service/service.h:67-79):

  relay mode  — the worker's SSE/JSON response is relayed byte-for-byte
                through a progressive reader (service.cpp:113-143, 206-222);
  rpc mode    — (``enable_decode_response_to_service``) tokens arrive at
                the RPC plane's ``/rpc/generations`` fan-in; this layer
                assembles OpenAI chunks from the per-request callback.

``/v1/models`` and ``/metrics`` are served from service-local state (the
reference reverse-proxies them to a worker, service.cpp:283-336 — an
improvement called out in SURVEY.md §5.5). ``/model/triggers`` implements
the manual sleep/wakeup surface (service.cpp:510-550).
"""

from __future__ import annotations

import http.client
import json
import logging
import math
import os
import queue
import threading
import time

from typing import Any, Dict, Iterator, List, Optional

from xllm_service_tpu.config import ServiceOptions
from xllm_service_tpu.obs import (
    REQUEST_ID_HEADER, AnomalyDetector, EventLog, Failpoints,
    InstanceSignal, Registry, SloConfig, SloEngine, SpanStore)
from xllm_service_tpu.obs import profiler
from xllm_service_tpu.obs import steptrace, timeline
from xllm_service_tpu.obs.expfmt import fraction_le_from_buckets
from xllm_service_tpu.service.httpd import (
    Request, Response, Router, http_json, http_stream_status,
    iter_sse_events)
from xllm_service_tpu.service.instance_types import RequestPhase
from xllm_service_tpu.service.recovery import RecoveryManager, RelayLedger
from xllm_service_tpu.utils import threads
from xllm_service_tpu.utils.threads import spawn
from xllm_service_tpu.service.response_handler import (
    SSE_DONE, ChatStreamAssembler, CompletionStreamAssembler,
    ResponseCollector)
from xllm_service_tpu.service.scheduler import Scheduler
from xllm_service_tpu.service.tracer import RequestTracer
from xllm_service_tpu.utils.misc import short_uuid
from xllm_service_tpu.utils.retry import RetryPolicy
from xllm_service_tpu.utils.types import (
    FinishReason, Request as SchedRequest, RequestOutput, StatusCode,
    parse_openai_sampling, validate_sampling)

logger = logging.getLogger(__name__)

# Pre-header transport deaths on a forwarded STREAM: the worker's
# process (or socket) died before it answered. Safe to re-dispatch in
# the relay-stream topology even though the worker may have started —
# its only delivery path is this broken socket, so its eventual write
# fails and the response cleanup cancels the engine request; the client
# can never see duplicate work. Timeouts stay excluded: a slow worker's
# socket is alive and still deliverable.
_DEAD_TRANSPORT_ERRORS = (ConnectionRefusedError, ConnectionResetError,
                          BrokenPipeError, http.client.RemoteDisconnected)


class _EngineFaultResume(Exception):
    """Internal relay control flow: a worker's in-stream engine-fault
    frame (device-plane fault boundary, docs/ROBUSTNESS.md) below the
    poison threshold — routed into the mid-stream resume machinery
    WITHOUT forwarding the fault frame to the client."""

    def __init__(self, verdict: str) -> None:
        super().__init__(verdict)
        self.verdict = verdict


def _engine_fault_error(obj: Any) -> Optional[str]:
    """The verdict string when ``obj`` is a worker engine-fault error
    envelope (``{"error": {"type": "engine_fault", ...}}``), else
    None."""
    if not isinstance(obj, dict):
        return None
    err = obj.get("error")
    if isinstance(err, dict) and err.get("type") == "engine_fault":
        return str(err.get("message") or "engine_fault")
    return None


def _engine_fault_status(out: RequestOutput) -> Optional[str]:
    """The verdict string when ``out`` is the worker's typed
    engine-fault terminal output (INTERNAL status whose message names
    the blame verdict), else None."""
    st = out.status
    if st is not None and st.code == StatusCode.INTERNAL \
            and (st.message or "").startswith("engine_fault"):
        return st.message
    return None


def _engine_fault_frame(verdict: str) -> bytes:
    """The typed in-stream error frame a client sees when its request
    is failed as a poison pill mid-stream (the non-stream paths return
    a clean 500 with the same envelope)."""
    return (b"data: " + json.dumps(
        {"error": {"message": verdict, "type": "engine_fault",
                   "code": 500}}).encode("utf-8") + b"\n\n")


class _RequestObs:
    """Per-request latency/span bookkeeping on the front door.

    One instance rides each completion request through its response
    path; every method is idempotent (retry paths and on_close backstops
    may reach the same milestone twice) and the FIRST occurrence is the
    truthful timestamp. TPOT in the relay-stream topology is a
    frame-interval approximation (the relay never parses tokens out of
    the proxied bytes — see docs/OBSERVABILITY.md)."""

    __slots__ = ("svc", "srid", "t0", "t_first", "tokens", "_done",
                 "_dispatched")

    def __init__(self, svc: "HttpService", srid: str, kind: str,
                 model: str) -> None:
        self.svc = svc
        self.srid = srid
        self.t0 = time.monotonic()
        self.t_first = 0.0
        self.tokens = 0
        self._done = False
        self._dispatched = False
        svc.spans.annotate(srid, kind=kind, model=model)
        svc.spans.record(srid, "received", t_mono=self.t0)

    def stage(self, stage: str, **attrs: Any) -> None:
        self.svc.spans.record(self.srid, stage, **attrs)

    def dispatched(self, target: str) -> None:
        now = time.monotonic()
        if self._dispatched:
            # Redispatch attempt: the first dispatch keeps the
            # queue-wait truth, but the instance that actually serves
            # the request must be visible in the trace (at most one
            # redispatch per request by design).
            self.svc.spans.record(self.srid, "redispatched", t_mono=now,
                                  target=target)
            return
        self._dispatched = True
        self.svc.spans.record(self.srid, "dispatched", t_mono=now,
                              target=target)
        self.svc.h_queue_wait.observe(1000.0 * (now - self.t0))

    def first_token(self) -> None:
        if self.t_first:
            return
        self.t_first = time.monotonic()
        self.svc.spans.record(self.srid, "first_token",
                              t_mono=self.t_first)
        self.svc.h_ttft.observe(1000.0 * (self.t_first - self.t0))

    def add_tokens(self, n: int) -> None:
        self.tokens += max(int(n), 0)

    def finished(self, error: bool = False) -> None:
        if self._done:
            return
        self._done = True
        now = time.monotonic()
        self.svc.spans.record(self.srid, "finished", t_mono=now,
                              error=bool(error))
        if error:
            return          # a refused/timed-out request is not a latency
        self.svc.h_e2e.observe(1000.0 * (now - self.t0))
        if self.t_first and self.tokens > 1:
            self.svc.h_tpot.observe(
                1000.0 * (now - self.t_first) / (self.tokens - 1))


class HttpService:
    def __init__(self, opts: ServiceOptions, scheduler: Scheduler,
                 events: Optional[EventLog] = None,
                 failpoints: Optional[Failpoints] = None) -> None:
        self.opts = opts
        self.scheduler = scheduler
        self.tracer = RequestTracer(opts.trace_path,
                                    opts.enable_request_trace)
        # {"http": Admission, "rpc": Admission} — injected by Master once
        # the servers exist; /metrics reports their pressure.
        self.admissions = None
        # The service plane's metrics registry + span ring. One
        # HttpService per process in production, so this IS the
        # process-global registry there; the co-located test harness
        # gets per-plane attribution for free (obs/metrics.py docstring).
        self.obs = Registry()
        self.spans = SpanStore(capacity=int(os.environ.get(
            "XLLM_SPAN_RING", "2048")))
        # Heartbeat-shipped worker step flight-recorder tails
        # (obs/steptrace.py): the /admin/timeline fallback source when
        # a live worker pull fails mid-incident.
        self.step_books = steptrace.StepBooks()
        # Default /admin/timeline merge window; read ONCE here (the
        # handler is serving-reachable — flag-registry discipline).
        try:
            self._timeline_window_s = float(os.environ.get(
                "XLLM_TIMELINE_WINDOW_S", "60") or 60)
        except ValueError:
            self._timeline_window_s = 60.0
        self._timeline_exports = 0
        self._m_requests = self.obs.counter(
            "xllm_service_requests_total",
            "completion/chat requests accepted by the front door")
        self._m_errors = self.obs.counter(
            "xllm_service_errors_total",
            "requests that ended in a scheduling/worker/timeout error")
        self._m_requests.inc(0.0)       # render 0 from boot, like the
        self._m_errors.inc(0.0)         # f-string exporter always did
        self.h_ttft = self.obs.histogram(
            "xllm_service_ttft_ms",
            "received -> first streamed token (stream/RPC topologies)")
        self.h_tpot = self.obs.histogram(
            "xllm_service_tpot_ms",
            "mean inter-token gap per request (frame-interval "
            "approximation in the relay-stream topology)")
        self.h_e2e = self.obs.histogram(
            "xllm_service_e2e_ms", "received -> finished")
        self.h_queue_wait = self.obs.histogram(
            "xllm_service_queue_wait_ms",
            "received -> dispatched to a worker (schedule + rewrite + "
            "redispatch time)")
        # EPD encode stage (docs/EPD.md): per-call vision-encode
        # durations shipped in worker heartbeats
        # (LatencyMetrics.encode_ms_samples) — observed by the
        # scheduler's heartbeat path into this same registry, judged
        # here by the "encode" SLO objective.
        self.h_encode = self.obs.histogram(
            "xllm_service_encode_ms",
            "per-call vision-encode duration across the worker fleet "
            "(heartbeat-shipped samples)")

        # --- the judgment layer (SLO engine + event log + watchdog) ----
        # Shared event log (Master passes the cluster-wide one so the
        # scheduler's election and instance events land in the same
        # ring); a standalone HttpService owns its own.
        self.events = events if events is not None else EventLog(
            capacity=int(os.environ.get("XLLM_EVENT_RING", "1024")))
        self.slo_cfg = SloConfig.from_env(
            default_ttft_ms=opts.target_ttft_ms)
        self.slo = SloEngine(self.slo_cfg, self._slo_snapshot,
                             events=self.events)
        self.watch = AnomalyDetector(events=self.events)
        self._wd_stop = threading.Event()
        self._wd_thread: Optional[threading.Thread] = None

        # --- robustness layer: failpoints + retry + mid-stream recovery
        # Service-plane fault injection (the "service.*" and "store.*"
        # catalog names; each worker owns its own set) — POST
        # /admin/failpoint also proxies worker arming through the
        # instance registry. Master passes ITS registry (created before
        # the store guard so `store.*` covers even boot-time election);
        # we late-bind our registry for the trip counters. A standalone
        # HttpService owns its own.
        if failpoints is not None:
            self.failpoints = failpoints
            self.failpoints.obs = self.obs
        else:
            self.failpoints = Failpoints(events=self.events, obs=self.obs)
        # Bounded service-plane admission (docs/ROBUSTNESS.md): beyond
        # XLLM_MAX_INFLIGHT tracked requests (0 = unbounded) — or
        # XLLM_MAX_INFLIGHT_PER_MODEL for one model — new work is SHED
        # with 429 + Retry-After instead of queueing unboundedly, so
        # goodput-under-SLO stays honest at overload. Literal env reads
        # for the flag-registry xlint rule.
        self.max_inflight = int(os.environ.get(
            "XLLM_MAX_INFLIGHT", "0") or 0)
        self.max_inflight_per_model = int(os.environ.get(
            "XLLM_MAX_INFLIGHT_PER_MODEL", "0") or 0)
        self._m_shed = self.obs.counter(
            "xllm_requests_shed_total",
            "requests shed by bounded admission, by reason",
            labelnames=("reason",))
        # The one retry/backoff policy every forward/redispatch loop
        # shares (utils/retry.py; XLLM_RETRY_* knobs) — replaced the
        # ad-hoc two-attempt loops that used to live here.
        self.retry = RetryPolicy.from_env()
        # Mid-stream failover (service/recovery.py): worker death
        # becomes a resume, not a client-visible error. Wired onto the
        # scheduler like spans/obs so fail_requests_on_instance can
        # hand recoverable requests over instead of cancelling.
        self.recovery = RecoveryManager(opts, scheduler, self.spans,
                                        self.events, self.obs,
                                        self.failpoints)
        scheduler.recovery = self.recovery

    # ------------------------------------------------------------------
    # Watchdog: periodic SLO evaluation + anomaly detection
    # ------------------------------------------------------------------
    def _slo_snapshot(self) -> Dict[str, Any]:
        """Cumulative (good, total) per SLO objective, read from the
        SAME histogram/counter families /metrics exports — the SLO
        engine judges exactly what the dashboards see. Latency "good"
        counts interpolate the threshold inside its bucket (one copy of
        the arithmetic: expfmt.fraction_le_from_buckets, shared with
        bench.py's slo_*_attainment fields)."""
        thresholds = {o.name: o.threshold_ms
                      for o in self.slo_cfg.objectives}
        out: Dict[str, Any] = {}
        for name, hist in (("ttft", self.h_ttft), ("e2e", self.h_e2e),
                           ("queue_wait", self.h_queue_wait),
                           ("encode", self.h_encode)):
            bs = hist.cumulative()
            if bs is None:
                out[name] = (0.0, 0.0)
                continue
            total = bs[-1][1]
            frac = fraction_le_from_buckets(
                bs, thresholds.get(name, 0.0)) or 0.0
            out[name] = (frac * total, total)
        requests = self._m_requests.value()
        errors = self._m_errors.value()
        out["availability"] = (max(requests - errors, 0.0), requests)
        return out

    def watchdog_tick(self) -> None:
        """One judgment pass: evaluate the SLO windows, then judge every
        instance's health signals. Signal gathering happens here (no obs
        lock held) so the detector itself never calls into the instance
        books."""
        self.slo.tick()
        mgr = self.scheduler.instance_mgr
        deadline = max(self.opts.detect_disconnected_instance_interval_s,
                       3.0 * self.opts.heartbeat_interval_s)
        signals = [
            InstanceSignal(
                name=row["name"],
                heartbeat_age_s=row["heartbeat_age_s"],
                heartbeat_deadline_s=deadline,
                step_ms_p99=row["latency"].get("step_ms_p99") or None,
                kv_usage=row["load"].get("kv_cache_usage", 0.0),
                engine_alive=int(row["load"].get("engine_alive", 1)))
            for row in mgr.instance_table()]
        self.watch.observe(signals)

    def _watchdog_loop(self) -> None:
        while not self._wd_stop.wait(self.slo_cfg.tick_s):
            try:
                self.watchdog_tick()
            except Exception:  # noqa: BLE001 — judgment must not die; next
                logger.exception("watchdog tick failed")  # tick retries

    def start_watchdog(self) -> None:
        if self._wd_thread is not None:
            return
        # Supervised + restarted: the watchdog is the judgment layer's
        # pulse — its per-tick try/except already survives a bad tick,
        # and the supervised restart survives a crash in the wait
        # machinery itself.
        self._wd_thread = spawn(
            "obs.watchdog_loop", self._watchdog_loop,
            thread_name="obs-watchdog",
            restart=threads.RESTART_POLICY,
            events=self.events, stop=self._wd_stop)
        self._wd_thread.start()

    def close(self) -> None:
        self._wd_stop.set()
        if self._wd_thread is not None:
            self._wd_thread.join(timeout=5)
            self._wd_thread = None
        self.tracer.close()

    def install(self, router: Router) -> None:
        router.route("GET", "/hello",
                     lambda r: Response.json({"ok": True}))
        router.route("POST", "/v1/chat/completions",
                     lambda r: self._completions(r, is_chat=True))
        router.route("POST", "/v1/completions",
                     lambda r: self._completions(r, is_chat=False))
        router.route("POST", "/v1/embeddings", self._embeddings)
        router.route("GET", "/v1/models", self._models)
        router.route("GET", "/metrics", self._metrics)
        router.route("POST", "/model/triggers", self._model_triggers)
        router.route("POST", "/admin/flags", self._admin_flags)
        router.route("GET", "/admin/flags", self._admin_flags_get)
        router.route_prefix("GET", "/admin/trace/", self._admin_trace)
        router.route("GET", "/admin/slo", self._admin_slo)
        router.route("GET", "/admin/events", self._admin_events)
        router.route("GET", "/admin/debug_bundle", self._admin_debug_bundle)
        router.route("GET", "/admin/timeline", self._admin_timeline)
        router.route("GET", "/admin/profile", self._admin_profile)
        router.route("POST", "/admin/failpoint", self._admin_failpoint)
        router.route("GET", "/admin/failpoints",
                     self._admin_failpoints_get)

    # ------------------------------------------------------------------
    # Request building (generate_request, service.cpp:239-267)
    # ------------------------------------------------------------------
    def _build_request(self, body: Dict[str, Any], is_chat: bool,
                       headers: Dict[str, str]) -> SchedRequest:
        srid = (headers.get("x-request-id")
                or f"{'chatcmpl' if is_chat else 'cmpl'}-{short_uuid()}")
        # Client-stamped send time (reference call_data.h:41-59 captures
        # x-request-id AND x-request-time); carried on the request and
        # surfaced in the ingress trace record.
        try:
            arrival = float(headers.get("x-request-time", ""))
        except ValueError:
            arrival = 0.0
        sampling = parse_openai_sampling(body, is_chat)
        req = SchedRequest(
            model=body.get("model", ""),
            service_request_id=srid,
            stream=bool(body.get("stream", False)),
            include_usage=bool((body.get("stream_options") or {})
                               .get("include_usage", False)),
            offline=bool(body.get("offline", False)),
            priority=int(body.get("priority", 0)),
            prompt=body.get("prompt", "") if not is_chat else "",
            messages=body.get("messages", []) if is_chat else [],
            token_ids=list(body.get("token_ids") or []),
            sampling=sampling,
            arrival_time=arrival)
        req.trace_callback = self.tracer.callback_for(srid)
        return req

    # ------------------------------------------------------------------
    # Completions / ChatCompletions (service.cpp:338-475)
    # ------------------------------------------------------------------
    def _admission_shed(self, model: str) -> Optional[Response]:
        """Bounded admission (docs/ROBUSTNESS.md): 429 + ``Retry-After``
        when the tracked in-flight population (global or per-model) is
        at its cap — shed BEFORE tokenization/scheduling, so an
        overloaded plane never pays preprocess cost for work it
        refuses. Counted by reason in xllm_requests_shed_total."""
        if self.max_inflight > 0 and \
                self.scheduler.num_tracked_requests() >= self.max_inflight:
            reason = "inflight"
        elif self.max_inflight_per_model > 0 and model \
                and self.scheduler.num_tracked_requests(model) >= \
                self.max_inflight_per_model:
            reason = "model_inflight"
        else:
            return None
        self._m_shed.inc(reason=reason)
        resp = Response.error(
            429, f"overloaded: in-flight cap reached ({reason}) — "
                 f"retry after the interval in Retry-After",
            err_type="overloaded_error")
        resp.headers["Retry-After"] = "1"
        return resp

    def _completions(self, http_req: Request, is_chat: bool) -> Response:
        self._m_requests.inc()
        try:
            body = http_req.json()
        except (ValueError, json.JSONDecodeError):
            return Response.error(400, "invalid JSON body")
        kind = "chat" if is_chat else "completion"
        if is_chat and not body.get("messages"):
            return Response.error(400, "messages is required")
        if not is_chat and not (body.get("prompt")
                                or body.get("token_ids")):
            return Response.error(400, "prompt is required")
        shed = self._admission_shed(body.get("model", ""))
        if shed is not None:
            return shed

        try:
            # Both the body parse (e.g. a non-numeric best_of/n) and the
            # cross-field rules map to 400, never a 500.
            req = self._build_request(body, is_chat, http_req.headers)
            validate_sampling(req.sampling, req.stream)
        except (TypeError, ValueError) as e:
            return Response.error(400, f"invalid request: {e}")
        robs = _RequestObs(self, req.service_request_id, kind,
                           body.get("model", ""))
        robs.stage("admitted", stream=req.stream)
        self.tracer.trace(req.service_request_id,
                          {"stage": "ingress", "kind": kind, "body": body,
                           "x_request_time": req.arrival_time or None})
        status, routing = self.scheduler.schedule(req)
        if not status.ok:
            self._m_errors.inc()
            if status.code == StatusCode.INTERNAL and \
                    status.message.startswith("request quarantined"):
                # The scheduler's poison-pill quarantine gate
                # (docs/ROBUSTNESS.md): surfaced as the same typed
                # engine_fault 500 the poisoning itself returned.
                self.scheduler.count_failed("quarantined")
                robs.finished(error=True)
                return Response.error(500, status.message,
                                      "engine_fault")
            if status.code.name == "UNAVAILABLE":
                self.scheduler.count_failed("no_instance")
            robs.finished(error=True)
            code = 503 if status.code.name == "UNAVAILABLE" else 400
            return Response.error(code, status.message)
        robs.stage("scheduled", prefill=routing.prefill_name,
                   decode=routing.decode_name)

        # Rewrite the forwarded body (service.cpp:457-463). The parsed
        # SamplingParams travel with it so the worker honors exactly what
        # the service normalized (max_completion_tokens, stop strings,
        # penalties, logprobs) instead of re-deriving a subset.
        fwd = dict(body)
        fwd["service_request_id"] = req.service_request_id
        fwd["token_ids"] = req.token_ids
        fwd["routing"] = routing.to_json()
        fwd["sampling"] = req.sampling.to_json()
        if req.mm_inputs:
            fwd["mm_inputs"] = req.mm_inputs
        path = "/v1/chat/completions" if is_chat else "/v1/completions"
        target = self.scheduler.instance_mgr.address_of(
            routing.prefill_name)
        if target is None:
            self._m_errors.inc()
            robs.finished(error=True)
            return Response.error(503, "routed instance vanished")

        if self.opts.enable_decode_response_to_service:
            return self._rpc_mode_response(req, fwd, target, path,
                                           is_chat, robs)
        return self._relay_mode_response(req, fwd, target, path, robs)

    def _fwd_headers(self, req: SchedRequest) -> Dict[str, str]:
        """Correlation header for every forward of this request — the
        worker stamps its span stages with the same id, so the merged
        timeline at /admin/trace/<id> crosses the plane boundary."""
        return {REQUEST_ID_HEADER: req.service_request_id}

    # -- re-dispatch ------------------------------------------------------
    def _redispatch(self, req: SchedRequest, fwd: Dict[str, Any],
                    exclude=()) -> Optional[str]:
        """Pick a new instance for a request its worker PROVABLY never
        worked on — an HTTP 503 refusal (draining/asleep) or a refused
        connection; never timeouts or mid-response failures, which could
        double-generate (mid-STREAM failures go through the recovery
        path instead — service/recovery.py). The reference README
        claims this rescheduling; its code never implements it
        (SURVEY.md §5.3). Walks up to K alternates, excluding every
        already-failed instance (``exclude``); the candidate walk and
        schedule bookkeeping live in RecoveryManager.reroute (one copy
        for redispatch and recovery). Returns the new target address,
        or None."""
        old = req.routing.prefill_name if req.routing else ""
        self.spans.record(req.service_request_id, "redispatch",
                          from_instance=old)
        name, addr = self.recovery.reroute(req, fwd, exclude)
        if name is None:
            return None
        self.events.emit("redispatch",
                         service_request_id=req.service_request_id,
                         from_instance=old, to=name)
        self.tracer.trace(req.service_request_id,
                          {"stage": "redispatch", "from": old,
                           "to": name})
        return addr

    @staticmethod
    def _routed_name(fwd: Dict[str, Any]) -> str:
        return (fwd.get("routing") or {}).get("prefill_name", "")

    def _send_with_redispatch(self, req: SchedRequest,
                              fwd: Dict[str, Any], target: str,
                              path: str):
        """One JSON forward with redispatch on refusal-class outcomes
        ONLY (503 status / refused connection) — shared by the
        non-stream relay and the RPC ack so their retry policies cannot
        drift apart. Walks alternates under the shared retry budget,
        excluding every instance that already refused; when everything
        refused, the answer is a CLEAN 503 (a ConnectionRefusedError on
        a redispatched target no longer escapes raw)."""
        failed: set = set()
        last_exc: Optional[Exception] = None
        attempts = max(self.retry.max_attempts, 1)
        for attempt in range(attempts):
            try:
                status, resp = http_json(
                    "POST", target, path, fwd,
                    timeout=self.opts.request_timeout_s,
                    headers=self._fwd_headers(req))
            except ConnectionRefusedError as e:
                last_exc = e
                failed.add(self._routed_name(fwd))
                new = self._redispatch(req, fwd, exclude=failed) \
                    if attempt + 1 < attempts else None
                if new:
                    target = new
                    continue
                break
            if status == 503 and attempt + 1 < attempts:
                failed.add(self._routed_name(fwd))
                new = self._redispatch(req, fwd, exclude=failed)
                if new:
                    target = new
                    continue
            verdict = _engine_fault_error(resp) if status == 500 \
                else None
            if verdict is not None:
                # Device-plane fault blamed on this request. The worker
                # already evicted it (fault boundary), so a re-dispatch
                # cannot double-generate — below the poison threshold
                # it hops to a survivor; at the threshold the typed 500
                # goes to the client as-is.
                name = self._routed_name(fwd)
                poisoned = self.scheduler.note_engine_fault(
                    req.service_request_id, req.token_ids, name,
                    verdict)
                if not poisoned and attempt + 1 < attempts:
                    failed.add(name)
                    new = self._redispatch(req, fwd, exclude=failed)
                    if new:
                        target = new
                        continue
            return status, resp
        detail = f": {last_exc}" if last_exc else ""
        return 503, {"error": {
            "message": f"no reachable instance{detail}",
            "type": "unavailable"}}

    # -- topology 1: HTTP relay (service.cpp:168-236) ---------------------
    def _relay_mode_response(self, req: SchedRequest, fwd: Dict[str, Any],
                             target: str, path: str,
                             robs: _RequestObs) -> Response:
        self.scheduler.record_new_request(req, lambda out: True)
        if req.stream:
            # Recoverable streams (service/recovery.py policy) forward
            # with the ledger extension armed: the worker emits token
            # ids per frame, the relay keeps the delivered ledger, and
            # a mid-stream worker death becomes a resume on a survivor
            # instead of a broken stream.
            recover = self.recovery.recoverable(req)
            if recover:
                self.recovery.arm(req, fwd, path, owner="relay")
            # Eager open: the worker's status is known BEFORE any bytes
            # reach the client, so a 503 can be re-dispatched and other
            # errors surface with their real status code instead of
            # error JSON inside a 200 SSE stream. Refusals walk
            # alternates under the shared retry budget, excluding every
            # instance that already refused.
            failed: set = set()
            attempts = max(self.retry.max_attempts, 1)
            for attempt in range(attempts):
                robs.dispatched(target)
                try:
                    status, body = http_stream_status(
                        "POST", target, path, fwd,
                        timeout=self.opts.request_timeout_s,
                        headers=self._fwd_headers(req))
                except Exception as e:  # noqa: BLE001
                    # Refusal-class failures (see _redispatch) — plus,
                    # for recoverable streams, any pre-header transport
                    # death (_DEAD_TRANSPORT_ERRORS): a timeout may
                    # mean the worker already started AND can still
                    # deliver, so it never re-dispatches.
                    retryable = isinstance(e, ConnectionRefusedError) \
                        or (recover and
                            isinstance(e, _DEAD_TRANSPORT_ERRORS))
                    new = None
                    if retryable and attempt + 1 < attempts:
                        failed.add(self._routed_name(fwd))
                        new = self._redispatch(req, fwd, exclude=failed)
                    if new:
                        target = new
                        continue
                    self.scheduler.finish_request(req.service_request_id,
                                                  cancelled=True)
                    self._m_errors.inc()
                    self.scheduler.count_failed("worker_error")
                    robs.finished(error=True)
                    return Response.error(503, f"worker error: {e}")
                if status == 200:
                    break
                err = b"".join(body)        # drain + close the conn
                if status == 503 and attempt + 1 < attempts:
                    failed.add(self._routed_name(fwd))
                    new = self._redispatch(req, fwd, exclude=failed)
                    if new:
                        target = new
                        continue
                self.scheduler.finish_request(req.service_request_id,
                                              cancelled=True)
                self._m_errors.inc()
                self.scheduler.count_failed("worker_refused")
                robs.finished(error=True)
                return Response(status=status, body=err)

            trace_egress = self.tracer.egress_for(req.service_request_id)

            if recover:
                ledger = RelayLedger(
                    self.recovery, req,
                    is_chat=path.endswith("/chat/completions"))
                resp_obj = Response.sse(self._recoverable_relay(
                    req, fwd, path, body, ledger, robs, trace_egress,
                    failed))
                done = [False]
                first_body = body

                def on_close_rec() -> None:
                    # Never-started body backstop (see relay on_close
                    # below): drop the worker-side connection and drain
                    # the registry entry.
                    if done[0]:
                        return
                    done[0] = True
                    try:
                        first_body.close()
                    except Exception:  # noqa: BLE001 — worker socket
                        pass            # may already be dead
                    robs.finished(error=True)
                    self.scheduler.finish_request(req.service_request_id)
                resp_obj.on_close = on_close_rec
                return resp_obj

            def relay() -> Iterator[bytes]:
                try:
                    for chunk in body:
                        robs.first_token()
                        # Frame-count approximation of the token count:
                        # the relay proxies bytes without parsing, and
                        # one worker StepOutput is one SSE data frame.
                        # [DONE] is a terminator, not a StepOutput.
                        robs.add_tokens(chunk.count(b"data: ")
                                        - chunk.count(b"data: [DONE]"))
                        if trace_egress is not None:
                            trace_egress(chunk)
                        yield chunk
                except GeneratorExit:
                    # Client went away mid-stream: a truncated request
                    # must not pollute the latency histograms.
                    robs.finished(error=True)
                    raise
                except Exception:
                    # Worker died mid-relay (non-recoverable request):
                    # an aborted stream is an error, not an e2e/tpot
                    # sample.
                    self._m_errors.inc()
                    self.scheduler.count_failed("worker_error")
                    robs.finished(error=True)
                    raise
                finally:
                    robs.finished()
                    self.scheduler.finish_request(req.service_request_id)
            resp_obj = Response.sse(relay())
            done = [False]

            def on_close() -> None:
                # Backstop for a never-started body (client died during
                # header write): the generator finallies cannot run, but
                # the registry entry must drain and the worker-side
                # connection must drop or the worker generates the full
                # completion into a dead socket.
                if done[0]:
                    return
                done[0] = True
                try:
                    body.close()
                except Exception:  # noqa: BLE001 — the worker socket may
                    pass            # already be dead; drop is the intent
                # A never-started body means the client died during the
                # header write — not a completed request.
                robs.finished(error=True)
                self.scheduler.finish_request(req.service_request_id)
            resp_obj.on_close = on_close
            return resp_obj
        robs.dispatched(target)
        try:
            status, resp = self._send_with_redispatch(req, fwd, target,
                                                      path)
        except Exception as e:  # noqa: BLE001 — worker unreachable
            self.scheduler.finish_request(req.service_request_id,
                                          cancelled=True)
            self._m_errors.inc()
            self.scheduler.count_failed("worker_error")
            robs.finished(error=True)
            return Response.error(503, f"worker error: {e}")
        if isinstance(resp, dict):
            # Non-stream relay: the worker's first token is invisible
            # here (one response body); TTFT for this request merges in
            # from the worker-side span. Usage gives the exact count.
            robs.add_tokens((resp.get("usage") or {})
                            .get("completion_tokens", 0))
        robs.finished(error=status != 200)
        if status != 200:
            self._m_errors.inc()
            self.scheduler.count_failed(
                "engine_fault" if _engine_fault_error(resp) is not None
                else "worker_refused")
        self.scheduler.finish_request(req.service_request_id)
        self.tracer.trace(req.service_request_id,
                          {"stage": "egress", "body": resp})
        return Response.json(resp, status=status)

    # -- mid-stream recovery: the ledger-aware relay ----------------------
    def _recoverable_relay(self, req: SchedRequest, fwd: Dict[str, Any],
                           path: str, body, ledger: RelayLedger,
                           robs: _RequestObs, trace_egress,
                           failed: set) -> Iterator[bytes]:
        """Relay one recoverable SSE stream frame-by-frame. Every frame
        runs through the RelayLedger (token ids → the scheduler's
        delivered ledger; the ``"xllm"`` extension stripped before the
        client sees bytes). A mid-stream worker failure — broken socket
        or stream ending without its terminator — re-schedules onto a
        survivor, re-prefills prompt + delivered tokens as forced
        context, and splices the continuation into this SAME open
        stream. Exactly-once: the survivor never re-generates delivered
        tokens (they are its prompt), and the ledger is contiguous by
        frame order (docs/ROBUSTNESS.md)."""
        srid = req.service_request_id
        ctx = self.scheduler.recovery_ctx(srid) or {
            "budget": 0, "resumes": 0}
        try:
            while True:
                err: Optional[BaseException] = None
                try:
                    for payload in iter_sse_events(body):
                        if '"engine_fault"' in payload:
                            # Worker fault boundary blamed THIS request
                            # (typed in-stream error frame). Strike the
                            # poison ledger; below the threshold the
                            # frame is withheld and the request resumes
                            # on a survivor like any mid-stream death —
                            # at the threshold the client sees the
                            # typed fault.
                            try:
                                obj = json.loads(payload)
                            except ValueError:
                                obj = None
                            verdict = _engine_fault_error(obj)
                            if verdict is not None:
                                poisoned = \
                                    self.scheduler.note_engine_fault(
                                        srid, req.token_ids,
                                        self._routed_name(fwd), verdict)
                                if poisoned:
                                    self._m_errors.inc()
                                    self.scheduler.count_failed(
                                        "engine_fault")
                                    robs.finished(error=True)
                                    frame = _engine_fault_frame(verdict)
                                    if trace_egress is not None:
                                        trace_egress(frame)
                                    yield frame
                                    return
                                raise _EngineFaultResume(verdict)
                        # The yield stays OUTSIDE the section: a
                        # suspended generator would bill downstream
                        # socket writes to the relay.
                        with profiler.section("relay.frame"):
                            frame, n_new = ledger.on_payload(payload)
                        if frame is None:
                            # Suppressed (dup role chunk / held-back-only
                            # ledger frame) — its token ids still count.
                            robs.add_tokens(n_new)
                            continue
                        robs.first_token()
                        robs.add_tokens(n_new)
                        if trace_egress is not None:
                            trace_egress(frame)
                        yield frame
                except GeneratorExit:
                    # Client went away mid-stream: a truncated request
                    # must not pollute the latency histograms (and is
                    # not a recovery trigger).
                    robs.finished(error=True)
                    raise
                except Exception as e:  # noqa: BLE001 — the worker died
                    err = e             # mid-relay: the recovery trigger
                if ledger.done:
                    return
                if ledger.finished:
                    # Finish delta delivered but [DONE] died with the
                    # worker: the completion is whole — terminate
                    # cleanly instead of re-prefilling for nothing
                    # (synthesizing the usage chunk this death window
                    # may have swallowed from an include_usage client).
                    for frame in ledger.close_finished(
                            req.include_usage):
                        if trace_egress is not None:
                            trace_egress(frame)
                        yield frame
                    return
                # --- mid-stream failure → resume -----------------------
                try:
                    body.close()
                except Exception:  # noqa: BLE001 — dead worker socket
                    pass
                dead = self._routed_name(fwd)
                if dead:
                    failed.add(dead)
                delivered_n = len(self.scheduler.delivered_snapshot(srid))
                logger.warning(
                    "stream %s broke mid-relay on %s after %d tokens "
                    "(%s); attempting recovery", srid, dead, delivered_n,
                    err)
                if ledger.content_frames and not ledger.tokens_seen:
                    # Content reached the client but no frame carried the
                    # "xllm" token-id extension (version skew: a worker
                    # that ignores the additive ledger_tokens field) —
                    # the ledger is blind to what was delivered, so a
                    # resume would replay the whole completion into the
                    # open stream. Fail clean instead.
                    self._m_errors.inc()
                    self.scheduler.count_failed("recovery_unledgered")
                    self.recovery.note_failure(
                        req, dead, "unledgered_stream", mode="relay")
                    robs.finished(error=True)
                    raise RuntimeError(
                        f"worker died mid-stream and the stream carried "
                        f"no token ledger; not recoverable "
                        f"(last error: {err})")
                if delivered_n >= req.sampling.max_tokens:
                    # Died between the last token and the finish delta.
                    for frame in ledger.synthesize_finish(
                            req.include_usage):
                        if trace_egress is not None:
                            trace_egress(frame)
                        yield frame
                    self.recovery.note_success(
                        req, ctx, dead, "(synthesized)", delivered_n,
                        mode="relay")
                    return
                # Deadline anchored at THIS failure (not stream start:
                # a healthy stream may outlive request_timeout_s, and
                # recovery matters most for exactly those).
                reopened = self._reopen_stream(
                    req, fwd, path, ctx, failed, dead, robs,
                    time.monotonic() + self.opts.request_timeout_s)
                if reopened is None:
                    self._m_errors.inc()
                    self.scheduler.count_failed("recovery_exhausted")
                    self.recovery.note_failure(
                        req, dead, "no_surviving_instance", mode="relay")
                    robs.finished(error=True)
                    if isinstance(err, _EngineFaultResume):
                        # The withheld fault frame was pending a resume
                        # that never came — surface the typed error
                        # instead of an opaque broken stream.
                        frame = _engine_fault_frame(err.verdict)
                        if trace_egress is not None:
                            trace_egress(frame)
                        yield frame
                        return
                    raise RuntimeError(
                        f"worker died mid-stream and recovery was "
                        f"exhausted (last error: {err})")
                body, fwd = reopened
                ledger.resumed = True
        finally:
            try:
                body.close()    # deterministic worker-conn release
            except Exception:  # noqa: BLE001 — may already be dead/closed
                pass
            robs.finished()
            self.scheduler.finish_request(srid)

    def _reopen_stream(self, req: SchedRequest, fwd: Dict[str, Any],
                       path: str, ctx: Dict[str, Any], failed: set,
                       dead: str, robs: _RequestObs,
                       deadline: float):
        """One-or-more resume attempts for a broken recoverable relay:
        re-schedule excluding every failed instance, forward the
        forced-context resume body, and eagerly open the continuation
        stream. Returns ``(body_iterator, resume_fwd)`` or None when
        the per-request budget / surviving instances / deadline are
        exhausted."""
        if ctx["resumes"] >= ctx["budget"]:
            return None
        # One budget unit per FAILOVER EVENT (mirrors begin_rpc_resume);
        # the reroute/dispatch walk below runs under the retry policy's
        # own attempt budget without burning resume budget — a reroute
        # that finds no candidate while a replacement boots must not
        # exhaust the failover allowance.
        ctx["resumes"] += 1
        for attempt in range(self.retry.max_attempts):
            if time.monotonic() > deadline:
                return None
            delivered = self.scheduler.resume_ledger(
                req.service_request_id)
            fwd2 = self.recovery.resume_fwd(fwd, req, delivered)
            name, addr = self.recovery.reroute(req, fwd2, failed)
            if name is None:
                if not self.retry.sleep(attempt, deadline=deadline):
                    return None
                continue
            robs.dispatched(addr)           # records "redispatched"
            try:
                status, new_body = http_stream_status(
                    "POST", addr, path, fwd2,
                    timeout=self.opts.request_timeout_s,
                    headers=self._fwd_headers(req))
            except Exception as e:  # noqa: BLE001 — survivor gone too:
                failed.add(name)    # exclude it and walk the next one
                logger.warning("resume of %s on %s failed: %s",
                               req.service_request_id, name, e)
                if not self.retry.sleep(attempt, deadline=deadline):
                    return None
                continue
            if status != 200:
                b"".join(new_body)          # drain + close
                failed.add(name)
                logger.warning("resume of %s on %s refused: %d",
                               req.service_request_id, name, status)
                if not self.retry.sleep(attempt, deadline=deadline):
                    return None
                continue
            ctx["fwd"] = fwd2
            self.recovery.note_success(req, ctx, dead, name,
                                       len(delivered), mode="relay")
            self.tracer.trace(req.service_request_id,
                              {"stage": "recovered", "from": dead,
                               "to": name,
                               "delivered": len(delivered)})
            logger.info("recovered %s: %s -> %s (%d tokens delivered)",
                        req.service_request_id, dead, name,
                        len(delivered))
            return new_body, fwd2
        return None

    # -- topology 2: decode → service RPC fan-in --------------------------
    def _rpc_mode_response(self, req: SchedRequest, fwd: Dict[str, Any],
                           target: str, path: str, is_chat: bool,
                           robs: _RequestObs) -> Response:
        out_q: "queue.Queue[Optional[RequestOutput]]" = queue.Queue()

        def on_output(out: RequestOutput) -> bool:
            out_q.put(out)
            if out.finished or out.cancelled:
                out_q.put(None)
            return True

        self.scheduler.record_new_request(req, on_output)
        # RPC-mode requests are recoverable out of the box: token ids
        # arrive at the fan-in, so the scheduler's ledger is authoritative
        # and fail_requests_on_instance resumes instead of cancelling.
        if self.recovery.recoverable(req):
            self.recovery.arm(req, fwd, path, owner="rpc")
        robs.dispatched(target)
        try:
            status, ack = self._send_with_redispatch(req, fwd, target,
                                                     path)
            if status != 200:
                raise RuntimeError(f"worker returned {status}: {ack}")
        except Exception as e:  # noqa: BLE001
            self.scheduler.finish_request(req.service_request_id,
                                          cancelled=True)
            self._m_errors.inc()
            self.scheduler.count_failed("worker_error")
            robs.finished(error=True)
            return Response.error(503, f"worker error: {e}")

        timeout = self.opts.request_timeout_s

        def next_output() -> Optional[RequestOutput]:
            """None = finished sentinel; raises queue.Empty on timeout —
            a worker that acked then died must not hang the client."""
            out = out_q.get(timeout=timeout)
            if out is not None:
                robs.first_token()
                robs.add_tokens(sum(len(s.token_ids)
                                    for s in out.outputs))
            return out

        if req.stream:
            asm = (ChatStreamAssembler if is_chat
                   else CompletionStreamAssembler)(
                req.service_request_id, req.model, req.include_usage)

            trace_egress = self.tracer.egress_for(req.service_request_id)

            def gen() -> Iterator[bytes]:
                try:
                    while True:
                        try:
                            out = next_output()
                        except queue.Empty:
                            self.scheduler.finish_request(
                                req.service_request_id, cancelled=True)
                            self.scheduler.count_failed("timeout")
                            robs.finished(error=True)
                            frame = (b'data: {"error": {"message": '
                                     b'"generation timed out", '
                                     b'"type": "timeout"}}\n\n')
                            if trace_egress is not None:
                                trace_egress(frame)
                            yield frame
                            return
                        if out is None:
                            return
                        verdict = _engine_fault_status(out)
                        if verdict is not None:
                            # Poisoned at the fan-in (the scheduler
                            # swallows below-threshold faults into RPC
                            # resumes; only terminal verdicts reach
                            # this queue).
                            self._m_errors.inc()
                            robs.finished(error=True)
                            frame = _engine_fault_frame(verdict)
                            if trace_egress is not None:
                                trace_egress(frame)
                            yield frame
                            return
                        for frame in asm.on_output(out):
                            if trace_egress is not None:
                                trace_egress(frame)
                            yield frame
                except GeneratorExit:
                    robs.finished(error=True)   # truncated by the client
                    raise
                finally:
                    robs.finished()
            resp_obj = Response.sse(gen())

            def on_close() -> None:
                # Never-started body (client died during header write):
                # close the span as an error, not a latency sample; a
                # normally-finished stream already sealed it (no-op).
                robs.finished(error=True)
            resp_obj.on_close = on_close
            return resp_obj

        coll = ResponseCollector(req.service_request_id, req.model, is_chat,
                                 target_n=max(1, req.sampling.n))
        while True:
            try:
                out = next_output()
            except queue.Empty:
                self.scheduler.finish_request(req.service_request_id,
                                              cancelled=True)
                self._m_errors.inc()
                self.scheduler.count_failed("timeout")
                robs.finished(error=True)
                self.tracer.trace(req.service_request_id,
                                  {"stage": "egress", "status": 504,
                                   "error": "generation timed out"})
                return Response.error(504, "generation timed out",
                                      "timeout")
            if out is None:
                break
            verdict = _engine_fault_status(out)
            if verdict is not None:
                self._m_errors.inc()
                robs.finished(error=True)
                self.tracer.trace(req.service_request_id,
                                  {"stage": "egress", "status": 500,
                                   "error": verdict})
                return Response.error(500, verdict, "engine_fault")
            coll.add(out)
        final = coll.body()
        robs.finished()
        self.tracer.trace(req.service_request_id,
                          {"stage": "egress", "body": final})
        return Response.json(final)

    # ------------------------------------------------------------------
    # Embeddings — implemented for real (the reference returns
    # "not support", service.cpp:492): routed to a least-loaded worker.
    # ------------------------------------------------------------------
    def _embeddings(self, http_req: Request) -> Response:
        try:
            body = http_req.json()
        except (ValueError, json.JSONDecodeError):
            return Response.error(400, "invalid JSON body")
        if not body.get("input"):
            return Response.error(400, "input is required")
        name = self.scheduler.pick_serving_instance()
        target = self.scheduler.instance_mgr.address_of(name) if name \
            else None
        if target is None:
            return Response.error(503, "no instance available")
        try:
            status, resp = http_json("POST", target, "/v1/embeddings",
                                     body, timeout=300.0)
        except Exception as e:  # noqa: BLE001 — the 503 carries the
            # error straight back to the client
            return Response.error(503, f"worker error: {e}")
        return Response.json(resp, status=status)

    # ------------------------------------------------------------------
    # Models / metrics — service-local (improves on the reference proxy)
    # ------------------------------------------------------------------
    def _models(self, http_req: Request) -> Response:
        mgr = self.scheduler.instance_mgr
        models: Dict[str, str] = {}
        for name in mgr.names():
            inst = mgr.get(name)
            if inst is None:
                continue
            for m, state in inst.model_states.items():
                if m not in models or state == "awake":
                    models[m] = state
        return Response.json({
            "object": "list",
            "data": [{"id": m, "object": "model",
                      "owned_by": "xllm-service-tpu", "state": st}
                     for m, st in sorted(models.items())]})

    def _metrics(self, http_req: Request) -> Response:
        return Response(body=self._render_metrics().encode(),
                        content_type="text/plain; version=0.0.4")

    def _render_metrics(self) -> str:
        """Refresh scrape-time mirrors from live state, then render the
        whole registry (series names unchanged from the hand-assembled
        exporter this replaced; the metrics-registry xlint rule keeps it
        that way). Shared by /metrics and the debug bundle so both show
        the same picture."""
        obs = self.obs
        mgr = self.scheduler.instance_mgr
        obs.gauge("xllm_service_tracked_requests").set(
            self.scheduler.num_tracked_requests())
        obs.gauge("xllm_service_instances").set(len(mgr.names()))
        obs.gauge("xllm_service_prefill_instances").set(
            len(mgr.prefill_instances()))
        obs.gauge("xllm_service_decode_instances").set(
            len(mgr.decode_instances()))
        obs.gauge("xllm_service_cache_blocks").set(
            self.scheduler.kvcache_mgr.num_blocks())
        obs.gauge("xllm_service_is_master").set(
            1 if self.scheduler.is_master else 0)
        # Control-plane outage visibility (service/store_guard.py +
        # fenced epochs, docs/ROBUSTNESS.md): store health 2/1/0
        # (healthy/flaky/down), whether this plane is serving from the
        # frozen last-known-good table, and the current master epoch.
        obs.gauge("xllm_store_health",
                  "coordination-store health as seen by this plane "
                  "(2 healthy / 1 flaky / 0 down)").set(
            self.scheduler.store_health())
        obs.gauge("xllm_service_degraded",
                  "1 while serving from the frozen instance table "
                  "during a store outage").set(
            1 if self.scheduler.degraded else 0)
        obs.gauge("xllm_service_epoch",
                  "fenced master epoch this replica carries").set(
            self.scheduler.current_epoch())
        # Keep-alive reuse pool: regressions show here as hit:miss
        # decay / overflow growth before they show as service_bench
        # latency. The pool is PROCESS-global (httpd._POOL), so the
        # plane label marks the exporting process — in the normal
        # separate-process deployment this is the service→worker
        # transport; co-located planes (the test harness) export the
        # same series under distinct labels instead of colliding.
        from xllm_service_tpu.service.httpd import flush_conn_pool_metrics
        flush_conn_pool_metrics(obs, plane="service")
        # Supervised-thread crash / swallowed-callback books
        # (utils/threads.py — process-global, root-labeled).
        threads.flush_metrics(obs)
        # Admission pressure (set by Master after server construction):
        # active slots + total 503-rejected per server.
        for srv_name, adm in (self.admissions or {}).items():
            obs.gauge("xllm_service_admission_active",
                      labelnames=("server",)).set(adm.active,
                                                  server=srv_name)
            obs.counter("xllm_service_admission_rejected_total",
                        labelnames=("server",)).set_total(
                adm.rejected_total, server=srv_name)
        # Per-instance load: rebuilt from scratch each scrape so gauges
        # for departed instances don't linger forever.
        g_wait = obs.gauge("xllm_instance_waiting_requests",
                           labelnames=("instance",))
        g_run = obs.gauge("xllm_instance_running_requests",
                          labelnames=("instance",))
        g_kv = obs.gauge("xllm_instance_kv_cache_usage",
                         labelnames=("instance",))
        for g in (g_wait, g_run, g_kv):
            g.clear()
        for name in mgr.names():
            inst = mgr.get(name)
            if inst is None:
                continue
            g_wait.set(inst.load.waiting_requests, instance=name)
            g_run.set(inst.load.running_requests, instance=name)
            g_kv.set(inst.load.kv_cache_usage, instance=name)
        # The judgment layer: SLO gauges, event totals, open anomalies,
        # and span-ring eviction visibility (all scrape-time mirrors of
        # state the slo/events/watchdog objects own).
        self.slo.export(obs)
        c_events = obs.counter("xllm_events_total",
                               "cluster events emitted, by type",
                               labelnames=("type",))
        for ev_type, n in self.events.counts().items():
            c_events.set_total(n, type=ev_type)
        self.watch.export(obs)
        obs.counter(
            "xllm_span_evictions_total",
            "request spans dropped by ring overflow "
            "(size the ring with XLLM_SPAN_RING)").set_total(
            self.spans.eviction_count())
        obs.counter(
            "xllm_service_timeline_exports_total",
            "cluster-merged /admin/timeline documents served").set_total(
            self._timeline_exports)
        # The master watching itself: hot-path section books, sampled
        # lock contention, per-root thread CPU, and self-gauges
        # (obs/profiler.py — scrape-time mirrors, same pattern as above).
        profiler.flush_metrics(obs)
        return obs.render()

    # ------------------------------------------------------------------
    # Cross-plane request spans: GET /admin/trace/<service_request_id>
    # ------------------------------------------------------------------
    def _admin_trace(self, http_req: Request) -> Response:
        rid = http_req.path[len("/admin/trace/"):]
        if not rid:
            return Response.error(400, "missing request id")
        span = self.spans.get(rid)
        if span is None:
            if self.spans.was_evicted(rid):
                # 410 Gone: the ring HELD this id and evicted it — a
                # different answer than "never seen" (404), so an
                # operator knows to grow XLLM_SPAN_RING rather than
                # doubt the request ever existed.
                return Response.json(
                    {"evicted": True, "request_id": rid,
                     "detail": "span evicted from the ring — size it "
                               "with XLLM_SPAN_RING"}, status=410)
            return Response.error(
                404, f"no span for {rid!r} (never seen, or evicted "
                     f"from the ring — size it with XLLM_SPAN_RING)")
        return Response.json(span)

    # ------------------------------------------------------------------
    # The judgment layer's query surface: SLO state, cluster events,
    # and the one-shot flight-recorder snapshot
    # ------------------------------------------------------------------
    def _admin_slo(self, http_req: Request) -> Response:
        """Current SLO state. Reads run a (rate-limited) tick first so
        the answer reflects NOW, not the last watchdog cadence."""
        return Response.json(self.slo.tick())

    def _admin_events(self, http_req: Request) -> Response:
        try:
            since = int(http_req.param("since", "0") or 0)
            limit = int(http_req.param("limit", "256") or 256)
        except ValueError:
            return Response.error(400, "since/limit must be integers")
        events = self.events.since(since, limit=max(1, limit))
        return Response.json({
            "events": events,
            "latest_seq": self.events.latest_seq,
            "dropped_total": self.events.dropped,
            # A reader that polls with since=<last seen> detects ring
            # truncation by the seq gap; next_since makes the resume
            # cursor explicit.
            "next_since": events[-1]["seq"] if events else since})

    def _admin_debug_bundle(self, http_req: Request) -> Response:
        """One-shot post-mortem flight recorder: everything an engineer
        pages through after an incident, as a single JSON document —
        cluster membership, in-flight requests, recent events, open
        anomalies, SLO state, recent finished spans, live flags, and the
        full rendered metrics exposition."""
        scheduler = self.scheduler
        bundle = {
            "captured_at": time.time(),
            "service_id": scheduler.service_id,
            "is_master": scheduler.is_master,
            "flags": {k: getattr(self.opts, k)
                      for k in self._RELOADABLE},
            "instances": scheduler.instance_mgr.instance_table(),
            "tracked_requests": scheduler.tracked_requests_info(),
            # The NEWEST ≤256 events (since() pages oldest-first; a
            # post-mortem wants the most recent history).
            "events": self.events.since(
                max(0, self.events.latest_seq - 256)),
            "anomalies": self.watch.active(),
            "slo": self.slo.tick(),
            "spans": {
                "size": len(self.spans),
                "evictions_total": self.spans.eviction_count(),
                "recent_finished": self.spans.tail(
                    32, finished_only=True)},
            # The self-profile snapshot (sections/locks/thread-CPU/GC)
            # WITHOUT a stack-sampling pass — the bundle must stay
            # cheap; hit /admin/profile?seconds=N for stacks.
            "profile": profiler.snapshot(),
            # Device-plane step flight recorder, as heartbeats shipped
            # it (no live worker pulls — the bundle must stay cheap and
            # answer even when the fleet doesn't): per-instance step-
            # record tails for the incident's last minutes.
            "steptrace": {
                name: self.step_books.tail(name, n=64)
                for name in self.step_books.instances()},
            "metrics": self._render_metrics(),
        }
        return Response.json(bundle)

    def _admin_timeline(self, http_req: Request) -> Response:
        """Cluster-merged Perfetto/chrome-trace export
        (obs/timeline.py): service-plane request spans + hot-path
        section slices + every worker's step flight recorder, one
        chrome://tracing-loadable JSON document. Workers are pulled
        live from ``GET /admin/steptrace`` (bounded timeout); a worker
        that doesn't answer degrades to its heartbeat-shipped StepBooks
        tail instead of failing the whole export."""
        try:
            window_s = float(http_req.param(
                "seconds", str(self._timeline_window_s))
                or self._timeline_window_s)
        except ValueError:
            window_s = self._timeline_window_s
        scheduler = self.scheduler
        workers: Dict[str, Dict[str, Any]] = {}
        for name in scheduler.instance_mgr.names():
            addr = scheduler.instance_mgr.address_of(name)
            pulled = None
            if addr is not None:
                try:
                    status, resp = http_json(
                        "GET", addr,
                        f"/admin/steptrace?seconds={window_s:g}",
                        timeout=5.0)
                    if status == 200 and isinstance(resp, dict):
                        pulled = resp
                except Exception:  # noqa: BLE001 — degrade to books
                    pulled = None
            if pulled is not None:
                workers[name] = {
                    "steps": pulled.get("steps", []),
                    "sections": pulled.get("sections", [])}
            else:
                workers[name] = {
                    "steps": self.step_books.tail(name),
                    "sections": []}
        trace = timeline.build_timeline(
            service_id=scheduler.service_id,
            spans=self.spans.tail(256),
            sections=profiler.recent_events(window_s=window_s),
            workers=workers,
            window_s=window_s,
            master_counters={
                "instances": float(len(workers)),
                "tracked_requests": float(
                    len(scheduler.tracked_requests_info()))})
        self._timeline_exports += 1
        return Response(body=timeline.render(trace).encode("utf-8"),
                        content_type="application/json")

    def _admin_profile(self, http_req: Request) -> Response:
        """Self-profile on demand: the live section/lock-contention/
        thread-CPU tables plus (with ``?seconds=N``, default 1) a
        ``sys._current_frames`` stack-sampling pass over that window —
        collapsed stacks and top functions, JSON. ``seconds=0`` skips
        sampling and returns the tables alone. The admission gate
        exempts /admin/, so this answers even at saturation — which is
        exactly when it's needed."""
        try:
            seconds = float(http_req.param("seconds", "1") or 1.0)
            hz = float(http_req.param("hz", "50") or 50.0)
        except ValueError:
            return Response.error(400, "seconds/hz must be numbers")
        out = profiler.snapshot()
        if seconds > 0:
            out["stacks"] = profiler.sample_stacks(seconds, hz=hz)
        return Response.json(out)

    # ------------------------------------------------------------------
    # Fault injection surface: arm failpoints on this plane or (with
    # {"instance": <name>}) proxy the arming to a worker's own endpoint
    # — the chaos tests' runtime lever (docs/ROBUSTNESS.md).
    # ------------------------------------------------------------------
    def _admin_failpoint(self, http_req: Request) -> Response:
        try:
            body = http_req.json()
        except (ValueError, json.JSONDecodeError):
            return Response.error(400, "invalid JSON body")
        if not isinstance(body, dict):
            return Response.error(400, "body must be a JSON object")
        instance = body.pop("instance", None)
        if instance == "*":
            # Broadcast arming (chaos harness): every registered worker
            # gets the same spec; per-instance results ride the payload
            # so a partially reachable fleet is visible to the caller.
            results: Dict[str, Any] = {}
            for name in self.scheduler.instance_mgr.names():
                addr = self.scheduler.instance_mgr.address_of(name)
                if addr is None:
                    results[name] = "unknown address"
                    continue
                try:
                    status, resp = http_json("POST", addr,
                                             "/admin/failpoint",
                                             dict(body), timeout=10.0)
                    results[name] = status
                except Exception as e:  # noqa: BLE001 — worker
                    results[name] = str(e)   # unreachable: report it
            return Response.json({"ok": True, "results": results})
        if instance:
            addr = self.scheduler.instance_mgr.address_of(instance)
            if addr is None:
                return Response.error(
                    404, f"unknown instance {instance}")
            try:
                status, resp = http_json("POST", addr,
                                         "/admin/failpoint", body,
                                         timeout=10.0)
            except Exception as e:  # noqa: BLE001 — worker unreachable
                return Response.error(503, f"worker error: {e}")
            return Response.json(resp, status=status)
        try:
            self.failpoints.arm_from_body(body)
        except (TypeError, ValueError) as e:
            return Response.error(400, str(e))
        return Response.json({"ok": True,
                              "state": self.failpoints.state()})

    def _admin_failpoints_get(self, http_req: Request) -> Response:
        return Response.json(self.failpoints.state())

    # ------------------------------------------------------------------
    # Manual sleep/wakeup (service.cpp:510-550)
    # ------------------------------------------------------------------
    def _model_triggers(self, http_req: Request) -> Response:
        body = http_req.json()
        model = body.get("model", "")
        action = body.get("action", "")
        if action not in ("sleep", "wakeup"):
            return Response.error(400, "action must be sleep|wakeup")
        mgr = self.scheduler.instance_mgr
        targets = ([body["instance"]] if body.get("instance")
                   else mgr.names())
        results: Dict[str, Any] = {}
        for name in targets:
            inst = mgr.get(name)
            if inst is None or model not in inst.model_states:
                continue
            try:
                status, resp = mgr.control(
                    inst.meta.rpc_address, f"/{action}", {"model": model})
                if status == 200:
                    inst.model_states[model] = (
                        "asleep" if action == "sleep" else "awake")
                results[name] = status
            except Exception as e:  # noqa: BLE001 — the error rides the
                results[name] = str(e)  # per-instance results payload
        if not results:
            return Response.error(404,
                                  f"model {model} not found on any instance")
        return Response.json({"ok": True, "results": results})

    # ------------------------------------------------------------------
    # Hot-reloadable SLO flags (the reference marks target_ttft /
    # target_tpot brpc-reloadable, global_gflags.cpp:95-104; here any
    # field in _RELOADABLE flips at runtime — ServiceOptions is shared by
    # reference with the scheduler and InstanceMgr, so routing sees the
    # new thresholds on the next request)
    # ------------------------------------------------------------------
    # max_concurrency reloads live because the servers' Admission reads
    # opts through a callable (master.py) — 0 disables the limit.
    _RELOADABLE = ("target_ttft_ms", "target_tpot_ms", "max_concurrency")
    _INT_FLAGS = ("max_concurrency",)
    _ZERO_OK = ("max_concurrency",)

    def _admin_flags_get(self, http_req: Request) -> Response:
        return Response.json(
            {k: getattr(self.opts, k) for k in self._RELOADABLE})

    def _admin_flags(self, http_req: Request) -> Response:
        try:
            body = http_req.json()
        except ValueError:
            return Response.error(400, "invalid JSON body")
        if not isinstance(body, dict):
            return Response.error(400, "body must be a JSON object")
        unknown = [k for k in body if k not in self._RELOADABLE]
        if unknown:
            return Response.error(
                400, f"not reloadable: {unknown}; "
                     f"reloadable flags: {list(self._RELOADABLE)}")
        # Validate everything BEFORE mutating anything: a 400 must leave
        # the service exactly as it was, never half-reconfigured.
        validated = {}
        for k, v in body.items():
            try:
                val = float(v)
            except (TypeError, ValueError):
                return Response.error(400, f"{k} must be a number")
            floor_ok = val >= 0 if k in self._ZERO_OK else val > 0
            if not (math.isfinite(val) and floor_ok):
                return Response.error(
                    400, f"{k} must be a positive finite number")
            validated[k] = int(val) if k in self._INT_FLAGS else val
        for k, val in validated.items():
            setattr(self.opts, k, val)
        logger.info("admin flag reload: %s", validated)
        return Response.json({"ok": True, "updated": validated})
