"""OpenAI-compatible HTTP front door of the service.

Rebuild of ``http_service/service.{h,cpp}`` (SURVEY.md §2 #2): parses the
OpenAI request, schedules it, rewrites the body with ``service_request_id``
+ ``token_ids`` + ``routing`` (so the worker never re-tokenizes,
service.cpp:457-463), forwards to the chosen prefill worker, and returns
the response through one of the reference's two topologies
(rpc_service/service.h:67-79):

  relay mode  — the worker's SSE/JSON response is relayed byte-for-byte
                through a progressive reader (service.cpp:113-143, 206-222);
  rpc mode    — (``enable_decode_response_to_service``) tokens arrive at
                the RPC plane's ``/rpc/generations`` fan-in; this layer
                assembles OpenAI chunks from the per-request callback.

``/v1/models`` and ``/metrics`` are served from service-local state (the
reference reverse-proxies them to a worker, service.cpp:283-336 — an
improvement called out in SURVEY.md §5.5). ``/model/triggers`` implements
the manual sleep/wakeup surface (service.cpp:510-550).
"""

from __future__ import annotations

import json
import logging
import math
import queue
import threading


from typing import Any, Dict, Iterator, List, Optional

from xllm_service_tpu.config import ServiceOptions
from xllm_service_tpu.service.httpd import (
    Request, Response, Router, http_json, http_stream_status)
from xllm_service_tpu.service.instance_types import RequestPhase
from xllm_service_tpu.service.response_handler import (
    ChatStreamAssembler, CompletionStreamAssembler, ResponseCollector)
from xllm_service_tpu.service.scheduler import Scheduler
from xllm_service_tpu.service.tracer import RequestTracer
from xllm_service_tpu.utils.misc import short_uuid
from xllm_service_tpu.utils.types import (
    FinishReason, Request as SchedRequest, RequestOutput,
    parse_openai_sampling, validate_sampling)
from xllm_service_tpu.utils.locks import make_lock

logger = logging.getLogger(__name__)


class HttpService:
    def __init__(self, opts: ServiceOptions, scheduler: Scheduler) -> None:
        self.opts = opts
        self.scheduler = scheduler
        self.tracer = RequestTracer(opts.trace_path,
                                    opts.enable_request_trace)
        self._num_requests = 0
        self._num_errors = 0
        # {"http": Admission, "rpc": Admission} — injected by Master once
        # the servers exist; /metrics reports their pressure.
        self.admissions = None
        self._lock = make_lock("http.stats", 90)

    def install(self, router: Router) -> None:
        router.route("GET", "/hello",
                     lambda r: Response.json({"ok": True}))
        router.route("POST", "/v1/chat/completions",
                     lambda r: self._completions(r, is_chat=True))
        router.route("POST", "/v1/completions",
                     lambda r: self._completions(r, is_chat=False))
        router.route("POST", "/v1/embeddings", self._embeddings)
        router.route("GET", "/v1/models", self._models)
        router.route("GET", "/metrics", self._metrics)
        router.route("POST", "/model/triggers", self._model_triggers)
        router.route("POST", "/admin/flags", self._admin_flags)
        router.route("GET", "/admin/flags", self._admin_flags_get)

    # ------------------------------------------------------------------
    # Request building (generate_request, service.cpp:239-267)
    # ------------------------------------------------------------------
    def _build_request(self, body: Dict[str, Any], is_chat: bool,
                       headers: Dict[str, str]) -> SchedRequest:
        srid = (headers.get("x-request-id")
                or f"{'chatcmpl' if is_chat else 'cmpl'}-{short_uuid()}")
        # Client-stamped send time (reference call_data.h:41-59 captures
        # x-request-id AND x-request-time); carried on the request and
        # surfaced in the ingress trace record.
        try:
            arrival = float(headers.get("x-request-time", ""))
        except ValueError:
            arrival = 0.0
        sampling = parse_openai_sampling(body, is_chat)
        req = SchedRequest(
            model=body.get("model", ""),
            service_request_id=srid,
            stream=bool(body.get("stream", False)),
            include_usage=bool((body.get("stream_options") or {})
                               .get("include_usage", False)),
            offline=bool(body.get("offline", False)),
            priority=int(body.get("priority", 0)),
            prompt=body.get("prompt", "") if not is_chat else "",
            messages=body.get("messages", []) if is_chat else [],
            token_ids=list(body.get("token_ids") or []),
            sampling=sampling,
            arrival_time=arrival)
        req.trace_callback = self.tracer.callback_for(srid)
        return req

    # ------------------------------------------------------------------
    # Completions / ChatCompletions (service.cpp:338-475)
    # ------------------------------------------------------------------
    def _completions(self, http_req: Request, is_chat: bool) -> Response:
        with self._lock:
            self._num_requests += 1
        try:
            body = http_req.json()
        except (ValueError, json.JSONDecodeError):
            return Response.error(400, "invalid JSON body")
        kind = "chat" if is_chat else "completion"
        if is_chat and not body.get("messages"):
            return Response.error(400, "messages is required")
        if not is_chat and not (body.get("prompt")
                                or body.get("token_ids")):
            return Response.error(400, "prompt is required")

        try:
            # Both the body parse (e.g. a non-numeric best_of/n) and the
            # cross-field rules map to 400, never a 500.
            req = self._build_request(body, is_chat, http_req.headers)
            validate_sampling(req.sampling, req.stream)
        except (TypeError, ValueError) as e:
            return Response.error(400, f"invalid request: {e}")
        self.tracer.trace(req.service_request_id,
                          {"stage": "ingress", "kind": kind, "body": body,
                           "x_request_time": req.arrival_time or None})
        status, routing = self.scheduler.schedule(req)
        if not status.ok:
            with self._lock:
                self._num_errors += 1
            code = 503 if status.code.name == "UNAVAILABLE" else 400
            return Response.error(code, status.message)

        # Rewrite the forwarded body (service.cpp:457-463). The parsed
        # SamplingParams travel with it so the worker honors exactly what
        # the service normalized (max_completion_tokens, stop strings,
        # penalties, logprobs) instead of re-deriving a subset.
        fwd = dict(body)
        fwd["service_request_id"] = req.service_request_id
        fwd["token_ids"] = req.token_ids
        fwd["routing"] = routing.to_json()
        fwd["sampling"] = req.sampling.to_json()
        if req.mm_inputs:
            fwd["mm_inputs"] = req.mm_inputs
        path = "/v1/chat/completions" if is_chat else "/v1/completions"
        target = self.scheduler.instance_mgr.address_of(
            routing.prefill_name)
        if target is None:
            return Response.error(503, "routed instance vanished")

        if self.opts.enable_decode_response_to_service:
            return self._rpc_mode_response(req, fwd, target, path, is_chat)
        return self._relay_mode_response(req, fwd, target, path)

    # -- re-dispatch ------------------------------------------------------
    def _redispatch(self, req: SchedRequest,
                    fwd: Dict[str, Any]) -> Optional[str]:
        """Pick a new instance for a request its worker PROVABLY never
        worked on — an HTTP 503 refusal (draining/asleep) or a refused
        connection; never timeouts or mid-response failures, which could
        double-generate. The reference README claims this rescheduling;
        its code never implements it (SURVEY.md §5.3). Reverses the
        failed instance's schedule bookkeeping and retargets the request
        registry so finish metrics drain the instance that actually does
        the work. Returns the new target address, or None."""
        old = req.routing.prefill_name if req.routing else ""
        status, routing = self.scheduler.schedule(req)
        if not status.ok or routing.prefill_name == old:
            if status.ok and old:
                # Scheduled straight back onto the refuser: undo the
                # duplicate SCHEDULE it just added; the original one is
                # drained by the caller's finish/cancel path.
                self.scheduler.instance_mgr.update_request_metrics(
                    old, RequestPhase.UNSCHEDULE, len(req.token_ids))
            return None
        if old:
            self.scheduler.instance_mgr.update_request_metrics(
                old, RequestPhase.UNSCHEDULE, len(req.token_ids))
        self.scheduler.retarget_request(req.service_request_id, routing)
        fwd["routing"] = routing.to_json()
        self.tracer.trace(req.service_request_id,
                          {"stage": "redispatch", "from": old,
                           "to": routing.prefill_name})
        return self.scheduler.instance_mgr.address_of(
            routing.prefill_name)

    def _send_with_redispatch(self, req: SchedRequest,
                              fwd: Dict[str, Any], target: str,
                              path: str):
        """One JSON forward with at most one re-dispatch, triggered ONLY
        by refusal-class outcomes (503 status / refused connection) —
        shared by the non-stream relay and the RPC ack so their retry
        policies cannot drift apart."""
        for attempt in (0, 1):
            try:
                status, resp = http_json(
                    "POST", target, path, fwd,
                    timeout=self.opts.request_timeout_s)
            except ConnectionRefusedError:
                new = self._redispatch(req, fwd) if attempt == 0 else None
                if new:
                    target = new
                    continue
                raise
            if status == 503 and attempt == 0:
                new = self._redispatch(req, fwd)
                if new:
                    target = new
                    continue
            return status, resp

    # -- topology 1: HTTP relay (service.cpp:168-236) ---------------------
    def _relay_mode_response(self, req: SchedRequest, fwd: Dict[str, Any],
                             target: str, path: str) -> Response:
        self.scheduler.record_new_request(req, lambda out: True)
        if req.stream:
            # Eager open: the worker's status is known BEFORE any bytes
            # reach the client, so a 503 can be re-dispatched and other
            # errors surface with their real status code instead of
            # error JSON inside a 200 SSE stream.
            for attempt in (0, 1):
                try:
                    status, body = http_stream_status(
                        "POST", target, path, fwd,
                        timeout=self.opts.request_timeout_s)
                except Exception as e:  # noqa: BLE001
                    # Refusal-class failures only (see _redispatch):
                    # a timeout may mean the worker already started.
                    new = (self._redispatch(req, fwd)
                           if attempt == 0
                           and isinstance(e, ConnectionRefusedError)
                           else None)
                    if new:
                        target = new
                        continue
                    self.scheduler.finish_request(req.service_request_id,
                                                  cancelled=True)
                    with self._lock:
                        self._num_errors += 1
                    return Response.error(503, f"worker error: {e}")
                if status == 200:
                    break
                err = b"".join(body)        # drain + close the conn
                if status == 503 and attempt == 0:
                    new = self._redispatch(req, fwd)
                    if new:
                        target = new
                        continue
                self.scheduler.finish_request(req.service_request_id,
                                              cancelled=True)
                with self._lock:
                    self._num_errors += 1
                return Response(status=status, body=err)

            trace_egress = self.tracer.egress_for(req.service_request_id)

            def relay() -> Iterator[bytes]:
                try:
                    for chunk in body:
                        if trace_egress is not None:
                            trace_egress(chunk)
                        yield chunk
                finally:
                    self.scheduler.finish_request(req.service_request_id)
            resp_obj = Response.sse(relay())
            done = [False]

            def on_close() -> None:
                # Backstop for a never-started body (client died during
                # header write): the generator finallies cannot run, but
                # the registry entry must drain and the worker-side
                # connection must drop or the worker generates the full
                # completion into a dead socket.
                if done[0]:
                    return
                done[0] = True
                try:
                    body.close()
                except Exception:  # noqa: BLE001 — the worker socket may
                    pass            # already be dead; drop is the intent
                self.scheduler.finish_request(req.service_request_id)
            resp_obj.on_close = on_close
            return resp_obj
        try:
            status, resp = self._send_with_redispatch(req, fwd, target,
                                                      path)
        except Exception as e:  # noqa: BLE001 — worker unreachable
            self.scheduler.finish_request(req.service_request_id,
                                          cancelled=True)
            with self._lock:
                self._num_errors += 1
            return Response.error(503, f"worker error: {e}")
        self.scheduler.finish_request(req.service_request_id)
        self.tracer.trace(req.service_request_id,
                          {"stage": "egress", "body": resp})
        return Response.json(resp, status=status)

    # -- topology 2: decode → service RPC fan-in --------------------------
    def _rpc_mode_response(self, req: SchedRequest, fwd: Dict[str, Any],
                           target: str, path: str,
                           is_chat: bool) -> Response:
        out_q: "queue.Queue[Optional[RequestOutput]]" = queue.Queue()

        def on_output(out: RequestOutput) -> bool:
            out_q.put(out)
            if out.finished or out.cancelled:
                out_q.put(None)
            return True

        self.scheduler.record_new_request(req, on_output)
        try:
            status, ack = self._send_with_redispatch(req, fwd, target,
                                                     path)
            if status != 200:
                raise RuntimeError(f"worker returned {status}: {ack}")
        except Exception as e:  # noqa: BLE001
            self.scheduler.finish_request(req.service_request_id,
                                          cancelled=True)
            with self._lock:
                self._num_errors += 1
            return Response.error(503, f"worker error: {e}")

        timeout = self.opts.request_timeout_s

        def next_output() -> Optional[RequestOutput]:
            """None = finished sentinel; raises queue.Empty on timeout —
            a worker that acked then died must not hang the client."""
            return out_q.get(timeout=timeout)

        if req.stream:
            asm = (ChatStreamAssembler if is_chat
                   else CompletionStreamAssembler)(
                req.service_request_id, req.model, req.include_usage)

            trace_egress = self.tracer.egress_for(req.service_request_id)

            def gen() -> Iterator[bytes]:
                while True:
                    try:
                        out = next_output()
                    except queue.Empty:
                        self.scheduler.finish_request(
                            req.service_request_id, cancelled=True)
                        frame = (b'data: {"error": {"message": '
                                 b'"generation timed out", '
                                 b'"type": "timeout"}}\n\n')
                        if trace_egress is not None:
                            trace_egress(frame)
                        yield frame
                        return
                    if out is None:
                        return
                    for frame in asm.on_output(out):
                        if trace_egress is not None:
                            trace_egress(frame)
                        yield frame
            return Response.sse(gen())

        coll = ResponseCollector(req.service_request_id, req.model, is_chat,
                                 target_n=max(1, req.sampling.n))
        while True:
            try:
                out = next_output()
            except queue.Empty:
                self.scheduler.finish_request(req.service_request_id,
                                              cancelled=True)
                with self._lock:
                    self._num_errors += 1
                self.tracer.trace(req.service_request_id,
                                  {"stage": "egress", "status": 504,
                                   "error": "generation timed out"})
                return Response.error(504, "generation timed out",
                                      "timeout")
            if out is None:
                break
            coll.add(out)
        final = coll.body()
        self.tracer.trace(req.service_request_id,
                          {"stage": "egress", "body": final})
        return Response.json(final)

    # ------------------------------------------------------------------
    # Embeddings — implemented for real (the reference returns
    # "not support", service.cpp:492): routed to a least-loaded worker.
    # ------------------------------------------------------------------
    def _embeddings(self, http_req: Request) -> Response:
        try:
            body = http_req.json()
        except (ValueError, json.JSONDecodeError):
            return Response.error(400, "invalid JSON body")
        if not body.get("input"):
            return Response.error(400, "input is required")
        name = self.scheduler.pick_serving_instance()
        target = self.scheduler.instance_mgr.address_of(name) if name \
            else None
        if target is None:
            return Response.error(503, "no instance available")
        try:
            status, resp = http_json("POST", target, "/v1/embeddings",
                                     body, timeout=300.0)
        except Exception as e:  # noqa: BLE001
            return Response.error(503, f"worker error: {e}")
        return Response.json(resp, status=status)

    # ------------------------------------------------------------------
    # Models / metrics — service-local (improves on the reference proxy)
    # ------------------------------------------------------------------
    def _models(self, http_req: Request) -> Response:
        mgr = self.scheduler.instance_mgr
        models: Dict[str, str] = {}
        for name in mgr.names():
            inst = mgr.get(name)
            if inst is None:
                continue
            for m, state in inst.model_states.items():
                if m not in models or state == "awake":
                    models[m] = state
        return Response.json({
            "object": "list",
            "data": [{"id": m, "object": "model",
                      "owned_by": "xllm-service-tpu", "state": st}
                     for m, st in sorted(models.items())]})

    def _metrics(self, http_req: Request) -> Response:
        mgr = self.scheduler.instance_mgr
        lines = [
            f"xllm_service_requests_total {self._num_requests}",
            f"xllm_service_errors_total {self._num_errors}",
            f"xllm_service_tracked_requests "
            f"{self.scheduler.num_tracked_requests()}",
            f"xllm_service_instances {len(mgr.names())}",
            f"xllm_service_prefill_instances "
            f"{len(mgr.prefill_instances())}",
            f"xllm_service_decode_instances "
            f"{len(mgr.decode_instances())}",
            f"xllm_service_cache_blocks "
            f"{self.scheduler.kvcache_mgr.num_blocks()}",
            f"xllm_service_is_master "
            f"{1 if self.scheduler.is_master else 0}",
        ]
        # Keep-alive reuse pool: regressions show here as hit:miss
        # decay / overflow growth before they show as service_bench
        # latency. The pool is PROCESS-global (httpd._POOL), so the
        # plane label marks the exporting process — in the normal
        # separate-process deployment this is the service→worker
        # transport; co-located planes (the test harness) export the
        # same series under distinct labels instead of colliding.
        from xllm_service_tpu.service.httpd import conn_pool_stats
        for k, v in conn_pool_stats().items():
            lines.append(f'xllm_http_conn_pool_{k}{{plane="service"}} '
                         f'{v}')
        # Admission pressure (set by Master after server construction):
        # active slots + total 503-rejected per server.
        for srv_name, adm in (self.admissions or {}).items():
            tag = f'server="{srv_name}"'
            lines.append(
                f"xllm_service_admission_active{{{tag}}} {adm.active}")
            lines.append(f"xllm_service_admission_rejected_total{{{tag}}} "
                         f"{adm.rejected_total}")
        for name in mgr.names():
            inst = mgr.get(name)
            if inst is None:
                continue
            tag = f'instance="{name}"'
            lines.append(f"xllm_instance_waiting_requests{{{tag}}} "
                         f"{inst.load.waiting_requests}")
            lines.append(f"xllm_instance_running_requests{{{tag}}} "
                         f"{inst.load.running_requests}")
            lines.append(f"xllm_instance_kv_cache_usage{{{tag}}} "
                         f"{inst.load.kv_cache_usage}")
        return Response(body="\n".join(lines).encode() + b"\n",
                        content_type="text/plain; version=0.0.4")

    # ------------------------------------------------------------------
    # Manual sleep/wakeup (service.cpp:510-550)
    # ------------------------------------------------------------------
    def _model_triggers(self, http_req: Request) -> Response:
        body = http_req.json()
        model = body.get("model", "")
        action = body.get("action", "")
        if action not in ("sleep", "wakeup"):
            return Response.error(400, "action must be sleep|wakeup")
        mgr = self.scheduler.instance_mgr
        targets = ([body["instance"]] if body.get("instance")
                   else mgr.names())
        results: Dict[str, Any] = {}
        for name in targets:
            inst = mgr.get(name)
            if inst is None or model not in inst.model_states:
                continue
            try:
                status, resp = mgr.control(
                    inst.meta.rpc_address, f"/{action}", {"model": model})
                if status == 200:
                    inst.model_states[model] = (
                        "asleep" if action == "sleep" else "awake")
                results[name] = status
            except Exception as e:  # noqa: BLE001
                results[name] = str(e)
        if not results:
            return Response.error(404,
                                  f"model {model} not found on any instance")
        return Response.json({"ok": True, "results": results})

    # ------------------------------------------------------------------
    # Hot-reloadable SLO flags (the reference marks target_ttft /
    # target_tpot brpc-reloadable, global_gflags.cpp:95-104; here any
    # field in _RELOADABLE flips at runtime — ServiceOptions is shared by
    # reference with the scheduler and InstanceMgr, so routing sees the
    # new thresholds on the next request)
    # ------------------------------------------------------------------
    # max_concurrency reloads live because the servers' Admission reads
    # opts through a callable (master.py) — 0 disables the limit.
    _RELOADABLE = ("target_ttft_ms", "target_tpot_ms", "max_concurrency")
    _INT_FLAGS = ("max_concurrency",)
    _ZERO_OK = ("max_concurrency",)

    def _admin_flags_get(self, http_req: Request) -> Response:
        return Response.json(
            {k: getattr(self.opts, k) for k in self._RELOADABLE})

    def _admin_flags(self, http_req: Request) -> Response:
        try:
            body = http_req.json()
        except ValueError:
            return Response.error(400, "invalid JSON body")
        if not isinstance(body, dict):
            return Response.error(400, "body must be a JSON object")
        unknown = [k for k in body if k not in self._RELOADABLE]
        if unknown:
            return Response.error(
                400, f"not reloadable: {unknown}; "
                     f"reloadable flags: {list(self._RELOADABLE)}")
        # Validate everything BEFORE mutating anything: a 400 must leave
        # the service exactly as it was, never half-reconfigured.
        validated = {}
        for k, v in body.items():
            try:
                val = float(v)
            except (TypeError, ValueError):
                return Response.error(400, f"{k} must be a number")
            floor_ok = val >= 0 if k in self._ZERO_OK else val > 0
            if not (math.isfinite(val) and floor_ok):
                return Response.error(
                    400, f"{k} must be a positive finite number")
            validated[k] = int(val) if k in self._INT_FLAGS else val
        for k, val in validated.items():
            setattr(self.opts, k, val)
        logger.info("admin flag reload: %s", validated)
        return Response.json({"ok": True, "updated": validated})
