"""ctypes bridge to the native epoll front door (csrc/xllm_httpd.cpp).

The reference's servers are brpc: a C++ event loop owning every socket
with a bounded worker pool behind it (reference master.cpp:60-140). This
module gives the rebuild the same split: ``csrc/xllm_httpd.cpp`` handles
accept/parse/keep-alive/chunked-writes in one epoll thread, and complete
requests surface here through a ctypes callback. Routing, admission
control (the live ``max_concurrency`` semantics tests pin), and handler
execution stay in Python — identical semantics to the pure-Python
``HttpServer``, which remains as the fallback when the native library
cannot build (``XLLM_NATIVE_HTTPD=0`` forces the fallback).

What moves off Python threads: idle keep-alive connections (the Python
server pins one thread per connection for up to 60 s), socket parsing,
slow-client writes (buffered in C++ so a stalled reader cannot block the
token producer), and shed requests (a 503 costs no thread spawn).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs

from xllm_service_tpu.utils.locks import make_lock
from xllm_service_tpu.utils import threads
from xllm_service_tpu.utils.threads import spawn

# The headers blob is "key\0value\0...": it MUST cross as pointer+length
# (c_void_p + c_int64) — a c_char_p conversion would truncate it at the
# first embedded NUL.
_CB_TYPE = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
    ctypes.c_char_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64,
    ctypes.c_void_p, ctypes.c_int64)
# Advisory early-shed check (epoll thread, header-complete, large bodies
# only): 1 = proceed, 0 = send the canned 503 without reading the body.
_ADMIT_TYPE = ctypes.CFUNCTYPE(ctypes.c_int32, ctypes.c_void_p,
                               ctypes.c_char_p, ctypes.c_char_p)

_native_lock = make_lock("native_httpd.lib", 96)
_native_lib: Optional[ctypes.CDLL] = None
_native_tried = False


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _build() -> Optional[str]:
    root = _repo_root()
    src = os.path.join(root, "csrc", "xllm_httpd.cpp")
    if not os.path.exists(src):
        return None
    out_dir = os.path.join(root, "build", "native")
    os.makedirs(out_dir, exist_ok=True)
    so = os.path.join(out_dir, "libxllm_httpd.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    cxx = os.environ.get("CXX", "g++")
    tmp = f"{so}.{os.getpid()}.tmp"
    cmd = [cxx, "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           src, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
    except Exception:  # noqa: BLE001 — no toolchain / compile failure:
        # None falls back to the stdlib ThreadingHTTPServer path
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return so


def _load() -> Optional[ctypes.CDLL]:
    global _native_lib, _native_tried
    with _native_lock:
        if _native_tried:
            return _native_lib
        _native_tried = True
        if os.environ.get("XLLM_NATIVE_HTTPD", "1") == "0" \
                or os.environ.get("XLLM_DISABLE_NATIVE"):
            return None
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.xllm_httpd_start.argtypes = [
                ctypes.c_char_p, ctypes.c_int32, _CB_TYPE, _ADMIT_TYPE,
                ctypes.c_void_p]
            lib.xllm_httpd_start.restype = ctypes.c_int64
            lib.xllm_httpd_port.argtypes = [ctypes.c_int64]
            lib.xllm_httpd_port.restype = ctypes.c_int32
            lib.xllm_httpd_run.argtypes = [ctypes.c_int64]
            lib.xllm_httpd_run.restype = ctypes.c_int32
            lib.xllm_httpd_set_shed_response.argtypes = [
                ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64]
            lib.xllm_httpd_set_shed_response.restype = ctypes.c_int32
            lib.xllm_httpd_stop.argtypes = [ctypes.c_int64]
            lib.xllm_httpd_respond.argtypes = [
                ctypes.c_int64, ctypes.c_uint64, ctypes.c_int32,
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
                ctypes.c_int64]
            lib.xllm_httpd_respond.restype = ctypes.c_int32
            lib.xllm_httpd_stream_begin.argtypes = [
                ctypes.c_int64, ctypes.c_uint64, ctypes.c_int32,
                ctypes.c_char_p, ctypes.c_int64]
            lib.xllm_httpd_stream_begin.restype = ctypes.c_int32
            lib.xllm_httpd_stream_chunk.argtypes = [
                ctypes.c_int64, ctypes.c_uint64, ctypes.c_char_p,
                ctypes.c_int64]
            lib.xllm_httpd_stream_chunk.restype = ctypes.c_int32
            lib.xllm_httpd_stream_end.argtypes = [
                ctypes.c_int64, ctypes.c_uint64]
            lib.xllm_httpd_stream_end.restype = ctypes.c_int32
            lib.xllm_httpd_stream_abort.argtypes = [
                ctypes.c_int64, ctypes.c_uint64]
            lib.xllm_httpd_stream_abort.restype = ctypes.c_int32
            _native_lib = lib
        except Exception:  # noqa: BLE001 — a stale .so missing a newer
            _native_lib = None  # export raises AttributeError, not OSError;
        return _native_lib      # any load failure means "use the fallback"


def native_httpd_available() -> bool:
    return _load() is not None


def _parse_headers_blob(blob: bytes) -> Dict[str, str]:
    # "key\0value\0...\0\0" with keys already lowercased by the parser.
    out: Dict[str, str] = {}
    parts = blob.split(b"\0")
    for i in range(0, len(parts) - 1, 2):
        if parts[i]:
            out[parts[i].decode("latin-1")] = parts[i + 1].decode("latin-1")
    return out


def _log_handler_crash(fut) -> None:
    if fut.cancelled():
        return
    exc = fut.exception()
    if exc is not None:
        import traceback
        traceback.print_exception(type(exc), exc, exc.__traceback__)


def _headers_blob(headers: Dict[str, str]) -> bytes:
    out = bytearray()
    for k, v in headers.items():
        out += k.encode("latin-1") + b"\0" + str(v).encode("latin-1") + b"\0"
    return bytes(out)


class NativeHttpServer:
    """Drop-in for ``httpd.HttpServer`` riding the epoll library.

    Construction raises ``OSError`` if the native library is unavailable;
    the ``HttpServer`` factory in ``httpd`` catches that and falls back
    to the pure-Python server, so callers never see the difference."""

    def __init__(self, host: str, port: int, router,
                 max_concurrency=None,
                 admission_exempt: Optional[Tuple[str, ...]] = None
                 ) -> None:
        from xllm_service_tpu.service.httpd import (_ADMISSION_EXEMPT,
                                                    Admission, Request)
        lib = _load()
        if lib is None:
            raise OSError("native httpd unavailable")
        self._lib = lib
        self._Request = Request
        self.router = router
        self.admission = (Admission(max_concurrency)
                          if max_concurrency is not None else None)
        # Stored VERBATIM like PyHttpServer: an explicitly empty tuple
        # means "no exemptions", not "use the defaults".
        self._exempt = (_ADMISSION_EXEMPT if admission_exempt is None
                        else tuple(admission_exempt))
        self._stopped = False
        self._stop_lock = threading.Lock()
        # The callback objects must outlive the server: C++ calls through
        # them until xllm_httpd_stop joins its threads.
        self._cb = _CB_TYPE(self._on_request)
        self._admit_cb = _ADMIT_TYPE(self._on_admit_early)
        self._h = lib.xllm_httpd_start(host.encode(), port, self._cb,
                                       self._admit_cb, None)
        if self._h <= 0:
            raise OSError(f"cannot bind {host}:{port}")
        self.host = host
        self.port = int(lib.xllm_httpd_port(self._h))
        shed = self._render_shed_response()
        lib.xllm_httpd_set_shed_response(self._h, shed, len(shed))
        # Handler pool: REUSED threads instead of one fresh Thread per
        # request (measured: ~1 thread start per request in the service
        # bench profile — spawn cost + GIL churn on the hot path; the
        # reference fronts a bounded brpc worker pool, master.cpp:60-140).
        # Streaming responses PIN their pool thread for the stream's
        # lifetime and the admission limit is LIVE (hot-reloadable
        # callable), so the pool is sized from the limit AT BOOT as a
        # reuse breadth only — _on_request overflows to a fresh Thread
        # whenever every pool thread is busy, preserving the old
        # unbounded-spawn liveness for long-poll handlers (StoreServer
        # /watch) and post-reload limit raises. Created after
        # xllm_httpd_start so thread names carry the RESOLVED port.
        limit = (self.admission._current_limit()
                 if self.admission is not None else None)
        self._pool_cap = max((limit or 0) + 32, 64)
        self._pool_busy = 0
        self._pool_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=self._pool_cap,
            thread_name_prefix=f"httpd-native-{self.port}")

    @staticmethod
    def _render_shed_response() -> bytes:
        from xllm_service_tpu.service.httpd import Response
        resp = Response.error(503, "server at max_concurrency",
                              "overloaded_error")
        return (b"HTTP/1.1 503 Service Unavailable\r\n"
                b"Content-Type: application/json\r\n"
                b"Retry-After: 1\r\nConnection: close\r\n"
                b"Content-Length: " + str(len(resp.body)).encode() +
                b"\r\n\r\n" + resp.body)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "NativeHttpServer":
        # Bound since construction (port known, connections queue in the
        # TCP backlog); accepting begins here — same lifecycle as the
        # Python server, whose handlers must not run before the rest of
        # the owning object (worker engine loop, scheduler) is wired up.
        self._lib.xllm_httpd_run(self._h)
        return self

    def stop(self) -> None:
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        # ctypes releases the GIL around the call, so the dispatch
        # thread can finish an in-flight callback while we join it.
        self._lib.xllm_httpd_stop(self._h)
        # After the C++ side is down no new submits can arrive; don't
        # wait — in-flight streams notice the dead connection via the
        # nonzero stream_chunk rc and unwind on their own. Queued
        # never-started tasks (shouldn't exist: overflow spawns instead
        # of queuing) are cancelled so nothing dispatches into the
        # torn-down owner after stop() returns.
        self._pool.shutdown(wait=False, cancel_futures=True)

    # --- request path (dispatch thread → handler threads) -------------

    def _on_admit_early(self, _user, method, path) -> int:
        """Advisory shed for large-body uploads, from the epoll thread at
        header-complete: returning 0 makes C++ answer with the canned 503
        before the body is buffered (the Python server's
        admission-before-body-read invariant). The authoritative
        try_enter still happens at dispatch."""
        try:
            if self.admission is None:
                return 1
            path_s = path.decode("latin-1")
            if path_s.startswith(self._exempt):
                return 1
            return 1 if self.admission.probe() else 0
        except Exception:  # noqa: BLE001 — never wedge the epoll thread
            return 1

    def _send_overloaded(self, rid: int) -> None:
        from xllm_service_tpu.service.httpd import Response
        resp = Response.error(503, "server at max_concurrency",
                              "overloaded_error")
        self._respond(rid, 503,
                      {"Content-Type": resp.content_type,
                       "Retry-After": "1", "Connection": "close"},
                      resp.body)

    def _on_request(self, _user, rid, method, path, query, headers_ptr,
                    headers_len, body_ptr, body_len) -> None:
        try:
            method_s = method.decode("latin-1")
            path_s = path.decode("latin-1")
            query_d = parse_qs(query.decode("latin-1")) if query else {}
            headers = _parse_headers_blob(
                ctypes.string_at(headers_ptr, headers_len)
                if headers_ptr and headers_len else b"")
            body = (ctypes.string_at(body_ptr, body_len)
                    if body_ptr and body_len else b"")
            req = self._Request(method_s, path_s, query_d, headers, body)
            counted = (self.admission is not None
                       and not path_s.startswith(self._exempt))
            if counted and not self.admission.try_enter():
                # Shed WITHOUT spawning a thread — the whole point of
                # admission control is that overload costs O(1).
                self._send_overloaded(rid)
                return
            try:
                with self._pool_lock:
                    overflow = self._pool_busy >= self._pool_cap
                    if not overflow:
                        self._pool_busy += 1
                if overflow:
                    # Every pool thread busy (pinned streams, long
                    # polls, or a live limit raise): fall back to the
                    # old per-request Thread so nothing queues behind a
                    # 30 s watcher or an SSE stream.
                    spawn("native_httpd.overflow", self._run,
                          args=(rid, req, counted),
                          thread_name=(f"httpd-native-{self.port}-ovf")
                          ).start()
                else:
                    try:
                        fut = self._pool.submit(self._run_pooled,
                                                rid, req, counted)
                    except BaseException:
                        # submit() raising (a late dispatch racing
                        # stop()'s pool shutdown) means _run_pooled's
                        # finally never runs: give the busy count back
                        # here or it stays inflated forever and every
                        # future request takes the per-request-Thread
                        # overflow path.
                        with self._pool_lock:
                            self._pool_busy -= 1
                        raise
                    # A fresh Thread's crash used to print via the
                    # default excepthook; an unread Future swallows it
                    # — re-surface.
                    fut.add_done_callback(_log_handler_crash)
            except BaseException:
                # Spawn/submit rejection after try_enter: the admission
                # slot MUST be returned or it leaks until restart.
                if counted:
                    self.admission.leave()
                raise
        except Exception:  # noqa: BLE001 — a broken request must not
            import traceback    # take down the dispatch thread
            traceback.print_exc()
            self._respond(rid, 500, {"Content-Type": "application/json"},
                          b'{"error":{"message":"dispatch error"}}')

    def _run_pooled(self, rid: int, req, counted: bool) -> None:
        try:
            self._run(rid, req, counted)
        except Exception as e:
            # _run answers its own 500s; anything still escaping here
            # would vanish into the executor's never-result()ed Future
            # — the silent-death class xlint rule 14 forbids. Logged +
            # counted; the pool thread survives for the next request.
            threads.record_callback_error("native_httpd.pool", e)
        finally:
            with self._pool_lock:
                self._pool_busy -= 1

    def _run(self, rid: int, req, counted: bool) -> None:
        try:
            resp = self.router.dispatch(req)
        except BaseException:
            if counted:
                self.admission.leave()
            raise
        try:
            self._write(rid, resp)
        finally:
            if counted:
                self.admission.leave()
            if resp.stream is not None and hasattr(resp.stream, "close"):
                try:
                    resp.stream.close()
                except Exception:  # noqa: BLE001 — best-effort cleanup;
                    pass            # the C++ side already resolved rid
            if resp.on_close is not None:
                try:
                    resp.on_close()
                except Exception:  # noqa: BLE001 — a failing finish hook
                    pass            # must not poison the pool thread

    def _respond(self, rid: int, status: int, headers: Dict[str, str],
                 body: bytes) -> None:
        blob = _headers_blob(headers)
        self._lib.xllm_httpd_respond(self._h, rid, status, blob, len(blob),
                                     body, len(body))

    def _write(self, rid: int, resp) -> None:
        headers = {"Content-Type": resp.content_type}
        headers.update(resp.headers)
        if resp.stream is not None:
            blob = _headers_blob(headers)
            self._lib.xllm_httpd_stream_begin(self._h, rid, resp.status,
                                              blob, len(blob))
            try:
                for chunk in resp.stream:
                    if not chunk:
                        continue
                    rc = self._lib.xllm_httpd_stream_chunk(
                        self._h, rid, chunk, len(chunk))
                    if rc != 0:
                        break   # client went away — stop producing
            except BaseException:
                # Producer failure mid-stream: ABORT (close without the
                # chunked terminator) so the client's decoder sees a
                # truncated response — a clean 0-chunk would make a
                # partial answer look complete. The connection must
                # always be resolved one way or the other: a
                # busy+streaming conn is skipped by the idle sweep.
                self._lib.xllm_httpd_stream_abort(self._h, rid)
                raise
            else:
                self._lib.xllm_httpd_stream_end(self._h, rid)
        else:
            self._respond(rid, resp.status, headers, resp.body)
