"""Real etcd v3 backend for the coordination plane.

The reference rides an etcd *cluster* through ``etcd-cpp-apiv3``
(scheduler/etcd_client/etcd_client.{h,cpp}: TTL leases, the
create-if-absent election txn at etcd_client.cpp:47-62, prefix watches).
Round 1 shipped only the contract-compatible in-process/HTTP store
(coordination.py / coordination_net.py) — fine for tests, a single point
of failure in deployment (VERDICT.md missing #1). ``EtcdStore`` slots a
real quorum behind the same ``CoordinationStore`` interface.

Transport is etcd's gRPC-gateway JSON API (``/v3/kv/range`` etc., etcd
≥3.4; ``api_prefix`` covers ``/v3beta``/``/v3alpha`` for older servers) —
plain HTTP/JSON with base64 keys, so no grpc/protobuf dependency enters
the image. Watches hold one streaming POST per prefix and re-connect from
the last seen revision on drop, so no event is lost across reconnects.

``MockEtcdServer`` serves the same JSON API off an ``InMemoryStore``; the
contract tests run ``EtcdStore`` against it unconditionally (wire
encoding, txn semantics, watch stream parsing), and against a real etcd
when ``XLLM_ETCD_ADDR`` is set.
"""

from __future__ import annotations

import base64
import http.client
import json
import logging
import os
import threading


from typing import Dict, Optional, Tuple

from xllm_service_tpu.service.coordination import (
    CoordinationStore, InMemoryStore, WatchCallback)
from xllm_service_tpu.utils.locks import make_lock
from xllm_service_tpu.utils.retry import RetryPolicy
from xllm_service_tpu.utils import threads
from xllm_service_tpu.utils.threads import spawn

logger = logging.getLogger(__name__)


def _safe_callback(callback: WatchCallback, ev) -> None:
    """Deliver one watch event, swallowing (with telemetry) a crashing
    CALLBACK: before this, a callback exception fell into the watch
    loop's reconnect handler, which re-fetched the same revision and
    re-crashed — an infinite redelivery loop visible only at DEBUG.
    The event is dropped for that callback (watchers are
    resync-tolerant by contract); the error is logged + counted as
    ``xllm_callback_errors_total{root="etcd.watch_loop"}``."""
    try:
        callback(ev)
    except Exception as e:
        threads.record_callback_error("etcd.watch_loop", e)


def _b64(s: str) -> str:
    return base64.b64encode(s.encode("utf-8")).decode("ascii")


def _ub64(s: str) -> str:
    return base64.b64decode(s).decode("utf-8")


def range_end_for_prefix(prefix: str) -> str:
    """etcd prefix convention: range_end = prefix with its last byte +1
    (trailing 0xff bytes drop); empty/all-0xff prefix scans to "\\0" (all
    keys)."""
    b = bytearray(prefix.encode("utf-8"))
    while b:
        if b[-1] < 0xFF:
            b[-1] += 1
            return base64.b64encode(bytes(b)).decode("ascii")
        b.pop()
    return base64.b64encode(b"\0").decode("ascii")


class EtcdStore(CoordinationStore):
    """CoordinationStore over an etcd v3 JSON gateway at ``addr``
    ("host:port")."""

    def __init__(self, addr: str, api_prefix: str = "/v3",
                 timeout_s: float = 5.0) -> None:
        host, _, port = addr.partition(":")
        self._host, self._port = host, int(port or 2379)
        self._api = api_prefix.rstrip("/")
        self._timeout = timeout_s
        # Read timeout on the watch STREAM socket (config-time knob).
        # A watch can sit idle far longer than a unary call, but never
        # unboundedly: on expiry the loop reconnects from ``next_rev``
        # and loses nothing. Generous by default — the cost of a spurious
        # expiry is one reconnect per idle period.
        self._watch_timeout_s = float(
            os.environ.get("XLLM_ETCD_WATCH_TIMEOUT_S", "300") or 300)
        # Reconnect pacing: jittered backoff so a watcher fleet does not
        # hammer a recovering etcd in lockstep; reset on a healthy
        # stream so one blip does not leave the cadence degraded.
        self._watch_retry = RetryPolicy(base_delay_s=0.1,
                                        max_delay_s=2.0)
        self._watches: Dict[int, Tuple[threading.Event,
                                       Optional[http.client.HTTPConnection]]] \
            = {}
        self._watch_seq = 0
        self._lock = make_lock("etcd.watches", 60)

    # -- plumbing ----------------------------------------------------------
    def _call(self, path: str, body: Dict) -> Dict:
        conn = http.client.HTTPConnection(self._host, self._port,
                                          timeout=self._timeout)
        try:
            conn.request("POST", self._api + path, json.dumps(body),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise RuntimeError(
                    f"etcd {path} -> {resp.status}: {data[:200]!r}")
            return json.loads(data) if data else {}
        finally:
            conn.close()

    # -- KV ----------------------------------------------------------------
    def put(self, key: str, value: str,
            lease_id: Optional[int] = None) -> None:
        body = {"key": _b64(key), "value": _b64(value)}
        if lease_id is not None:
            body["lease"] = str(lease_id)
        self._call("/kv/put", body)

    def get(self, key: str) -> Optional[str]:
        out = self._call("/kv/range", {"key": _b64(key)})
        kvs = out.get("kvs") or []
        # protojson drops empty fields: an empty value arrives as no
        # "value" key at all.
        return _ub64(kvs[0].get("value", "")) if kvs else None

    def get_prefix(self, prefix: str) -> Dict[str, str]:
        out = self._call("/kv/range", {
            "key": _b64(prefix), "range_end": range_end_for_prefix(prefix)})
        return {_ub64(kv["key"]): _ub64(kv.get("value", ""))
                for kv in out.get("kvs") or []}

    def delete(self, key: str) -> bool:
        out = self._call("/kv/deleterange", {"key": _b64(key)})
        return int(out.get("deleted", 0)) > 0

    def delete_prefix(self, prefix: str) -> int:
        out = self._call("/kv/deleterange", {
            "key": _b64(prefix), "range_end": range_end_for_prefix(prefix)})
        return int(out.get("deleted", 0))

    # -- leases ------------------------------------------------------------
    def lease_grant(self, ttl_s: float) -> int:
        out = self._call("/lease/grant",
                         {"TTL": str(max(1, int(round(ttl_s))))})
        return int(out["ID"])

    def lease_keepalive(self, lease_id: int) -> bool:
        try:
            out = self._call("/lease/keepalive", {"ID": str(lease_id)})
        except RuntimeError:
            return False
        result = out.get("result", out)
        return int(result.get("TTL", 0)) > 0

    def lease_revoke(self, lease_id: int) -> None:
        try:
            self._call("/kv/lease/revoke", {"ID": str(lease_id)})
        except RuntimeError:
            # Older gateways expose /lease/revoke instead.
            self._call("/lease/revoke", {"ID": str(lease_id)})

    # -- txn ---------------------------------------------------------------
    def compare_create(self, key: str, value: str,
                       lease_id: Optional[int] = None) -> bool:
        """The election txn: create iff the key has never been written
        (CREATE revision 0 — reference etcd_client.cpp:47-62)."""
        put_op = {"key": _b64(key), "value": _b64(value)}
        if lease_id is not None:
            put_op["lease"] = str(lease_id)
        out = self._call("/kv/txn", {
            "compare": [{"key": _b64(key), "target": "CREATE",
                         "result": "EQUAL", "create_revision": "0"}],
            "success": [{"request_put": put_op}],
        })
        return bool(out.get("succeeded", False))

    # -- watches -----------------------------------------------------------
    def add_watch(self, prefix: str, callback: WatchCallback) -> int:
        with self._lock:
            self._watch_seq += 1
            wid = self._watch_seq
            stop = threading.Event()
            self._watches[wid] = (stop, None)
        # Supervised + restarted: a watch loop that dies silently means
        # instance books that never update again (the degradation class
        # rule 14 exists for); the loop's own reconnect handles stream
        # failures, the supervised restart handles crashes outside it.
        t = spawn("etcd.watch_loop", self._watch_loop,
                  args=(wid, prefix, callback, stop),
                  thread_name=f"etcd-watch-{wid}",
                  restart=threads.RESTART_POLICY, stop=stop)
        t.start()
        return wid

    def _watch_loop(self, wid: int, prefix: str, callback: WatchCallback,
                    stop: threading.Event) -> None:
        next_rev = 0                 # 0 = "from now"; >0 = resume point
        # Last value the watcher reported per key — the resync diff base
        # when compaction invalidates the resume revision.
        known: Dict[str, str] = {}
        attempt = 0
        while not stop.is_set():
            # The stream socket gets a (long) read timeout: an idle watch
            # is normal, an eternally-silent one is indistinguishable from
            # a dead peer. Expiry just reconnects from next_rev.
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=self._watch_timeout_s)
            with self._lock:
                if wid not in self._watches:
                    return           # cancelled between iterations
                self._watches[wid] = (stop, conn)
            if stop.is_set():        # cancel raced the registration above
                conn.close()
                return
            try:
                req = {"create_request": {
                    "key": _b64(prefix),
                    "range_end": range_end_for_prefix(prefix)}}
                if next_rev:
                    req["create_request"]["start_revision"] = str(next_rev)
                conn.request("POST", self._api + "/watch", json.dumps(req),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                attempt = 0          # stream is up — reset the backoff
                for line in resp:     # one JSON object per line
                    if stop.is_set():
                        return
                    line = line.strip()
                    if not line:
                        continue
                    msg = json.loads(line)
                    result = msg.get("result", msg)
                    header_rev = int(result.get("header", {})
                                     .get("revision", 0))
                    if header_rev:
                        next_rev = header_rev + 1
                    if result.get("canceled") \
                            or int(result.get("compact_revision", 0)):
                        # Compaction ate our resume point: the missed
                        # events are unrecoverable from the watch, so
                        # resync by diffing current state against what
                        # this watcher last reported.
                        self._resync(prefix, known, callback)
                        break        # reconnect from next_rev
                    for ev in result.get("events") or []:
                        kv = ev.get("kv", {})
                        key = _ub64(kv.get("key", ""))
                        if ev.get("type") == "DELETE":
                            known.pop(key, None)
                            _safe_callback(callback,
                                           ("DELETE", key, None))
                        else:
                            value = _ub64(kv.get("value", ""))
                            known[key] = value
                            _safe_callback(callback,
                                           ("PUT", key, value))
            except Exception as e:  # noqa: BLE001 — reconnect from next_rev
                if not stop.is_set():
                    logger.debug("etcd watch %d reconnecting: %s", wid, e)
                    self._watch_retry.sleep(attempt, stop_event=stop)
                    attempt += 1
            finally:
                conn.close()

    def _resync(self, prefix: str, known: Dict[str, str],
                callback: WatchCallback) -> None:
        """Replace missed (compacted-away) events with a state diff:
        synthetic DELETEs for keys that vanished, PUTs for new/changed."""
        try:
            current = self.get_prefix(prefix)
        except Exception as e:  # noqa: BLE001 — next reconnect retries
            logger.warning("etcd watch resync of %r failed: %s", prefix, e)
            return
        for key in list(known):
            if key not in current:
                known.pop(key)
                _safe_callback(callback, ("DELETE", key, None))
        for key, value in current.items():
            if known.get(key) != value:
                known[key] = value
                _safe_callback(callback, ("PUT", key, value))

    def cancel_watch(self, watch_id: int) -> None:
        with self._lock:
            entry = self._watches.pop(watch_id, None)
        if entry:
            stop, conn = entry
            stop.set()
            if conn is not None:
                try:
                    conn.sock and conn.sock.close()
                except Exception:  # noqa: BLE001 — a dead socket is
                    pass            # the goal state of cancel


    def close(self) -> None:
        with self._lock:
            wids = list(self._watches)
        for wid in wids:
            self.cancel_watch(wid)


# ---------------------------------------------------------------------------
# Mock etcd (JSON-gateway facade over InMemoryStore) — lets the contract
# tests exercise EtcdStore's wire handling without an etcd deployment.
# ---------------------------------------------------------------------------

class MockEtcdServer:
    """Serves the subset of etcd's v3 JSON gateway EtcdStore speaks,
    backed by an ``InMemoryStore`` (which supplies revisions, lease expiry
    and watch semantics)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 store: Optional[InMemoryStore] = None) -> None:
        from xllm_service_tpu.service.httpd import (
            HttpServer, Response, Router)
        self.store = store or InMemoryStore(sweep_interval_s=0.02)
        self._resp = Response
        router = Router()
        router.route("POST", "/v3/kv/put", self._put)
        router.route("POST", "/v3/kv/range", self._range)
        router.route("POST", "/v3/kv/deleterange", self._deleterange)
        router.route("POST", "/v3/lease/grant", self._grant)
        router.route("POST", "/v3/lease/keepalive", self._keepalive)
        router.route("POST", "/v3/kv/lease/revoke", self._revoke)
        router.route("POST", "/v3/kv/txn", self._txn)
        router.route("POST", "/v3/watch", self._watch)
        self._srv = HttpServer(host, port, router)

    @property
    def address(self) -> str:
        return self._srv.address

    def start(self) -> "MockEtcdServer":
        self._srv.start()
        return self

    def stop(self) -> None:
        self._srv.stop()
        self.store.close()

    # -- handlers ----------------------------------------------------------
    def _put(self, req):
        body = req.json()
        lease = int(body["lease"]) if body.get("lease") else None
        self.store.put(_ub64(body["key"]), _ub64(body["value"]), lease)
        return self._resp.json({"header": {
            "revision": str(self.store.revision)}})

    def _in_range(self, key: str, start: str, range_end: str) -> bool:
        end = base64.b64decode(range_end).decode("utf-8") \
            if range_end else None
        return key >= start and (end is None or key < end)

    def _range(self, req):
        body = req.json()
        start = _ub64(body["key"])
        if body.get("range_end"):
            kvs = [{"key": _b64(k), "value": _b64(v)}
                   for k, v in sorted(self.store.get_prefix("").items())
                   if self._in_range(k, start, body["range_end"])]
        else:
            v = self.store.get(start)
            kvs = [] if v is None else [{"key": _b64(start),
                                         "value": _b64(v)}]
        return self._resp.json({
            "header": {"revision": str(self.store.revision)},
            "kvs": kvs, "count": str(len(kvs))})

    def _deleterange(self, req):
        body = req.json()
        start = _ub64(body["key"])
        if body.get("range_end"):
            keys = [k for k in self.store.get_prefix("")
                    if self._in_range(k, start, body["range_end"])]
            deleted = sum(1 for k in keys if self.store.delete(k))
        else:
            deleted = 1 if self.store.delete(start) else 0
        return self._resp.json({"deleted": str(deleted)})

    def _grant(self, req):
        ttl = int(req.json()["TTL"])
        lid = self.store.lease_grant(float(ttl))
        return self._resp.json({"ID": str(lid), "TTL": str(ttl)})

    def _keepalive(self, req):
        lid = int(req.json()["ID"])
        ok = self.store.lease_keepalive(lid)
        return self._resp.json(
            {"result": {"ID": str(lid), "TTL": "1" if ok else "0"}})

    def _revoke(self, req):
        self.store.lease_revoke(int(req.json()["ID"]))
        return self._resp.json({})

    def _txn(self, req):
        body = req.json()
        cmp0 = body["compare"][0]
        key = _ub64(cmp0["key"])
        # EtcdStore only issues create-if-absent txns.
        assert cmp0["target"] == "CREATE"
        put_op = body["success"][0]["request_put"]
        lease = int(put_op["lease"]) if put_op.get("lease") else None
        ok = self.store.compare_create(key, _ub64(put_op["value"]), lease)
        return self._resp.json({"succeeded": ok})

    def _watch(self, req):
        body = req.json()["create_request"]
        prefix = _ub64(body["key"])
        store = self.store

        def stream():
            yield (json.dumps({"result": {
                "created": True,
                "header": {"revision": str(store.revision)}}})
                + "\n").encode()
            rev = int(body.get("start_revision", 0) or 0) - 1
            if rev < 0:
                rev = store.revision
            while True:
                rev, events = store.events_since(rev, prefix,
                                                 timeout_s=10.0)
                if not events:
                    # Keepalive progress line (etcd sends these too).
                    yield (json.dumps({"result": {"header": {
                        "revision": str(rev)}}}) + "\n").encode()
                    continue
                evs = []
                for typ, key, value in events:
                    if typ == "DELETE":
                        evs.append({"type": "DELETE",
                                    "kv": {"key": _b64(key)}})
                    else:
                        evs.append({"kv": {"key": _b64(key),
                                           "value": _b64(value)}})
                yield (json.dumps({"result": {
                    "header": {"revision": str(rev)},
                    "events": evs}}) + "\n").encode()

        return self._resp(content_type="application/json",
                          stream=stream())
