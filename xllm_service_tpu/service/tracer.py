"""RequestTracer: per-request JSONL trace appender.

Rebuild of ``http_service/request_tracer.{h,cpp}``: when enabled, every
inbound/outbound payload of a request is appended as
``{"timestamp", "service_request_id", "data"}`` lines under a mutex
(request_tracer.cpp:37-59), wired into request handling as a
``trace_callback``.
"""

from __future__ import annotations

import json
import os
import threading


import time
from typing import Any, Dict, Optional
from xllm_service_tpu.utils.locks import make_lock


class RequestTracer:
    def __init__(self, path: str = "trace/trace.jsonl",
                 enable: bool = False) -> None:
        self.enable = enable
        self.path = path
        self._lock = make_lock("tracer", 90)
        self._f = None
        self._closed = False
        self._written = 0
        # Size cap (bytes) before rotation; 0 = unbounded, exactly the
        # pre-cap behavior. A capped tracer rotates ONCE to <path>.1
        # (replacing any previous rotation), so the worst case on disk
        # is 2x the cap instead of an unbounded stream of egress frames.
        try:
            self.max_bytes = int(os.environ.get(
                "XLLM_TRACE_MAX_BYTES", "0"))
        except ValueError:
            self.max_bytes = 0
        if enable:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def _rotate_locked(self) -> None:
        """Caller holds the lock and the cap is exceeded: close, shift
        the full file to <path>.1, start fresh."""
        self._f.close()
        self._f = None
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            # Rotation impossible (permissions, cross-mount .1 path):
            # degrade to the pre-cap unbounded behavior instead of
            # paying a reopen + failed rename PER LINE under the global
            # lock — the exact churn the keep-the-file-open design
            # exists to avoid.
            self.max_bytes = 0
        self._written = 0

    def trace(self, service_request_id: str, data: Any) -> None:
        if not self.enable:
            return
        line = json.dumps({
            "timestamp": time.time(),
            "service_request_id": service_request_id,
            "data": data,
        })
        # One open for the process lifetime: per-frame egress tracing
        # calls this once per streamed token, and an open/close cycle
        # under the global lock would throttle every concurrent stream.
        with self._lock:
            if self._closed:
                # A late trace() racing close() (an SSE stream draining
                # while the service shuts down) must not silently
                # reopen the file the caller just finalized.
                return
            if self._f is None:
                self._f = open(self.path, "a", encoding="utf-8")
                try:
                    self._written = os.fstat(self._f.fileno()).st_size
                except OSError:
                    self._written = 0
            self._f.write(line + "\n")
            self._f.flush()
            # Byte length, not character count: the cap is seeded from
            # os.fstat and documented as XLLM_TRACE_MAX_BYTES — counting
            # characters lets multibyte traces overrun the 2x-cap disk
            # bound.
            self._written += len(line.encode("utf-8")) + 1
            if self.max_bytes > 0 and self._written >= self.max_bytes:
                self._rotate_locked()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._f is not None:
                self._f.close()
                self._f = None

    def reopen(self) -> None:
        """Explicitly arm a closed tracer again (tests / hot reconfig);
        the implicit reopen-on-late-trace race is what close() seals."""
        with self._lock:
            self._closed = False

    def callback_for(self, service_request_id: str):
        """Bind a per-request trace callback (reference
        http_service/service.cpp:258-264)."""
        if not self.enable:
            return None

        def cb(stage: str, data: Dict[str, Any]) -> None:
            self.trace(service_request_id, {"stage": stage, **data})
        return cb

    def egress_for(self, service_request_id: str):
        """Per-WRITE egress tracer for streamed responses — the response
        half of ``--enable_request_trace`` (the reference captures every
        outbound payload via the CallData trace callback,
        common/call_data.h:151-162). Each write to the client becomes one
        trace line, so response corruption (a malformed frame, a
        truncated stream, an out-of-order delta) is debuggable from the
        trace alone. In the relay topology a write is a transport chunk
        (may carry several SSE frames, or split one); in the RPC fan-in
        topology it is exactly one assembler frame. ``backslashreplace``
        keeps the line lossless when a multibyte character straddles a
        chunk boundary (``replace`` would forge corruption that never
        reached the client). Returns None when disabled so hot paths
        skip even the closure call."""
        if not self.enable:
            return None
        seq = [0]

        def cb(frame: bytes) -> None:
            self.trace(service_request_id, {
                "stage": "egress",
                "seq": seq[0],
                "frame": frame.decode("utf-8", errors="backslashreplace"),
            })
            seq[0] += 1
        return cb
