"""RequestTracer: per-request JSONL trace appender.

Rebuild of ``http_service/request_tracer.{h,cpp}``: when enabled, every
inbound/outbound payload of a request is appended as
``{"timestamp", "service_request_id", "data"}`` lines under a mutex
(request_tracer.cpp:37-59), wired into request handling as a
``trace_callback``.
"""

from __future__ import annotations

import json
import os
import threading


import time
from typing import Any, Dict, Optional
from xllm_service_tpu.utils.locks import make_lock


class RequestTracer:
    def __init__(self, path: str = "trace/trace.json",
                 enable: bool = False) -> None:
        self.enable = enable
        self.path = path
        self._lock = make_lock("tracer", 90)
        if enable:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def trace(self, service_request_id: str, data: Any) -> None:
        if not self.enable:
            return
        line = json.dumps({
            "timestamp": time.time(),
            "service_request_id": service_request_id,
            "data": data,
        })
        with self._lock:
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line + "\n")

    def callback_for(self, service_request_id: str):
        """Bind a per-request trace callback (reference
        http_service/service.cpp:258-264)."""
        if not self.enable:
            return None

        def cb(stage: str, data: Dict[str, Any]) -> None:
            self.trace(service_request_id, {"stage": stage, **data})
        return cb
