"""Load-balance policies: RoundRobin / CacheAwareRouting / SloAware.

Rebuild of ``scheduler/loadbalance_policy/`` (SURVEY.md §2 #9-11). Each
policy picks a (prefill, decode) instance pair for one tokenized request.
Unlike the reference — whose ``schedule()`` bypasses the pluggable policy
(scheduler.cpp:100-119, TODO at :102; SURVEY.md §7.4) — the scheduler here
actually routes through the configured policy.

Explainability: ``select_instances_pair`` takes an optional ``audit``
dict and fills it with the decision's evidence — which candidates were
considered, each candidate's score terms (match ratio / KV usage /
waiting ratio for cache-aware routing), the winner per role, and the
fallback reason when the scored pick was discarded. The scheduler
attaches the audit to the request's span (``attrs.schedule_decision``
at ``GET /admin/trace/<id>``) and aggregates outcomes as
``xllm_schedule_decisions_total{policy,reason}``. Audits are
observe-only: passing ``audit`` never changes which pair is picked.
"""

from __future__ import annotations

import abc
import math
from typing import Any, Dict, List, Optional, Tuple

from xllm_service_tpu.config import LoadBalancePolicyType, ServiceOptions
from xllm_service_tpu.service.instance_mgr import InstanceMgr
from xllm_service_tpu.service.kvcache_mgr import GlobalKVCacheMgr


class LoadBalancePolicy(abc.ABC):
    """``select_instances_pair`` (reference loadbalance_policy.h:25-35)."""

    policy_name = "base"

    def __init__(self, mgr: InstanceMgr) -> None:
        self.mgr = mgr

    @abc.abstractmethod
    def select_instances_pair(self, token_ids: List[int],
                              audit: Optional[Dict[str, Any]] = None
                              ) -> Tuple[Optional[str], Optional[str]]: ...


class RoundRobinPolicy(LoadBalancePolicy):
    """Delegates to the instance manager's RR indexes
    (round_robin.cpp:18-22)."""

    policy_name = "round_robin"

    def select_instances_pair(self, token_ids, audit=None):
        prefill, decode = self.mgr.get_next_instance_pair()
        if audit is not None:
            audit.update(policy=self.policy_name,
                         reason="rr" if prefill else "no_instance",
                         prefill={"winner": prefill},
                         decode={"winner": decode})
        return prefill, decode


class CacheAwareRoutingPolicy(LoadBalancePolicy):
    """Score = prefix-match ratio − kv-cache usage − waiting-queue ratio,
    argmax per pool; least-loaded fallback when nothing overlaps
    (cache_aware_routing.cpp:22-87)."""

    policy_name = "cache_aware"

    def __init__(self, mgr: InstanceMgr, kvcache: GlobalKVCacheMgr,
                 block_size: int = 128) -> None:
        super().__init__(mgr)
        self.kvcache = kvcache
        self.block_size = block_size

    def _cost(self, name: str, match_score: float,
              total_blocks: int) -> Optional[Dict[str, float]]:
        """One candidate's score AND its terms — the terms are the
        explanation, so they are computed once here, not re-derived by
        the audit path (which could drift)."""
        inst = self.mgr.get(name)
        if inst is None:
            return None
        match_ratio = match_score / max(total_blocks, 1)
        kv_usage = inst.load.kv_cache_usage
        waiting_ratio = min(inst.load.waiting_requests / 16.0, 1.0)
        return {"score": match_ratio - kv_usage - waiting_ratio,
                "match_ratio": match_ratio, "kv_usage": kv_usage,
                "waiting_ratio": waiting_ratio}

    def _pick(self, pool: List[str], scores, total_blocks: int,
              audit: Optional[Dict[str, Any]] = None,
              role: str = "prefill") -> Optional[str]:
        candidates: List[Dict[str, Any]] = []
        best, best_cost = None, None
        for name in pool:
            cost = self._cost(name, scores.get(name, 0.0), total_blocks)
            if cost is None:
                continue
            candidates.append({"instance": name, **cost})
            if best_cost is None or cost["score"] > best_cost:
                best, best_cost = name, cost["score"]
        fallback_reason = None
        winner = best
        if best is None or scores.get(best, 0.0) == 0.0:
            fallback_reason = ("no_candidates" if best is None
                               else "no_prefix_overlap")
            fallback = self.mgr.least_loaded_instance(pool)
            winner = fallback or best
        if audit is not None:
            audit[role] = {"candidates": candidates, "winner": winner,
                           "fallback_reason": fallback_reason}
        return winner

    def select_instances_pair(self, token_ids, audit=None):
        total_blocks = max(len(token_ids) // self.block_size, 1)
        matched, scores, holders = self.kvcache.match_prefix_tiers(
            token_ids)
        if audit is not None:
            # Hand the walk's full evidence to the scheduler's
            # fetch-vs-recompute planner (it pops this before the audit
            # reaches the span): ONE prefix match per schedule(), not
            # one for scoring and another for planning.
            audit["_match_tiers"] = (matched, holders)
        prefill = self._pick(self.mgr.prefill_instances(), scores,
                             total_blocks, audit=audit, role="prefill")
        decode = self._pick(self.mgr.decode_instances(), scores,
                            total_blocks, audit=audit, role="decode")
        if audit is not None:
            fallbacks = [r for r in ("prefill", "decode")
                         if audit.get(r, {}).get("fallback_reason")]
            audit.update(
                policy=self.policy_name, total_blocks=total_blocks,
                reason=("fallback" if fallbacks else "scored")
                if (prefill or decode) else "no_instance")
        return prefill if prefill is not None else decode, decode


class SloAwarePolicy(LoadBalancePolicy):
    """Routes via the TimePredictor-driven SLO selection; RR fallback for
    un-tokenized requests (slo_aware_policy.cpp:26-38)."""

    policy_name = "slo_aware"

    def select_instances_pair(self, token_ids, audit=None):
        if not token_ids:
            prefill, decode = self.mgr.get_next_instance_pair()
            if audit is not None:
                audit.update(policy=self.policy_name,
                             reason="rr_untokenized",
                             prefill={"winner": prefill},
                             decode={"winner": decode})
            return prefill, decode
        backlog_terms: dict = {}
        prefill, decode, est_ttft = self.mgr.select_instance_pair_on_slo(
            len(token_ids), audit=backlog_terms)
        reason = "slo"
        if prefill is None:
            prefill, rr_decode = self.mgr.get_next_instance_pair()
            decode = decode or rr_decode
            reason = "fallback" if prefill else "no_instance"
        if audit is not None:
            # The winner's heartbeat-advertised prefill backlog rides
            # the audit (attrs.schedule_decision) so a routing decision
            # shaped by worker-side queueing is explainable after the
            # fact, not just the ledger-estimated TTFT.
            prefill_terms = {"winner": prefill,
                             "estimated_ttft_ms":
                                 round(est_ttft, 3)
                                 if math.isfinite(est_ttft)
                                 else None}
            prefill_terms.update(backlog_terms)
            audit.update(policy=self.policy_name, reason=reason,
                         prefill=prefill_terms,
                         decode={"winner": decode})
        return prefill, decode


def create_policy(opts: ServiceOptions, mgr: InstanceMgr,
                  kvcache: GlobalKVCacheMgr) -> LoadBalancePolicy:
    """Factory (reference scheduler.cpp:47-54)."""
    if opts.load_balance_policy == LoadBalancePolicyType.CACHE_AWARE:
        return CacheAwareRoutingPolicy(mgr, kvcache, opts.block_size)
    if opts.load_balance_policy == LoadBalancePolicyType.SLO_AWARE:
        return SloAwarePolicy(mgr)
    return RoundRobinPolicy(mgr)
