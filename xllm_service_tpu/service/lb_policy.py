"""Load-balance policies: RoundRobin / CacheAwareRouting / SloAware.

Rebuild of ``scheduler/loadbalance_policy/`` (SURVEY.md §2 #9-11). Each
policy picks a (prefill, decode) instance pair for one tokenized request.
Unlike the reference — whose ``schedule()`` bypasses the pluggable policy
(scheduler.cpp:100-119, TODO at :102; SURVEY.md §7.4) — the scheduler here
actually routes through the configured policy.
"""

from __future__ import annotations

import abc
from typing import List, Optional, Tuple

from xllm_service_tpu.config import LoadBalancePolicyType, ServiceOptions
from xllm_service_tpu.service.instance_mgr import InstanceMgr
from xllm_service_tpu.service.kvcache_mgr import GlobalKVCacheMgr


class LoadBalancePolicy(abc.ABC):
    """``select_instances_pair`` (reference loadbalance_policy.h:25-35)."""

    def __init__(self, mgr: InstanceMgr) -> None:
        self.mgr = mgr

    @abc.abstractmethod
    def select_instances_pair(self, token_ids: List[int]
                              ) -> Tuple[Optional[str], Optional[str]]: ...


class RoundRobinPolicy(LoadBalancePolicy):
    """Delegates to the instance manager's RR indexes
    (round_robin.cpp:18-22)."""

    def select_instances_pair(self, token_ids):
        return self.mgr.get_next_instance_pair()


class CacheAwareRoutingPolicy(LoadBalancePolicy):
    """Score = prefix-match ratio − kv-cache usage − waiting-queue ratio,
    argmax per pool; least-loaded fallback when nothing overlaps
    (cache_aware_routing.cpp:22-87)."""

    def __init__(self, mgr: InstanceMgr, kvcache: GlobalKVCacheMgr,
                 block_size: int = 128) -> None:
        super().__init__(mgr)
        self.kvcache = kvcache
        self.block_size = block_size

    def _cost(self, name: str, match_score: float,
              total_blocks: int) -> Optional[float]:
        inst = self.mgr.get(name)
        if inst is None:
            return None
        match_ratio = match_score / max(total_blocks, 1)
        waiting_ratio = min(inst.load.waiting_requests / 16.0, 1.0)
        return match_ratio - inst.load.kv_cache_usage - waiting_ratio

    def _pick(self, pool: List[str], scores, total_blocks: int
              ) -> Optional[str]:
        best, best_cost = None, None
        for name in pool:
            cost = self._cost(name, scores.get(name, 0.0), total_blocks)
            if cost is None:
                continue
            if best_cost is None or cost > best_cost:
                best, best_cost = name, cost
        if best is None or scores.get(best, 0.0) == 0.0:
            fallback = self.mgr.least_loaded_instance(pool)
            return fallback or best
        return best

    def select_instances_pair(self, token_ids):
        total_blocks = max(len(token_ids) // self.block_size, 1)
        _, scores = self.kvcache.match(token_ids)
        prefill = self._pick(self.mgr.prefill_instances(), scores,
                             total_blocks)
        decode = self._pick(self.mgr.decode_instances(), scores,
                            total_blocks)
        return prefill if prefill is not None else decode, decode


class SloAwarePolicy(LoadBalancePolicy):
    """Routes via the TimePredictor-driven SLO selection; RR fallback for
    un-tokenized requests (slo_aware_policy.cpp:26-38)."""

    def select_instances_pair(self, token_ids):
        if not token_ids:
            return self.mgr.get_next_instance_pair()
        prefill, decode, _ = self.mgr.select_instance_pair_on_slo(
            len(token_ids))
        if prefill is None:
            prefill, rr_decode = self.mgr.get_next_instance_pair()
            decode = decode or rr_decode
        return prefill, decode


def create_policy(opts: ServiceOptions, mgr: InstanceMgr,
                  kvcache: GlobalKVCacheMgr) -> LoadBalancePolicy:
    """Factory (reference scheduler.cpp:47-54)."""
    if opts.load_balance_policy == LoadBalancePolicyType.CACHE_AWARE:
        return CacheAwareRoutingPolicy(mgr, kvcache, opts.block_size)
    if opts.load_balance_policy == LoadBalancePolicyType.SLO_AWARE:
        return SloAwarePolicy(mgr)
    return RoundRobinPolicy(mgr)
