"""ResponseHandler: OpenAI chat/completion payload assembly.

Rebuild of ``scheduler/response_handler.{h,cpp}`` — the exact streaming
chunk grammar matters for OpenAI-SDK compatibility and is golden-tested:

  chat stream:  role chunk → content delta chunks → finish_reason chunk →
                (optional) usage chunk → ``data: [DONE]``
                (response_handler.cpp:20-134)
  completion stream: text delta chunks → finish chunk → usage → [DONE]
  non-stream:   one full JSON body (:136-216, :218-278, :280-326)

Extensions past the reference: multiple choices (OpenAI ``n``) keyed by
``SequenceOutput.index``, and ``logprobs`` rendered in both the chat
(``{"content": [...]}``) and completion
(``{"tokens", "token_logprobs", "top_logprobs", "text_offset"}``) shapes —
the reference accepts these fields in its protos (xllm/chat.proto:1-192)
but the rebuild actually serves them.

SSE framing (``data: <json>\\n\\n``) mirrors the reference's
``StreamCallData::write`` (common/call_data.h:173-201).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from xllm_service_tpu.obs import profiler
from xllm_service_tpu.utils.types import (
    FinishReason, LogProb, RequestOutput, Usage)

SSE_DONE = b"data: [DONE]\n\n"


def _now() -> int:
    return int(time.time())


def sse_frame(obj: Dict[str, Any]) -> bytes:
    with profiler.section("sse.assemble"):
        return b"data: " \
            + json.dumps(obj, separators=(",", ":")).encode() + b"\n\n"


def _chat_logprob_entry(lp: LogProb) -> Dict[str, Any]:
    return {
        "token": lp.token,
        "logprob": lp.logprob,
        "bytes": list(lp.token.encode("utf-8")),
        "top_logprobs": [
            {"token": t.get("token", ""), "logprob": t.get("logprob", 0.0),
             "bytes": list(str(t.get("token", "")).encode("utf-8"))}
            for t in lp.top_logprobs],
    }


def _chat_logprobs_json(lps: List[LogProb]) -> Optional[Dict[str, Any]]:
    if not lps:
        return None
    return {"content": [_chat_logprob_entry(lp) for lp in lps]}


class _CompletionLogprobs:
    """Accumulates the completion API's parallel-array logprobs shape."""

    def __init__(self) -> None:
        self.tokens: List[str] = []
        self.token_logprobs: List[float] = []
        self.top_logprobs: List[Dict[str, float]] = []
        self.text_offset: List[int] = []
        self._offset = 0

    def add(self, lps: List[LogProb]) -> None:
        for lp in lps:
            self.tokens.append(lp.token)
            self.token_logprobs.append(lp.logprob)
            self.top_logprobs.append(
                {t.get("token", ""): t.get("logprob", 0.0)
                 for t in lp.top_logprobs})
            self.text_offset.append(self._offset)
            self._offset += len(lp.token)

    def to_json(self) -> Optional[Dict[str, Any]]:
        if not self.tokens:
            return None
        return {"tokens": self.tokens,
                "token_logprobs": self.token_logprobs,
                "top_logprobs": self.top_logprobs,
                "text_offset": self.text_offset}


class ChatStreamAssembler:
    """Builds the chat-completion SSE chunk sequence for one request
    (every choice index streams role → deltas → finish).

    ``emit_token_ids``: the recovery ledger extension — every delta
    chunk carries its engine token ids under a top-level ``"xllm"``
    key, and deltas are emitted even when their text is empty (UTF-8 /
    stop-string holdback), so a ledger-aware relay sees every token id
    in order. The relay STRIPS the key before bytes reach the client;
    OpenAI chunk grammar is unchanged when the flag is off
    (docs/ROBUSTNESS.md)."""

    def __init__(self, request_id: str, model: str,
                 include_usage: bool = False,
                 emit_token_ids: bool = False) -> None:
        self.request_id = request_id
        self.model = model
        self.include_usage = include_usage
        self.emit_token_ids = emit_token_ids
        self.created = _now()
        self._sent_role: set = set()
        self._usage = Usage()

    def _chunk(self, delta: Dict[str, Any], index: int = 0,
               finish_reason: Optional[str] = None,
               logprobs: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        choice: Dict[str, Any] = {"index": index, "delta": delta,
                                  "finish_reason": finish_reason}
        if logprobs is not None:
            choice["logprobs"] = logprobs
        return {
            "id": self.request_id,
            "object": "chat.completion.chunk",
            "created": self.created,
            "model": self.model,
            "choices": [choice],
        }

    def on_output(self, out: RequestOutput) -> List[bytes]:
        frames: List[bytes] = []
        if out.usage:
            self._usage = out.usage
        for seq in out.outputs:
            if seq.index not in self._sent_role:
                frames.append(sse_frame(
                    self._chunk({"role": "assistant"}, seq.index)))
                self._sent_role.add(seq.index)
            if seq.text or seq.logprobs or (self.emit_token_ids
                                            and seq.token_ids):
                # A token whose text delta is empty (UTF-8 or stop-string
                # holdback) still carries its logprob entry — and, under
                # the ledger extension, its token ids (a held-back token
                # missing from the ledger would corrupt the resume
                # context).
                chunk = self._chunk(
                    {"content": seq.text}, seq.index,
                    logprobs=_chat_logprobs_json(seq.logprobs))
                if self.emit_token_ids and seq.token_ids:
                    chunk["xllm"] = {"token_ids": list(seq.token_ids)}
                frames.append(sse_frame(chunk))
            if seq.finish_reason != FinishReason.NONE:
                frames.append(sse_frame(
                    self._chunk({}, seq.index, seq.finish_reason.openai)))
        if out.finished:
            if self.include_usage:
                frames.append(sse_frame({
                    "id": self.request_id,
                    "object": "chat.completion.chunk",
                    "created": self.created,
                    "model": self.model,
                    "choices": [],
                    "usage": self._usage.to_json(),
                }))
            frames.append(SSE_DONE)
        return frames


class CompletionStreamAssembler:
    """Text-completion SSE chunks (response_handler.cpp:218-278).
    ``emit_token_ids``: recovery ledger extension — see
    ChatStreamAssembler."""

    def __init__(self, request_id: str, model: str,
                 include_usage: bool = False,
                 emit_token_ids: bool = False) -> None:
        self.request_id = request_id
        self.model = model
        self.include_usage = include_usage
        self.emit_token_ids = emit_token_ids
        self.created = _now()
        self._usage = Usage()
        self._lp: Dict[int, _CompletionLogprobs] = {}

    def _chunk(self, text: str, index: int = 0,
               finish_reason: Optional[str] = None,
               logprobs: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        return {
            "id": self.request_id,
            "object": "text_completion",
            "created": self.created,
            "model": self.model,
            "choices": [{"index": index, "text": text,
                         "logprobs": logprobs,
                         "finish_reason": finish_reason}],
        }

    def on_output(self, out: RequestOutput) -> List[bytes]:
        frames: List[bytes] = []
        if out.usage:
            self._usage = out.usage
        for seq in out.outputs:
            lp_json = None
            if seq.logprobs:
                # Per-index accumulator keeps text_offset global across
                # the whole completion; each chunk ships only its new
                # entries.
                acc = self._lp.setdefault(seq.index, _CompletionLogprobs())
                before = len(acc.tokens)
                acc.add(seq.logprobs)
                lp_json = {
                    "tokens": acc.tokens[before:],
                    "token_logprobs": acc.token_logprobs[before:],
                    "top_logprobs": acc.top_logprobs[before:],
                    "text_offset": acc.text_offset[before:],
                }
            if seq.text or seq.logprobs or (self.emit_token_ids
                                            and seq.token_ids):
                chunk = self._chunk(seq.text, seq.index, logprobs=lp_json)
                if self.emit_token_ids and seq.token_ids:
                    chunk["xllm"] = {"token_ids": list(seq.token_ids)}
                frames.append(sse_frame(chunk))
            if seq.finish_reason != FinishReason.NONE:
                frames.append(sse_frame(
                    self._chunk("", seq.index,
                                seq.finish_reason.openai)))
        if out.finished:
            if self.include_usage:
                frames.append(sse_frame({
                    "id": self.request_id,
                    "object": "text_completion",
                    "created": self.created,
                    "model": self.model,
                    "choices": [],
                    "usage": self._usage.to_json(),
                }))
            frames.append(SSE_DONE)
        return frames


class ResponseCollector:
    """Aggregates streamed RequestOutputs into one non-stream OpenAI body
    (all ``n`` choices, logprobs, usage).

    ``target_n``: server-side ``best_of`` selection — when more candidate
    choices were generated than requested, keep the ``target_n`` with the
    highest mean token logprob (the ranking key rides the finish delta's
    ``mean_logprob``) and renumber them 0..target_n-1. Usage still counts
    every candidate's tokens, matching OpenAI billing semantics."""

    def __init__(self, request_id: str, model: str, is_chat: bool,
                 target_n: Optional[int] = None) -> None:
        self.request_id = request_id
        self.model = model
        self.is_chat = is_chat
        self.target_n = target_n
        self.usage = Usage()
        self._texts: Dict[int, List[str]] = {}
        self._finish: Dict[int, FinishReason] = {}
        self._chat_lps: Dict[int, List[LogProb]] = {}
        self._cmpl_lps: Dict[int, _CompletionLogprobs] = {}
        self._mean_lp: Dict[int, float] = {}

    def add(self, out: RequestOutput) -> None:
        if out.usage:
            self.usage = out.usage
        for seq in out.outputs:
            self._texts.setdefault(seq.index, []).append(seq.text)
            if seq.finish_reason != FinishReason.NONE:
                self._finish[seq.index] = seq.finish_reason
            if seq.mean_logprob is not None:
                self._mean_lp[seq.index] = seq.mean_logprob
            if seq.logprobs:
                if self.is_chat:
                    self._chat_lps.setdefault(seq.index, []).extend(
                        seq.logprobs)
                else:
                    self._cmpl_lps.setdefault(
                        seq.index, _CompletionLogprobs()).add(seq.logprobs)

    def body(self) -> Dict[str, Any]:
        indices = sorted(self._texts) or [0]
        if self.target_n is not None and len(indices) > self.target_n:
            # best_of selection: rank candidates by mean token logprob
            # (candidates missing a finish delta rank last), keep the
            # best target_n in rank order.
            indices = sorted(
                indices,
                key=lambda i: self._mean_lp.get(i, float("-inf")),
                reverse=True)[:self.target_n]
        choices = []
        for rank, i in enumerate(indices):
            text = "".join(self._texts.get(i, []))
            finish = self._finish.get(i, FinishReason.STOP)
            if self.is_chat:
                choice: Dict[str, Any] = {
                    "index": rank,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": finish.openai or "stop",
                }
                lps = self._chat_lps.get(i)
                choice["logprobs"] = _chat_logprobs_json(lps or [])
            else:
                choice = {
                    "index": rank,
                    "text": text,
                    "logprobs": (self._cmpl_lps[i].to_json()
                                 if i in self._cmpl_lps else None),
                    "finish_reason": finish.openai or "stop",
                }
            choices.append(choice)
        return {
            "id": self.request_id,
            "object": "chat.completion" if self.is_chat
            else "text_completion",
            "created": _now(),
            "model": self.model,
            "choices": choices,
            "usage": self.usage.to_json(),
        }


