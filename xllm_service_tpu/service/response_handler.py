"""ResponseHandler: OpenAI chat/completion payload assembly.

Rebuild of ``scheduler/response_handler.{h,cpp}`` — the exact streaming
chunk grammar matters for OpenAI-SDK compatibility and is golden-tested:

  chat stream:  role chunk → content delta chunks → finish_reason chunk →
                (optional) usage chunk → ``data: [DONE]``
                (response_handler.cpp:20-134)
  completion stream: text delta chunks → finish chunk → usage → [DONE]
  non-stream:   one full JSON body (:136-216, :218-278, :280-326)

SSE framing (``data: <json>\\n\\n``) mirrors the reference's
``StreamCallData::write`` (common/call_data.h:173-201).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from xllm_service_tpu.utils.types import FinishReason, RequestOutput, Usage

SSE_DONE = b"data: [DONE]\n\n"


def _now() -> int:
    return int(time.time())


def sse_frame(obj: Dict[str, Any]) -> bytes:
    return b"data: " + json.dumps(obj, separators=(",", ":")).encode() \
        + b"\n\n"


class ChatStreamAssembler:
    """Builds the chat-completion SSE chunk sequence for one request."""

    def __init__(self, request_id: str, model: str,
                 include_usage: bool = False) -> None:
        self.request_id = request_id
        self.model = model
        self.include_usage = include_usage
        self.created = _now()
        self._sent_role = False
        self._usage = Usage()

    def _chunk(self, delta: Dict[str, Any],
               finish_reason: Optional[str] = None) -> Dict[str, Any]:
        return {
            "id": self.request_id,
            "object": "chat.completion.chunk",
            "created": self.created,
            "model": self.model,
            "choices": [{"index": 0, "delta": delta,
                         "finish_reason": finish_reason}],
        }

    def on_output(self, out: RequestOutput) -> List[bytes]:
        frames: List[bytes] = []
        if not self._sent_role:
            frames.append(sse_frame(self._chunk({"role": "assistant"})))
            self._sent_role = True
        if out.usage:
            self._usage = out.usage
        for seq in out.outputs:
            if seq.text:
                frames.append(sse_frame(
                    self._chunk({"content": seq.text})))
            if seq.finish_reason != FinishReason.NONE:
                frames.append(sse_frame(
                    self._chunk({}, seq.finish_reason.openai)))
        if out.finished:
            if self.include_usage:
                frames.append(sse_frame({
                    "id": self.request_id,
                    "object": "chat.completion.chunk",
                    "created": self.created,
                    "model": self.model,
                    "choices": [],
                    "usage": self._usage.to_json(),
                }))
            frames.append(SSE_DONE)
        return frames


class CompletionStreamAssembler:
    """Text-completion SSE chunks (response_handler.cpp:218-278)."""

    def __init__(self, request_id: str, model: str,
                 include_usage: bool = False) -> None:
        self.request_id = request_id
        self.model = model
        self.include_usage = include_usage
        self.created = _now()
        self._usage = Usage()

    def _chunk(self, text: str,
               finish_reason: Optional[str] = None) -> Dict[str, Any]:
        return {
            "id": self.request_id,
            "object": "text_completion",
            "created": self.created,
            "model": self.model,
            "choices": [{"index": 0, "text": text, "logprobs": None,
                         "finish_reason": finish_reason}],
        }

    def on_output(self, out: RequestOutput) -> List[bytes]:
        frames: List[bytes] = []
        if out.usage:
            self._usage = out.usage
        for seq in out.outputs:
            if seq.text:
                frames.append(sse_frame(self._chunk(seq.text)))
            if seq.finish_reason != FinishReason.NONE:
                frames.append(sse_frame(
                    self._chunk("", seq.finish_reason.openai)))
        if out.finished:
            if self.include_usage:
                frames.append(sse_frame({
                    "id": self.request_id,
                    "object": "text_completion",
                    "created": self.created,
                    "model": self.model,
                    "choices": [],
                    "usage": self._usage.to_json(),
                }))
            frames.append(SSE_DONE)
        return frames


def full_chat_response(request_id: str, model: str, text: str,
                       finish_reason: FinishReason, usage: Usage
                       ) -> Dict[str, Any]:
    """Non-streaming chat completion (response_handler.cpp:136-216)."""
    return {
        "id": request_id,
        "object": "chat.completion",
        "created": _now(),
        "model": model,
        "choices": [{
            "index": 0,
            "message": {"role": "assistant", "content": text},
            "finish_reason": finish_reason.openai or "stop",
        }],
        "usage": usage.to_json(),
    }


def full_completion_response(request_id: str, model: str, text: str,
                             finish_reason: FinishReason, usage: Usage
                             ) -> Dict[str, Any]:
    return {
        "id": request_id,
        "object": "text_completion",
        "created": _now(),
        "model": model,
        "choices": [{
            "index": 0,
            "text": text,
            "logprobs": None,
            "finish_reason": finish_reason.openai or "stop",
        }],
        "usage": usage.to_json(),
    }
