"""Worker-facing RPC plane of the service.

Rebuild of ``rpc_service/service.{h,cpp}`` (SURVEY.md §2 #3): heartbeat
ingestion, instance metainfo queries, static PD lists, the ``Generations``
token fan-in (decode worker → service, response topology 2), and
``GetConfig`` exposing ``enable_decode_response_to_service``
(rpc_service/service.cpp:215-223). Carried over HTTP/JSON instead of brpc
baidu_std; the method surface is the same.
"""

from __future__ import annotations

import logging
from typing import Any, Dict

from xllm_service_tpu.utils.wire import check_version
from xllm_service_tpu.config import ServiceOptions
from xllm_service_tpu.service.httpd import Request, Response, Router
from xllm_service_tpu.service.instance_types import Heartbeat
from xllm_service_tpu.service.scheduler import Scheduler
from xllm_service_tpu.utils.types import RequestOutput

logger = logging.getLogger(__name__)


class RpcService:
    def __init__(self, opts: ServiceOptions, scheduler: Scheduler) -> None:
        self.opts = opts
        self.scheduler = scheduler
        # SpanStore of the co-resident HttpService (wired by Master):
        # heartbeat-shipped worker span stages merge here so the
        # /admin/trace/<id> timeline crosses the plane boundary.
        self.spans = None
        # StepBooks of the co-resident HttpService (wired by Master):
        # heartbeat-shipped step flight-recorder tails land here — the
        # /admin/timeline fallback when a live worker pull fails.
        self.step_books = None

    def install(self, router: Router) -> None:
        router.route("GET", "/rpc/hello",
                     lambda r: Response.json({"ok": True}))
        router.route("POST", "/rpc/heartbeat", self.heartbeat)
        router.route("POST", "/rpc/generations", self.generations)
        router.route("GET", "/rpc/instance_info", self.instance_info)
        router.route("GET", "/rpc/static_prefill_list",
                     self.static_prefill_list)
        router.route("GET", "/rpc/static_decode_list",
                     self.static_decode_list)
        router.route("GET", "/rpc/config", self.get_config)

    # -- Heartbeat (rpc_service/service.cpp:114-121) ----------------------
    def heartbeat(self, req: Request) -> Response:
        body = req.json()
        check_version(body, "Heartbeat")
        hb = Heartbeat.from_json(body)
        if not hb.name:
            return Response.error(400, "heartbeat missing name")
        registered = self.scheduler.handle_instance_heartbeat(hb)
        if self.spans is not None:
            for rec in hb.spans:
                rid = rec.get("request_id")
                if rid:
                    self.spans.merge_remote(
                        rid, plane="worker",
                        events=rec.get("events", []), source=hb.name,
                        attrs=rec.get("attrs") or None)
        if self.step_books is not None and hb.steps:
            self.step_books.ingest(hb.name, hb.steps)
        # The ack carries the master epoch (fenced elections) — workers
        # reject an ack whose epoch regresses below one they've already
        # acked (a deposed master still answering) — and the degraded
        # flag so a worker knows its lease-keepalive failures are a
        # store outage, not its own death (docs/ROBUSTNESS.md).
        return Response.json({"ok": True, "registered": registered,
                              "epoch": self.scheduler.current_epoch(),
                              "degraded": self.scheduler.degraded})

    # -- Generations fan-in (rpc_service/service.cpp:149-213) -------------
    def generations(self, req: Request) -> Response:
        body = req.json()
        check_version(body, "generations")
        # Sender identity (additive wire field): the scheduler's
        # exactly-once guard for recovered requests — a straggler push
        # from a deposed instance must not duplicate tokens. Absent
        # from old workers' pushes → accepted (pre-recovery behavior).
        source = body.get("from", "")
        for d in body.get("outputs", []):
            out = RequestOutput.from_json(d)
            self.scheduler.handle_generation(out, source=source)
        return Response.json({"ok": True})

    # -- Instance queries (rpc_service/service.cpp:81-147) ----------------
    def instance_info(self, req: Request) -> Response:
        name = req.param("name")
        info = self.scheduler.instance_mgr.instance_info(name)
        if info is None:
            return Response.error(404, f"unknown instance {name}")
        return Response.json(info)

    def static_prefill_list(self, req: Request) -> Response:
        return Response.json(
            {"instances": self.scheduler.instance_mgr.prefill_instances()})

    def static_decode_list(self, req: Request) -> Response:
        return Response.json(
            {"instances": self.scheduler.instance_mgr.decode_instances()})

    # -- GetConfig (rpc_service/service.cpp:215-223) ----------------------
    def get_config(self, req: Request) -> Response:
        return Response.json({
            "enable_decode_response_to_service":
                self.opts.enable_decode_response_to_service,
            "block_size": self.opts.block_size,
            "murmur_hash3_seed": self.opts.murmur_hash3_seed,
            "epoch": self.scheduler.current_epoch(),
        })
