"""GlobalKVCacheMgr: cluster-wide prefix KV-cache index.

Rebuild of ``scheduler/managers/global_kvcache_mgr.{h,cpp}``: a map from
128-bit chained block digests to the set of instances holding that block,
tiered HBM → host-DRAM → SSD (reference CacheLocations, common/types.h:
272-317). ``match()`` walks a prompt's block-aligned prefix digests until
first miss and scores per-instance overlap (global_kvcache_mgr.cpp:71-129)
— the signal cache-aware routing maximizes. Heartbeats deliver per-worker
deltas (stored/offload/removed, :175-223); the master replica uploads
accumulated deltas to the coordination store under ``XLLM:CACHE:`` every
upload interval (:225-245) and non-masters learn the index by watching that
prefix (:131-173).

Digests travel as hex strings on the wire; in-memory keys are the raw
16-byte digests from ``utils.hashing`` (bit-identical to the worker's
page hashes, so service-side match and worker-side reuse agree exactly).
"""

from __future__ import annotations

import threading


from typing import Dict, Iterable, List, Optional, Set, Tuple

from xllm_service_tpu.service.coordination import (
    KEY_CACHE, CoordinationStore)
from xllm_service_tpu.utils.hashing import prefix_block_hashes
from xllm_service_tpu.utils.locks import make_lock

TIER_HBM = "hbm"
TIER_DRAM = "dram"
TIER_SSD = "ssd"
_TIERS = (TIER_HBM, TIER_DRAM, TIER_SSD)
# Match-score weight per tier: an HBM hit saves more than a DRAM/SSD hit.
TIER_WEIGHT = {TIER_HBM: 1.0, TIER_DRAM: 0.7, TIER_SSD: 0.4}


class CacheLocations:
    """Which instances hold one block, per storage tier."""

    __slots__ = ("tiers",)

    def __init__(self) -> None:
        self.tiers: Dict[str, Set[str]] = {t: set() for t in _TIERS}

    @property
    def empty(self) -> bool:
        return not any(self.tiers.values())

    def holders(self) -> Set[str]:
        out: Set[str] = set()
        for s in self.tiers.values():
            out |= s
        return out


class GlobalKVCacheMgr:
    def __init__(self, store: CoordinationStore, block_size: int = 128,
                 seed: int = 0, is_master: bool = True) -> None:
        self.store = store
        self.block_size = block_size
        self.seed = seed
        self.is_master = is_master
        self._lock = make_lock("kvcache_mgr", 35)
        self._index: Dict[bytes, CacheLocations] = {}  # guarded-by: kvcache_mgr
        # Deltas accumulated since the last master upload, keyed by digest:
        # value None → block gone everywhere (delete the store key).
        self._dirty: Dict[bytes, Optional[Dict[str, List[str]]]] = {}  # guarded-by: kvcache_mgr
        self._watch_id: Optional[int] = None
        if not is_master:
            self._watch_id = store.add_watch(KEY_CACHE, self._on_watch)
        self._bootstrap()

    # ------------------------------------------------------------------
    # Bootstrap / replication
    # ------------------------------------------------------------------
    def _bootstrap(self) -> None:
        """Load the persisted index (global_kvcache_mgr.cpp:45-49).

        The watch is registered BEFORE this runs (no event gap), so
        ``_on_watch`` can already be firing on the store's dispatch
        thread — the index writes must happen under the lock (xlint
        thread-root-race finding XLINT13-003: ``GlobalKVCacheMgr._index``
        mutated from the init tail and the watch root with no common
        guard). The store read stays OUTSIDE the lock: it is network
        I/O for the etcd/remote stores (blocking-under-lock)."""
        items = self.store.get_prefix_json(KEY_CACHE)
        with self._lock:
            for key, val in items.items():
                digest = bytes.fromhex(key[len(KEY_CACHE):])
                self._apply_locations(digest, val)

    def _on_watch(self, event) -> None:
        ev_type, key, value = event
        digest = bytes.fromhex(key[len(KEY_CACHE):])
        with self._lock:
            if ev_type == "DELETE":
                self._index.pop(digest, None)
            else:
                import json
                self._apply_locations(digest, json.loads(value))

    def _apply_locations(self, digest: bytes, val: Dict[str, List[str]]
                         ) -> None:
        loc = CacheLocations()
        for tier in _TIERS:
            loc.tiers[tier] = set(val.get(tier, []))
        if loc.empty:
            self._index.pop(digest, None)
        else:
            self._index[digest] = loc

    # ------------------------------------------------------------------
    # Match
    # ------------------------------------------------------------------
    def match(self, token_ids: List[int]
              ) -> Tuple[int, Dict[str, float]]:
        """Walk block-aligned prefix digests until first global miss.

        Returns (num_matched_blocks, per-instance weighted overlap score in
        blocks). An instance's score counts only its *contiguous* prefix
        blocks — a hole in its copy ends its usable prefix, matching how the
        worker can only reuse contiguous leading pages."""
        matched, scores, _ = self.match_prefix_tiers(token_ids)
        return matched, scores

    def match_prefix_tiers(self, token_ids: List[int]
                           ) -> Tuple[int, Dict[str, float],
                                      Dict[str, List[str]]]:
        """``match()`` plus the evidence the fetch-vs-recompute planner
        needs: per instance, the best storage tier of EVERY block in its
        contiguous leading run (``holders[inst][i]`` = tier of block i).
        ``len(holders[inst])`` is the instance's usable prefix in blocks
        — unweighted, unlike the routing score."""
        hashes = prefix_block_hashes(token_ids, self.block_size, self.seed)
        scores: Dict[str, float] = {}
        holders: Dict[str, List[str]] = {}
        alive: Dict[str, bool] = {}
        matched = 0
        with self._lock:
            for idx, h in enumerate(hashes):
                loc = self._index.get(h)
                if loc is None or loc.empty:
                    break
                matched += 1
                block_holders: Dict[str, Tuple[float, str]] = {}
                for tier in _TIERS:
                    w = TIER_WEIGHT[tier]
                    for inst in loc.tiers[tier]:
                        cur = block_holders.get(inst)
                        if cur is None or w > cur[0]:
                            block_holders[inst] = (w, tier)
                for inst, (w, tier) in block_holders.items():
                    # An instance first seen past block 0 has a hole at the
                    # front — its copy is not a usable leading prefix.
                    if alive.get(inst, idx == 0):
                        scores[inst] = scores.get(inst, 0.0) + w
                        holders.setdefault(inst, []).append(tier)
                        alive[inst] = True
                for inst in list(alive):
                    if inst not in block_holders:
                        alive[inst] = False
        return matched, scores, holders

    def num_blocks(self) -> int:
        with self._lock:
            return len(self._index)

    # ------------------------------------------------------------------
    # Heartbeat ingestion (master path)
    # ------------------------------------------------------------------
    def record_updated_kvcaches(self, instance: str,
                                stored: Iterable[bytes] = (),
                                removed: Iterable[bytes] = (),
                                offloaded: Iterable[bytes] = (),
                                offloaded_ssd: Iterable[bytes] = ()
                                ) -> None:
        """Apply one worker's cache delta (global_kvcache_mgr.cpp:175-223).
        ``stored`` means the block is in HBM *now* — a restore from the
        worker's spill tier re-stores it, so any DRAM/SSD claim this
        instance held is superseded (the worker's tier consumed its
        copy). ``offloaded`` demotes HBM→DRAM (the TPU worker's host-RAM
        spill tier); ``offloaded_ssd`` demotes DRAM→SSD (disk tier);
        ``removed`` drops the instance from every tier.

        Cross-list ordering within one delta is lost on the wire, so
        demotions apply BEFORE ``stored``: a block that spilled and was
        restored inside one beat (the common compound) ends HBM, which
        is its true final state."""
        with self._lock:
            for h in offloaded:
                loc = self._index.get(h)
                if loc is None:
                    continue
                loc.tiers[TIER_HBM].discard(instance)
                loc.tiers[TIER_DRAM].add(instance)
                self._mark_dirty(h, loc)
            for h in offloaded_ssd:
                loc = self._index.get(h)
                if loc is None:
                    continue
                loc.tiers[TIER_HBM].discard(instance)
                loc.tiers[TIER_DRAM].discard(instance)
                loc.tiers[TIER_SSD].add(instance)
                self._mark_dirty(h, loc)
            for h in stored:
                loc = self._index.setdefault(h, CacheLocations())
                loc.tiers[TIER_HBM].add(instance)
                loc.tiers[TIER_DRAM].discard(instance)
                loc.tiers[TIER_SSD].discard(instance)
                self._mark_dirty(h, loc)
            for h in removed:
                loc = self._index.get(h)
                if loc is None:
                    continue
                for tier in _TIERS:
                    loc.tiers[tier].discard(instance)
                if loc.empty:
                    del self._index[h]
                    self._dirty[h] = None
                else:
                    self._mark_dirty(h, loc)

    def remove_instance(self, instance: str) -> None:
        """Instance died: scrub it from every block (part of the etcd-DELETE
        cleanup path, instance_mgr.cpp:606-686)."""
        with self._lock:
            for h in list(self._index):
                loc = self._index[h]
                present = any(instance in loc.tiers[t] for t in _TIERS)
                if not present:
                    continue
                for tier in _TIERS:
                    loc.tiers[tier].discard(instance)
                if loc.empty:
                    del self._index[h]
                    self._dirty[h] = None
                else:
                    self._mark_dirty(h, loc)

    def _mark_dirty(self, h: bytes, loc: CacheLocations) -> None:
        self._dirty[h] = {t: sorted(loc.tiers[t]) for t in _TIERS
                          if loc.tiers[t]}

    # ------------------------------------------------------------------
    # Master upload (called from the scheduler's 3 s loop)
    # ------------------------------------------------------------------
    def upload_kvcache(self) -> int:
        """Flush accumulated deltas to the store (:225-245). Returns the
        number of keys written/deleted."""
        with self._lock:
            dirty, self._dirty = self._dirty, {}
        for h, val in dirty.items():
            key = KEY_CACHE + h.hex()
            if val is None:
                self.store.delete(key)
            else:
                self.store.put_json(key, val)
        return len(dirty)

    def close(self) -> None:
        if self._watch_id is not None:
            self.store.cancel_watch(self._watch_id)
