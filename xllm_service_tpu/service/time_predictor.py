"""TTFT/TPOT latency predictors for SLO-aware routing.

Reference: ``common/time_predictor.{h,cpp}`` — a degree-2 polynomial TTFT
fit over per-instance profiling points (Eigen Vandermonde + QR,
time_predictor.cpp:22-48) and a linear TPOT model
``c0 + c1*batch + c2*batch*(seq_len-1)`` (:50-95). Rebuilt on numpy
least-squares; the reference's bug where the TPOT else-branch zeroes the
*ttft* coefficients (time_predictor.cpp:70-72, SURVEY.md §7.4) is not
replicated.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


class TimePredictor:
    """Per-instance latency model fit from registration profiling data."""

    def __init__(self) -> None:
        self._ttft_coef: np.ndarray | None = None      # [c0, c1, c2]
        self._tpot_coef: np.ndarray | None = None      # [c0, c1, c2]

    @property
    def has_ttft(self) -> bool:
        return self._ttft_coef is not None

    @property
    def has_tpot(self) -> bool:
        return self._tpot_coef is not None

    def fit_ttft(self, samples: Sequence[Tuple[float, float]]) -> bool:
        """samples: [(num_prompt_tokens, ttft_ms)]; fits
        ttft ≈ c0 + c1*n + c2*n²."""
        if len(samples) < 3:
            return False
        n = np.asarray([s[0] for s in samples], np.float64)
        y = np.asarray([s[1] for s in samples], np.float64)
        A = np.stack([np.ones_like(n), n, n * n], axis=1)
        self._ttft_coef, *_ = np.linalg.lstsq(A, y, rcond=None)
        return True

    def fit_tpot(self,
                 samples: Sequence[Tuple[float, float, float]]) -> bool:
        """samples: [(batch, seq_len, tpot_ms)]; fits
        tpot ≈ c0 + c1*batch + c2*batch*(seq_len-1)."""
        if len(samples) < 3:
            return False
        b = np.asarray([s[0] for s in samples], np.float64)
        t = np.asarray([s[1] for s in samples], np.float64)
        y = np.asarray([s[2] for s in samples], np.float64)
        A = np.stack([np.ones_like(b), b, b * (t - 1.0)], axis=1)
        self._tpot_coef, *_ = np.linalg.lstsq(A, y, rcond=None)
        return True

    def predict_ttft(self, num_tokens: int) -> float:
        if self._ttft_coef is None:
            return 0.0
        c = self._ttft_coef
        return float(c[0] + c[1] * num_tokens + c[2] * num_tokens ** 2)

    def predict_tpot(self, total_tokens: int, num_requests: int) -> float:
        """Predicted per-token latency with ``num_requests`` decoding and
        ``total_tokens`` total context across them (reference call shape:
        instance_mgr.cpp:849-877)."""
        if self._tpot_coef is None:
            return 0.0
        c = self._tpot_coef
        b = float(max(num_requests, 1))
        mean_len = float(total_tokens) / b
        return float(c[0] + c[1] * b + c[2] * b * (mean_len - 1.0))

    @classmethod
    def from_profiling(cls, ttft: Sequence[Tuple[float, float]],
                       tpot: Sequence[Tuple[float, float, float]]
                       ) -> "TimePredictor":
        p = cls()
        p.fit_ttft(ttft)
        p.fit_tpot(tpot)
        return p


def profile_engine(engine, prompt_lens: Sequence[int] = (32, 64, 128),
                   batches: Sequence[int] = (1, 2, 4)
                   ) -> Tuple[List[Tuple[float, float]],
                              List[Tuple[float, float, float]]]:
    """Worker-side profiling mode: measure real TTFT/TPOT points on the
    live engine so registration metadata carries hardware-true samples
    (SURVEY.md §7.3 item 6). Small and synchronous — run once at startup."""
    import time as _time

    from xllm_service_tpu.runtime.engine import EngineRequest
    from xllm_service_tpu.utils.types import SamplingParams

    ttft_samples: List[Tuple[float, float]] = []
    tpot_samples: List[Tuple[float, float, float]] = []
    max_prompt = engine.ecfg.prefill_buckets[-1]
    for n in prompt_lens:
        if n > max_prompt:
            continue
        t0 = _time.monotonic()
        engine.add_request(EngineRequest(
            request_id=f"__profile_ttft_{n}", token_ids=[1] * n,
            sampling=SamplingParams(max_tokens=1, ignore_eos=True)))
        while engine.has_work():
            engine.step()
        ttft_samples.append((float(n), 1000.0 *
                             (_time.monotonic() - t0)))
    gen = 8
    for b in batches:
        if b > engine.ecfg.max_batch_size:
            continue
        n = min(32, max_prompt)
        for i in range(b):
            engine.add_request(EngineRequest(
                request_id=f"__profile_tpot_{b}_{i}", token_ids=[1] * n,
                sampling=SamplingParams(max_tokens=gen, ignore_eos=True)))
        while engine.waiting:
            engine.step()
        t0 = _time.monotonic()
        steps = 0
        while engine.has_work():
            engine.step()
            steps += 1
        if steps > 1:
            tpot_ms = 1000.0 * (_time.monotonic() - t0) / steps
            tpot_samples.append((float(b), float(n + gen), tpot_ms))
    return ttft_samples, tpot_samples
