"""Instance-plane value types: metainfo, load/latency/request metrics.

Python equivalents of the reference's ``common/types.h`` cluster types:
``InstanceMetaInfo`` (types.h:193-258 — name, rpc address, role type,
KV-transfer handles, profiling data), ``LoadMetrics`` (types.h:81-115),
``LatencyMetrics`` (types.h:118-127), ``RequestMetrics`` (types.h:138-155).
These cross the coordination store and heartbeats as JSON.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Tuple

from xllm_service_tpu.config import InstanceType


@dataclasses.dataclass
class InstanceMetaInfo:
    """Registration record a worker writes under ``XLLM:<TYPE>:<name>``.

    ``cluster_ids``/``addrs``/``k_cache_ids``/``v_cache_ids``/``dp_size``
    keep the reference's KV-transfer brokerage contract (types.h:174-178):
    for the TPU worker, ``addrs`` are the worker KV-transfer endpoints and
    the cache ids name its preallocated per-layer KV page pools.
    """

    name: str = ""                      # "host:port" of the worker HTTP server
    rpc_address: str = ""               # where the service reaches the worker
    instance_type: InstanceType = InstanceType.DEFAULT
    models: List[str] = dataclasses.field(default_factory=list)
    # KV-transfer brokerage handles.
    cluster_ids: List[int] = dataclasses.field(default_factory=list)
    addrs: List[str] = dataclasses.field(default_factory=list)
    k_cache_ids: List[int] = dataclasses.field(default_factory=list)
    v_cache_ids: List[int] = dataclasses.field(default_factory=list)
    dp_size: int = 1
    # Profiling samples for the SLO TimePredictor (types.h:180-182):
    # ttft: [(num_tokens, ttft_ms)], tpot: [(batch, seq_len, tpot_ms)].
    ttft_profiling_data: List[Tuple[float, float]] = \
        dataclasses.field(default_factory=list)
    tpot_profiling_data: List[Tuple[float, float, float]] = \
        dataclasses.field(default_factory=list)
    # Serverless memory accounting (GB) for the multi-model allocator.
    memory_budget_gb: float = 60.0
    # Block-hash contract advertisement (docs/KV_CACHE.md): the engine's
    # KV page size (tokens per content-addressed block) and murmur hash
    # seed. The service's GlobalKVCacheMgr keys on (block_size, seed) —
    # if a worker's pair diverges, its reported digests can NEVER match
    # service-side digests and cache-aware routing scores it on garbage;
    # InstanceMgr fails loud (event + log) on mismatch. 0 page_size =
    # not advertised (pre-contract worker).
    page_size: int = 0
    hash_seed: int = 0
    # Bytes of one content-addressed KV block (k+v, all layers) — the
    # fetch-vs-recompute cost model's bytes term.
    kv_block_bytes: int = 0
    # EPD encode-plane advertisement (docs/EPD.md): True when this
    # worker serves the vision tower as its own stage (dedicated ENCODE
    # workers, and encode-capable MIX workers). ``encode_image_size`` is
    # the fixed serve-time image grid side the tower was compiled for —
    # the requester needs it only for diagnostics (the mrope grid is
    # derived from the returned embeds), 0 = not advertised.
    encode_capable: bool = False
    encode_image_size: int = 0

    def to_json(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["instance_type"] = self.instance_type.value
        return d

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "InstanceMetaInfo":
        try:
            itype = InstanceType(d.get("instance_type", "DEFAULT"))
        except ValueError:
            itype = InstanceType.DEFAULT
        return cls(
            name=d.get("name", ""),
            rpc_address=d.get("rpc_address", d.get("name", "")),
            instance_type=itype,
            models=list(d.get("models", [])),
            cluster_ids=list(d.get("cluster_ids", [])),
            addrs=list(d.get("addrs", [])),
            k_cache_ids=list(d.get("k_cache_ids", [])),
            v_cache_ids=list(d.get("v_cache_ids", [])),
            dp_size=d.get("dp_size", 1),
            ttft_profiling_data=[tuple(x) for x in
                                 d.get("ttft_profiling_data", [])],
            tpot_profiling_data=[tuple(x) for x in
                                 d.get("tpot_profiling_data", [])],
            memory_budget_gb=d.get("memory_budget_gb", 60.0),
            page_size=int(d.get("page_size", 0) or 0),
            hash_seed=int(d.get("hash_seed", 0) or 0),
            kv_block_bytes=int(d.get("kv_block_bytes", 0) or 0),
            encode_capable=bool(d.get("encode_capable", False)),
            encode_image_size=int(d.get("encode_image_size", 0) or 0),
        )


@dataclasses.dataclass
class LoadMetrics:
    """Queue/cache pressure shipped in every heartbeat (types.h:81-115)."""

    waiting_requests: int = 0
    running_requests: int = 0
    kv_cache_usage: float = 0.0          # [0, 1]
    num_preemptions: int = 0
    # MoE capacity-dropped (token, expert) assignments since engine boot
    # (0 on dense models) — routing/ops visibility into quality pressure.
    moe_dropped_tokens: int = 0
    # EPD encode-plane pressure (docs/EPD.md): jobs waiting in the
    # worker's batched encode queue at heartbeat time — the cost-aware
    # encode pick's queue-depth term.
    encode_queue_depth: int = 0
    # Engine-loop liveness (docs/ROBUSTNESS.md, device-plane fault
    # contract): 1 while the worker's engine loop serves, 0 once the
    # fault breaker let it die — the watchdog opens an ``engine_dead``
    # anomaly on 0 instead of waiting for lease expiry.
    engine_alive: int = 1

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: Optional[Dict[str, Any]]) -> "LoadMetrics":
        if not d:
            return cls()
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class LatencyMetrics:
    """Recent max TTFT / inter-token latency (types.h:118-127), plus the
    worker's recent engine-step p99 (additive vs. the reference): the
    p99 of ``xllm_worker_step_ms`` over the samples since the previous
    heartbeat, computed worker-side from the same registry buckets the
    worker's /metrics exports. The service watchdog compares it against
    a per-instance rolling baseline to open ``step_ms_regression``
    anomalies. 0.0 = no steps ran in the interval (no signal)."""

    recent_max_ttft_ms: float = 0.0
    recent_max_tbt_ms: float = 0.0
    step_ms_p99: float = 0.0
    # Measured prefill throughput (tokens/s, cumulative over this
    # worker's prefill steps) — the fetch-vs-recompute cost model's
    # recompute-rate term. 0.0 = no prefill ran yet (no signal).
    prefill_tok_s: float = 0.0
    # Measured KV-transfer bandwidth (GB/s) from this worker's actual
    # migrations/probes — the cost model's fetch-rate term. 0.0 = never
    # measured (the service falls back to XLLM_KV_FETCH_GBPS).
    kv_gbps: float = 0.0
    # Prefill backlog at heartbeat time: prompt tokens queued on the
    # worker but not yet computed. The SLO-aware policy converts this
    # to milliseconds (via prefill_tok_s) inside its predicted-TTFT
    # term so prefill queueing can't hide behind a single global queue
    # (P/D-Serve backlog awareness).
    waiting_prefill_tokens: int = 0
    # EPD encode stage (docs/EPD.md): mean per-image encode ms over the
    # tower calls since the previous beat (0.0 = no encodes ran — the
    # cost-aware pick falls back to its prior), plus the raw per-call
    # durations (ms, bounded) the service observes into its
    # ``xllm_service_encode_ms`` histogram for the encode SLO objective.
    encode_ms: float = 0.0
    encode_ms_samples: List[float] = dataclasses.field(
        default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: Optional[Dict[str, Any]]) -> "LatencyMetrics":
        if not d:
            return cls()
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class RequestMetrics:
    """Service-side in-flight ledger per instance (types.h:138-155,
    maintained like instance_mgr.cpp:745-817): what the SLO policy uses to
    estimate prefill backlog and decode load."""

    num_prefill_requests: int = 0
    num_prefill_tokens: int = 0
    num_decode_requests: int = 0
    num_decode_tokens: int = 0
    estimated_prefill_time_ms: float = 0.0
    estimated_ttft_ms: float = 0.0

    def to_json(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class RequestPhase:
    """Request-metrics transition points (reference update_request_metrics
    call sites: SCHEDULE scheduler.cpp:127, PREFILL_FINISH :183-202,
    GENERATE :345, FINISH_DECODE/CANCEL :304-327)."""

    SCHEDULE = "schedule"
    # Exact reversal of SCHEDULE — used when a scheduled request is
    # re-dispatched to another instance before any work happened (the
    # failed instance must not keep phantom prefill backlog).
    UNSCHEDULE = "unschedule"
    PREFILL_FINISH = "prefill_finish"
    GENERATE = "generate"
    FINISH_DECODE = "finish_decode"
    CANCEL = "cancel"


@dataclasses.dataclass
class Heartbeat:
    """Wire form of one worker heartbeat (xllm_rpc_service.proto
    HeartbeatRequest)."""

    name: str = ""
    instance_type: InstanceType = InstanceType.DEFAULT
    load: LoadMetrics = dataclasses.field(default_factory=LoadMetrics)
    latency: LatencyMetrics = dataclasses.field(default_factory=LatencyMetrics)
    # Prefix-cache delta: hex digests stored/removed since last beat.
    # ``cache_offloaded`` = spilled HBM→host-DRAM (still servable from
    # this worker, one tier down); ``cache_offloaded_ssd`` = demoted
    # DRAM→disk — the deltas that make the cluster index's DRAM/SSD
    # tier slots real (docs/KV_CACHE.md).
    cache_stored: List[str] = dataclasses.field(default_factory=list)
    cache_removed: List[str] = dataclasses.field(default_factory=list)
    cache_offloaded: List[str] = dataclasses.field(default_factory=list)
    cache_offloaded_ssd: List[str] = dataclasses.field(
        default_factory=list)
    # EPD embedding-cache delta (docs/EPD.md): hex image digests whose
    # encoded embeddings this worker gained/evicted since the last
    # beat. The instance manager folds them into its per-instance
    # digest books so the cost-aware encode pick can credit cache hits.
    embed_stored: List[str] = dataclasses.field(default_factory=list)
    embed_removed: List[str] = dataclasses.field(default_factory=list)
    # Per-model sleep/wake state for the serverless layer.
    model_states: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Finished request-span timelines since the last beat
    # ([{"request_id", "attrs", "events": [...]}], obs/spans.py): the
    # service merges them into its span ring under the same correlation
    # id, so /admin/trace/<id> shows worker-side stages too.
    spans: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    # Step flight-recorder tail since the last delivered beat
    # (obs/steptrace.py STEP_FIELDS records): the master's StepBooks
    # dedupe on seq, so a re-shipped tail after a failed beat is safe.
    steps: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    timestamp: float = dataclasses.field(default_factory=time.time)

    def to_json(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "instance_type": self.instance_type.value,
            "load": self.load.to_json(),
            "latency": self.latency.to_json(),
            "cache_stored": self.cache_stored,
            "cache_removed": self.cache_removed,
            "cache_offloaded": self.cache_offloaded,
            "cache_offloaded_ssd": self.cache_offloaded_ssd,
            "embed_stored": self.embed_stored,
            "embed_removed": self.embed_removed,
            "model_states": self.model_states,
            "spans": self.spans,
            "steps": self.steps,
            "timestamp": self.timestamp,
        }

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "Heartbeat":
        try:
            itype = InstanceType(d.get("instance_type", "DEFAULT"))
        except ValueError:
            itype = InstanceType.DEFAULT
        return cls(
            name=d.get("name", ""),
            instance_type=itype,
            load=LoadMetrics.from_json(d.get("load")),
            latency=LatencyMetrics.from_json(d.get("latency")),
            cache_stored=list(d.get("cache_stored", [])),
            cache_removed=list(d.get("cache_removed", [])),
            cache_offloaded=list(d.get("cache_offloaded", [])),
            cache_offloaded_ssd=list(d.get("cache_offloaded_ssd", [])),
            embed_stored=list(d.get("embed_stored", [])),
            embed_removed=list(d.get("embed_removed", [])),
            model_states=dict(d.get("model_states", {})),
            spans=list(d.get("spans", [])),
            steps=list(d.get("steps", [])),
            timestamp=d.get("timestamp", time.time()),
        )
