"""Coordination store: the etcd-shaped metadata plane.

The reference coordinates everything through etcd (discovery, leader
election, state replication — scheduler/etcd_client/etcd_client.{h,cpp},
SURVEY.md §2 #8): TTL leases, ``compare_create`` transactions, and prefix
watches. This module provides the same contract without requiring an
external etcd deployment:

- ``InMemoryStore`` — a complete single-process implementation with
  revisions, leases (expiry fires DELETE watch events), transactions and
  prefix watches. Unit tests and single-host clusters use it directly.
- ``StoreServer``/``RemoteStore`` (coordination_net.py) — the same store
  served over HTTP/JSON (watch via long-poll on revision) so multiple
  service replicas and worker hosts share one coordination plane across
  processes/hosts. A real etcd can be slotted in behind the same
  ``CoordinationStore`` interface; nothing above this module knows the
  difference.

Key schema kept from the reference (instance_mgr.cpp:34-41, scheduler.cpp:25):
``XLLM:{DEFAULT,PREFILL,DECODE,MIX,ENCODE}:<name>``, ``XLLM:LOADMETRICS:``,
``XLLM:CACHE:``, ``XLLM:SERVICE:MASTER``.
"""

from __future__ import annotations

import abc
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from xllm_service_tpu.utils import threads
from xllm_service_tpu.utils.threads import spawn

# Watch event: ("PUT" | "DELETE", key, value-or-None)
WatchEvent = Tuple[str, str, Optional[str]]
WatchCallback = Callable[[WatchEvent], None]

KEY_MASTER = "XLLM:SERVICE:MASTER"
# Current master's reachable addresses, JSON {service_id, rpc, http},
# written under the master's lease. Workers watch this key so heartbeats /
# generation pushes follow a replica takeover instead of orphaning on the
# dead master's static address (the reference leaves this to an external
# VIP; here it is part of the coordination contract).
KEY_MASTER_ADDR = "XLLM:SERVICE:ADDR"
KEY_LOADMETRICS = "XLLM:LOADMETRICS:"
KEY_CACHE = "XLLM:CACHE:"
# Fenced master epochs (docs/ROBUSTNESS.md, control-plane outage
# contract): each election mints XLLM:SERVICE:EPOCH:<n> via
# compare_create with NO lease — the keys are a monotonic ledger that
# survives master death, so a deposed master healing from a partition
# discovers a higher epoch and self-demotes instead of dual-serving.
KEY_EPOCH_PREFIX = "XLLM:SERVICE:EPOCH:"


def instance_prefix(instance_type: str) -> str:
    return f"XLLM:{instance_type}:"


class CoordinationStore(abc.ABC):
    """etcd-shaped KV interface (reference etcd_client.h:32-144)."""

    @abc.abstractmethod
    def put(self, key: str, value: str,
            lease_id: Optional[int] = None) -> None: ...

    @abc.abstractmethod
    def get(self, key: str) -> Optional[str]: ...

    @abc.abstractmethod
    def get_prefix(self, prefix: str) -> Dict[str, str]: ...

    @abc.abstractmethod
    def delete(self, key: str) -> bool: ...

    @abc.abstractmethod
    def delete_prefix(self, prefix: str) -> int: ...

    @abc.abstractmethod
    def lease_grant(self, ttl_s: float) -> int: ...

    @abc.abstractmethod
    def lease_keepalive(self, lease_id: int) -> bool: ...

    @abc.abstractmethod
    def lease_revoke(self, lease_id: int) -> None: ...

    @abc.abstractmethod
    def compare_create(self, key: str, value: str,
                       lease_id: Optional[int] = None) -> bool:
        """Atomically create ``key`` iff absent (leader-election txn,
        reference etcd_client.cpp:47-62). True iff this caller created it."""

    @abc.abstractmethod
    def add_watch(self, prefix: str, callback: WatchCallback) -> int: ...

    @abc.abstractmethod
    def cancel_watch(self, watch_id: int) -> None: ...

    # -- typed helpers (reference etcd_client.h:37-118 duck-typed json) ----
    def put_json(self, key: str, value: Any,
                 lease_id: Optional[int] = None) -> None:
        self.put(key, json.dumps(value), lease_id)

    def get_json(self, key: str) -> Optional[Any]:
        v = self.get(key)
        return None if v is None else json.loads(v)

    def get_prefix_json(self, prefix: str) -> Dict[str, Any]:
        return {k: json.loads(v) for k, v in self.get_prefix(prefix).items()}

    def close(self) -> None:
        pass


class InMemoryStore(CoordinationStore):
    """Thread-safe in-process store with leases, revisions and watches.

    Lease expiry is checked by a background sweeper thread; expiry deletes
    every key attached to the lease and fires DELETE watch events — the
    mechanism the reference relies on for instance failure detection
    (SURVEY.md §5.3) and master takeover.
    """

    def __init__(self, sweep_interval_s: float = 0.05) -> None:
        self._lock = threading.RLock()
        self._data: Dict[str, str] = {}
        self._key_lease: Dict[str, int] = {}
        self._leases: Dict[int, float] = {}       # id → deadline
        self._lease_ttl: Dict[int, float] = {}
        self._next_lease = 1
        self._next_watch = 1
        self._watches: Dict[int, Tuple[str, WatchCallback]] = {}
        self.revision = 0
        # Bounded event log for long-poll watchers (coordination_net).
        self._events: List[Tuple[int, WatchEvent]] = []
        self._events_cv = threading.Condition(self._lock)
        self._max_events = 65536
        self._closed = False
        # Watch callbacks run on ONE dispatcher thread draining an ordered
        # queue — events are delivered in revision order (a per-event
        # thread could reorder a worker's DELETE/re-PUT and permanently
        # wedge registration state downstream).
        import queue as _queue
        self._dispatch_q: "_queue.Queue" = _queue.Queue()
        # Supervised + restarted (utils/threads.py): the dispatcher and
        # the lease sweeper are the store's pulse — a crash restarts
        # them with backoff (the queue and lease books persist across a
        # restart) instead of silently wedging every watcher.
        self._dispatcher = spawn(
            "coord.dispatch", self._dispatch_loop,
            thread_name="coord-dispatch",
            restart=threads.RESTART_POLICY)
        self._dispatcher.start()
        self._sweeper = spawn(
            "coord.sweep", self._sweep_loop, args=(sweep_interval_s,),
            thread_name="coord-sweeper",
            restart=threads.RESTART_POLICY)
        self._sweeper.start()

    # -- internal ---------------------------------------------------------
    def _emit(self, ev_type: str, key: str, value: Optional[str]) -> None:
        """Caller holds the lock."""
        self.revision += 1
        ev = (ev_type, key, value)
        self._events.append((self.revision, ev))
        if len(self._events) > self._max_events:
            del self._events[: self._max_events // 2]
        callbacks = [cb for _, (pfx, cb) in self._watches.items()
                     if key.startswith(pfx)]
        self._events_cv.notify_all()
        if callbacks:
            self._dispatch_q.put((callbacks, ev))

    def _dispatch_loop(self) -> None:
        while True:
            item = self._dispatch_q.get()
            if item is None:
                return
            callbacks, ev = item
            for cb in callbacks:
                try:
                    cb(ev)
                except Exception as e:
                    # A broken watch callback must not kill the
                    # dispatcher (every other watcher starves) — but
                    # the drop is TELEMETRY, not silence: logged with
                    # traceback + counted as
                    # xllm_callback_errors_total{root="coord.dispatch"}
                    # (xlint rule 16 verifies this path).
                    threads.record_callback_error("coord.dispatch", e)

    def _delete_locked(self, key: str) -> bool:
        if key not in self._data:
            return False
        del self._data[key]
        self._key_lease.pop(key, None)
        self._emit("DELETE", key, None)
        return True

    def _sweep_loop(self, interval: float) -> None:
        while not self._closed:
            time.sleep(interval)
            now = time.monotonic()
            with self._lock:
                expired = [lid for lid, dl in self._leases.items()
                           if dl <= now]
                for lid in expired:
                    self._revoke_locked(lid)

    def _revoke_locked(self, lease_id: int) -> None:
        self._leases.pop(lease_id, None)
        self._lease_ttl.pop(lease_id, None)
        for key in [k for k, l in self._key_lease.items() if l == lease_id]:
            self._delete_locked(key)

    # -- CoordinationStore ------------------------------------------------
    def put(self, key: str, value: str,
            lease_id: Optional[int] = None) -> None:
        with self._lock:
            if lease_id is not None and lease_id not in self._leases:
                raise KeyError(f"unknown lease {lease_id}")
            self._data[key] = value
            if lease_id is not None:
                self._key_lease[key] = lease_id
            else:
                self._key_lease.pop(key, None)
            self._emit("PUT", key, value)

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            return self._data.get(key)

    def get_prefix(self, prefix: str) -> Dict[str, str]:
        with self._lock:
            return {k: v for k, v in self._data.items()
                    if k.startswith(prefix)}

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._delete_locked(key)

    def delete_prefix(self, prefix: str) -> int:
        with self._lock:
            keys = [k for k in self._data if k.startswith(prefix)]
            for k in keys:
                self._delete_locked(k)
            return len(keys)

    def lease_grant(self, ttl_s: float) -> int:
        with self._lock:
            lid = self._next_lease
            self._next_lease += 1
            self._leases[lid] = time.monotonic() + ttl_s
            self._lease_ttl[lid] = ttl_s
            return lid

    def lease_keepalive(self, lease_id: int) -> bool:
        with self._lock:
            if lease_id not in self._leases:
                return False
            self._leases[lease_id] = (time.monotonic()
                                      + self._lease_ttl[lease_id])
            return True

    def lease_revoke(self, lease_id: int) -> None:
        with self._lock:
            self._revoke_locked(lease_id)

    def compare_create(self, key: str, value: str,
                       lease_id: Optional[int] = None) -> bool:
        with self._lock:
            if key in self._data:
                return False
            self.put(key, value, lease_id)
            return True

    def add_watch(self, prefix: str, callback: WatchCallback) -> int:
        with self._lock:
            wid = self._next_watch
            self._next_watch += 1
            self._watches[wid] = (prefix, callback)
            return wid

    def cancel_watch(self, watch_id: int) -> None:
        with self._lock:
            self._watches.pop(watch_id, None)

    # -- long-poll support (used by StoreServer) --------------------------
    def events_since(self, rev: int, prefix: str,
                     timeout_s: float = 10.0
                     ) -> Tuple[int, List[WatchEvent]]:
        """Block until an event with revision > ``rev`` under ``prefix``
        exists (or timeout). Returns (latest_revision, matching events)."""
        deadline = time.monotonic() + timeout_s
        with self._events_cv:
            while True:
                evs = [e for r, e in self._events
                       if r > rev and e[1].startswith(prefix)]
                if evs:
                    return self.revision, evs
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return self.revision, []
                self._events_cv.wait(remaining)

    @property
    def oldest_retained_revision(self) -> int:
        with self._lock:
            return self._events[0][0] if self._events else self.revision + 1

    def close(self) -> None:
        self._closed = True
        self._dispatch_q.put(None)
