"""Store guard: deadline'd health tracking around the coordination store.

The reference's headline fault-tolerance story is *etcd-based* discovery
— which makes the store the cluster's one single point of failure. This
wrapper separates the control plane into its own fault domain (the
P/D-Serve argument, PAPERS.md 2408.08147): every ``CoordinationStore``
call routes through ``_call``, which

- injects the closed-catalog ``store.*`` failpoints so a blackout is a
  deterministic tier-1 event, not a SIGKILL race;
- times the call against a deadline (``XLLM_STORE_DEADLINE_S``) so a
  hung store surfaces as a failure instead of wedging the caller;
- tracks consecutive failures through a healthy→flaky→down state
  machine (``XLLM_STORE_DOWN_THRESHOLD`` consecutive failures = down),
  visible as the ``xllm_store_health`` gauge (2/1/0) and the
  ``store_outage_open``/``store_outage_close`` events;
- while *partitioned* (``store.partition`` armed) suppresses incoming
  watch events — a client cut off from the store receives no watch
  traffic, so lease-expiry DELETEs never reach the instance books and
  the last-known-good table stays frozen, exactly like a real blackout;
- on the down→healthy transition runs registered heal callbacks
  *synchronously, before the healing call returns* — the scheduler's
  callback re-reads the fenced-epoch keys and self-demotes a deposed
  master before a single stale master-authored write can land;
- fences master-authored writes: when the owner installed a fence
  check (``fence_check``) and it returns True (local epoch behind the
  cluster epoch), every write raises ``EpochFencedError`` instead of
  dual-serving the store (docs/ROBUSTNESS.md, control-plane outage
  contract).

Liveness during an outage is judged by the direct worker→master
heartbeats that keep flowing — the guard only decides what the STORE
is allowed to tell us, never who is alive.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional

from xllm_service_tpu.service.coordination import (
    CoordinationStore, WatchCallback)
from xllm_service_tpu.utils import threads
from xllm_service_tpu.utils.locks import make_lock

logger = logging.getLogger(__name__)

HEALTHY, FLAKY, DOWN = 2, 1, 0
_HEALTH_NAMES = {HEALTHY: "healthy", FLAKY: "flaky", DOWN: "down"}


class StoreOutageError(RuntimeError):
    """A coordination-store call failed because the store is
    unreachable (injected or real). Callers treat it as 'the control
    plane is gone', never as 'the answer is no'."""


class EpochFencedError(RuntimeError):
    """A master-authored store write was rejected because a
    higher-epoch master exists — the writer must self-demote, not
    retry (split-brain fence, docs/ROBUSTNESS.md)."""


def _as_float(raw: Optional[str], default: float) -> float:
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


class StoreGuard(CoordinationStore):
    """Health-tracking, failpoint-injecting, epoch-fencing wrapper
    around any ``CoordinationStore`` backend. One guard per plane
    (service process / worker) — health is the CLIENT's view of the
    store, and the co-located test harness blacks out one plane
    without touching its twin."""

    def __init__(self, store: CoordinationStore, failpoints=None,
                 events=None) -> None:
        self.inner = store
        self.failpoints = failpoints
        self.events = events
        # Guards health-state + callback books only; never held across
        # an inner store call or a heal/watch callback.
        self._mu = make_lock("store_guard", 74)
        self._consecutive_failures = 0
        self._health = HEALTHY
        self._outage_since: Optional[float] = None
        self.outages_opened = 0
        # Deadline for one store call: a store slower than this is a
        # failure, not a wait (the hang failpoint proves the path).
        self.deadline_s = _as_float(
            os.environ.get("XLLM_STORE_DEADLINE_S"), 5.0)
        # Consecutive failures before healthy→down (the single step
        # in between is flaky).
        self.down_threshold = max(1, int(_as_float(
            os.environ.get("XLLM_STORE_DOWN_THRESHOLD"), 3)))
        # Epoch fence: installed by the scheduler on the master plane.
        # Returns True when this process believes it is master but its
        # epoch is behind the cluster's → writes must be rejected.
        self.fence_check: Optional[Callable[[], bool]] = None
        # Down→healthy transition listeners (scheduler resync/demote,
        # worker re-registration). Run synchronously on the thread
        # that observed the heal, BEFORE its store call returns.
        self._heal_cbs: List[Callable[[], None]] = []
        # watch_id → wrapped callback (for partition suppression).
        self._suppressed_events = 0

    # -- health state machine -------------------------------------------
    @property
    def health(self) -> int:
        """2 = healthy, 1 = flaky, 0 = down (the gauge value)."""
        with self._mu:
            return self._health

    @property
    def is_down(self) -> bool:
        with self._mu:
            return self._health == DOWN

    def on_heal(self, cb: Callable[[], None]) -> None:
        with self._mu:
            self._heal_cbs.append(cb)

    def state(self) -> Dict[str, Any]:
        with self._mu:
            return {"health": _HEALTH_NAMES[self._health],
                    "consecutive_failures": self._consecutive_failures,
                    "outages_opened": self.outages_opened,
                    "outage_open_s": (
                        round(time.monotonic() - self._outage_since, 3)
                        if self._outage_since is not None else 0.0),
                    "suppressed_watch_events": self._suppressed_events}

    def _partitioned(self) -> bool:
        """Watch-event suppression predicate: while ``store.partition``
        is armed this client hears NOTHING from the store — checked
        per event, outside the guard lock (failpoints has its own)."""
        if self.failpoints is None:
            return False
        return self.failpoints.fire("store.partition") is not None

    def _record_failure(self, op: str, exc: Exception) -> None:
        opened = False
        with self._mu:
            self._consecutive_failures += 1
            if self._consecutive_failures >= self.down_threshold:
                if self._health != DOWN:
                    opened = True
                    self._outage_since = time.monotonic()
                    self.outages_opened += 1
                self._health = DOWN
            else:
                self._health = min(self._health, FLAKY)
        if opened:
            logger.warning("coordination store declared DOWN after %d "
                           "consecutive failures (last: %s on %s)",
                           self.down_threshold, exc, op)
            if self.events is not None:
                self.events.emit("store_outage_open", op=op,
                                 error=str(exc))

    def _record_success(self) -> None:
        with self._mu:
            was = self._health
            self._consecutive_failures = 0
            self._health = HEALTHY
            if was != DOWN:
                return
            healed_after = (time.monotonic() - self._outage_since
                            if self._outage_since is not None else 0.0)
            self._outage_since = None
            cbs = list(self._heal_cbs)
        logger.info("coordination store healed after %.3fs outage",
                    healed_after)
        if self.events is not None:
            self.events.emit("store_outage_close",
                             outage_s=round(healed_after, 3))
        # Synchronous, pre-return: a deposed master's heal callback
        # demotes it before the caller can issue a stale write.
        for cb in cbs:
            try:
                cb()
            except Exception as e:  # noqa: BLE001 — one broken heal
                # hook must not mask the heal from the others
                threads.record_callback_error("store_guard.heal", e)

    # -- the guarded call ------------------------------------------------
    def _call(self, op: str, fn: Callable, *args: Any) -> Any:
        fp = self.failpoints
        if fp is not None:
            if fp.fire("store.partition") is not None:
                exc: Exception = StoreOutageError(
                    f"store partitioned (failpoint store.partition): {op}")
                self._record_failure(op, exc)
                raise exc
            if fp.fire("store.fail_rpc") is not None:
                exc = StoreOutageError(
                    f"store rpc failed (failpoint store.fail_rpc): {op}")
                self._record_failure(op, exc)
                raise exc
            hang = fp.fire("store.hang")
            if hang is not None:
                # Deterministic slow-store: sleep the armed value (s),
                # capped by the guard deadline, then fail like a
                # timed-out call would.
                delay = float(hang) if hang is not True else self.deadline_s
                time.sleep(min(delay, self.deadline_s))
                exc = StoreOutageError(
                    f"store call deadline exceeded (failpoint "
                    f"store.hang, {self.deadline_s}s): {op}")
                self._record_failure(op, exc)
                raise exc
        t0 = time.monotonic()
        try:
            out = fn(*args)
        except Exception as e:  # noqa: BLE001 — ANY backend failure is a
            # health event; the caller sees the original error class via
            # the StoreOutageError chain
            self._record_failure(op, e)
            raise StoreOutageError(f"store {op} failed: {e}") from e
        took = time.monotonic() - t0
        if took > self.deadline_s:
            # The call returned, but past the deadline: count it against
            # health (a store this slow is an outage in progress) while
            # still handing the caller its answer.
            self._record_failure(op, TimeoutError(
                f"{op} took {took:.3f}s > {self.deadline_s}s"))
            return out
        self._record_success()
        return out

    def _write(self, op: str, fn: Callable, *args: Any) -> Any:
        fence = self.fence_check
        if fence is not None and fence():
            raise EpochFencedError(
                f"store write {op} rejected: a higher-epoch master "
                f"exists — self-demote instead of dual-serving")
        return self._call(op, fn, *args)

    # -- CoordinationStore surface ---------------------------------------
    def put(self, key: str, value: str,
            lease_id: Optional[int] = None) -> None:
        self._write("put", self.inner.put, key, value, lease_id)

    def get(self, key: str) -> Optional[str]:
        return self._call("get", self.inner.get, key)

    def get_prefix(self, prefix: str) -> Dict[str, str]:
        return self._call("get_prefix", self.inner.get_prefix, prefix)

    def delete(self, key: str) -> bool:
        return self._write("delete", self.inner.delete, key)

    def delete_prefix(self, prefix: str) -> int:
        return self._write("delete_prefix", self.inner.delete_prefix,
                           prefix)

    def lease_grant(self, ttl_s: float) -> int:
        return self._call("lease_grant", self.inner.lease_grant, ttl_s)

    def lease_keepalive(self, lease_id: int) -> bool:
        return self._call("lease_keepalive", self.inner.lease_keepalive,
                          lease_id)

    def lease_revoke(self, lease_id: int) -> None:
        self._call("lease_revoke", self.inner.lease_revoke, lease_id)

    def compare_create(self, key: str, value: str,
                       lease_id: Optional[int] = None) -> bool:
        # Election txn: fenced like a write — a deposed master must not
        # be able to re-grab ANY key while behind the cluster epoch.
        return self._write("compare_create", self.inner.compare_create,
                           key, value, lease_id)

    def add_watch(self, prefix: str, callback: WatchCallback) -> int:
        def guarded(event) -> None:
            if self._partitioned():
                # A partitioned client hears nothing: the DELETE from a
                # lease expiring mid-blackout must NOT reach the books
                # (that is the freeze). Healing resyncs from get_prefix.
                with self._mu:
                    self._suppressed_events += 1
                return
            callback(event)
        # Registering the watch is itself a store call on remote
        # backends — guard it too.
        return self._call("add_watch", self.inner.add_watch, prefix,
                          guarded)

    def cancel_watch(self, watch_id: int) -> None:
        self._call("cancel_watch", self.inner.cancel_watch, watch_id)

    def close(self) -> None:
        self.inner.close()
