"""Master: service process entry — builds the scheduler and runs both
servers.

Rebuild of ``master.{h,cpp}`` (SURVEY.md §2 #1): constructs the Scheduler,
installs the HTTP (OpenAI) and RPC (worker) services on two servers, and
runs until asked to stop. The reference starts two brpc servers on separate
threads (master.cpp:60-140); here each ``HttpServer`` owns its own accept
thread, and ``main()`` mirrors the reference's gflags surface
(common/global_gflags.cpp) with argparse.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import threading
from typing import Dict, List, Optional

from xllm_service_tpu.config import (
    LoadBalancePolicyType, ServiceOptions, options_from_env)
from xllm_service_tpu.obs import EventLog
from xllm_service_tpu.obs.failpoints import Failpoints
from xllm_service_tpu.service.coordination import CoordinationStore
from xllm_service_tpu.service.coordination_net import connect_store
from xllm_service_tpu.service.http_service import HttpService
from xllm_service_tpu.service.httpd import HttpServer, Router
from xllm_service_tpu.service.rpc_service import RpcService
from xllm_service_tpu.service.scheduler import Scheduler
from xllm_service_tpu.service.store_guard import StoreGuard

logger = logging.getLogger(__name__)


class Master:
    def __init__(self, opts: ServiceOptions,
                 store: Optional[CoordinationStore] = None,
                 control=None,
                 model_memory_gb: Optional[Dict[str, float]] = None,
                 serverless_models: Optional[List[str]] = None) -> None:
        self.opts = opts
        self.store = store if store is not None \
            else connect_store(opts.etcd_addr)
        # One cluster event log for the whole service process, created
        # BEFORE the scheduler so the initial master election is the
        # first thing it records (ring size: XLLM_EVENT_RING).
        self.events = EventLog(
            capacity=int(os.environ.get("XLLM_EVENT_RING", "1024")))
        # One failpoint registry for the service plane, created before
        # the store guard so the `store.*` sites can black out even the
        # scheduler's boot-time election; HttpService adopts it (and
        # late-binds its registry for trip counters).
        self.failpoints = Failpoints(events=self.events)
        # Every coordination call routes through the guard
        # (service/store_guard.py): health state machine, store.*
        # failpoints, epoch write fence, heal-triggered resync.
        if not isinstance(self.store, StoreGuard):
            self.store = StoreGuard(self.store,
                                    failpoints=self.failpoints,
                                    events=self.events)
        self.scheduler = Scheduler(
            opts, self.store, control=control,
            model_memory_gb=model_memory_gb,
            serverless_models=serverless_models, events=self.events)
        self.http_service = HttpService(opts, self.scheduler,
                                        events=self.events,
                                        failpoints=self.failpoints)
        self.rpc_service = RpcService(opts, self.scheduler)
        # Worker span stages arrive on the RPC plane (heartbeats) but
        # are queried on the HTTP plane (/admin/trace/<id>): one store.
        self.rpc_service.spans = self.http_service.spans
        # Step flight-recorder tails arrive the same way and feed the
        # HTTP plane's /admin/timeline merge: one set of books.
        self.rpc_service.step_books = self.http_service.step_books
        # Routing audits land on the request's span and in
        # xllm_schedule_decisions_total — the scheduler is built first,
        # so it learns the HTTP plane's span ring/registry here.
        self.scheduler.spans = self.http_service.spans
        self.scheduler.obs = self.http_service.obs

        # Both servers enforce opts.max_concurrency as live admission
        # control (the reference's brpc max_concurrency backpressure,
        # global_gflags.cpp:33-48) — the callable reads the shared opts
        # so /admin/flags reloads apply immediately.
        limit = lambda: self.opts.max_concurrency  # noqa: E731
        http_router = Router()
        self.http_service.install(http_router)
        self._http_srv = HttpServer(opts.host, opts.http_port, http_router,
                                    max_concurrency=limit)

        rpc_router = Router()
        self.rpc_service.install(rpc_router)
        self._rpc_srv = HttpServer(opts.host, opts.rpc_port, rpc_router,
                                   max_concurrency=limit)
        self.http_service.admissions = {
            "http": self._http_srv.admission,
            "rpc": self._rpc_srv.admission}

        self._stopped = threading.Event()

    @property
    def http_address(self) -> str:
        return self._http_srv.address

    @property
    def rpc_address(self) -> str:
        return self._rpc_srv.address

    def start(self) -> "Master":
        self._http_srv.start()
        self._rpc_srv.start()
        # SLO burn-rate evaluation + per-instance anomaly watchdog
        # (obs/slo.py; cadence XLLM_SLO_TICK_S).
        self.http_service.start_watchdog()
        # Advertise reachable addresses through the store (current master
        # publishes them; replicas re-publish on takeover) so workers can
        # follow a failover without a fronting VIP.
        self.scheduler.announce(self.rpc_address, self.http_address)
        logger.info("service up: http=%s rpc=%s master=%s",
                    self.http_address, self.rpc_address,
                    self.scheduler.is_master)
        return self

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        self._http_srv.stop()
        self._rpc_srv.stop()
        # After the servers: in-flight requests drain first, so the
        # watchdog/tracer shutdown can't drop their last writes.
        self.http_service.close()
        self.scheduler.stop()

    def wait(self) -> None:
        self._stopped.wait()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="xllm-service-tpu master (service process)")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--http-port", type=int, default=9888)
    parser.add_argument("--rpc-port", type=int, default=9889)
    parser.add_argument("--etcd-addr", default="",
                        help="coordination store host:port "
                             "('' = in-process store)")
    parser.add_argument("--load-balance-policy", default="CAR",
                        choices=[p.value for p in LoadBalancePolicyType])
    parser.add_argument("--block-size", type=int, default=128)
    parser.add_argument("--murmur-hash3-seed", type=int, default=0)
    parser.add_argument("--tokenizer-path", default="")
    parser.add_argument("--enable-request-trace", action="store_true")
    parser.add_argument("--enable-decode-response-to-service",
                        action="store_true")
    parser.add_argument("--target-ttft-ms", type=float, default=1000.0)
    parser.add_argument("--target-tpot-ms", type=float, default=50.0)
    parser.add_argument("--heartbeat-interval", type=float, default=3.0,
                        help="election lease scale + instance liveness (s)")
    parser.add_argument("--master-upload-interval", type=float, default=3.0)
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    opts = options_from_env(
        host=args.host, http_port=args.http_port, rpc_port=args.rpc_port,
        etcd_addr=args.etcd_addr,
        load_balance_policy=LoadBalancePolicyType(args.load_balance_policy),
        block_size=args.block_size,
        murmur_hash3_seed=args.murmur_hash3_seed,
        tokenizer_path=args.tokenizer_path,
        enable_request_trace=args.enable_request_trace,
        target_ttft_ms=args.target_ttft_ms,
        target_tpot_ms=args.target_tpot_ms,
        heartbeat_interval_s=args.heartbeat_interval,
        master_upload_interval_s=args.master_upload_interval)
    if args.enable_decode_response_to_service:
        opts.enable_decode_response_to_service = True

    master = Master(opts).start()
    # Machine-parseable liveness line (HA test harness + ops scripts read
    # this to learn the bound ports when started with --http-port 0).
    print(f"XLLM_SERVICE_UP http={master.http_address} "
          f"rpc={master.rpc_address} "
          f"master={int(master.scheduler.is_master)}", flush=True)

    def on_signal(signum, frame) -> None:
        logger.info("signal %d: shutting down", signum)
        master.stop()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    master.wait()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
