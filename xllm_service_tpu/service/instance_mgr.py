"""InstanceMgr: worker-instance lifecycle, routing state, PD flips,
multi-model serverless allocation.

Rebuild of the reference's largest component,
``scheduler/managers/instance_mgr.{h,cpp}`` (1452 LoC, SURVEY.md §2 #6):

- two-phase registration: store PUT → pending → first heartbeat confirms
  liveness → registered (instance_mgr.cpp:423-521, 553-604);
- removal on store DELETE (lease expiry = failure detection, :606-686);
- prefill/decode index arrays with O(1) swap-remove (:606-689) and
  round-robin pair selection (:170-186);
- load/latency/request-metrics books (:387-416, :734-817);
- SLO-aware pair selection with per-instance ``TimePredictor`` and
  prefill-overflow-onto-decode (:819-920);
- dynamic PD role flips (:922-970) with auto flip-back when decode drains
  (:812-816);
- multi-model serverless: heat tracking, awake/asleep model states,
  ``fork_master_and_sleep`` on registration, allocation with exhaustive
  coldest-subset eviction (:1067-1243).

Worker control is HTTP POSTs to the worker's endpoints (``/fork_master``,
``/sleep``, ``/wakeup``, ``/flip_role`` — the reference's raw-HTTP engine
control, instance_mgr.cpp:236-250). The transport is injectable so unit
tests can script workers without sockets.
"""

from __future__ import annotations

import itertools
import logging
import threading


import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from xllm_service_tpu.config import InstanceType, ServiceOptions
from xllm_service_tpu.service.coordination import (
    KEY_LOADMETRICS, CoordinationStore, instance_prefix)
from xllm_service_tpu.service.httpd import http_json
from xllm_service_tpu.service.instance_types import (
    Heartbeat, InstanceMetaInfo, LatencyMetrics, LoadMetrics, RequestMetrics,
    RequestPhase)
from xllm_service_tpu.service.time_predictor import TimePredictor
from xllm_service_tpu.utils.locks import make_rlock
from xllm_service_tpu.utils.threads import spawn

logger = logging.getLogger(__name__)

MODEL_AWAKE = "awake"
MODEL_ASLEEP = "asleep"
MODEL_DRAINING = "draining"    # graceful shutdown: route nothing, wake never

# Default model memory footprints (GB) for the serverless allocator. The
# reference hardcodes its list (instance_mgr.cpp:217-225, flagged TODO);
# here it is a config default that ``ServiceOptions``-level config can
# override per deployment.
DEFAULT_MODEL_MEMORY_GB: Dict[str, float] = {}


class InstanceState:
    """Everything the service tracks about one registered worker."""

    def __init__(self, meta: InstanceMetaInfo) -> None:
        self.meta = meta
        self.instance_type = meta.instance_type
        self.load = LoadMetrics()
        self.latency = LatencyMetrics()
        self.req_metrics = RequestMetrics()
        self.predictor = TimePredictor.from_profiling(
            meta.ttft_profiling_data, meta.tpot_profiling_data)
        self.model_states: Dict[str, str] = {}
        self.last_heartbeat = time.monotonic()
        self.flipped_from: Optional[InstanceType] = None
        # Block-hash contract: False when the worker advertised a
        # page_size/hash_seed pair that diverges from the service's
        # (block_size, murmur seed) — its cache digests can never match
        # service-side digests, so the cluster index must not ingest
        # them and the fetch planner must not elect it a holder.
        self.digest_compatible = True
        # EPD embedding-cache advertisement (docs/EPD.md): hex image
        # digests this worker's embed cache currently holds, folded
        # from heartbeat deltas (embed_stored/embed_removed). Bounded
        # by the worker's own cache cap; the cost-aware encode pick
        # credits hits against it.
        self.embed_digests: Set[str] = set()

    @property
    def name(self) -> str:
        return self.meta.name


ControlFn = Callable[[str, str, Dict[str, Any]], Tuple[int, Any]]


def _default_control(address: str, path: str,
                     body: Dict[str, Any]) -> Tuple[int, Any]:
    return http_json("POST", address, path, body, timeout=120.0)


class InstanceMgr:
    def __init__(self, opts: ServiceOptions, store: CoordinationStore,
                 is_master: bool = True,
                 control: Optional[ControlFn] = None,
                 model_memory_gb: Optional[Dict[str, float]] = None,
                 serverless_models: Optional[List[str]] = None,
                 events=None) -> None:
        self.opts = opts
        self.store = store
        self.is_master = is_master
        self.control = control or _default_control
        # Cluster event log (obs.EventLog, optional): instance lifecycle
        # and role flips land there so a post-mortem can replay what the
        # cluster did. emit() never calls out and ranks above this
        # class's lock, so emitting under _lock is safe.
        self.events = events
        self.model_memory_gb = dict(model_memory_gb
                                    or DEFAULT_MODEL_MEMORY_GB)
        # Models every instance should hold as sleeping replicas
        # (fork_master_and_sleep, instance_mgr.cpp:229-260).
        self.serverless_models = list(serverless_models or [])

        self._lock = make_rlock("instance_mgr", 30)
        self._instances: Dict[str, InstanceState] = {}
        self._pending: Dict[str, InstanceMetaInfo] = {}
        self._removed: Set[str] = set()
        # Role index arrays with O(1) swap-remove.
        self._prefill_idx: List[str] = []
        self._decode_idx: List[str] = []
        self._pos: Dict[str, int] = {}          # name → position in its array
        self._rr_prefill = 0
        self._rr_decode = 0
        self._model_heat: Dict[str, float] = {}
        self._watch_ids: List[int] = []
        self._mix_names: Set[str] = set()
        # Removal hook: the scheduler fails in-flight requests routed to a
        # dead instance (set post-construction to avoid a ctor cycle).
        self.on_removed: Optional[Callable[[str], None]] = None
        # Post-heal settle window (guarded-by: instance_mgr): while
        # time.monotonic() < _delete_thaw_at, watch DELETEs are still
        # deferred — the watch replays blackout bookkeeping (or a
        # wiped-store resync's synthetic DELETEs) right after the guard
        # heals, before live workers have re-registered. The follow-up
        # resync at _post_heal_resync_at reconciles anything deferred.
        self._delete_thaw_at = 0.0
        self._post_heal_resync_at = 0.0

        for itype in InstanceType:
            self._watch_ids.append(store.add_watch(
                instance_prefix(itype.value), self._on_instance_event))
        if not is_master:
            self._watch_ids.append(store.add_watch(
                KEY_LOADMETRICS, self._on_loadmetrics_event))
        self._bootstrap()

    # ------------------------------------------------------------------
    # Bootstrap + store events
    # ------------------------------------------------------------------
    def _bootstrap(self) -> None:
        """Adopt instances already registered in the store
        (instance_mgr.cpp:68-154). They are live by definition of their
        lease still existing, so they skip the pending phase.

        The watches are registered BEFORE this runs (no event gap), so
        ``_on_instance_event`` can already be firing on the store's
        dispatch thread — registration must happen under the (re-entrant)
        lock (xlint thread-root-race finding XLINT13-002:
        ``_instances``/``_mix_names``/role arrays mutated from the init
        tail and the watch root with no common guard). The store reads
        stay OUTSIDE the lock: they are network I/O for the etcd/remote
        stores (blocking-under-lock)."""
        for itype in InstanceType:
            items = self.store.get_prefix_json(
                instance_prefix(itype.value))
            with self._lock:
                for key, val in items.items():
                    meta = InstanceMetaInfo.from_json(val)
                    if meta.name:
                        self._register(meta, from_bootstrap=True)

    def _on_instance_event(self, event) -> None:
        ev_type, key, value = event
        name = key.split(":", 2)[-1]
        if ev_type == "PUT":
            import json
            meta = InstanceMetaInfo.from_json(json.loads(value))
            with self._lock:
                if name in self._instances:
                    # Re-registration with new metadata (e.g. role flip
                    # confirmed by the worker re-writing its key).
                    self._instances[name].meta = meta
                    if meta.instance_type == InstanceType.MIX:
                        self._mix_names.add(name)
                        self._reseat_mix()
                    else:
                        self._set_role(name, meta.instance_type)
                        if name in self._mix_names:
                            # A seat holder leaving the MIX pool must
                            # hand the decode seat to the next MIX name.
                            self._mix_names.discard(name)
                            self._reseat_mix()
                elif self.is_master:
                    self._pending[name] = meta
                    self._removed.discard(name)
                    if self.events is not None:
                        self.events.emit(
                            "instance_join", instance=name,
                            instance_type=meta.instance_type.value)
                else:
                    # Replica path: heartbeats flow to the MASTER only, so
                    # a replica must treat store presence as registration
                    # (same rationale as _bootstrap: the key's lease is
                    # alive, and the master is the one gating liveness) —
                    # otherwise a standing replica can never route to
                    # workers that registered after it booted, and
                    # active-active serving / instant takeover both break.
                    # Load state arrives via the master's KEY_LOADMETRICS
                    # uploads; lease expiry arrives as a DELETE event.
                    self._removed.discard(name)
                    self._register(meta, from_bootstrap=True)
        elif ev_type == "DELETE":
            if getattr(self.store, "is_down", False):
                # Control-plane outage (service/store_guard.py): lease
                # expiry is frozen — a DELETE arriving while the store
                # is DOWN is bookkeeping fallout from the outage, not
                # evidence the worker died. Liveness is judged by the
                # direct worker→master heartbeats until the store
                # heals; resync_from_store reconciles afterwards
                # (docs/ROBUSTNESS.md outage contract).
                logger.warning("store outage: freezing DELETE for "
                               "instance %s (lease expiry ignored)", name)
                return
            with self._lock:
                settling = time.monotonic() < self._delete_thaw_at
            if settling:
                # The outage just healed: DELETEs arriving now are the
                # watch catching up on blackout bookkeeping (leases
                # that expired while we were blind) or a wiped-store
                # resync's synthetic DELETEs — live workers re-register
                # within a heartbeat, and the scheduled follow-up
                # resync removes the ones that really went silent.
                logger.warning("post-heal settle: deferring DELETE for "
                               "instance %s until the follow-up resync",
                               name)
                return
            self.remove_instance(name)

    def resync_from_store(self, settle: bool = True) -> None:
        """Post-outage reconciliation (docs/ROBUSTNESS.md outage
        contract), run from the store guard's heal callback:
        registrations that landed in the store while this plane was
        blind become live instances, and book entries whose store key
        vanished are dropped ONLY if their direct heartbeats also went
        silent — a worker whose lease expired during the blackout but
        that kept beating re-registers itself on ITS OWN heal path and
        must not be bounced here. Store reads stay outside the lock
        (same rationale as _bootstrap); re-runnable at any time.

        ``settle=True`` (the heal-callback invocation) also opens the
        post-heal settle window: watch DELETEs stay deferred while the
        watch replays blackout bookkeeping, and a follow-up
        ``resync_from_store(settle=False)`` is scheduled (driven by the
        master loop via :meth:`post_heal_resync_due`) to reconcile
        whatever the deferral skipped."""
        stale_deadline = max(3 * self.opts.heartbeat_interval_s, 3.0)
        if settle:
            # Long enough to cover one full remote watch long-poll
            # round (5s) plus the beat-staleness bound, so by the time
            # the follow-up resync runs, every synthetic DELETE has
            # been seen and a dead worker's beats HAVE gone stale.
            grace = 5.0 + stale_deadline
            with self._lock:
                self._delete_thaw_at = time.monotonic() + grace
                self._post_heal_resync_at = self._delete_thaw_at
        else:
            with self._lock:
                self._post_heal_resync_at = 0.0
        in_store: Set[str] = set()
        for itype in InstanceType:
            items = self.store.get_prefix_json(
                instance_prefix(itype.value))
            with self._lock:
                for _key, val in items.items():
                    meta = InstanceMetaInfo.from_json(val)
                    if not meta.name:
                        continue
                    in_store.add(meta.name)
                    if meta.name in self._instances:
                        continue
                    self._pending.pop(meta.name, None)
                    self._removed.discard(meta.name)
                    self._register(meta, from_bootstrap=True)
        now = time.monotonic()
        with self._lock:
            silent = [n for n, s in self._instances.items()
                      if n not in in_store
                      and now - s.last_heartbeat > stale_deadline]
        for name in silent:
            logger.warning("post-heal resync: %s absent from the store "
                           "and silent for > %.1fs of beats, removing",
                           name, stale_deadline)
            self.remove_instance(name)

    def post_heal_resync_due(self) -> bool:
        """True once the post-heal settle window has elapsed and the
        follow-up reconciliation hasn't run yet (master-loop driven)."""
        with self._lock:
            return self._post_heal_resync_at > 0.0 and \
                time.monotonic() >= self._post_heal_resync_at

    def _on_loadmetrics_event(self, event) -> None:
        """Replica path: learn load metrics from the master's uploads
        (instance_mgr.cpp:691-732)."""
        ev_type, key, value = event
        name = key[len(KEY_LOADMETRICS):]
        if ev_type != "PUT":
            return
        import json
        d = json.loads(value)
        with self._lock:
            inst = self._instances.get(name)
            if inst:
                inst.load = LoadMetrics.from_json(d.get("load"))
                inst.latency = LatencyMetrics.from_json(d.get("latency"))

    # ------------------------------------------------------------------
    # Registration / removal
    # ------------------------------------------------------------------
    def on_heartbeat(self, hb: Heartbeat) -> bool:
        """First heartbeat of a pending instance completes registration
        (instance_mgr.cpp:423-439). Returns True if the instance is (now)
        registered."""
        if self._heartbeat_locked(hb, None):
            return True
        # Unknown instance with nothing pending: the heartbeat raced
        # ahead of the watch's PUT. Read through to the store OUTSIDE
        # the lock — it is network I/O on the etcd/remote stores, and
        # on_heartbeat runs on the RPC fan-in path where every
        # scheduler/route thread contends for this lock (xlint
        # blocking-under-lock finding XLINT12-001) — then retry with
        # the fetched meta. _heartbeat_locked re-checks _removed under
        # the lock, so a removal landing mid-read still wins.
        with self._lock:
            if hb.name in self._removed:
                return False
        val = self.store.get_json(
            instance_prefix(hb.instance_type.value) + hb.name)
        if not val:
            return False
        return self._heartbeat_locked(hb, InstanceMetaInfo.from_json(val))

    def _heartbeat_locked(self, hb: Heartbeat,
                          fallback_meta: Optional[InstanceMetaInfo]
                          ) -> bool:
        """One locked heartbeat-apply attempt; ``fallback_meta`` is the
        out-of-lock store read-through result (None on the first try)."""
        stage: Optional[InstanceState] = None
        with self._lock:
            inst = self._instances.get(hb.name)
            if inst is None:
                meta = self._pending.pop(hb.name, None)
                if meta is None and hb.name not in self._removed:
                    meta = fallback_meta
                if meta is None:
                    return False
                inst = self._register(meta)
                if self.serverless_models and self.is_master:
                    stage = inst
            inst.last_heartbeat = time.monotonic()
            inst.load = hb.load
            inst.latency = hb.latency
            if hb.embed_stored or hb.embed_removed:
                inst.embed_digests.difference_update(hb.embed_removed)
                inst.embed_digests.update(hb.embed_stored)
                # Defensive bound: a worker that never reports
                # evictions must not grow this set without limit.
                if len(inst.embed_digests) > 4096:
                    inst.embed_digests = set(
                        list(inst.embed_digests)[-4096:])
            if hb.model_states:
                inst.model_states.update(hb.model_states)
        if stage is not None:
            # Control I/O OUTSIDE the lock (XLINT12-002): the staging
            # round trip can take up to the control timeout, and every
            # routing thread contends for the instance lock.
            self._fork_master_and_sleep(stage)
        return True

    def _register(self, meta: InstanceMetaInfo,
                  from_bootstrap: bool = False) -> InstanceState:
        inst = InstanceState(meta)
        # Block-hash single source of truth (docs/KV_CACHE.md): a worker
        # whose engine page size / murmur seed diverges from the
        # service's (block_size, seed) reports digests that can NEVER
        # match service-side digests — cache-aware scores for it would
        # be garbage and a fetch from it would adopt wrong-keyed blocks.
        # Fail loud (event + log) and quarantine its cache reporting;
        # the instance still serves traffic (correctness is unaffected,
        # only prefix reuse is off for it).
        if meta.page_size and (
                meta.page_size != self.opts.block_size
                or meta.hash_seed != self.opts.murmur_hash3_seed):
            inst.digest_compatible = False
            logger.error(
                "instance %s advertises block hashing (page_size=%d, "
                "seed=%d) incompatible with the service's (block_size="
                "%d, seed=%d): its prefix-cache digests are quarantined "
                "— fix block_size/page_size to re-enable prefix reuse",
                meta.name, meta.page_size, meta.hash_seed,
                self.opts.block_size, self.opts.murmur_hash3_seed)
            if self.events is not None:
                self.events.emit(
                    "cache_digest_mismatch", instance=meta.name,
                    worker_page_size=meta.page_size,
                    worker_hash_seed=meta.hash_seed,
                    service_block_size=self.opts.block_size,
                    service_hash_seed=self.opts.murmur_hash3_seed)
        self._instances[meta.name] = inst
        itype = meta.instance_type
        if itype == InstanceType.MIX:
            # MIX split: one MIX instance decodes, the rest prefill
            # (instance_mgr.cpp:497-514). The reference seats whichever
            # arrives first; with replicas registering from watch events
            # (different delivery order than the master's heartbeat
            # order) arrival order is NOT shared state, so the seat is
            # the lexicographically smallest live MIX name — every node
            # computes the same split from membership alone.
            self._mix_names.add(meta.name)
            self._set_role(meta.name, InstanceType.PREFILL)
            self._reseat_mix()
        else:
            self._set_role(meta.name, itype)
        for m in meta.models:
            inst.model_states[m] = MODEL_AWAKE
        # Serverless staging (_fork_master_and_sleep) is the CALLER's
        # job after releasing the lock: it is an up-to-120 s control
        # HTTP round trip, the same blocking-under-lock class as
        # XLINT12-001 (finding XLINT12-002). _register is always
        # invoked under self._lock, so it must never do I/O.
        if self.events is not None:
            self.events.emit(
                "instance_confirm", instance=meta.name,
                instance_type=inst.instance_type.value,
                models=list(meta.models), bootstrap=from_bootstrap)
        logger.info("registered instance %s type=%s models=%s",
                    meta.name, inst.instance_type.value, meta.models)
        return inst

    def _fork_master_and_sleep(self, inst: InstanceState) -> None:
        """Stage every serverless model on the new instance asleep
        (weights parked in host RAM, compiled executables cached) —
        the TPU translation of /fork_master + /sleep per model
        (instance_mgr.cpp:229-260, SURVEY.md §7.1)."""
        with self._lock:
            extra = [m for m in self.serverless_models
                     if m not in inst.model_states]
        if not extra:
            return
        try:
            # The control round trip runs UNLOCKED (XLINT12-002); only
            # the resulting state flip goes back under the lock.
            status, _ = self.control(inst.meta.rpc_address, "/fork_master",
                                     {"models": extra})
            if status == 200:
                with self._lock:
                    for m in extra:
                        inst.model_states[m] = MODEL_ASLEEP
        except Exception as e:  # noqa: BLE001
            logger.warning("fork_master_and_sleep(%s) failed: %s",
                           inst.name, e)

    def _reseat_mix(self) -> None:
        """Re-derive the MIX decode seat (min live name) after MIX
        membership changes. Reassignments are routing-table-only: a MIX
        worker serves both phases, so flipping its classification needs
        no worker round trip."""
        if not self._mix_names:
            return
        seat = min(self._mix_names)
        for name in self._mix_names:
            want = (InstanceType.DECODE if name == seat
                    else InstanceType.PREFILL)
            inst = self._instances.get(name)
            if inst is not None and inst.instance_type != want:
                self._set_role(name, want)

    def _set_role(self, name: str, itype: InstanceType) -> None:
        self._remove_from_indexes(name)
        inst = self._instances[name]
        inst.instance_type = itype
        if itype in (InstanceType.PREFILL, InstanceType.DEFAULT):
            self._pos[name] = len(self._prefill_idx)
            self._prefill_idx.append(name)
        elif itype == InstanceType.DECODE:
            self._pos[name] = len(self._decode_idx)
            self._decode_idx.append(name)
        # ENCODE instances live only in _instances (EPD encode pool).

    def _remove_from_indexes(self, name: str) -> None:
        pos = self._pos.pop(name, None)
        if pos is None:
            return
        for arr in (self._prefill_idx, self._decode_idx):
            if pos < len(arr) and arr[pos] == name:
                last = arr.pop()
                if pos < len(arr):
                    arr[pos] = last
                    self._pos[last] = pos
                return
        # Name was in the other array's index space; linear fallback.
        for arr in (self._prefill_idx, self._decode_idx):
            if name in arr:
                i = arr.index(name)
                last = arr.pop()
                if i < len(arr):
                    arr[i] = last
                    self._pos[last] = i
                return

    def remove_instance(self, name: str) -> None:
        """Full cleanup on store DELETE / lease expiry
        (instance_mgr.cpp:606-686)."""
        with self._lock:
            self._pending.pop(name, None)
            if name not in self._instances:
                return
            self._remove_from_indexes(name)
            del self._instances[name]
            self._removed.add(name)
            if name in self._mix_names:
                self._mix_names.discard(name)
                self._reseat_mix()
        if self.events is not None:
            self.events.emit("instance_remove", instance=name)
        logger.info("removed instance %s", name)
        if self.on_removed is not None:
            try:
                self.on_removed(name)
            except Exception:  # noqa: BLE001
                logger.exception("on_removed(%s) hook failed", name)

    # ------------------------------------------------------------------
    # Accessors
    # ------------------------------------------------------------------
    def get(self, name: str) -> Optional[InstanceState]:
        with self._lock:
            return self._instances.get(name)

    def digest_ok(self, name: str) -> bool:
        """True when ``name`` is registered AND its block-hash contract
        matches the service's (see ``_register``). Gates cache-delta
        ingestion and holder election."""
        with self._lock:
            inst = self._instances.get(name)
            return inst is not None and inst.digest_compatible

    def names(self) -> List[str]:
        with self._lock:
            return list(self._instances)

    def _is_draining_locked(self, name: str) -> bool:
        """Drain is whole-worker: any model advertising "draining"
        means the instance must receive no new work of any kind (it is
        finishing in-flight requests before a graceful shutdown)."""
        inst = self._instances.get(name)
        return inst is not None and any(
            st == MODEL_DRAINING for st in inst.model_states.values())

    def prefill_instances(self) -> List[str]:
        with self._lock:
            return [n for n in self._prefill_idx
                    if not self._is_draining_locked(n)]

    def decode_instances(self) -> List[str]:
        with self._lock:
            return [n for n in self._decode_idx
                    if not self._is_draining_locked(n)]

    def encode_instances(self) -> List[str]:
        with self._lock:
            return [n for n, s in self._instances.items()
                    if s.instance_type == InstanceType.ENCODE
                    and not self._is_draining_locked(n)]

    def get_next_encode_instance(self) -> Optional[str]:
        """RR over the EPD encode pool."""
        with self._lock:
            pool = [n for n, s in self._instances.items()
                    if s.instance_type == InstanceType.ENCODE
                    and not self._is_draining_locked(n)]
            if not pool:
                return None
            self._rr_encode = getattr(self, "_rr_encode", 0)
            name = pool[self._rr_encode % len(pool)]
            self._rr_encode += 1
            return name

    # Prior for the per-image encode cost before a worker has reported
    # a measured value (LatencyMetrics.encode_ms == 0.0).
    _ENCODE_MS_PRIOR = 50.0

    def select_encode_instance(self, digests: List[str],
                               audit: Optional[Dict[str, Any]] = None
                               ) -> Tuple[Optional[str], List[str]]:
        """Cost-aware EPD encode pick (docs/EPD.md): score every live
        ENCODE instance on measured per-image encode ms × the work it
        would actually do — queued jobs ahead plus THIS request's
        cache-missed images (heartbeat-advertised embed digests credit
        the hits). Returns (winner, ranked survivors); the survivors
        ride ``Routing.encode_fallbacks`` so the prefill worker's
        reroute on encode death is deterministic. (None, []) when no
        encode pool exists — the prefill worker encodes locally."""
        n_img = max(1, len(digests))
        scored: List[Tuple[float, str, Dict[str, Any]]] = []
        with self._lock:
            for name, s in self._instances.items():
                if s.instance_type != InstanceType.ENCODE \
                        or self._is_draining_locked(name):
                    continue
                queue = int(getattr(s.load, "encode_queue_depth", 0))
                enc_ms = float(getattr(s.latency, "encode_ms", 0.0)) \
                    or self._ENCODE_MS_PRIOR
                hits = sum(1 for d in digests if d in s.embed_digests)
                misses = len(digests) - hits
                # Queued jobs ahead are priced at one image each (the
                # queue ships depth, not image count); cache-hit images
                # skip the tower entirely.
                est_ms = enc_ms * (queue + misses)
                scored.append((est_ms, name, {
                    "queue": queue, "encode_ms": round(enc_ms, 3),
                    "cache_hits": hits, "est_ms": round(est_ms, 3)}))
        scored.sort(key=lambda t: (t[0], t[1]))
        if audit is not None:
            audit["encode"] = {
                "policy": "cost", "images": n_img,
                "candidates": {name: terms for _, name, terms in scored},
                "winner": scored[0][1] if scored else None,
            }
        if not scored:
            return None, []
        return scored[0][1], [name for _, name, _ in scored[1:]]

    def address_of(self, name: str) -> Optional[str]:
        inst = self.get(name)
        return inst.meta.rpc_address if inst else None

    def instance_table(self) -> List[Dict[str, Any]]:
        """Flight-recorder view of the live instance books (the debug
        bundle's cluster evidence): role, addresses, model states, last
        load/latency, and heartbeat age per registered instance."""
        now = time.monotonic()
        with self._lock:
            return [{"name": name,
                     "instance_type": s.instance_type.value,
                     "declared_type": s.meta.instance_type.value,
                     "rpc_address": s.meta.rpc_address,
                     "models": dict(s.model_states),
                     "load": s.load.to_json(),
                     "latency": s.latency.to_json(),
                     "heartbeat_age_s": round(now - s.last_heartbeat, 3),
                     "flipped_from": s.flipped_from.value
                     if s.flipped_from else None}
                    for name, s in self._instances.items()]

    def instance_info(self, name: str) -> Optional[Dict[str, Any]]:
        inst = self.get(name)
        return inst.meta.to_json() if inst else None

    # ------------------------------------------------------------------
    # Round-robin pair selection (instance_mgr.cpp:170-186)
    # ------------------------------------------------------------------
    def get_next_instance_pair(self) -> Tuple[Optional[str], Optional[str]]:
        with self._lock:
            prefill = decode = None
            prefills = [n for n in self._prefill_idx
                        if not self._is_draining_locked(n)]
            decodes = [n for n in self._decode_idx
                       if not self._is_draining_locked(n)]
            if prefills:
                prefill = prefills[self._rr_prefill % len(prefills)]
                self._rr_prefill += 1
            if decodes:
                decode = decodes[self._rr_decode % len(decodes)]
                self._rr_decode += 1
            if prefill is None:
                # Degenerate pool (e.g. a single MIX instance that took the
                # decode slot): decode workers can prefill too.
                prefill = decode
            return prefill, decode

    def least_loaded_instance(self, pool: Optional[List[str]] = None
                              ) -> Optional[str]:
        """Fallback pick when no cache overlap exists
        (instance_mgr.cpp:316-385)."""
        with self._lock:
            cands = pool if pool is not None else list(self._prefill_idx)
            best, best_score = None, None
            for name in cands:
                inst = self._instances.get(name)
                if inst is None or self._is_draining_locked(name):
                    continue
                score = (inst.load.waiting_requests
                         + inst.load.kv_cache_usage)
                if best_score is None or score < best_score:
                    best, best_score = name, score
            return best

    # ------------------------------------------------------------------
    # Request metrics ledger (instance_mgr.cpp:745-817)
    # ------------------------------------------------------------------
    def update_request_metrics(self, name: str, phase: str,
                               num_tokens: int = 0) -> None:
        with self._lock:
            inst = self._instances.get(name)
            if inst is None:
                return
            m = inst.req_metrics
            if phase == RequestPhase.SCHEDULE:
                m.num_prefill_requests += 1
                m.num_prefill_tokens += num_tokens
                if inst.predictor.has_ttft:
                    m.estimated_prefill_time_ms += \
                        inst.predictor.predict_ttft(num_tokens)
            elif phase == RequestPhase.UNSCHEDULE:
                m.num_prefill_requests = max(0, m.num_prefill_requests - 1)
                m.num_prefill_tokens = max(0, m.num_prefill_tokens
                                           - num_tokens)
                if inst.predictor.has_ttft:
                    m.estimated_prefill_time_ms = max(
                        0.0, m.estimated_prefill_time_ms
                        - inst.predictor.predict_ttft(num_tokens))
            elif phase == RequestPhase.PREFILL_FINISH:
                m.num_prefill_requests = max(0, m.num_prefill_requests - 1)
                m.num_prefill_tokens = max(0, m.num_prefill_tokens
                                           - num_tokens)
                if inst.predictor.has_ttft:
                    m.estimated_prefill_time_ms = max(
                        0.0, m.estimated_prefill_time_ms
                        - inst.predictor.predict_ttft(num_tokens))
                m.num_decode_requests += 1
                m.num_decode_tokens += num_tokens
            elif phase == RequestPhase.GENERATE:
                m.num_decode_tokens += num_tokens
            elif phase in (RequestPhase.FINISH_DECODE, RequestPhase.CANCEL):
                m.num_decode_requests = max(0, m.num_decode_requests - 1)
                m.num_decode_tokens = max(0, m.num_decode_tokens
                                          - num_tokens)
                # Auto flip-back when a flipped decode instance drains
                # (instance_mgr.cpp:812-816).
                if (m.num_decode_requests == 0
                        and inst.flipped_from == InstanceType.PREFILL):
                    self._flip_locked(name, InstanceType.PREFILL)

    # ------------------------------------------------------------------
    # SLO-aware selection + dynamic PD flips (instance_mgr.cpp:819-970)
    # ------------------------------------------------------------------
    def _backlog_ms(self, inst) -> float:
        """Heartbeat-advertised prefill backlog converted to time: the
        worker's queued-but-uncomputed prompt tokens over its measured
        prefill throughput (falling back to the planner's 4000 tok/s
        default until a measurement arrives). This is the P/D-Serve
        term: the service-side in-flight ledger alone misses prompts
        already sitting in a worker's own queue."""
        toks = getattr(inst.latency, "waiting_prefill_tokens", 0) or 0
        if toks <= 0:
            return 0.0
        rate = inst.latency.prefill_tok_s or 4000.0
        return 1000.0 * toks / rate

    def select_instance_pair_on_slo(self, num_prompt_tokens: int,
                                    audit: Optional[Dict[str, Any]] = None
                                    ) -> Tuple[Optional[str], Optional[str],
                                               float]:
        """Returns (prefill, decode, estimated_ttft_ms). ``audit``, when
        given, gains the prefill winner's backlog term so the routing
        decision stays explainable (attrs.schedule_decision)."""
        with self._lock:
            # Prefill: argmin of estimated prefill backlog — the
            # service-side ledger estimate PLUS the worker-advertised
            # queue converted to ms (falling back to
            # the decode pool when no dedicated prefill instance exists).
            best_p, best_p_time, best_p_backlog = None, float("inf"), 0.0
            for name in (self._prefill_idx or self._decode_idx):
                if self._is_draining_locked(name):
                    continue
                inst = self._instances[name]
                backlog = self._backlog_ms(inst)
                t = inst.req_metrics.estimated_prefill_time_ms + backlog
                if t < best_p_time:
                    best_p, best_p_time, best_p_backlog = name, t, backlog
            if audit is not None and best_p is not None:
                audit["backlog_ms"] = round(best_p_backlog, 3)
                audit["waiting_prefill_tokens"] = int(getattr(
                    self._instances[best_p].latency,
                    "waiting_prefill_tokens", 0) or 0)

            # Decode: first instance whose predicted TPOT meets the target,
            # else argmin predicted TPOT.
            target_tpot = self.opts.target_tpot_ms
            best_d, best_d_tpot = None, float("inf")
            for name in self._decode_idx:
                if self._is_draining_locked(name):
                    # A draining worker's emptying backlog makes it look
                    # MOST attractive to the SLO argmin — skip it.
                    continue
                inst = self._instances[name]
                m = inst.req_metrics
                tpot = inst.predictor.predict_tpot(
                    m.num_decode_tokens + num_prompt_tokens,
                    m.num_decode_requests + 1)
                if tpot <= target_tpot:
                    best_d, best_d_tpot = name, tpot
                    break
                if tpot < best_d_tpot:
                    best_d, best_d_tpot = name, tpot

            est_ttft = best_p_time
            if best_p is not None:
                inst = self._instances[best_p]
                if inst.predictor.has_ttft:
                    est_ttft = (best_p_time
                                + inst.predictor.predict_ttft(
                                    num_prompt_tokens))

            # Prefill overflow onto an idle decode instance
            # (instance_mgr.cpp:879-905).
            if (best_p is not None and est_ttft > self.opts.target_ttft_ms
                    and self._decode_idx):
                idle = [n for n in self._decode_idx
                        if self._instances[n].req_metrics.num_decode_requests
                        == 0 and n != best_d
                        # a draining worker is precisely the one
                        # guaranteed to look idle — never overflow to it
                        and not self._is_draining_locked(n)]
                if idle:
                    best_p = idle[0]
                    est_ttft = self._instances[best_p].predictor.predict_ttft(
                        num_prompt_tokens)

            # No decode meets TPOT and prefill pool has slack → flip a
            # prefill instance to decode (instance_mgr.cpp:907-917).
            if (best_d is not None and best_d_tpot > target_tpot
                    and len(self._prefill_idx) > 1):
                flip = next((n for n in self._prefill_idx
                             if n != best_p
                             and not self._is_draining_locked(n)), None)
                if flip:
                    self._flip_locked(flip, InstanceType.DECODE)
                    best_d = flip
            return best_p, best_d, est_ttft

    def flip_prefill_to_decode(self, name: str) -> bool:
        with self._lock:
            inst = self._instances.get(name)
            if inst is None or inst.instance_type != InstanceType.PREFILL:
                return False
            return self._flip_locked(name, InstanceType.DECODE)

    def flip_decode_to_prefill(self, name: str) -> bool:
        with self._lock:
            inst = self._instances.get(name)
            if inst is None or inst.instance_type != InstanceType.DECODE:
                return False
            return self._flip_locked(name, InstanceType.PREFILL)

    def _flip_locked(self, name: str, to_type: InstanceType) -> bool:
        inst = self._instances[name]
        from_type = inst.instance_type
        if from_type == to_type:
            return False
        inst.flipped_from = None if inst.flipped_from else from_type
        self._set_role(name, to_type)
        if self.events is not None:
            self.events.emit("role_flip", instance=name,
                             from_type=from_type.value,
                             to_type=to_type.value)
        logger.info("flipped %s %s→%s", name, from_type.value, to_type.value)
        # Fire-and-forget worker notification; on TPU a flip just changes
        # which compiled program set the worker prioritizes (SURVEY.md §7.1).
        def notify() -> None:
            try:
                self.control(inst.meta.rpc_address, "/flip_role",
                             {"instance_type": to_type.value})
            except Exception as e:  # noqa: BLE001
                logger.warning("flip notify %s failed: %s", name, e)
        spawn("instance_mgr.flip_notify", notify,
              events=lambda: self.events).start()
        return True

    # ------------------------------------------------------------------
    # Load metrics replication (master upload, instance_mgr.cpp:398-416)
    # ------------------------------------------------------------------
    def upload_load_metrics(self) -> None:
        with self._lock:
            snapshot = {name: {"load": inst.load.to_json(),
                               "latency": inst.latency.to_json()}
                        for name, inst in self._instances.items()}
        for name, val in snapshot.items():
            self.store.put_json(KEY_LOADMETRICS + name, val)

    # ------------------------------------------------------------------
    # Multi-model serverless (instance_mgr.cpp:1067-1243)
    # ------------------------------------------------------------------
    def update_model_heat(self, model: str) -> None:
        with self._lock:
            self._model_heat[model] = self._model_heat.get(model, 0.0) + 1.0

    def model_heat(self, model: str) -> float:
        with self._lock:
            return self._model_heat.get(model, 0.0)

    def get_awake_instance(self, model: str) -> Optional[str]:
        """Least-loaded instance where ``model`` is awake
        (instance_mgr.cpp:1087-1105)."""
        with self._lock:
            cands = [n for n, s in self._instances.items()
                     if s.model_states.get(model) == MODEL_AWAKE]
            return self.least_loaded_instance(cands) if cands else None

    def filter_model_awake(self, pool: List[str], model: str
                           ) -> List[str]:
        """Restrict ``pool`` to instances where ``model`` is awake.
        A pool with no per-model state at all (single-model deployments
        never populate ``model_states``) passes through unchanged —
        the filter only bites where model placement is actually
        tracked, so a model-blind fallback pick can't land on an
        instance that holds the model asleep or not at all."""
        with self._lock:
            states = [(n, self._instances[n].model_states.get(model)
                       if n in self._instances else None)
                      for n in pool]
        if not any(st is not None for _, st in states):
            return list(pool)
        return [n for n, st in states if st == MODEL_AWAKE]

    def allocate_instance_for_model(self, model: str) -> Optional[str]:
        """Wake ``model`` somewhere, evicting the coldest model subset if
        memory requires (instance_mgr.cpp:1107-1243)."""
        need_gb = self.model_memory_gb.get(model, 0.0)
        with self._lock:
            best: Optional[Tuple[str, List[str]]] = None
            best_heat = float("inf")
            for name, inst in self._instances.items():
                if model not in inst.model_states:
                    continue
                if inst.model_states[model] == MODEL_DRAINING:
                    # A draining instance must not be woken back up —
                    # it is finishing in-flight work before shutdown.
                    continue
                awake = [m for m, st in inst.model_states.items()
                         if st == MODEL_AWAKE]
                used = sum(self.model_memory_gb.get(m, 0.0) for m in awake)
                free = inst.meta.memory_budget_gb - used
                if free >= need_gb:
                    victims: List[str] = []
                    heat = 0.0
                else:
                    victims = self._select_eviction_candidates(
                        awake, need_gb - free)
                    if victims is None:
                        continue
                    heat = sum(self._model_heat.get(m, 0.0)
                               for m in victims)
                if heat < best_heat:
                    best, best_heat = (name, victims), heat
            if best is None:
                return None
            name, victims = best
            inst = self._instances[name]
        # Control calls outside the lock.
        for victim in victims:
            try:
                self.control(inst.meta.rpc_address, "/sleep",
                             {"model": victim})
                with self._lock:
                    inst.model_states[victim] = MODEL_ASLEEP
            except Exception as e:  # noqa: BLE001
                logger.warning("sleep(%s@%s) failed: %s", victim, name, e)
        try:
            status, _ = self.control(inst.meta.rpc_address, "/wakeup",
                                     {"model": model})
            if status != 200:
                return None
        except Exception as e:  # noqa: BLE001
            logger.warning("wakeup(%s@%s) failed: %s", model, name, e)
            return None
        with self._lock:
            inst.model_states[model] = MODEL_AWAKE
        return name

    def _select_eviction_candidates(self, awake: List[str],
                                    need_gb: float) -> Optional[List[str]]:
        """Exhaustive subset search: the subset freeing ≥ need_gb with
        minimum total heat, smallest size as tiebreak
        (instance_mgr.cpp:1188-1243)."""
        best: Optional[List[str]] = None
        best_key: Optional[Tuple[float, int]] = None
        for r in range(1, len(awake) + 1):
            for subset in itertools.combinations(awake, r):
                freed = sum(self.model_memory_gb.get(m, 0.0)
                            for m in subset)
                if freed < need_gb:
                    continue
                heat = sum(self._model_heat.get(m, 0.0) for m in subset)
                key = (heat, r)
                if best_key is None or key < best_key:
                    best, best_key = list(subset), key
            if best is not None:
                # Any larger subset has ≥ heat (heats are non-negative) at
                # larger size, so the first radius with a fit is optimal
                # only per-size; continue searching all sizes for min heat.
                pass
        return best

    # ------------------------------------------------------------------
    def stale_instances(self, timeout_s: float) -> List[str]:
        """Instances whose heartbeats stopped (the reference's dead
        ``detect_disconnected_instance_interval`` flag, implemented here —
        SURVEY.md §7.4)."""
        now = time.monotonic()
        with self._lock:
            return [n for n, s in self._instances.items()
                    if now - s.last_heartbeat > timeout_s]

    def close(self) -> None:
        for wid in self._watch_ids:
            try:
                self.store.cancel_watch(wid)
            except Exception:  # noqa: BLE001 — store may be mid-outage
                # at shutdown; the watch dies with the process anyway
                pass
