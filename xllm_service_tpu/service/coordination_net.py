"""Networked coordination plane: ``InMemoryStore`` served over HTTP.

Deployment equivalent of the reference's external etcd cluster: one process
runs ``StoreServer`` (or any process embeds it — e.g. the first service
replica), every other service replica / worker host connects a
``RemoteStore``, which implements the same ``CoordinationStore`` interface.
Watches are long-polls on the store's revision counter, so remote watchers
see the same PUT/DELETE event stream in order.
"""

from __future__ import annotations

import threading


from typing import Dict, Optional

from xllm_service_tpu.service.coordination import (
    CoordinationStore, InMemoryStore, WatchCallback)
from xllm_service_tpu.service.httpd import (
    HttpServer, Request, Response, Router, http_json)
from xllm_service_tpu.utils.locks import make_lock
from xllm_service_tpu.utils.retry import RetryPolicy
from xllm_service_tpu.utils import threads
from xllm_service_tpu.utils.threads import spawn


class StoreServer:
    """HTTP facade over an ``InMemoryStore``."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 store: Optional[InMemoryStore] = None) -> None:
        self.store = store or InMemoryStore()
        router = Router()
        router.route("POST", "/kv/put", self._put)
        router.route("GET", "/kv/get", self._get)
        router.route("GET", "/kv/prefix", self._prefix)
        router.route("POST", "/kv/delete", self._delete)
        router.route("POST", "/kv/delete_prefix", self._delete_prefix)
        router.route("POST", "/lease/grant", self._grant)
        router.route("POST", "/lease/keepalive", self._keepalive)
        router.route("POST", "/lease/revoke", self._revoke)
        router.route("POST", "/txn/compare_create", self._compare_create)
        router.route("GET", "/watch", self._watch)
        router.route("GET", "/rev", self._rev)
        self._srv = HttpServer(host, port, router)

    @property
    def address(self) -> str:
        return self._srv.address

    def start(self) -> "StoreServer":
        self._srv.start()
        return self

    def stop(self) -> None:
        self._srv.stop()
        self.store.close()

    # -- handlers ---------------------------------------------------------
    def _put(self, req: Request) -> Response:
        d = req.json()
        try:
            self.store.put(d["key"], d["value"], d.get("lease_id"))
        except KeyError as e:
            return Response.json({"ok": False, "error": str(e)}, status=400)
        return Response.json({"ok": True})

    def _get(self, req: Request) -> Response:
        v = self.store.get(req.param("key"))
        return Response.json({"value": v})

    def _prefix(self, req: Request) -> Response:
        return Response.json({"kvs": self.store.get_prefix(
            req.param("prefix"))})

    def _delete(self, req: Request) -> Response:
        return Response.json(
            {"deleted": self.store.delete(req.json()["key"])})

    def _delete_prefix(self, req: Request) -> Response:
        return Response.json(
            {"count": self.store.delete_prefix(req.json()["prefix"])})

    def _grant(self, req: Request) -> Response:
        return Response.json(
            {"lease_id": self.store.lease_grant(req.json()["ttl_s"])})

    def _keepalive(self, req: Request) -> Response:
        return Response.json(
            {"ok": self.store.lease_keepalive(req.json()["lease_id"])})

    def _revoke(self, req: Request) -> Response:
        self.store.lease_revoke(req.json()["lease_id"])
        return Response.json({"ok": True})

    def _compare_create(self, req: Request) -> Response:
        d = req.json()
        created = self.store.compare_create(d["key"], d["value"],
                                            d.get("lease_id"))
        return Response.json({"created": created})

    def _rev(self, req: Request) -> Response:
        return Response.json({"rev": self.store.revision})

    def _watch(self, req: Request) -> Response:
        rev = int(req.param("rev", "0"))
        timeout = min(float(req.param("timeout", "10")), 30.0)
        if rev > self.store.revision:
            # A resume revision from the FUTURE: the client watched a
            # previous incarnation of this store (we restarted, wiped).
            # Answer immediately with the real revision so the client's
            # regression handling can re-bootstrap, instead of blocking
            # the long-poll until the new log catches up.
            return Response.json({"rev": self.store.revision,
                                  "compacted": False, "events": []})
        # Events older than the bounded log's head are gone; tell the
        # watcher so it can resync instead of silently missing deletes.
        compacted = rev + 1 < self.store.oldest_retained_revision
        new_rev, events = self.store.events_since(
            rev, req.param("prefix"), timeout)
        return Response.json({"rev": new_rev, "compacted": compacted,
                              "events": [list(e) for e in events]})


class RemoteStore(CoordinationStore):
    """Client-side ``CoordinationStore`` over a ``StoreServer``."""

    def __init__(self, address: str, timeout: float = 10.0) -> None:
        self.address = address
        self.timeout = timeout
        self._watches: Dict[int, threading.Event] = {}
        self._next_watch = 1
        self._lock = make_lock("coordination_net", 60)
        # Watch-reconnect pacing: jittered so a fleet of watchers does
        # not hammer a restarting store in 1 Hz lockstep (the loop
        # itself is infinite by design — supervised restart owns
        # crashes, this policy owns the cadence).
        self._watch_retry = RetryPolicy(base_delay_s=0.25,
                                        max_delay_s=5.0)

    def _call(self, method: str, path: str, obj=None):
        status, resp = http_json(method, self.address, path, obj,
                                 timeout=self.timeout)
        if status != 200:
            raise RuntimeError(f"store {path} -> {status}: {resp}")
        return resp

    def put(self, key: str, value: str,
            lease_id: Optional[int] = None) -> None:
        self._call("POST", "/kv/put",
                   {"key": key, "value": value, "lease_id": lease_id})

    def get(self, key: str) -> Optional[str]:
        return self._call("GET", f"/kv/get?key={_q(key)}")["value"]

    def get_prefix(self, prefix: str) -> Dict[str, str]:
        return self._call("GET", f"/kv/prefix?prefix={_q(prefix)}")["kvs"]

    def delete(self, key: str) -> bool:
        return self._call("POST", "/kv/delete", {"key": key})["deleted"]

    def delete_prefix(self, prefix: str) -> int:
        return self._call("POST", "/kv/delete_prefix",
                          {"prefix": prefix})["count"]

    def lease_grant(self, ttl_s: float) -> int:
        return self._call("POST", "/lease/grant",
                          {"ttl_s": ttl_s})["lease_id"]

    def lease_keepalive(self, lease_id: int) -> bool:
        return self._call("POST", "/lease/keepalive",
                          {"lease_id": lease_id})["ok"]

    def lease_revoke(self, lease_id: int) -> None:
        self._call("POST", "/lease/revoke", {"lease_id": lease_id})

    def compare_create(self, key: str, value: str,
                       lease_id: Optional[int] = None) -> bool:
        return self._call("POST", "/txn/compare_create",
                          {"key": key, "value": value,
                           "lease_id": lease_id})["created"]

    def add_watch(self, prefix: str, callback: WatchCallback) -> int:
        with self._lock:
            wid = self._next_watch
            self._next_watch += 1
            stop = threading.Event()
            self._watches[wid] = stop
        # Supervised + restarted: the long-poll loop already absorbs
        # transport failures; the supervised restart absorbs crashes
        # outside its try blocks so a remote watcher can't die silently.
        spawn("coordination_net.watch_loop", self._watch_loop,
              args=(prefix, callback, stop),
              thread_name=f"remote-watch-{wid}",
              restart=threads.RESTART_POLICY, stop=stop).start()
        return wid

    def _watch_loop(self, prefix: str, callback: WatchCallback,
                    stop: threading.Event) -> None:
        # Like local add_watch, deliver only *future* events: start at the
        # server's current revision, not 0 (a fresh watcher must not replay
        # the whole retained history).
        rev: Optional[int] = None
        attempt = 0
        while not stop.is_set() and rev is None:
            try:
                status, resp = http_json("GET", self.address, "/rev",
                                         timeout=self.timeout)
                if status == 200:
                    rev = resp["rev"]
            except Exception:  # noqa: BLE001 — store still booting or
                # unreachable; this loop IS the retry
                self._watch_retry.sleep(attempt, stop_event=stop)
                attempt += 1
        # Last state this watcher DELIVERED per key — the compaction
        # fallback's baseline. When the server says our revision was
        # compacted away (we reconnected older than
        # oldest_retained_revision), retrying that revision would loop
        # forever: instead re-bootstrap from get_prefix and deliver the
        # STATE DIFF (synthetic DELETEs for vanished keys, PUTs for
        # new/changed) — same contract as EtcdStore._resync.
        known: Dict[str, str] = {}
        attempt = 0
        while not stop.is_set():
            try:
                status, resp = http_json(
                    "GET", self.address,
                    f"/watch?prefix={_q(prefix)}&rev={rev}&timeout=5",
                    timeout=self.timeout + 10)
                if status != 200:
                    self._watch_retry.sleep(attempt, stop_event=stop)
                    attempt += 1
                    continue
                attempt = 0     # healthy exchange resets the backoff
                if resp["rev"] < rev:
                    # The server restarted with a YOUNGER event log (the
                    # memory-backed store was killed and rebooted): our
                    # resume revision is from a dead timeline and would
                    # leave this watcher deaf until the new log catches
                    # up to it. Adopt the new timeline and state-diff,
                    # exactly like a compaction.
                    import logging
                    logging.getLogger(__name__).warning(
                        "watch on %r saw the store's revision regress "
                        "(%d -> %d): store restarted; re-bootstrapping",
                        prefix, rev, resp["rev"])
                    rev = resp["rev"]
                    self._resync(prefix, known, callback, stop)
                    continue
                rev = resp["rev"]
                if resp.get("compacted"):
                    import logging
                    logging.getLogger(__name__).warning(
                        "watch on %r fell behind the event log "
                        "(compacted); re-bootstrapping from get_prefix",
                        prefix)
                    self._resync(prefix, known, callback, stop)
                    continue
                for ev_type, key, value in resp["events"]:
                    if stop.is_set():
                        return
                    if ev_type == "DELETE":
                        known.pop(key, None)
                    else:
                        known[key] = value
                    try:
                        callback((ev_type, key, value))
                    except Exception as e:
                        # A broken callback must not kill (or stall)
                        # the watch loop — logged + counted, never
                        # silently printed to a stderr nobody tails.
                        threads.record_callback_error(
                            "coordination_net.watch_loop", e)
            except Exception:  # noqa: BLE001 — store restarting/unreachable
                self._watch_retry.sleep(attempt, stop_event=stop)
                attempt += 1

    def _resync(self, prefix: str, known: Dict[str, str],
                callback: WatchCallback, stop: threading.Event) -> None:
        """Replace compacted-away events with a state diff (the
        EtcdStore._resync contract): synthetic DELETEs for keys that
        vanished while we were behind, PUTs for new/changed values."""
        try:
            current = self.get_prefix(prefix)
        except Exception as e:  # noqa: BLE001 — next long-poll round
            # hits compacted again and retries the resync
            import logging
            logging.getLogger(__name__).warning(
                "watch resync of %r failed: %s", prefix, e)
            return
        for key in list(known):
            if stop.is_set():
                return
            if key not in current:
                known.pop(key)
                try:
                    callback(("DELETE", key, None))
                except Exception as e:  # noqa: BLE001
                    threads.record_callback_error(
                        "coordination_net.watch_loop", e)
        for key, value in current.items():
            if stop.is_set():
                return
            if known.get(key) != value:
                known[key] = value
                try:
                    callback(("PUT", key, value))
                except Exception as e:  # noqa: BLE001
                    threads.record_callback_error(
                        "coordination_net.watch_loop", e)

    def cancel_watch(self, watch_id: int) -> None:
        with self._lock:
            stop = self._watches.pop(watch_id, None)
        if stop:
            stop.set()

    def close(self) -> None:
        with self._lock:
            for stop in self._watches.values():
                stop.set()
            self._watches.clear()


def _q(s: str) -> str:
    from urllib.parse import quote
    return quote(s, safe="")


def connect_store(addr: str) -> CoordinationStore:
    """'' → fresh in-process store; 'etcd://host:port' → real etcd v3
    (quorum deployments); 'host:port' → RemoteStore (StoreServer)."""
    if not addr:
        return InMemoryStore()
    if addr.startswith("etcd://"):
        from xllm_service_tpu.service.etcd_store import EtcdStore
        return EtcdStore(addr[len("etcd://"):])
    return RemoteStore(addr)


def main(argv=None) -> int:
    """Standalone coordination-store server (the deployment's 'etcd')."""
    import argparse
    import signal

    parser = argparse.ArgumentParser(
        description="xllm-service-tpu coordination store server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=12379)
    args = parser.parse_args(argv)
    server = StoreServer(args.host, args.port).start()
    print(f"coordination store serving on {server.address}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda s, f: stop.set())
    signal.signal(signal.SIGTERM, lambda s, f: stop.set())
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
