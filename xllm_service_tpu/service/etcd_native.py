"""Launcher for the native coordination server (``csrc/xllm_etcd.cpp``).

The reference FATALs without a reachable etcd cluster
(scheduler/etcd_client/etcd_client.cpp:24-33); this rebuild ships its own
etcd-v3-JSON-gateway-compatible server binary instead, so (a) deployments
get a coordination plane without an external etcd install, and (b) the
``EtcdStore`` contract suite always runs against a *genuinely separate
implementation* over real sockets — an independently-written C++ server,
not the Python mock that shares its author's assumptions (round-3
verdict weak #6). ``XLLM_ETCD_ADDR`` still points the same tests at a
stock etcd when one is available.

Build is on-demand (g++, same pattern as the native httpd/hash modules)
into ``build/native/xllm_etcd``; the server prints ``LISTENING <port>``
once bound, so port 0 (ephemeral) works for parallel test runs.
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional

from xllm_service_tpu.utils.locks import make_lock

_build_lock = make_lock("etcd_native.build", 97)


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def build_binary() -> Optional[str]:
    """Compile (if stale) and return the server binary path, or None when
    the toolchain/source is unavailable."""
    root = _repo_root()
    src = os.path.join(root, "csrc", "xllm_etcd.cpp")
    if not os.path.exists(src):
        return None
    out_dir = os.path.join(root, "build", "native")
    os.makedirs(out_dir, exist_ok=True)
    binary = os.path.join(out_dir, "xllm_etcd")
    with _build_lock:
        if os.path.exists(binary) \
                and os.path.getmtime(binary) >= os.path.getmtime(src):
            return binary
        cxx = os.environ.get("CXX", "g++")
        tmp = f"{binary}.{os.getpid()}.tmp"
        cmd = [cxx, "-O2", "-std=c++17", "-pthread", src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True,
                           timeout=180)
            os.replace(tmp, binary)
        except Exception:  # noqa: BLE001 — no toolchain / compile
            # failure: None falls back to the in-process store, which
            # the caller reports
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
    return binary


class NativeEtcdServer:
    """One xllm_etcd OS process on an ephemeral loopback port."""

    def __init__(self, port: int = 0) -> None:
        self._port = port
        self._proc: Optional[subprocess.Popen] = None
        self.address: str = ""

    def start(self) -> "NativeEtcdServer":
        binary = build_binary()
        if binary is None:
            raise RuntimeError("xllm_etcd binary unavailable (no g++?)")
        self._proc = subprocess.Popen(
            [binary, str(self._port)], stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        line = self._proc.stdout.readline().decode("ascii", "replace")
        if not line.startswith("LISTENING "):
            self.stop()
            raise RuntimeError(f"xllm_etcd failed to bind: {line!r}")
        self.address = f"127.0.0.1:{int(line.split()[1])}"
        return self

    def stop(self) -> None:
        if self._proc is not None:
            self._proc.kill()
            self._proc.wait(timeout=10)
            self._proc = None
