"""Vision encoder for the EPD (encode→prefill→decode) multimodal pipeline.

The reference *claims* EPD multimodal disaggregation but keeps the encode
stage engine-side and out of repo (README.md:44, SURVEY.md §2 intro); this
is the net-new TPU implementation: a ViT-style patch encoder compiled as
its own XLA program (SURVEY.md §7.1 EPD row), runnable on a dedicated
ENCODE worker or inline on a prefill worker. Output is a sequence of patch
embeddings projected into the language model's hidden size, spliced into
the prompt at image-placeholder token positions.

TPU-first choices mirror the text stack: stacked layers + ``lax.scan``,
bfloat16 matmuls / fp32 norms, static shapes (images are resized host-side
to a fixed grid; the token count per image is a compile-time constant).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from xllm_service_tpu.config import ModelConfig
from xllm_service_tpu.ops.norm import rms_norm

VisionParams = Dict[str, Any]


def num_patches(image_size: int, patch_size: int) -> int:
    return (image_size // patch_size) ** 2


def init_vision_params(cfg: "VisionConfig", key: jax.Array) -> VisionParams:
    dtype = jnp.dtype(cfg.dtype)
    D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    H, Dh = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    P = cfg.patch_size
    keys = iter(jax.random.split(key, 16))

    def w(shape, fan_in):
        return (jax.random.normal(next(keys), shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(dtype)

    n_patch = num_patches(cfg.image_size, P)
    return {
        "patch_embed": w((P * P * 3, D), P * P * 3),
        "pos_embed": w((n_patch, D), D),
        "layers": {
            "input_norm": jnp.ones((L, D), dtype),
            "qkv": w((L, D, 3 * H * Dh), D),
            "o_proj": w((L, H * Dh, D), H * Dh),
            "post_norm": jnp.ones((L, D), dtype),
            "up_proj": w((L, D, F), D),
            "down_proj": w((L, F, D), F),
        },
        "final_norm": jnp.ones((D,), dtype),
        "proj": w((D, cfg.output_size), D),
    }


def patchify(pixels: jnp.ndarray, patch_size: int) -> jnp.ndarray:
    """[B, H, W, 3] → [B, n_patches, P*P*3]."""
    B, H, W, C = pixels.shape
    P = patch_size
    x = pixels.reshape(B, H // P, P, W // P, P, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, (H // P) * (W // P), P * P * C)


def encode_image(params: VisionParams, cfg: "VisionConfig",
                 pixels: jnp.ndarray) -> jnp.ndarray:
    """pixels [B, H, W, 3] float in [0, 1] → patch embeddings
    [B, n_patches, output_size] in the LLM's hidden space."""
    dtype = jnp.dtype(cfg.dtype)
    H, Dh = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    x = patchify(pixels.astype(dtype), cfg.patch_size) @ params["patch_embed"]
    x = x + params["pos_embed"][None]

    def layer(x, lp):
        B, T, D = x.shape
        h = rms_norm(x, lp["input_norm"], 1e-5)
        qkv = (h @ lp["qkv"]).reshape(B, T, 3, H, Dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        scale = 1.0 / jnp.sqrt(jnp.asarray(Dh, jnp.float32))
        logits = jnp.einsum("bthd,bshd->bhts", q, k,
                            preferred_element_type=jnp.float32) * scale
        p = jax.nn.softmax(logits, axis=-1)          # bidirectional
        attn = jnp.einsum("bhts,bshd->bthd", p.astype(v.dtype), v)
        x = x + attn.reshape(B, T, -1) @ lp["o_proj"]
        h = rms_norm(x, lp["post_norm"], 1e-5)
        x = x + jax.nn.gelu(h @ lp["up_proj"]) @ lp["down_proj"]
        return x, None

    x, _ = jax.lax.scan(layer, x, params["layers"])
    x = rms_norm(x, params["final_norm"], 1e-5)
    return (x @ params["proj"]).astype(dtype)


class VisionConfig:
    """Static config; hashable for use as a jit closure constant."""

    def __init__(self, image_size: int = 224, patch_size: int = 14,
                 hidden_size: int = 1024, intermediate_size: int = 4096,
                 num_layers: int = 24, num_heads: int = 16,
                 output_size: int = 4096, dtype: str = "bfloat16") -> None:
        self.image_size = image_size
        self.patch_size = patch_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.output_size = output_size
        self.dtype = dtype

    @property
    def tokens_per_image(self) -> int:
        return num_patches(self.image_size, self.patch_size)

    @classmethod
    def tiny(cls, output_size: int = 64) -> "VisionConfig":
        return cls(image_size=16, patch_size=4, hidden_size=32,
                   intermediate_size=64, num_layers=2, num_heads=2,
                   output_size=output_size)

    @classmethod
    def for_model(cls, model_cfg: ModelConfig) -> "VisionConfig":
        """Qwen2-VL-flavored encoder sized for ``model_cfg``'s hidden."""
        return cls(output_size=model_cfg.hidden_size)
