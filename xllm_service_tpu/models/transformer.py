"""Functional decoder-only transformer over a paged KV cache.

One scanned layer body serves every family in ``docs/MODELS.md`` —
Llama-2/3.x, Qwen2/2.5/3 (+Qwen3-MoE), Phi-3, Mistral (v0.1 sliding
window and v0.2+), Gemma-2/3 (four-norm blocks, soft-caps, per-layer
windows and rope bases as traced scan xs), Mixtral, GPT-OSS (attention
sinks, clamped-GLU experts), the Qwen2/2.5-VL mrope text stacks — plus
a dedicated multi-head-latent-attention path (DeepSeek-V2/V3/R1) that
serves a latent pool through the same paged machinery. Design choices
are TPU-first (SURVEY.md §7.1):

- **Stacked layers + ``lax.scan``**: every per-layer weight carries a leading
  ``[L, ...]`` axis and the layer body is traced once, so compile time and
  program size are depth-independent and XLA pipelines HBM prefetch of layer
  l+1's weights behind layer l's compute.
- **Plain pytree params** (no framework modules): the sharding layer
  (``parallel/sharding.py``) attaches ``NamedSharding`` per leaf path; pjit
  then partitions the same function over any mesh.
- **Paged KV cache** threaded through scan as per-layer xs/ys (see
  ``ops/attention.py`` for the page pool layout).
- **bfloat16 weights/activations, float32 softmax/norm/rope/logits** — the
  MXU-native mix.

The reference repo has no model code at all (its engine is out-of-repo,
SURVEY.md §2 intro); this file is the net-new compute path it assumes.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from xllm_service_tpu.config import ModelConfig
from xllm_service_tpu.ops.norm import rms_norm
from xllm_service_tpu.ops.rope import (apply_rope,
                                       apply_rope_dynamic,
                                       rope_for)
from xllm_service_tpu.ops.attention import (
    FULL_WINDOW,
    mha_prefill,
    mha_prefill_auto,
    paged_decode_attention,
    paged_decode_attention_auto,
    paged_decode_attention_current,
    paged_decode_attention_current_auto,
    gather_pages,
    overlay_fresh_kv,
    write_prefill_kv_all_layers,
    write_prefill_kv_layer,
    write_decode_kv_all_layers,
    write_decode_kv_layer,
)

Params = Dict[str, Any]
KVCache = Tuple[jnp.ndarray, jnp.ndarray]  # k_pages, v_pages: [L, P, ps, Hkv, Dh]


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------

def init_params(cfg: ModelConfig, key: jax.Array,
                dtype: Optional[jnp.dtype] = None) -> Params:
    """Random-init a parameter pytree with the stacked-layer layout."""
    if cfg.mla:
        return _init_mla_params(cfg, key, dtype)
    dtype = dtype or jnp.dtype(cfg.dtype)
    L, D, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    Hq, Hkv, Dh = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    keys = iter(jax.random.split(key, 32))

    def w(shape, fan_in):
        return (jax.random.normal(next(keys), shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(dtype)

    layers: Dict[str, jnp.ndarray] = {
        "input_norm": jnp.ones((L, D), dtype),
        "q_proj": w((L, D, Hq * Dh), D),
        "k_proj": w((L, D, Hkv * Dh), D),
        "v_proj": w((L, D, Hkv * Dh), D),
        "o_proj": w((L, Hq * Dh, D), Hq * Dh),
        "post_norm": jnp.ones((L, D), dtype),
    }
    if cfg.attention_bias:
        layers["q_bias"] = jnp.zeros((L, Hq * Dh), dtype)
        layers["k_bias"] = jnp.zeros((L, Hkv * Dh), dtype)
        layers["v_bias"] = jnp.zeros((L, Hkv * Dh), dtype)
    if cfg.gemma:
        layers["pre_ff_norm"] = jnp.ones((L, D), dtype)
        layers["post_ff_norm"] = jnp.ones((L, D), dtype)
    if cfg.gptoss:
        layers["sinks"] = jnp.zeros((L, Hq), jnp.float32)
        layers["o_bias"] = jnp.zeros((L, D), dtype)
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((L, Dh), dtype)
        layers["k_norm"] = jnp.ones((L, Dh), dtype)
    if cfg.is_moe:
        E = cfg.num_experts
        Fe = cfg.moe_intermediate_size or F
        layers["router"] = w((L, D, E), D)
        layers["gate_proj"] = w((L, E, D, Fe), D)
        layers["up_proj"] = w((L, E, D, Fe), D)
        layers["down_proj"] = w((L, E, Fe, D), Fe)
        if cfg.gptoss:
            layers["router_bias"] = jnp.zeros((L, E), jnp.float32)
            layers["gate_bias"] = jnp.zeros((L, E, Fe), dtype)
            layers["up_bias"] = jnp.zeros((L, E, Fe), dtype)
            layers["down_bias"] = jnp.zeros((L, E, D), dtype)
    else:
        layers["gate_proj"] = w((L, D, F), D)
        layers["up_proj"] = w((L, D, F), D)
        layers["down_proj"] = w((L, F, D), F)

    params: Params = {
        "embed": w((cfg.vocab_size, D), D),
        "layers": layers,
        "final_norm": jnp.ones((D,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w((D, cfg.vocab_size), D)
    return params


def num_params(params: Params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


def init_kv_cache(cfg: ModelConfig, num_pages: int, page_size: int,
                  dtype: Optional[jnp.dtype] = None) -> KVCache:
    dtype = dtype or jnp.dtype(cfg.dtype)
    # MLA: one latent "head" of width kv_lora_rank + qk_rope_head_dim per
    # token instead of per-head K/V (cfg.kv_cache_{heads,dim}).
    shape = (cfg.num_layers, num_pages, page_size, cfg.kv_cache_heads,
             cfg.kv_cache_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


# ---------------------------------------------------------------------------
# Layer body (shared by prefill and decode via an `is_prefill` closure switch
# — two separate compiled programs, one source of truth)
# ---------------------------------------------------------------------------

def _layer_unroll() -> int:
    """Unroll factor for the layer scans (XLLM_UNROLL_LAYERS, default
    1 = rolled). Round-5 pool-copy experiment knob: XLA cannot prove
    the post-scan KV write in-place while the pool is read inside a
    NESTED while loop, so it copies both pools every burst iteration;
    unrolling exposes straight-line reads the alias analysis can see
    through."""
    import os
    try:
        return max(1, int(os.environ.get("XLLM_UNROLL_LAYERS", "1")))
    except ValueError:
        return 1


def _use_prefill_kernel(window: int, page_size: int) -> bool:
    """Trace-time gate for the Pallas flash-prefill kernel: env-enabled
    AND the window tiles exactly into pool pages (engine buckets are pow2
    multiples of the page size at serving shapes; odd test shapes fall
    back to the XLA path)."""
    from xllm_service_tpu.ops.pallas import prefill_kernel_enabled
    return prefill_kernel_enabled() and window % page_size == 0


def _use_ragged_kernel() -> bool:
    """Trace-time gate for the ragged mixed-batch kernel: just the base
    Pallas gate — the ragged layout reads everything through the page
    table (write-then-attend), so there is no window/page alignment
    requirement and no separate env knob at this level (the engine's
    XLLM_RAGGED_ATTN gate decides whether ragged batches are built at
    all; off TPU the XLA gather reference serves them)."""
    from xllm_service_tpu.ops import pallas
    return pallas.enabled()


# Sentinel window for full-attention layers when windows ride the layer
# scan as traced per-layer values (Gemma-2 alternation): larger than any
# context, so the window mask is a no-op. Shared with the Pallas kernels
# (whose int32 window arithmetic bounds it at 2^30 — see ops/attention).
_FULL_WINDOW = FULL_WINDOW


def _scatter_topk(vals: jnp.ndarray, idx: jnp.ndarray,
                  num_classes: int) -> jnp.ndarray:
    """Scatter per-token top-k ``vals`` [.., k] at expert ids ``idx``
    [.., k] into a dense [.., E] map (k is tiny/static). The one shared
    idiom behind every router's dense weight map."""
    out = jnp.zeros(vals.shape[:-1] + (num_classes,), vals.dtype)
    for j in range(vals.shape[-1]):
        out = out + vals[..., j:j + 1] * jax.nn.one_hot(
            idx[..., j], num_classes, dtype=vals.dtype)
    return out


def _attn_extras(cfg: ModelConfig) -> Dict[str, Any]:
    """Per-model attention kwargs beyond the tensors: Gemma-2's logit
    soft-cap and query_pre_attn_scalar**-0.5 scale override."""
    out: Dict[str, Any] = {"logits_soft_cap": cfg.attn_logit_softcapping}
    if cfg.query_pre_attn_scalar is not None:
        out["scale"] = cfg.query_pre_attn_scalar ** -0.5
    return out


def _layer_windows(cfg: ModelConfig) -> Optional[jnp.ndarray]:
    """[L] int32 per-layer window xs when the model alternates
    local/global layers; None for uniform models (static window)."""
    if cfg.layer_sliding is None:
        return None
    return jnp.asarray(
        [cfg.sliding_window if s else _FULL_WINDOW
         for s in cfg.layer_sliding], jnp.int32)


def _layer_rope(cfg: ModelConfig) -> Optional[jnp.ndarray]:
    """[L, 2] (theta, linear factor) per layer when rope bases differ by
    layer type (Gemma-3): sliding layers use rope_local_base_freq
    unscaled; full layers use rope_theta with the linear factor."""
    if cfg.rope_local_base_freq is None:
        return None
    factor = (cfg.rope_scaling[1]
              if cfg.rope_scaling is not None
              and cfg.rope_scaling[0] == "linear" else 1.0)
    pattern = cfg.layer_sliding
    if pattern is None:
        # Uniform models: an all-sliding pattern collapses to
        # layer_sliding None + sliding_window set at config load — every
        # layer is then LOCAL; no window at all means every layer is
        # global.
        pattern = (cfg.sliding_window is not None,) * cfg.num_layers
    rows = [(cfg.rope_local_base_freq, 1.0) if s
            else (cfg.rope_theta, factor) for s in pattern]
    return jnp.asarray(rows, jnp.float32)


def _scale_embed(cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    """Gemma scales token embeddings by sqrt(hidden) (cast to the
    activation dtype first, as HF does)."""
    if not cfg.gemma:
        return x
    return x * jnp.asarray(math.sqrt(cfg.hidden_size), x.dtype)


def _head_logits(cfg: ModelConfig, x: jnp.ndarray,
                 head: jnp.ndarray) -> jnp.ndarray:
    """lm_head matmul in fp32, with Gemma-2's final tanh soft-cap."""
    logits = (x @ head).astype(jnp.float32)
    cap = cfg.final_logit_softcapping
    if cap > 0.0:
        logits = cap * jnp.tanh(logits / cap)
    return logits


def _qkv(lp: Dict[str, jnp.ndarray], cfg: ModelConfig, x: jnp.ndarray):
    """x: [B, T, D] → q [B, T, Hq, Dh], k/v [B, T, Hkv, Dh]."""
    B, T, _ = x.shape
    q = x @ lp["q_proj"]
    k = x @ lp["k_proj"]
    v = x @ lp["v_proj"]
    if "q_bias" in lp:
        q = q + lp["q_bias"]
        k = k + lp["k_bias"]
        v = v + lp["v_bias"]
    q = q.reshape(B, T, cfg.num_heads, cfg.head_dim)
    k = k.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(B, T, cfg.num_kv_heads, cfg.head_dim)
    if "q_norm" in lp:
        # Qwen3: per-head RMSNorm on q/k before rope.
        q = rms_norm(q, lp["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, lp["k_norm"], cfg.rms_norm_eps)
    return q, k, v


def _mlp(lp: Dict[str, jnp.ndarray], cfg: ModelConfig,
         x: jnp.ndarray, valid: Optional[jnp.ndarray] = None
         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """SwiGLU MLP; MoE routes each token through its top-k experts.
    ``valid`` [B, T] bool marks real tokens — padding / inactive lanes are
    kept out of sparse-MoE routing so they can't consume expert capacity
    (a real token's output must not depend on batch composition).

    Returns ``(out, moe_dropped)`` — the int32 count of (token, expert)
    assignments lost to capacity (always 0 for dense / oracle paths), so
    the serving layer can surface drop pressure instead of degrading
    silently."""
    zero = jnp.zeros((), jnp.int32)
    if cfg.gptoss:
        # GPT-OSS router: top-k over BIASED LOGITS, softmax over just
        # the selected k logits → dense weight map (sums to 1 on the
        # chosen experts); clamped-GLU experts with biases.
        logits = (x @ lp["router"]).astype(jnp.float32) + lp["router_bias"]
        k = cfg.num_experts_per_tok
        topv, topi = jax.lax.top_k(logits, k)
        weights = _scatter_topk(jax.nn.softmax(topv, axis=-1), topi,
                                logits.shape[-1])
        if cfg.moe_capacity_factor > 0:
            from xllm_service_tpu.parallel.expert import moe_mlp
            return moe_mlp(
                x, lp["router"], lp["gate_proj"], lp["up_proj"],
                lp["down_proj"], k, cfg.moe_capacity_factor,
                valid=valid, group_size=cfg.moe_group_size,
                norm_topk=False, gates=weights, expert_style="gptoss",
                gate_b=lp["gate_bias"], up_b=lp["up_bias"],
                down_b=lp["down_bias"])
        # Dense oracle: every expert on every token, weighted.
        hg = jnp.einsum("btd,edf->btef", x, lp["gate_proj"]) \
            + lp["gate_bias"][None, None]
        hu = jnp.einsum("btd,edf->btef", x, lp["up_proj"]) \
            + lp["up_bias"][None, None]
        hg = jnp.clip(hg, None, 7.0)
        hu = jnp.clip(hu, -7.0, 7.0)
        h = (hu + 1.0) * (hg * jax.nn.sigmoid(1.702 * hg))
        out = jnp.einsum("btef,efd->bted", h, lp["down_proj"]) \
            + lp["down_bias"][None, None]
        return jnp.einsum("bted,bte->btd", out,
                          weights.astype(x.dtype)), zero
    if not cfg.is_moe:
        gate = x @ lp["gate_proj"]
        # Gemma gates with tanh-GELU (gelu_pytorch_tanh); llama-family
        # with SiLU.
        act = jax.nn.gelu(gate, approximate=True) if cfg.gemma \
            else jax.nn.silu(gate)
        return (act * (x @ lp["up_proj"])) @ lp["down_proj"], zero
    if cfg.moe_capacity_factor > 0:
        # Sparse top-k dispatch into capacity buckets: per-token FLOPs are
        # k×(expert MLP), independent of E; GSPMD partitions the expert
        # axis over 'ep' from the weight shardings (parallel/expert.py).
        from xllm_service_tpu.parallel.expert import moe_mlp
        return moe_mlp(x, lp["router"], lp["gate_proj"], lp["up_proj"],
                       lp["down_proj"], cfg.num_experts_per_tok,
                       cfg.moe_capacity_factor, valid=valid,
                       group_size=cfg.moe_group_size,
                       norm_topk=cfg.norm_topk_prob)
    # Dense oracle (moe_capacity_factor == 0): every expert on every token,
    # mixed by routing weight — the test reference for the sparse path.
    gates = jax.nn.softmax((x @ lp["router"]).astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(gates, cfg.num_experts_per_tok)   # [B,T,K]
    if cfg.norm_topk_prob:
        topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
    weights = _scatter_topk(topv, topi, gates.shape[-1])         # [B,T,E]
    h = jax.nn.silu(jnp.einsum("btd,edf->btef", x, lp["gate_proj"])) \
        * jnp.einsum("btd,edf->btef", x, lp["up_proj"])
    out = jnp.einsum("btef,efd->bted", h, lp["down_proj"])
    return jnp.einsum("bted,bte->btd", out,
                      weights.astype(x.dtype)), zero


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------

def forward_prefill(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
                    start_pos: jnp.ndarray, lengths: jnp.ndarray,
                    kv: KVCache, page_table: jnp.ndarray,
                    return_all_logits: bool = False,
                    mm_embeds: Optional[jnp.ndarray] = None,
                    mm_positions: Optional[jnp.ndarray] = None,
                    prompt_lp_targets: Optional[jnp.ndarray] = None,
                    return_stats: bool = False,
                    rope_pos: Optional[jnp.ndarray] = None,
                    page_aligned_prefill: bool = True,
                    write_then_attend: bool = False,
                    ragged: bool = False,
                    ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray], KVCache]:
    """Prefill ``tokens`` [B, T] (padded; true new-token counts in
    ``lengths``; nonzero ``start_pos`` = prefix-cache hit, those tokens are
    already resident in the cache).

    ``return_stats`` (static) appends a stats dict (``moe_dropped``:
    int32 capacity-dropped assignments summed over layers) as the final
    element — the serving engine's drop accounting; default off keeps the
    3-tuple contract for existing callers.

    ``mm_embeds`` [B, M, D] + ``mm_positions`` [B, M] splice multimodal
    (vision-encoder) embeddings over the token embeddings at the given
    window-relative positions (EPD prefill stage; pad positions ≥ T are
    dropped).

    ``rope_pos`` [B, 3, T] — explicit 3-D rope positions for mrope
    models (Qwen2-VL: image tokens rotate by (t, h, w) grid ids,
    decoupled from KV storage positions). None → streams broadcast from
    the storage positions (pure-text requests; equals standard rope).

    MLA models (DeepSeek-V2) take a dedicated path over the latent
    pool (``_mla_forward_prefill``); multimodal splice is not defined
    for them.

    ``write_then_attend`` (static): the round-5 "known residue" fix —
    the pool rides the layer scan as a CARRY and each layer writes its
    fresh window into the pool FIRST (aliased Pallas writer = the
    pool's first consumer), then attention reads everything — cached
    prefix AND the current window — from the pool. Kills the jit-call-
    boundary pool copies XLA inserts when an opaque attention call
    reads a buffer the post-scan writer aliases (~10-15 GB per prefill
    call at the bench shape). Default off here; the engine turns it on
    per EngineConfig.write_then_attend.

    ``ragged`` (static): the batch is a RAGGED MIX — rows may be prefill
    windows (lengths > 1) or single decode continuations (lengths = 1,
    start_pos = context − 1), assembled by the engine's one-dispatch
    interleaved step (XLLM_RAGGED_ATTN). Requires ``write_then_attend``
    (every row's new K/V must land in the pool before attention) and
    ``page_aligned_prefill=False`` (decode rows start mid-page).
    Attention dispatches to the ragged Pallas kernel
    (ops/pallas/ragged_attention.py) when the base Pallas gate is on;
    otherwise the pool-gather XLA reference below already handles
    arbitrary (start, length) rows.

    Returns (last_logits [B, V] fp32, all_logits [B, T, V] fp32 or None,
    kv'). ``return_all_logits`` (static) gates the full-prompt lm_head: at
    serving shapes a [B, T, V] fp32 tensor is gigabytes of HBM and a T×
    larger matmul, so by default only the last valid hidden state per
    sequence hits the head — all_logits exists for prompt-logprob requests.
    """
    if cfg.mla:
        assert mm_embeds is None, "MLA models have no multimodal splice"
        assert not ragged, "MLA models have no ragged mixed-batch path"
        return _mla_forward_prefill(
            params, cfg, tokens, start_pos, lengths, kv, page_table,
            return_all_logits=return_all_logits,
            prompt_lp_targets=prompt_lp_targets,
            return_stats=return_stats,
            page_aligned_prefill=page_aligned_prefill,
            write_then_attend=write_then_attend)
    k_pages, v_pages = kv
    x = _scale_embed(cfg, params["embed"][tokens]
                     .astype(jnp.dtype(cfg.dtype)))              # [B, T, D]
    if mm_embeds is not None:
        x = jax.vmap(
            lambda xb, eb, pb: xb.at[pb].set(
                eb.astype(xb.dtype), mode="drop"))(
            x, mm_embeds, mm_positions)
    positions = start_pos[:, None] + jnp.arange(tokens.shape[1],
                                                dtype=jnp.int32)[None, :]
    kv_lengths = start_pos + lengths                             # [B]
    tok_valid = (jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
                 < lengths[:, None])                             # [B, T]
    extras = _attn_extras(cfg)
    win_arr = _layer_windows(cfg)
    rope_arr = _layer_rope(cfg)

    def layer(carry, xs):
        if write_then_attend:
            x, kp_c, vp_c = carry
        else:
            x = carry
            kp_c, vp_c = k_pages, v_pages
        ro = None
        if win_arr is not None and rope_arr is not None:
            lp, li, w_l, ro = xs
        elif win_arr is not None:
            lp, li, w_l = xs
        elif rope_arr is not None:
            lp, li, ro = xs
            w_l = cfg.sliding_window or 0
        else:
            lp, li = xs
            w_l = cfg.sliding_window or 0
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(lp, cfg, h)
        if ro is not None:
            q = apply_rope_dynamic(q, positions, ro[0], ro[1])
            k = apply_rope_dynamic(k, positions, ro[0], ro[1])
        else:
            q = rope_for(cfg.rope_scaling, q, positions, cfg.rope_theta,
                         positions3=rope_pos)
            k = rope_for(cfg.rope_scaling, k, positions, cfg.rope_theta,
                         positions3=rope_pos)
        B, T = tokens.shape
        if write_then_attend:
            # Write-then-attend: the window's fresh K/V lands in the
            # pool FIRST (the aliased writer is the pool's first
            # consumer inside the scan carry — no defensive copy), then
            # attention reads everything — cached prefix AND current
            # window — from the pool. No dual cached/fresh source, no
            # overlay.
            kp_c, vp_c = write_prefill_kv_layer(
                kp_c, vp_c, k, v, page_table, start_pos, lengths, li,
                page_aligned_starts=page_aligned_prefill)
            if ragged and _use_ragged_kernel():
                from xllm_service_tpu.ops.pallas import (
                    ragged_paged_attention_pallas)
                attn = ragged_paged_attention_pallas(
                    q, kp_c, vp_c, page_table, start_pos, lengths,
                    sliding_window=w_l, sinks=lp.get("sinks"),
                    logits_soft_cap=cfg.attn_logit_softcapping,
                    scale=extras.get("scale"), layer=li)
            elif _use_prefill_kernel(T, kp_c.shape[2]):
                from xllm_service_tpu.ops.pallas import (
                    paged_prefill_attention_pallas)
                attn = paged_prefill_attention_pallas(
                    q, None, None, kp_c, vp_c, page_table, start_pos,
                    lengths, sliding_window=w_l, sinks=lp.get("sinks"),
                    logits_soft_cap=cfg.attn_logit_softcapping,
                    scale=extras.get("scale"), layer=li, from_pool=True)
            else:
                kp = jax.lax.dynamic_index_in_dim(kp_c, li, axis=0,
                                                  keepdims=False)
                vp = jax.lax.dynamic_index_in_dim(vp_c, li, axis=0,
                                                  keepdims=False)
                # Pool already holds the window — gather, no overlay.
                attn = mha_prefill_auto(
                    q, gather_pages(kp, page_table),
                    gather_pages(vp, page_table), kv_lengths, start_pos,
                    sliding_window=w_l, sinks=lp.get("sinks"), **extras)
        elif _use_prefill_kernel(T, kp_c.shape[2]):
            # Attend against cache (prefix-cache hits) + this step's
            # fresh K/V; the pool itself is NOT written here: emitting
            # updated pools as scan ys would rewrite the whole pool per
            # call — the fresh rows come out as small ys instead and
            # land in one scatter after the scan. The gated Pallas
            # kernel streams pool pages + fresh blocks from the FULL 5D
            # pools (the traced layer index joins the page in its DMA
            # indices — a per-layer slice feeding a custom call is
            # MATERIALIZED, the round-5 conviction). The kernel
            # implements the full model-delta surface — windows (static
            # or traced per-layer), Gemma soft-cap and scale, GPT-OSS
            # sinks — so SWA families are no longer trace-time-bypassed
            # to the gather path (round-4 verdict).
            from xllm_service_tpu.ops.pallas import (
                paged_prefill_attention_pallas)
            attn = paged_prefill_attention_pallas(
                q, k, v, kp_c, vp_c, page_table, start_pos,
                lengths, sliding_window=w_l, sinks=lp.get("sinks"),
                logits_soft_cap=cfg.attn_logit_softcapping,
                scale=extras.get("scale"), layer=li)
        else:
            # The XLA reference slices locally (its gather fuses) then
            # overlays the not-yet-written fresh window.
            kp = jax.lax.dynamic_index_in_dim(kp_c, li, axis=0,
                                              keepdims=False)
            vp = jax.lax.dynamic_index_in_dim(vp_c, li, axis=0,
                                              keepdims=False)
            k_all = overlay_fresh_kv(gather_pages(kp, page_table), k,
                                     start_pos)
            v_all = overlay_fresh_kv(gather_pages(vp, page_table), v,
                                     start_pos)
            attn = mha_prefill_auto(q, k_all, v_all, kv_lengths, start_pos,
                                    sliding_window=w_l,
                                    sinks=lp.get("sinks"), **extras)
        a = attn.reshape(B, T, -1) @ lp["o_proj"]
        if "o_bias" in lp:
            a = a + lp["o_bias"]
        if cfg.gemma:
            # Gemma four-norm block: post-norms apply to the SUBLAYER
            # OUTPUT before the residual add.
            x = x + rms_norm(a, lp["post_norm"], cfg.rms_norm_eps)
            h = rms_norm(x, lp["pre_ff_norm"], cfg.rms_norm_eps)
            m, dropped = _mlp(lp, cfg, h, valid=tok_valid)
            x = x + rms_norm(m, lp["post_ff_norm"], cfg.rms_norm_eps)
        else:
            x = x + a
            h = rms_norm(x, lp["post_norm"], cfg.rms_norm_eps)
            m, dropped = _mlp(lp, cfg, h, valid=tok_valid)
            x = x + m
        if write_then_attend:
            return (x, kp_c, vp_c), dropped
        return x, (k, v, dropped)

    li_arr = jnp.arange(cfg.num_layers, dtype=jnp.int32)
    if win_arr is not None and rope_arr is not None:
        xs = (params["layers"], li_arr, win_arr, rope_arr)
    elif win_arr is not None:
        xs = (params["layers"], li_arr, win_arr)
    elif rope_arr is not None:
        xs = (params["layers"], li_arr, rope_arr)
    else:
        xs = (params["layers"], li_arr)
    if write_then_attend:
        (x, k_pages, v_pages), dropped_l = jax.lax.scan(
            layer, (x, k_pages, v_pages), xs, unroll=_layer_unroll())
    else:
        x, (k_new, v_new, dropped_l) = jax.lax.scan(
            layer, x, xs, unroll=_layer_unroll())
        k_pages, v_pages = write_prefill_kv_all_layers(
            k_pages, v_pages, k_new, v_new, page_table, start_pos,
            lengths, page_aligned_starts=page_aligned_prefill)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    last_idx = jnp.maximum(lengths - 1, 0)
    last_x = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    last_logits = _head_logits(cfg, last_x, head)                # [B, V]
    all_logits = _head_logits(cfg, x, head) if return_all_logits else None
    outs = [last_logits, all_logits, (k_pages, v_pages)]
    if prompt_lp_targets is not None:
        # 4th element ONLY on the echo+logprobs path: existing callers
        # (and the driver's entry contract) unpack three.
        outs.append(_prompt_logprobs(x, head, prompt_lp_targets,
                                     cap=cfg.final_logit_softcapping))
    if return_stats:
        outs.append({"moe_dropped": jnp.sum(dropped_l)})
    return tuple(outs)


def _prompt_logprobs(x: jnp.ndarray, head: jnp.ndarray,
                     targets: jnp.ndarray,
                     chunk: int = 128, cap: float = 0.0) -> jnp.ndarray:
    """logprob of ``targets[b, t]`` under the distribution predicted at
    position ``t`` — the completion API's ``echo`` + ``logprobs`` prompt
    scoring. Chunked over T so the [B, c, V] logits block (not the full
    [B, T, V]) is the peak intermediate."""
    B, T, D = x.shape
    c = math.gcd(T, min(chunk, T))
    xc = x.reshape(B, T // c, c, D).transpose(1, 0, 2, 3)     # [nc,B,c,D]
    tc = targets.reshape(B, T // c, c).transpose(1, 0, 2)     # [nc,B,c]

    def one(args):
        xb, tb = args                                  # [B, c, D], [B, c]
        logits = (xb @ head).astype(jnp.float32)       # [B, c, V]
        if cap > 0.0:
            logits = cap * jnp.tanh(logits / cap)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(
            logits, tb[..., None].astype(jnp.int32), axis=-1)[..., 0]
        return tgt - lse                               # [B, c]

    out = jax.lax.map(one, (xc, tc))                   # [nc, B, c]
    return out.transpose(1, 0, 2).reshape(B, T)


def forward_prefill_ring(params: Params, cfg: ModelConfig,
                         tokens: jnp.ndarray, lengths: jnp.ndarray,
                         kv: KVCache, page_table: jnp.ndarray, mesh,
                         axis_name: str = "sp",
                         return_stats: bool = False,
                         ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray],
                                    KVCache]:
    """Sequence-parallel long-context prefill: exact causal attention with
    the sequence axis sharded over the mesh's ``sp`` axis via ring attention
    (parallel/ring.py — KV blocks rotate over ``ppermute``, flash-style
    accumulator, O(T/sp) attention memory per device).

    Restrictions vs ``forward_prefill`` (the engine falls back to chunked
    windows otherwise): no cached prefix (start_pos == 0 — the sequence is
    entirely fresh), no multimodal splice, and T must divide by the sp size.
    The serving engine dispatches here when a prompt exceeds the largest
    single-chip bucket and the whole prompt fits one ring window
    (runtime/engine.py _run_prefill; round-1 left ring attention
    unintegrated, VERDICT.md weak #3).
    """
    from xllm_service_tpu.parallel.mesh import AXIS_TP
    from xllm_service_tpu.parallel.ring import ring_attention_sharded

    if cfg.sliding_window or cfg.gemma or cfg.mla or cfg.gptoss:
        # Ring rotation assumes full causal reach and the plain llama
        # layer body; SWA/Gemma/MLA/GPT-OSS long prompts take the
        # chunked-window path (whose flash fold skips out-of-window
        # chunks, so the work is O(T·W) there anyway).
        raise NotImplementedError(
            "ring prefill implements neither sliding-window masks, the "
            "gemma layer body, latent attention, nor attention sinks")

    k_pages, v_pages = kv
    B, T = tokens.shape
    x = params["embed"][tokens].astype(jnp.dtype(cfg.dtype))     # [B, T, D]
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None],
                                 (B, T))

    # Heads shard over tp only when BOTH head counts divide it (the GQA
    # head grouping inside the ring block must stay aligned); otherwise
    # heads are replicated inside the shard_map island, mirroring
    # kv_cache_pspec's replication rule.
    tp = mesh.shape.get(AXIS_TP, 1)
    head_axis = (AXIS_TP if tp > 1 and cfg.num_heads % tp == 0
                 and cfg.num_kv_heads % tp == 0 else None)
    _ring = ring_attention_sharded(mesh, axis_name, head_axis)

    tok_valid = (jnp.arange(T, dtype=jnp.int32)[None, :]
                 < lengths[:, None])                             # [B, T]

    def layer(x, lp):
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(lp, cfg, h)
        q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
        attn = _ring(q, k, v, lengths)
        x = x + attn.reshape(B, T, -1) @ lp["o_proj"]
        h = rms_norm(x, lp["post_norm"], cfg.rms_norm_eps)
        m, dropped = _mlp(lp, cfg, h, valid=tok_valid)
        x = x + m
        return x, (k, v, dropped)

    x, (k_new, v_new, dropped_l) = jax.lax.scan(layer, x, params["layers"])
    k_pages, v_pages = write_prefill_kv_all_layers(
        k_pages, v_pages, k_new, v_new, page_table,
        jnp.zeros((B,), jnp.int32), lengths)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    last_idx = jnp.maximum(lengths - 1, 0)
    last_x = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    last_logits = (last_x @ head).astype(jnp.float32)
    if return_stats:
        return last_logits, None, (k_pages, v_pages), \
            {"moe_dropped": jnp.sum(dropped_l)}
    return last_logits, None, (k_pages, v_pages)


# ---------------------------------------------------------------------------
# Embeddings (net-new capability: the reference's /v1/embeddings returns
# "not support", http_service/service.cpp:492)
# ---------------------------------------------------------------------------

def forward_embedding(params: Params, cfg: ModelConfig,
                      tokens: jnp.ndarray, lengths: jnp.ndarray
                      ) -> jnp.ndarray:
    """Sequence embeddings: causal forward (no KV cache), masked mean-pool
    of the final hidden states, L2-normalized. tokens [B, T] padded,
    lengths [B] → [B, hidden] float32."""
    if cfg.mla:
        raise NotImplementedError(
            "/v1/embeddings is not implemented for MLA models")
    B, T = tokens.shape
    x = _scale_embed(cfg, params["embed"][tokens]
                     .astype(jnp.dtype(cfg.dtype)))
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None], (B, T))

    tok_valid = (jnp.arange(T, dtype=jnp.int32)[None, :]
                 < lengths[:, None])                             # [B, T]
    extras = _attn_extras(cfg)
    win_arr = _layer_windows(cfg)
    rope_arr = _layer_rope(cfg)

    def layer(x, xs):
        ro = None
        if win_arr is not None and rope_arr is not None:
            lp, w_l, ro = xs
        elif win_arr is not None:
            lp, w_l = xs
        elif rope_arr is not None:
            lp, ro = xs
            w_l = cfg.sliding_window or 0
        else:
            lp = xs
            w_l = cfg.sliding_window or 0
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(lp, cfg, h)
        if ro is not None:
            q = apply_rope_dynamic(q, positions, ro[0], ro[1])
            k = apply_rope_dynamic(k, positions, ro[0], ro[1])
        else:
            q = rope_for(cfg.rope_scaling, q, positions, cfg.rope_theta)
            k = rope_for(cfg.rope_scaling, k, positions, cfg.rope_theta)
        attn = mha_prefill(q, k, v, lengths,
                           jnp.zeros((B,), jnp.int32),
                           sliding_window=w_l,
                           sinks=lp.get("sinks"), **extras)
        a = attn.reshape(B, T, -1) @ lp["o_proj"]
        if "o_bias" in lp:
            a = a + lp["o_bias"]
        if cfg.gemma:
            x = x + rms_norm(a, lp["post_norm"], cfg.rms_norm_eps)
            h = rms_norm(x, lp["pre_ff_norm"], cfg.rms_norm_eps)
            x = x + rms_norm(_mlp(lp, cfg, h, valid=tok_valid)[0],
                             lp["post_ff_norm"], cfg.rms_norm_eps)
        else:
            x = x + a
            h = rms_norm(x, lp["post_norm"], cfg.rms_norm_eps)
            x = x + _mlp(lp, cfg, h, valid=tok_valid)[0]
        return x, None

    if win_arr is not None and rope_arr is not None:
        xs = (params["layers"], win_arr, rope_arr)
    elif win_arr is not None:
        xs = (params["layers"], win_arr)
    elif rope_arr is not None:
        xs = (params["layers"], rope_arr)
    else:
        xs = params["layers"]
    x, _ = jax.lax.scan(layer, x, xs, unroll=_layer_unroll())
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps).astype(
        jnp.float32)
    mask = (jnp.arange(T, dtype=jnp.int32)[None] <
            lengths[:, None]).astype(jnp.float32)
    pooled = jnp.sum(x * mask[..., None], axis=1) / \
        jnp.maximum(jnp.sum(mask, axis=1, keepdims=True), 1.0)
    return pooled / jnp.maximum(
        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12)


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------

def forward_decode(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
                   positions: jnp.ndarray, active: jnp.ndarray,
                   kv: KVCache, page_table: jnp.ndarray,
                   return_stats: bool = False,
                   rope_delta: Optional[jnp.ndarray] = None,
                   write_then_attend: bool = False,
                   ) -> Tuple[jnp.ndarray, KVCache]:
    """One decode step for ``tokens`` [B] at ``positions`` [B]
    (``active`` [B] bool masks empty batch slots). Returns
    (logits [B, V] fp32, kv'); with ``return_stats`` (static) a trailing
    stats dict (``moe_dropped``) is appended.

    ``rope_delta`` [B] — mrope models only: per-sequence offset between
    the rope position of a generated token and its KV storage position
    (images compress T·H·W patch tokens into a max(t,h,w)-sized rope
    span, so post-image rope positions trail storage positions).

    ``write_then_attend`` (static): the pool rides the layer scan as a
    carry; each layer writes the current token's K/V in place (aliased
    Pallas writer) BEFORE attending, and attention reads the pool alone
    — the ``k_cur``/``v_cur`` plumbing disappears, and so do the
    jit-call-boundary pool copies around the post-scan scatter."""
    if cfg.mla:
        return _mla_forward_decode(params, cfg, tokens, positions,
                                   active, kv, page_table,
                                   return_stats=return_stats,
                                   write_then_attend=write_then_attend)
    k_pages, v_pages = kv
    x = _scale_embed(cfg, params["embed"][tokens[:, None]]
                     .astype(jnp.dtype(cfg.dtype)))              # [B,1,D]
    cache_lens = jnp.where(active, positions, 0)   # tokens already written
    extras = _attn_extras(cfg)
    win_arr = _layer_windows(cfg)
    rope_arr = _layer_rope(cfg)

    # The attention dispatch gets the FULL 5D pools + a traced layer
    # scalar: on the Pallas path the kernel's page DMAs index
    # [L, P, ps, Hkv, D] directly (round-5: a per-layer pool slice
    # feeding a custom call is MATERIALIZED — 134 MB x 2 pools x layers
    # per step); the XLA gather fallback slices per layer, which fuses.
    def layer(carry, xs):
        if write_then_attend:
            x, kp_c, vp_c = carry
        else:
            x = carry
            kp_c, vp_c = k_pages, v_pages
        ro = None
        if win_arr is not None and rope_arr is not None:
            lp, li, w_l, ro = xs
        elif win_arr is not None:
            lp, li, w_l = xs
        elif rope_arr is not None:
            lp, li, ro = xs
            w_l = cfg.sliding_window or 0
        else:
            lp, li = xs
            w_l = cfg.sliding_window or 0
        h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(lp, cfg, h)                               # [B,1,H,Dh]
        pos2 = positions[:, None]
        if ro is not None:
            q = apply_rope_dynamic(q, pos2, ro[0], ro[1])
            k = apply_rope_dynamic(k, pos2, ro[0], ro[1])
        else:
            rp3 = None
            if rope_delta is not None:
                rp3 = jnp.broadcast_to(
                    (positions + rope_delta)[:, None, None],
                    (positions.shape[0], 3, 1))
            q = rope_for(cfg.rope_scaling, q, pos2, cfg.rope_theta,
                         positions3=rp3)
            k = rope_for(cfg.rope_scaling, k, pos2, cfg.rope_theta,
                         positions3=rp3)
        if write_then_attend:
            # Write-then-attend: the current token's K/V goes into the
            # pool FIRST (per-layer aliased write; the writer is the
            # carried pool's first consumer), then attention reads the
            # pool alone with context INCLUDING the current token — no
            # k_cur/v_cur plumbing.
            kp_c, vp_c = write_decode_kv_layer(
                kp_c, vp_c, k[:, 0], v[:, 0], page_table, positions,
                active, li)
            attn = paged_decode_attention_auto(
                q[:, 0], kp_c, vp_c, page_table,
                jnp.where(active, positions + 1, 0),
                sliding_window=w_l, sinks=lp.get("sinks"),
                layer=li, **extras)                              # [B,Hq,Dh]
        else:
            # The current token's K/V stays in-registers for attention;
            # the pool write happens once for all layers after the scan
            # (carrying the pool as scan ys would rewrite the whole pool
            # per step).
            attn = paged_decode_attention_current_auto(
                q[:, 0], kp_c, vp_c, page_table, cache_lens,
                k[:, 0], v[:, 0],
                sliding_window=w_l, sinks=lp.get("sinks"),
                layer=li, **extras)                              # [B,Hq,Dh]
        B = tokens.shape[0]
        a = attn.reshape(B, 1, -1) @ lp["o_proj"]
        if "o_bias" in lp:
            a = a + lp["o_bias"]
        if cfg.gemma:
            x = x + rms_norm(a, lp["post_norm"], cfg.rms_norm_eps)
            h = rms_norm(x, lp["pre_ff_norm"], cfg.rms_norm_eps)
            m, dropped = _mlp(lp, cfg, h, valid=active[:, None])
            x = x + rms_norm(m, lp["post_ff_norm"], cfg.rms_norm_eps)
        else:
            x = x + a
            h = rms_norm(x, lp["post_norm"], cfg.rms_norm_eps)
            m, dropped = _mlp(lp, cfg, h, valid=active[:, None])
            x = x + m
        if write_then_attend:
            return (x, kp_c, vp_c), dropped
        return x, (k[:, 0], v[:, 0], dropped)

    li_arr = jnp.arange(cfg.num_layers, dtype=jnp.int32)
    if win_arr is not None and rope_arr is not None:
        xs = (params["layers"], li_arr, win_arr, rope_arr)
    elif win_arr is not None:
        xs = (params["layers"], li_arr, win_arr)
    elif rope_arr is not None:
        xs = (params["layers"], li_arr, rope_arr)
    else:
        xs = (params["layers"], li_arr)
    if write_then_attend:
        (x, k_pages, v_pages), dropped_l = jax.lax.scan(
            layer, (x, k_pages, v_pages), xs, unroll=_layer_unroll())
    else:
        x, (k_new, v_new, dropped_l) = jax.lax.scan(
            layer, x, xs, unroll=_layer_unroll())
        k_pages, v_pages = write_decode_kv_all_layers(
            k_pages, v_pages, k_new, v_new, page_table, positions, active)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    logits = _head_logits(cfg, x[:, 0], head)                    # [B, V]
    if return_stats:
        return logits, (k_pages, v_pages), \
            {"moe_dropped": jnp.sum(dropped_l)}
    return logits, (k_pages, v_pages)


# ---------------------------------------------------------------------------
# DeepSeek-V2 multi-head latent attention (MLA)
#
# The cache stores one LATENT row per token — [kv_lora_rank (post
# kv_a_layernorm) ‖ rotated k_pe] — in the standard paged pool with a
# single KV "head" (cfg.kv_cache_{heads,dim}), so every page-table,
# migration, and trimming mechanism applies unchanged. The kv_b
# up-projections are ABSORBED: scores = (W_bk^T q_nope)·c + q_pe·k_pe and
# out_h = W_bv (Σ p·c), which is exactly HF's per-head math by
# associativity but reads r+rope bytes per token instead of
# Hq·(qk_head+v_head). DeepSeek's rope sub-head uses the adjacent-pair
# (complex) rotation — ops/rope.apply_rope_interleaved.
# (HF oracle: transformers deepseek_v2 — DeepseekV2Attention,
# DeepseekV2MoEGate greedy/group_limited_greedy, shared experts.)
# ---------------------------------------------------------------------------

def _init_mla_params(cfg: ModelConfig, key: jax.Array,
                     dtype: Optional[jnp.dtype]) -> Params:
    dtype = dtype or jnp.dtype(cfg.dtype)
    D, Hq = cfg.hidden_size, cfg.num_heads
    r, rope = cfg.kv_lora_rank, cfg.qk_rope_head_dim
    nope, vd = cfg.qk_nope_head_dim, cfg.v_head_dim
    keys = iter(jax.random.split(key, 64))

    def w(shape, fan_in):
        return (jax.random.normal(next(keys), shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(dtype)

    def attn_block(L):
        blk = {
            "input_norm": jnp.ones((L, D), dtype),
            "kv_a": w((L, D, r + rope), D),
            "kv_a_norm": jnp.ones((L, r), dtype),
            "kv_b_k": w((L, Hq, nope, r), r),
            "kv_b_v": w((L, Hq, vd, r), r),
            "o_proj": w((L, Hq * vd, D), Hq * vd),
            "post_norm": jnp.ones((L, D), dtype),
        }
        if cfg.q_lora_rank:
            blk["q_a"] = w((L, D, cfg.q_lora_rank), D)
            blk["q_a_norm"] = jnp.ones((L, cfg.q_lora_rank), dtype)
            blk["q_b"] = w((L, cfg.q_lora_rank, Hq * cfg.qk_head_dim),
                           cfg.q_lora_rank)
        else:
            blk["q_proj"] = w((L, D, Hq * cfg.qk_head_dim), D)
        return blk

    k_dense = cfg.first_k_dense_replace if cfg.is_moe else cfg.num_layers
    n_moe = cfg.num_layers - k_dense
    dense = attn_block(k_dense)
    dense["gate_proj"] = w((k_dense, D, cfg.intermediate_size), D)
    dense["up_proj"] = w((k_dense, D, cfg.intermediate_size), D)
    dense["down_proj"] = w((k_dense, cfg.intermediate_size, D),
                           cfg.intermediate_size)
    params: Params = {
        "embed": w((cfg.vocab_size, D), D),
        "layers": dense,
        "final_norm": jnp.ones((D,), dtype),
    }
    if n_moe:
        Fe = cfg.moe_intermediate_size or cfg.intermediate_size
        E = cfg.num_experts
        moe = attn_block(n_moe)
        moe["router"] = w((n_moe, D, E), D)
        if cfg.moe_scoring == "sigmoid":
            moe["router_bias"] = jnp.zeros((n_moe, E), jnp.float32)
        moe["gate_proj"] = w((n_moe, E, D, Fe), D)
        moe["up_proj"] = w((n_moe, E, D, Fe), D)
        moe["down_proj"] = w((n_moe, E, Fe, D), Fe)
        if cfg.n_shared_experts:
            Fs = Fe * cfg.n_shared_experts
            moe["shared_gate"] = w((n_moe, D, Fs), D)
            moe["shared_up"] = w((n_moe, D, Fs), D)
            moe["shared_down"] = w((n_moe, Fs, D), Fs)
        params["layers_moe"] = moe
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w((D, cfg.vocab_size), D)
    return params


def _deepseek_gate(cfg: ModelConfig, x: jnp.ndarray,
                   router_w: jnp.ndarray,
                   bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Routing scores AFTER DeepSeek's selection rules, as a dense [.., E]
    weight map.

    V2 (softmax scoring): softmax over fp32 logits; group-limited
    routing zeroes every expert outside the top ``topk_group`` of
    ``n_group`` groups (group score = max member score); top-k selected
    weights scale by routed_scaling_factor, no normalization.

    V3 (sigmoid scoring): sigmoid scores; SELECTION uses scores + the
    learned per-expert ``e_score_correction_bias`` with top-2-SUM group
    scores, but the combine WEIGHTS are the raw sigmoid scores of the
    chosen experts, optionally normalized (norm_topk_prob), then scaled.
    (HF DeepseekV2MoEGate / DeepseekV3TopkRouter.)"""
    logits = (x @ router_w).astype(jnp.float32)
    E = logits.shape[-1]
    sigmoid = cfg.moe_scoring == "sigmoid"
    scores = jax.nn.sigmoid(logits) if sigmoid \
        else jax.nn.softmax(logits, axis=-1)
    choice = scores + bias if (sigmoid and bias is not None) else scores
    if cfg.topk_method == "group_limited_greedy":
        G = cfg.n_group
        grouped = choice.reshape(*choice.shape[:-1], G, E // G)
        if sigmoid:
            g2, _ = jax.lax.top_k(grouped, 2)
            gs = jnp.sum(g2, axis=-1)                        # top-2 sum
        else:
            gs = grouped.max(axis=-1)
        _, gidx = jax.lax.top_k(gs, cfg.topk_group)          # [.., tg]
        gmask = jnp.sum(jax.nn.one_hot(gidx, G, dtype=choice.dtype),
                        axis=-2)                             # [.., G]
        choice = jnp.where(jnp.repeat(gmask, E // G, axis=-1) > 0,
                           choice, 0.0)
    _, topi = jax.lax.top_k(choice, cfg.num_experts_per_tok)
    sel = _scatter_topk(
        jnp.ones(topi.shape, scores.dtype), topi, E)
    # V3 combines with the RAW sigmoid scores (bias shapes choice only);
    # V2 combines with the masked selection values themselves.
    weights = (scores if sigmoid else choice) * sel
    if sigmoid and cfg.norm_topk_prob:
        weights = weights / (jnp.sum(weights, axis=-1, keepdims=True)
                             + 1e-20)
    return weights * cfg.routed_scaling_factor


def _mla_moe_mlp(cfg: ModelConfig, lp: Dict[str, jnp.ndarray],
                 x: jnp.ndarray,
                 valid: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Routed experts + the always-on shared experts. The DeepSeek gate
    (group limits, scaling, no normalization) produces a dense weight
    map; with a capacity factor the map feeds the group-chunked sparse
    dispatch (top-k FLOPs, ep-shardable) — only cf == 0 runs the dense
    every-expert oracle (the test reference)."""
    weights = _deepseek_gate(cfg, x, lp["router"],
                             lp.get("router_bias"))          # [B, T, E]
    if cfg.moe_capacity_factor > 0:
        from xllm_service_tpu.parallel.expert import moe_mlp
        routed, _ = moe_mlp(
            x, lp["router"], lp["gate_proj"], lp["up_proj"],
            lp["down_proj"], cfg.num_experts_per_tok,
            cfg.moe_capacity_factor, valid=valid,
            group_size=cfg.moe_group_size, norm_topk=False,
            gates=weights)
    else:
        h = jax.nn.silu(jnp.einsum("btd,edf->btef", x, lp["gate_proj"])) \
            * jnp.einsum("btd,edf->btef", x, lp["up_proj"])
        out = jnp.einsum("btef,efd->bted", h, lp["down_proj"])
        routed = jnp.einsum("bted,bte->btd", out, weights.astype(x.dtype))
    shared = (jax.nn.silu(x @ lp["shared_gate"]) * (x @ lp["shared_up"])) \
        @ lp["shared_down"] if "shared_gate" in lp else 0.0
    return routed + shared


def _mla_qkv(cfg: ModelConfig, lp, h, positions):
    """Absorbed-query and latent-row computation for one layer.

    Returns (q_tilde [B, T, Hq, r+rope], latent [B, T, 1, r+rope]):
    q_tilde = [W_bk^T q_nope ‖ rope(q_pe)], latent = [c_hat ‖ rope(k_pe)].
    """
    from xllm_service_tpu.ops.rope import (apply_rope,
                                           apply_rope_interleaved)

    rope_fn = apply_rope_interleaved if cfg.rope_interleave else apply_rope
    B, T, _ = h.shape
    Hq = cfg.num_heads
    r, rope = cfg.kv_lora_rank, cfg.qk_rope_head_dim
    nope = cfg.qk_nope_head_dim
    if cfg.q_lora_rank:
        q = rms_norm(h @ lp["q_a"], lp["q_a_norm"], cfg.rms_norm_eps) \
            @ lp["q_b"]
    else:
        q = h @ lp["q_proj"]
    q = q.reshape(B, T, Hq, cfg.qk_head_dim)
    q_nope, q_pe = q[..., :nope], q[..., nope:]
    q_pe = rope_fn(q_pe, positions, cfg.rope_theta, cfg.rope_scaling)
    # Absorb the key up-projection into the query side.
    q_eff = jnp.einsum("bthn,hnr->bthr", q_nope, lp["kv_b_k"])
    q_tilde = jnp.concatenate([q_eff, q_pe], axis=-1)        # [B,T,Hq,r+rope]

    ckv = h @ lp["kv_a"]                                     # [B,T,r+rope]
    c_hat = rms_norm(ckv[..., :r], lp["kv_a_norm"], cfg.rms_norm_eps)
    k_pe = rope_fn(ckv[..., r:], positions, cfg.rope_theta,
                   cfg.rope_scaling)
    latent = jnp.concatenate([c_hat, k_pe], axis=-1)[:, :, None, :]
    return q_tilde, latent


def _mla_out(cfg: ModelConfig, lp, attn: jnp.ndarray) -> jnp.ndarray:
    """attn [..., Hq, r+rope] → absorbed value up-projection → o_proj."""
    o_lat = attn[..., :cfg.kv_lora_rank]                     # [...,Hq,r]
    o = jnp.einsum("...hr,hvr->...hv", o_lat, lp["kv_b_v"])
    return o.reshape(*o.shape[:-2], -1) @ lp["o_proj"]


def _mla_scale(cfg: ModelConfig) -> float:
    scale = cfg.qk_head_dim ** -0.5
    rs = cfg.rope_scaling
    if cfg.mla_yarn_mscale and rs is not None and rs[0] == "yarn":
        # DeepSeek folds yarn's mscale into the softmax scale (squared
        # — query and key sides), on top of the rope module's cos/sin
        # attention factor, whenever the checkpoint ships a nonzero
        # mscale_all_dim (real V2 and V3 both do; HF's in-tree V2 port
        # omits the factor — config.py keys the flag on the checkpoint).
        factor, msa = rs[1], rs[7] if len(rs) > 7 else 0.0
        if msa and factor > 1.0:
            m = 0.1 * msa * math.log(factor) + 1.0
            scale = scale * m * m
    return scale


def _mla_forward_prefill(params: Params, cfg: ModelConfig,
                         tokens: jnp.ndarray, start_pos: jnp.ndarray,
                         lengths: jnp.ndarray, kv: KVCache,
                         page_table: jnp.ndarray,
                         return_all_logits: bool = False,
                         prompt_lp_targets: Optional[jnp.ndarray] = None,
                         return_stats: bool = False,
                         page_aligned_prefill: bool = True,
                         write_then_attend: bool = False):
    k_pages, v_pages = kv
    L_dense = params["layers"]["input_norm"].shape[0]
    x = params["embed"][tokens].astype(jnp.dtype(cfg.dtype))
    positions = start_pos[:, None] + jnp.arange(tokens.shape[1],
                                                dtype=jnp.int32)[None, :]
    kv_lengths = start_pos + lengths
    B, T = tokens.shape
    tok_valid = (jnp.arange(T, dtype=jnp.int32)[None, :]
                 < lengths[:, None])                             # [B, T]

    def body(moe: bool):
        def layer(carry, xs):
            if write_then_attend:
                x, kp_full, vp_full = carry
                lp, li = xs
                h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
                q_t, latent = _mla_qkv(cfg, lp, h, positions)
                # Write the latent window first (both pools carry the
                # same latent row — the engine's uniform (k, v)
                # plumbing), then attend from the pool: no overlay.
                kp_full, vp_full = write_prefill_kv_layer(
                    kp_full, vp_full, latent, latent,
                    page_table, start_pos, lengths, li,
                    page_aligned_starts=page_aligned_prefill)
                kp = jax.lax.dynamic_index_in_dim(kp_full, li, axis=0,
                                                  keepdims=False)
                lat_all = gather_pages(kp, page_table)
                attn = mha_prefill_auto(q_t, lat_all, lat_all,
                                        kv_lengths, start_pos,
                                        scale=_mla_scale(cfg))
            else:
                x, = carry
                lp, kp, vp = xs
                h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
                q_t, latent = _mla_qkv(cfg, lp, h, positions)
                lat_all = overlay_fresh_kv(gather_pages(kp, page_table),
                                           latent, start_pos)
                attn = mha_prefill_auto(q_t, lat_all, lat_all, kv_lengths,
                                        start_pos, scale=_mla_scale(cfg))
            x = x + _mla_out(cfg, lp, attn)
            h = rms_norm(x, lp["post_norm"], cfg.rms_norm_eps)
            if moe:
                x = x + _mla_moe_mlp(cfg, lp, h, valid=tok_valid)
            else:
                x = x + (jax.nn.silu(h @ lp["gate_proj"])
                         * (h @ lp["up_proj"])) @ lp["down_proj"]
            if write_then_attend:
                return (x, kp_full, vp_full), None
            return (x,), (latent, latent)
        return layer

    if write_then_attend:
        li_d = jnp.arange(L_dense, dtype=jnp.int32)
        (x, k_pages, v_pages), _ = jax.lax.scan(
            body(False), (x, k_pages, v_pages), (params["layers"], li_d))
        if "layers_moe" in params:
            n_moe = params["layers_moe"]["input_norm"].shape[0]
            li_m = L_dense + jnp.arange(n_moe, dtype=jnp.int32)
            (x, k_pages, v_pages), _ = jax.lax.scan(
                body(True), (x, k_pages, v_pages),
                (params["layers_moe"], li_m))
    else:
        (x,), (k_d, v_d) = jax.lax.scan(
            body(False), (x,),
            (params["layers"], k_pages[:L_dense], v_pages[:L_dense]))
        if "layers_moe" in params:
            (x,), (k_m, v_m) = jax.lax.scan(
                body(True), (x,),
                (params["layers_moe"], k_pages[L_dense:],
                 v_pages[L_dense:]))
            k_new = jnp.concatenate([k_d, k_m], axis=0)
            v_new = jnp.concatenate([v_d, v_m], axis=0)
        else:
            k_new, v_new = k_d, v_d
        k_pages, v_pages = write_prefill_kv_all_layers(
            k_pages, v_pages, k_new, v_new, page_table, start_pos,
            lengths, page_aligned_starts=page_aligned_prefill)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    last_idx = jnp.maximum(lengths - 1, 0)
    last_x = jnp.take_along_axis(x, last_idx[:, None, None], axis=1)[:, 0]
    outs = [_head_logits(cfg, last_x, head),
            _head_logits(cfg, x, head) if return_all_logits else None,
            (k_pages, v_pages)]
    if prompt_lp_targets is not None:
        outs.append(_prompt_logprobs(x, head, prompt_lp_targets))
    if return_stats:
        outs.append({"moe_dropped": jnp.zeros((), jnp.int32)})
    return tuple(outs)


def _mla_forward_decode(params: Params, cfg: ModelConfig,
                        tokens: jnp.ndarray, positions: jnp.ndarray,
                        active: jnp.ndarray, kv: KVCache,
                        page_table: jnp.ndarray,
                        return_stats: bool = False,
                        write_then_attend: bool = False):
    k_pages, v_pages = kv
    L_dense = params["layers"]["input_norm"].shape[0]
    x = params["embed"][tokens[:, None]].astype(jnp.dtype(cfg.dtype))
    cache_lens = jnp.where(active, positions, 0)
    B = tokens.shape[0]

    def body(moe: bool):
        def layer(carry, xs):
            from xllm_service_tpu.ops import pallas as _pallas
            if write_then_attend:
                x, kp_full, vp_full = carry
                lp, li = xs
                h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
                q_t, latent = _mla_qkv(cfg, lp, h, positions[:, None])
                # Latent row into the pool first (aliased write), then
                # attend from the pool with context INCLUDING the
                # current token — no k_cur/v_cur plumbing. MLA keeps
                # its own kernel opt-in (XLLM_PALLAS_MLA): the absorbed
                # block shape (Hkv=1, D=576) routes to the XLA gather
                # reference otherwise.
                kp_full, vp_full = write_decode_kv_layer(
                    kp_full, vp_full, latent[:, 0], latent[:, 0],
                    page_table, positions, active, li)
                ctx = jnp.where(active, positions + 1, 0)
                if _pallas.mla_kernel_enabled():
                    attn = _pallas.paged_decode_attention_pallas(
                        q_t[:, 0], kp_full, kp_full, page_table, ctx,
                        k_cur=None, v_cur=None, scale=_mla_scale(cfg),
                        layer=li)
                else:
                    kp = jax.lax.dynamic_index_in_dim(
                        kp_full, li, axis=0, keepdims=False)
                    attn = paged_decode_attention(
                        q_t[:, 0], kp, kp, page_table, ctx,
                        scale=_mla_scale(cfg))
            else:
                x, = carry
                lp, kp, vp = xs
                h = rms_norm(x, lp["input_norm"], cfg.rms_norm_eps)
                q_t, latent = _mla_qkv(cfg, lp, h, positions[:, None])
                # Both "k" and "v" reads come from the SAME latent pool
                # (kp twice — XLA CSEs the duplicate gather into one HBM
                # read); the duplicate v_pages pool is write-only under
                # MLA, a known 2x-storage cost of keeping the engine's
                # uniform (k, v) pool plumbing (single-pool layout is a
                # follow-up). The XLA reference path is the DEFAULT here
                # even with XLLM_PALLAS on: the absorbed-MLA block shape
                # (Hkv=1, D=r+rope=576 — not 128-lane-aligned) has never
                # been Mosaic-validated; XLLM_PALLAS_MLA=1 opts into the
                # kernel once tools/kernel_compile_probes.py clears it
                # on hardware.
                if _pallas.mla_kernel_enabled():
                    attn = paged_decode_attention_current_auto(
                        q_t[:, 0], kp, kp, page_table, cache_lens,
                        latent[:, 0], latent[:, 0], scale=_mla_scale(cfg))
                else:
                    attn = paged_decode_attention_current(
                        q_t[:, 0], kp, kp, page_table, cache_lens,
                        latent[:, 0], latent[:, 0], scale=_mla_scale(cfg))
            x = x + _mla_out(cfg, lp, attn)[:, None, :]
            h = rms_norm(x, lp["post_norm"], cfg.rms_norm_eps)
            if moe:
                x = x + _mla_moe_mlp(cfg, lp, h, valid=active[:, None])
            else:
                x = x + (jax.nn.silu(h @ lp["gate_proj"])
                         * (h @ lp["up_proj"])) @ lp["down_proj"]
            if write_then_attend:
                return (x, kp_full, vp_full), None
            return (x,), (latent[:, 0], latent[:, 0])
        return layer

    if write_then_attend:
        li_d = jnp.arange(L_dense, dtype=jnp.int32)
        (x, k_pages, v_pages), _ = jax.lax.scan(
            body(False), (x, k_pages, v_pages), (params["layers"], li_d))
        if "layers_moe" in params:
            n_moe = params["layers_moe"]["input_norm"].shape[0]
            li_m = L_dense + jnp.arange(n_moe, dtype=jnp.int32)
            (x, k_pages, v_pages), _ = jax.lax.scan(
                body(True), (x, k_pages, v_pages),
                (params["layers_moe"], li_m))
    else:
        (x,), (k_d, v_d) = jax.lax.scan(
            body(False), (x,),
            (params["layers"], k_pages[:L_dense], v_pages[:L_dense]))
        if "layers_moe" in params:
            (x,), (k_m, v_m) = jax.lax.scan(
                body(True), (x,),
                (params["layers_moe"], k_pages[L_dense:],
                 v_pages[L_dense:]))
            k_new = jnp.concatenate([k_d, k_m], axis=0)
            v_new = jnp.concatenate([v_d, v_m], axis=0)
        else:
            k_new, v_new = k_d, v_d
        k_pages, v_pages = write_decode_kv_all_layers(
            k_pages, v_pages, k_new, v_new, page_table, positions, active)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    logits = _head_logits(cfg, x[:, 0], head)
    if return_stats:
        return logits, (k_pages, v_pages), \
            {"moe_dropped": jnp.zeros((), jnp.int32)}
    return logits, (k_pages, v_pages)
