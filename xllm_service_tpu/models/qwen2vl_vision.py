"""Qwen2-VL vision tower — the real encoder for the EPD multimodal
pipeline, faithful to the HF architecture so genuine Qwen2-VL checkpoints
load and match the torch oracle (tests/test_qwen2vl_vision.py).

Reference claims EPD multimodal disaggregation as a headline feature but
keeps the encode stage out of repo (README.md:44); rounds 1-3 stood in a
synthetic ViT (models/vision.py, retained for registry models without
checkpoint dirs). This module is the checkpoint-bearing replacement:

- **Conv3D patch embed as one matmul**: the (tp, P, P)-kernel conv with
  stride == kernel over pre-flattened patches IS a linear layer on
  [C·tp·P·P] rows — the MXU-native form; no conv op needed.
- **2D rotary position embeddings**: per-patch (h, w) ids in the
  merge-block-major sequence order the HF image processor emits; half the
  head rotates by h-frequencies, half by w (HF rot_pos_emb semantics).
- **LayerNorm blocks, qkv+proj with bias, QuickGELU MLP** (the vision
  tower's norm/activation family differs from the RMS/SiLU text stack).
- **Per-image full attention** via segment masking (HF splits the packed
  sequence at cu_seqlens; a segment-id equality mask is the same math in
  one batched einsum — no Python loop over images).
- **PatchMerger**: ln_q, group spatial_merge_size² consecutive patches,
  2-layer GELU MLP into the language model's hidden size.
- Stacked layers + ``lax.scan``; fp32 softmax/norm/rope.

Grid geometry (``grid_thw``) is static at trace time — one compiled
program per image shape; the serving path resizes to a fixed grid so
there is exactly one.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from xllm_service_tpu.ops.norm import layer_norm

Qwen2VLVisionParams = Dict[str, Any]

# HF Qwen2VLImageProcessor normalization constants (OPENAI_CLIP_MEAN/STD).
CLIP_MEAN = np.asarray([0.48145466, 0.4578275, 0.40821073], np.float32)
CLIP_STD = np.asarray([0.26862954, 0.26130258, 0.27577711], np.float32)


@dataclasses.dataclass(frozen=True)
class Qwen2VLVisionConfig:
    """vision_config of a Qwen2-VL config.json, plus the serving-side
    fixed resize target (``image_size``) that pins one compiled grid."""

    depth: int = 32
    embed_dim: int = 1280
    num_heads: int = 16
    patch_size: int = 14
    temporal_patch_size: int = 2
    spatial_merge_size: int = 2
    mlp_ratio: float = 4.0
    in_channels: int = 3
    hidden_size: int = 3584          # language model hidden (output)
    image_size: int = 224            # host-side resize target
    dtype: str = "float32"

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def patch_dim(self) -> int:
        return (self.in_channels * self.temporal_patch_size
                * self.patch_size ** 2)

    @property
    def grid_side(self) -> int:
        return self.image_size // self.patch_size

    @property
    def tokens_per_image(self) -> int:
        """Merged (post-PatchMerger) tokens one fixed-grid image yields —
        what placeholder expansion splices into the prompt."""
        return self.grid_side ** 2 // self.spatial_merge_size ** 2

    @classmethod
    def from_hf_config(cls, d: Dict[str, Any],
                       image_size: int = 224) -> "Qwen2VLVisionConfig":
        """``d`` = config.json["vision_config"] of a qwen2_vl checkpoint.
        ``hidden_size`` in that block is already the LLM hidden. The
        serve-time resize target must tile exactly into merged patches —
        refuse a bad one here, at load, not as a numpy reshape error
        inside the first encode request."""
        unit = d.get("patch_size", 14) * d.get("spatial_merge_size", 2)
        if image_size <= 0 or image_size % unit != 0:
            raise ValueError(
                f"vision image_size {image_size} must be a positive "
                f"multiple of patch_size*spatial_merge_size ({unit})")
        return cls(
            depth=d.get("depth", 32),
            embed_dim=d.get("embed_dim", 1280),
            num_heads=d.get("num_heads", 16),
            patch_size=d.get("patch_size", 14),
            temporal_patch_size=d.get("temporal_patch_size", 2),
            spatial_merge_size=d.get("spatial_merge_size", 2),
            mlp_ratio=d.get("mlp_ratio", 4.0),
            in_channels=d.get("in_channels", 3),
            hidden_size=d.get("hidden_size", 3584),
            image_size=image_size,
        )

    @classmethod
    def tiny(cls, hidden_size: int = 48) -> "Qwen2VLVisionConfig":
        return cls(depth=2, embed_dim=64, num_heads=4, patch_size=4,
                   mlp_ratio=2.0, hidden_size=hidden_size, image_size=16)


def init_vision_params(cfg: Qwen2VLVisionConfig,
                       key: jax.Array) -> Qwen2VLVisionParams:
    """Random init in the exact tree shape ``load_checkpoint`` produces."""
    dtype = jnp.dtype(cfg.dtype)
    D, L = cfg.embed_dim, cfg.depth
    F = int(cfg.embed_dim * cfg.mlp_ratio)
    M = D * cfg.spatial_merge_size ** 2
    keys = iter(jax.random.split(key, 16))

    def w(shape, fan_in):
        return (jax.random.normal(next(keys), shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(dtype)

    return {
        "patch_embed": w((cfg.patch_dim, D), cfg.patch_dim),
        "blocks": {
            "norm1_w": jnp.ones((L, D), dtype),
            "norm1_b": jnp.zeros((L, D), dtype),
            "qkv_w": w((L, D, 3 * D), D),
            "qkv_b": jnp.zeros((L, 3 * D), dtype),
            "proj_w": w((L, D, D), D),
            "proj_b": jnp.zeros((L, D), dtype),
            "norm2_w": jnp.ones((L, D), dtype),
            "norm2_b": jnp.zeros((L, D), dtype),
            "fc1_w": w((L, D, F), D),
            "fc1_b": jnp.zeros((L, F), dtype),
            "fc2_w": w((L, F, D), F),
            "fc2_b": jnp.zeros((L, D), dtype),
        },
        "merger": {
            "ln_q_w": jnp.ones((D,), dtype),
            "ln_q_b": jnp.zeros((D,), dtype),
            "mlp0_w": w((M, M), M),
            "mlp0_b": jnp.zeros((M,), dtype),
            "mlp2_w": w((M, cfg.hidden_size), M),
            "mlp2_b": jnp.zeros((cfg.hidden_size,), dtype),
        },
    }


# ---------------------------------------------------------------------------
# Host-side geometry (numpy, static per grid)
# ---------------------------------------------------------------------------

def rot_pos_ids(grid_thw: Sequence[Tuple[int, int, int]],
                merge: int) -> np.ndarray:
    """Per-patch (h, w) position ids in the merge-block-major order the
    image processor flattens patches in (HF rot_pos_emb,
    modeling_qwen2_vl.py) → [S, 2] int32."""
    out: List[np.ndarray] = []
    for t, h, w in grid_thw:
        hp = np.broadcast_to(np.arange(h, dtype=np.int32)[:, None], (h, w))
        hp = hp.reshape(h // merge, merge, w // merge, merge) \
            .transpose(0, 2, 1, 3).reshape(-1)
        wp = np.broadcast_to(np.arange(w, dtype=np.int32)[None, :], (h, w))
        wp = wp.reshape(h // merge, merge, w // merge, merge) \
            .transpose(0, 2, 1, 3).reshape(-1)
        out.append(np.tile(np.stack([hp, wp], axis=-1), (t, 1)))
    return np.concatenate(out, axis=0)


def segment_ids(grid_thw: Sequence[Tuple[int, int, int]]) -> np.ndarray:
    """[S] int32 attention-segment id per patch. HF's cu_seqlens are
    ``repeat_interleave(h·w, t)`` — each temporal FRAME is its own full
    attention segment, not the whole image."""
    segs: List[np.ndarray] = []
    n = 0
    for t, h, w in grid_thw:
        for _ in range(t):
            segs.append(np.full(h * w, n, np.int32))
            n += 1
    return np.concatenate(segs)


def rotary_cos_sin(cfg: Qwen2VLVisionConfig,
                   grid_thw: Sequence[Tuple[int, int, int]]
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """cos/sin [S, head_dim] fp32: the first half of the rotary angles
    comes from the h position, the second from w; then duplicated
    (rotate_half layout), matching HF's cat((freqs, freqs))."""
    dim = cfg.head_dim // 2          # angles per position component pair
    inv_freq = 1.0 / (10000.0 ** (np.arange(0, dim, 2, dtype=np.float32)
                                  / dim))
    ids = rot_pos_ids(grid_thw, cfg.spatial_merge_size)       # [S, 2]
    freqs = ids[:, :, None].astype(np.float32) * inv_freq[None, None, :]
    emb = freqs.reshape(ids.shape[0], -1)                     # [S, hd/2]
    emb = np.concatenate([emb, emb], axis=-1)                 # [S, hd]
    return np.cos(emb), np.sin(emb)


def flatten_image(pixels: np.ndarray, cfg: Qwen2VLVisionConfig,
                  normalize: bool = True
                  ) -> Tuple[np.ndarray, Tuple[int, int, int]]:
    """[H, W, 3] (or [T, H, W, 3]) float in [0, 1] → the flattened-patch
    rows the tower consumes, in the HF image processor's exact ordering
    (image_processing_qwen2_vl.py:281-295: reshape to
    (t, tp, C, h/m, m, P, w/m, m, P), transpose (0,3,6,4,7,2,1,5,8)).
    A lone frame is repeated to fill temporal_patch_size, as the
    processor does."""
    if pixels.ndim == 3:
        pixels = pixels[None]
    T, H, W, C = pixels.shape
    P, tp, m = cfg.patch_size, cfg.temporal_patch_size, cfg.spatial_merge_size
    if normalize:
        pixels = (pixels.astype(np.float32) - CLIP_MEAN) / CLIP_STD
    x = pixels.transpose(0, 3, 1, 2)                          # [T, C, H, W]
    if T % tp:
        x = np.concatenate([x] + [x[-1:]] * (tp - T % tp), axis=0)
        T = x.shape[0]
    gt, gh, gw = T // tp, H // P, W // P
    x = x.reshape(gt, tp, C, gh // m, m, P, gw // m, m, P)
    x = x.transpose(0, 3, 6, 4, 7, 2, 1, 5, 8)
    return (x.reshape(gt * gh * gw, C * tp * P * P).astype(np.float32),
            (gt, gh, gw))


# ---------------------------------------------------------------------------
# The tower (jit-safe; grid geometry baked in as constants)
# ---------------------------------------------------------------------------

def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def _quick_gelu(x: jnp.ndarray) -> jnp.ndarray:
    return x * jax.nn.sigmoid(1.702 * x)


def encode_patches(params: Qwen2VLVisionParams, cfg: Qwen2VLVisionConfig,
                   patches: jnp.ndarray, cos: jnp.ndarray,
                   sin: jnp.ndarray, seg: jnp.ndarray) -> jnp.ndarray:
    """patches [S, C·tp·P·P] → merged embeddings [S/m², hidden_size].

    cos/sin [S, head_dim] and seg [S] come from ``rotary_cos_sin`` /
    ``segment_ids`` for the (static) grid; S must be a multiple of
    spatial_merge_size² with merge blocks consecutive in sequence order
    (guaranteed by ``flatten_image``)."""
    S = patches.shape[0]
    H, Dh = cfg.num_heads, cfg.head_dim
    dtype = jnp.dtype(cfg.dtype)
    x = patches.astype(dtype) @ params["patch_embed"]          # [S, D]
    mask = (seg[:, None] == seg[None, :])                      # [S, S]
    cos_h = cos[:, None, :]                                    # [S, 1, hd]
    sin_h = sin[:, None, :]

    def block(x, lp):
        h = layer_norm(x, lp["norm1_w"], lp["norm1_b"])
        qkv = (h @ lp["qkv_w"] + lp["qkv_b"]).reshape(S, 3, H, Dh)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]              # [S, H, Dh]
        q32, k32 = q.astype(jnp.float32), k.astype(jnp.float32)
        q = ((q32 * cos_h) + (_rotate_half(q32) * sin_h)).astype(q.dtype)
        k = ((k32 * cos_h) + (_rotate_half(k32) * sin_h)).astype(k.dtype)
        scale = 1.0 / jnp.sqrt(jnp.asarray(Dh, jnp.float32))
        logits = jnp.einsum("shd,thd->hst", q, k,
                            preferred_element_type=jnp.float32) * scale
        logits = jnp.where(mask[None], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("hst,thd->shd", p.astype(v.dtype), v)
        x = x + attn.reshape(S, -1) @ lp["proj_w"] + lp["proj_b"]
        h = layer_norm(x, lp["norm2_w"], lp["norm2_b"])
        h = _quick_gelu(h @ lp["fc1_w"] + lp["fc1_b"])
        x = x + (h @ lp["fc2_w"] + lp["fc2_b"])
        return x, None

    x, _ = jax.lax.scan(block, x, params["blocks"])
    mg = params["merger"]
    x = layer_norm(x, mg["ln_q_w"], mg["ln_q_b"])
    x = x.reshape(S // cfg.spatial_merge_size ** 2, -1)        # [S/m², m²D]
    x = jax.nn.gelu(x @ mg["mlp0_w"] + mg["mlp0_b"], approximate=False)
    return x @ mg["mlp2_w"] + mg["mlp2_b"]                     # [S/m², out]


def encode_images_fixed_grid(params: Qwen2VLVisionParams,
                             cfg: Qwen2VLVisionConfig,
                             pixel_batch: np.ndarray,
                             jit_fn=None) -> np.ndarray:
    """Serving entry: [N, image_size, image_size, 3] in [0, 1] → merged
    embeddings [N, tokens_per_image, hidden].

    One tower call PER IMAGE, all on the single fixed-grid shape: the
    compiled program is independent of how many images a request carries
    (no recompile per distinct N), and attention stays [S, S] per image
    rather than a mostly-masked [N·S, N·S] block."""
    fn = jit_fn if jit_fn is not None else encode_patches
    grid0 = None
    cos = sin = seg = None
    outs = []
    for img in pixel_batch:
        patches, grid = flatten_image(img, cfg)
        if grid != grid0:           # same for every image; compute once
            cos, sin = rotary_cos_sin(cfg, [grid])
            seg = segment_ids([grid])
            grid0 = grid
        outs.append(np.asarray(fn(
            params, cfg, jnp.asarray(patches), jnp.asarray(cos),
            jnp.asarray(sin), jnp.asarray(seg)), np.float32))
    return np.stack(outs)


# ---------------------------------------------------------------------------
# Qwen2.5-VL vision tower (variant): RMSNorm blocks, biased gated-SwiGLU
# MLPs, and WINDOW attention — merge-cells are reordered into
# window_size//merge//patch square windows, every layer attends within
# its window except the fullatt_block_indexes layers which attend across
# the whole image; the merger output is restored to the original order.
# (HF oracle: Qwen2_5_VisionTransformerPretrainedModel.)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Qwen25VLVisionConfig:
    depth: int = 32
    embed_dim: int = 1280            # vision_config.hidden_size
    num_heads: int = 16
    intermediate_size: int = 3420
    patch_size: int = 14
    temporal_patch_size: int = 2
    spatial_merge_size: int = 2
    in_channels: int = 3
    hidden_size: int = 3584          # out_hidden_size (LLM width)
    window_size: int = 112
    fullatt_block_indexes: Tuple[int, ...] = (7, 15, 23, 31)
    image_size: int = 224
    dtype: str = "float32"

    # Shared geometry with the 2-VL tower (same patch/merger layout).
    head_dim = Qwen2VLVisionConfig.head_dim
    patch_dim = Qwen2VLVisionConfig.patch_dim
    grid_side = Qwen2VLVisionConfig.grid_side
    tokens_per_image = Qwen2VLVisionConfig.tokens_per_image

    @classmethod
    def from_hf_config(cls, d: Dict[str, Any],
                       image_size: int = 224) -> "Qwen25VLVisionConfig":
        unit = d.get("patch_size", 14) * d.get("spatial_merge_size", 2)
        if image_size <= 0 or image_size % unit != 0:
            raise ValueError(
                f"vision image_size {image_size} must be a positive "
                f"multiple of patch_size*spatial_merge_size ({unit})")
        return cls(
            depth=d.get("depth", 32),
            embed_dim=d.get("hidden_size", 1280),
            num_heads=d.get("num_heads", 16),
            intermediate_size=d.get("intermediate_size", 3420),
            patch_size=d.get("patch_size", 14),
            temporal_patch_size=d.get("temporal_patch_size", 2),
            spatial_merge_size=d.get("spatial_merge_size", 2),
            in_channels=d.get("in_channels", 3),
            hidden_size=d.get("out_hidden_size", 3584),
            window_size=d.get("window_size", 112),
            fullatt_block_indexes=tuple(
                d.get("fullatt_block_indexes", (7, 15, 23, 31))),
            image_size=image_size,
        )


def window_order(cfg: Qwen25VLVisionConfig,
                 grid_thw: Sequence[Tuple[int, int, int]]
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """(window_index [S/m²], window segment ids [S]) — HF
    get_window_index: merge-cells regroup into vit_merger_window_size²
    square windows (ragged edges keep partial windows); the returned
    index permutes merge-cell blocks, the segment ids mark window
    membership PER PATCH in the permuted order."""
    m = cfg.spatial_merge_size
    win = cfg.window_size // m // cfg.patch_size
    order: List[np.ndarray] = []
    seg: List[np.ndarray] = []
    base = 0
    wid = 0
    for t, h, w in grid_thw:
        lh, lw = h // m, w // m
        idx = np.arange(t * lh * lw).reshape(t, lh, lw)
        pad_h = (-lh) % win
        pad_w = (-lw) % win
        padded = np.pad(idx, ((0, 0), (0, pad_h), (0, pad_w)),
                        constant_values=-100)
        nh, nw = (lh + pad_h) // win, (lw + pad_w) // win
        padded = padded.reshape(t, nh, win, nw, win) \
            .transpose(0, 1, 3, 2, 4).reshape(t * nh * nw, win * win)
        for row in padded:
            cells = row[row != -100]
            if cells.size:
                order.append(cells + base)
                seg.append(np.full(cells.size * m * m, wid, np.int32))
                wid += 1
        base += t * lh * lw
    return (np.concatenate(order).astype(np.int32),
            np.concatenate(seg))


def encode_patches_v25(params: Qwen2VLVisionParams,
                       cfg: Qwen25VLVisionConfig,
                       patches: jnp.ndarray, cos: jnp.ndarray,
                       sin: jnp.ndarray, seg_full: jnp.ndarray,
                       seg_win: jnp.ndarray,
                       reverse_index: jnp.ndarray) -> jnp.ndarray:
    """patches/cos/sin/segments arrive ALREADY in window order (host
    side reorders by ``window_order``); ``reverse_index`` restores the
    merged rows at the end. Per-layer attention scope: window segments
    except the fullatt_block_indexes layers (per-image segments)."""
    from xllm_service_tpu.ops.norm import rms_norm

    S = patches.shape[0]
    H, Dh = cfg.num_heads, cfg.head_dim
    dtype = jnp.dtype(cfg.dtype)
    x = patches.astype(dtype) @ params["patch_embed"]
    mask_full = (seg_full[:, None] == seg_full[None, :])
    mask_win = (seg_win[:, None] == seg_win[None, :])
    full_flags = jnp.asarray(
        [i in cfg.fullatt_block_indexes for i in range(cfg.depth)])
    cos_h = cos[:, None, :]
    sin_h = sin[:, None, :]

    def block(x, xs):
        lp, is_full = xs
        mask = jnp.where(is_full, mask_full, mask_win)
        h = rms_norm(x, lp["norm1_w"], 1e-6)
        qkv = (h @ lp["qkv_w"] + lp["qkv_b"]).reshape(S, 3, H, Dh)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
        q32, k32 = q.astype(jnp.float32), k.astype(jnp.float32)
        q = ((q32 * cos_h) + (_rotate_half(q32) * sin_h)).astype(q.dtype)
        k = ((k32 * cos_h) + (_rotate_half(k32) * sin_h)).astype(k.dtype)
        scale = 1.0 / jnp.sqrt(jnp.asarray(Dh, jnp.float32))
        logits = jnp.einsum("shd,thd->hst", q, k,
                            preferred_element_type=jnp.float32) * scale
        logits = jnp.where(mask[None], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("hst,thd->shd", p.astype(v.dtype), v)
        x = x + attn.reshape(S, -1) @ lp["proj_w"] + lp["proj_b"]
        h = rms_norm(x, lp["norm2_w"], 1e-6)
        h = jax.nn.silu(h @ lp["gate_w"] + lp["gate_b"]) \
            * (h @ lp["up_w"] + lp["up_b"])
        x = x + (h @ lp["down_w"] + lp["down_b"])
        return x, None

    x, _ = jax.lax.scan(block, x, (params["blocks"], full_flags))
    mg = params["merger"]
    x = rms_norm(x, mg["ln_q_w"], 1e-6)
    x = x.reshape(S // cfg.spatial_merge_size ** 2, -1)
    x = jax.nn.gelu(x @ mg["mlp0_w"] + mg["mlp0_b"], approximate=False)
    x = x @ mg["mlp2_w"] + mg["mlp2_b"]
    return x[reverse_index]


def encode_images_fixed_grid_v25(params, cfg: Qwen25VLVisionConfig,
                                 pixel_batch: np.ndarray,
                                 jit_fn=None) -> np.ndarray:
    """Serving entry for the 2.5 tower: one compiled fixed-grid program
    per image, window machinery precomputed host-side."""
    fn = jit_fn if jit_fn is not None else encode_patches_v25
    m2 = cfg.spatial_merge_size ** 2
    grid0 = None
    cached = None
    outs = []
    for img in pixel_batch:
        patches, grid = flatten_image(img, cfg)
        if grid != grid0:
            cos, sin = rotary_cos_sin(cfg, [grid])
            seg_full = segment_ids([grid])
            widx, seg_win = window_order(cfg, [grid])
            # Patch-level permutation from the merge-cell permutation.
            perm = (widx[:, None] * m2
                    + np.arange(m2, dtype=np.int32)[None, :]).reshape(-1)
            cached = (perm, cos[perm], sin[perm], seg_full[perm],
                      seg_win, np.argsort(widx).astype(np.int32))
            grid0 = grid
        perm, cosp, sinp, segf, segw, rev = cached
        outs.append(np.asarray(fn(
            params, cfg, jnp.asarray(patches[perm]), jnp.asarray(cosp),
            jnp.asarray(sinp), jnp.asarray(segf), jnp.asarray(segw),
            jnp.asarray(rev)), np.float32))
    return np.stack(outs)
