"""Model zoo for the TPU worker engine.

A single functional transformer (``transformer.py``) covers the Llama-2/3,
TinyLlama, and Qwen-2/2.5 dense families plus Mixtral-style MoE via
``ModelConfig`` switches; ``vision.py`` adds the ViT encoder used by the EPD
multimodal pipeline. Parameters are plain pytrees with layers stacked on a
leading axis so the forward pass is one ``lax.scan`` — one compiled layer
body regardless of depth.
"""

from xllm_service_tpu.models.transformer import (
    init_params,
    init_kv_cache,
    forward_prefill,
    forward_decode,
    num_params,
)

__all__ = ["init_params", "init_kv_cache", "forward_prefill",
           "forward_decode", "num_params"]
