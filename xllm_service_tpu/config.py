"""Configuration objects for the service layer, worker engine, and models.

``ServiceOptions`` mirrors the reference's gflags surface
(``common/global_gflags.cpp`` — ports, thread counts, etcd address, load
balance policy, block_size, murmur seed, SLO targets) as a typed dataclass;
``EngineConfig`` and ``ModelConfig`` configure the net-new TPU worker engine
that the reference delegated to NPU-side xLLM.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
from typing import Any, Dict, Optional, Tuple


class LoadBalancePolicyType(str, enum.Enum):
    ROUND_ROBIN = "RR"
    CACHE_AWARE = "CAR"
    SLO_AWARE = "SLO_AWARE"


class InstanceType(str, enum.Enum):
    """Worker roles. Mirrors reference ``common/types.h:71-79``; ENCODE is the
    net-new EPD multimodal encode role (reference claims EPD but keeps it
    engine-side)."""

    DEFAULT = "DEFAULT"
    PREFILL = "PREFILL"
    DECODE = "DECODE"
    MIX = "MIX"
    ENCODE = "ENCODE"


@dataclasses.dataclass
class ServiceOptions:
    """Service-process options (reference: common/global_gflags.cpp + options.h)."""

    host: str = "127.0.0.1"
    http_port: int = 9888
    rpc_port: int = 9889
    num_threads: int = 32
    max_concurrency: int = 128

    etcd_addr: str = ""           # empty → in-process coordination store
    load_balance_policy: LoadBalancePolicyType = LoadBalancePolicyType.CACHE_AWARE

    block_size: int = 128          # prefix-hash granularity (tokens per KV block)
    murmur_hash3_seed: int = 0

    tokenizer_path: str = ""
    model_id: str = ""

    enable_request_trace: bool = False
    # .jsonl: the file has always been JSON Lines (one record per line).
    trace_path: str = "trace/trace.jsonl"
    enable_decode_response_to_service: bool = False

    # SLO routing thresholds (hot-reloadable in the reference,
    # global_gflags.cpp:95-104).
    target_ttft_ms: float = 1000.0
    target_tpot_ms: float = 50.0

    # End-to-end bound on one generation (RPC fan-in waits, relay reads).
    request_timeout_s: float = 600.0

    # Cluster cadences.
    heartbeat_interval_s: float = 3.0
    master_upload_interval_s: float = 3.0
    detect_disconnected_instance_interval_s: float = 10.0

    # Token fan-in ordering pools (reference: scheduler.h:114).
    num_output_pools: int = 128

    # Multi-model serverless allocator budget per instance, GB
    # (reference: instance_mgr.h:143).
    instance_memory_budget_gb: float = 60.0

    def to_json(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["load_balance_policy"] = self.load_balance_policy.value
        return d


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture config covering Llama-2/3, Qwen2(.5), Qwen3, TinyLlama, and the
    MoE (Mixtral-style) variant used for expert parallelism.

    Frozen (hashable) so it can be a static jit argument — one compiled
    program per architecture. Derive variants with ``dataclasses.replace``.
    """

    name: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None            # default hidden_size // num_heads
    rope_theta: float = 10000.0
    # Frequency scaling from config.json:rope_scaling, in hashable tuple
    # form ("llama3", factor, low_freq, high_freq, original_max_pos) or
    # ("linear", factor, 0, 0, 0) — see ops/rope.py. None = unscaled.
    rope_scaling: Optional[Tuple[Any, ...]] = None
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    attention_bias: bool = False              # True for Qwen2 QKV
    # Per-head RMSNorm on q/k before rope (Qwen3's replacement for the
    # Qwen2 QKV bias).
    qk_norm: bool = False
    # Checkpoint stores fused qkv_proj / gate_up_proj rows (Phi-3).
    # Pure load/save-mapping concern: the in-memory tree keeps separate
    # projections, so compute paths are untouched.
    fused_proj: bool = False
    # Sliding-window attention width W (Mistral v0.1's 4096, Phi-3-mini's
    # 2047): each token attends only to the last W positions including
    # itself. None/0 = full causal attention. Threaded as a static mask
    # parameter through every attention path (ops/attention.py), so one
    # transformer body serves both regimes; the Pallas fast paths are
    # bypassed at trace time when a window is set.
    sliding_window: Optional[int] = None
    # Per-layer window activation (Gemma-2's alternating local/global
    # layers): tuple of bools, True = this layer uses sliding_window,
    # False = full attention. None = uniform (sliding_window applies to
    # every layer, or to none).
    layer_sliding: Optional[Tuple[bool, ...]] = None
    # Gemma-2 layer-body deltas (all default-off):
    # tanh soft-cap on attention logits / final lm_head logits.
    attn_logit_softcapping: float = 0.0
    final_logit_softcapping: float = 0.0
    # Attention scale = query_pre_attn_scalar**-0.5 instead of
    # head_dim**-0.5 (Gemma-2 fixes it at 256 regardless of head_dim).
    query_pre_attn_scalar: Optional[int] = None
    # Gemma family conventions: sqrt(hidden) embedding scale, the
    # four-norm block (post-attn/post-ffw norms on the SUBLAYER OUTPUT
    # before the residual add), and tanh-GELU gating in the MLP. The
    # (1 + weight) RMSNorm convention is normalized away at checkpoint
    # load (runtime/checkpoint.py adds 1; save subtracts it back).
    gemma: bool = False
    # Gemma-3: sliding (local) layers rotate with their own rope base
    # and WITHOUT the long-context scaling; full (global) layers use
    # rope_theta + rope_scaling. None = single rope base everywhere.
    rope_local_base_freq: Optional[float] = None
    # MoE (0 experts → dense MLP).
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Expert MLP width when it differs from the dense intermediate size
    # (Qwen3-MoE's moe_intermediate_size). None → intermediate_size.
    moe_intermediate_size: Optional[int] = None
    # Divide the selected experts' routing weights by their sum (Mixtral
    # semantics; Qwen3-MoE checkpoints declare it via norm_topk_prob —
    # False uses the raw softmax values).
    norm_topk_prob: bool = True
    # Checkpoint expert-key dialect: mlp.experts.N.{gate,up,down}_proj +
    # mlp.gate (Qwen3-MoE) vs block_sparse_moe.experts.N.w1/w3/w2 +
    # block_sparse_moe.gate (Mixtral).
    qwen_moe: bool = False
    # --- DeepSeek-V2 multi-head latent attention (MLA) ---
    # kv_lora_rank > 0 enables MLA: per token the cache holds ONE latent
    # row [kv_lora_rank + qk_rope_head_dim] instead of per-head K/V; the
    # up-projections are absorbed into the query/output sides so the
    # standard paged-attention machinery serves the latent pool with a
    # single KV "head" (models/transformer.py MLA branch).
    kv_lora_rank: int = 0
    q_lora_rank: Optional[int] = None   # None = direct q_proj (V2-Lite)
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    v_head_dim: int = 0
    # DeepSeek MoE shape: shared experts run densely beside the routed
    # ones; routed weights scale by routed_scaling_factor; device-limited
    # routing restricts the top-k to topk_group of n_group expert groups.
    n_shared_experts: int = 0
    routed_scaling_factor: float = 1.0
    topk_method: str = "greedy"         # or "group_limited_greedy"
    n_group: Optional[int] = None
    topk_group: Optional[int] = None
    # First k layers use a dense MLP (DeepSeek's first_k_dense_replace);
    # the layer stack splits into a dense prefix + MoE suffix scan.
    first_k_dense_replace: int = 0
    # DeepSeek-V3 deltas over V2: sigmoid routing with a learned
    # per-expert selection bias (e_score_correction_bias — biases the
    # CHOICE, not the weights) and top-2-sum group scores; a flag for
    # the rope sub-head pair layout; and yarn's mscale² folded into the
    # softmax scale. The mscale fold is keyed on the CHECKPOINT (yarn
    # with nonzero mscale_all_dim), matching DeepSeek's original
    # modeling code and vLLM for both V2 and V3 — real V2/V2-Lite
    # checkpoints ship mscale_all_dim 0.707. (HF's in-tree V2 port
    # omits the factor; that is its divergence, not ours.)
    moe_scoring: str = "softmax"        # or "sigmoid" (V3)
    rope_interleave: bool = True
    mla_yarn_mscale: bool = False
    # GPT-OSS: per-head attention-sink logits, biased q/k/v/o, a
    # post-top-k-softmax router with bias, and clamped-GLU experts
    # ((up+1)·gate·sigmoid(1.702·gate), clamp ±7) with fused interleaved
    # gate_up weights split at load. Alternating sliding layers reuse
    # the layer_sliding machinery.
    gptoss: bool = False
    # Sparse dispatch capacity factor (parallel/expert.py): each expert
    # takes ≤ ceil(k·G·cf/E) tokens per group. ≥ E/k guarantees no drops;
    # 0 selects the dense-compute oracle (every expert on every token).
    moe_capacity_factor: float = 2.0
    # Dispatch group size G: tokens route in groups so the dispatch /
    # combine masks are [G, E, C_g] per group — linear, not quadratic, in
    # window length (GShard's group axis; parallel/expert.py).
    moe_group_size: int = 512
    dtype: str = "bfloat16"

    def __post_init__(self) -> None:
        if self.head_dim is None:
            object.__setattr__(self, "head_dim",
                               self.hidden_size // self.num_heads)

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def is_mrope(self) -> bool:
        """Qwen2-VL-style 3-D multimodal rope (ops/rope.apply_mrope)."""
        return (self.rope_scaling is not None
                and self.rope_scaling[0] == "mrope")

    @property
    def mla(self) -> bool:
        return self.kv_lora_rank > 0

    @property
    def qk_head_dim(self) -> int:
        """Per-head query/key width under MLA (nope + rope parts)."""
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    @property
    def kv_cache_heads(self) -> int:
        """KV-pool head count: 1 latent "head" under MLA."""
        return 1 if self.mla else self.num_kv_heads

    @property
    def kv_cache_dim(self) -> int:
        """KV-pool per-head width: the latent row under MLA."""
        return (self.kv_lora_rank + self.qk_rope_head_dim if self.mla
                else self.head_dim)

    @classmethod
    def llama3_8b(cls) -> "ModelConfig":
        return cls(name="llama3-8b", vocab_size=128256, hidden_size=4096,
                   intermediate_size=14336, num_layers=32, num_heads=32,
                   num_kv_heads=8, rope_theta=500000.0,
                   max_position_embeddings=8192)

    @classmethod
    def llama3_1b(cls) -> "ModelConfig":
        # Llama-3.2-1B shape: the single-chip flagship for bench.py.
        return cls(name="llama3-1b", vocab_size=128256, hidden_size=2048,
                   intermediate_size=8192, num_layers=16, num_heads=32,
                   num_kv_heads=8, head_dim=64, rope_theta=500000.0,
                   max_position_embeddings=8192, tie_word_embeddings=True)

    @classmethod
    def qwen2_7b(cls) -> "ModelConfig":
        return cls(name="qwen2-7b", vocab_size=152064, hidden_size=3584,
                   intermediate_size=18944, num_layers=28, num_heads=28,
                   num_kv_heads=4, rope_theta=1000000.0, rms_norm_eps=1e-6,
                   attention_bias=True, max_position_embeddings=32768)

    @classmethod
    def qwen25_7b(cls) -> "ModelConfig":
        # Qwen2.5-7B: identical wiring to Qwen2-7B (per-checkpoint quirks
        # come from config.json when loading from a model dir).
        return dataclasses.replace(cls.qwen2_7b(), name="qwen2.5-7b")

    @classmethod
    def qwen3_8b(cls) -> "ModelConfig":
        # Qwen3-8B: qk-norm generation (no attention bias).
        return cls(name="qwen3-8b", vocab_size=151936, hidden_size=4096,
                   intermediate_size=12288, num_layers=36, num_heads=32,
                   num_kv_heads=8, head_dim=128, rope_theta=1000000.0,
                   rms_norm_eps=1e-6, max_position_embeddings=40960,
                   qk_norm=True)

    @classmethod
    def mistral_7b(cls) -> "ModelConfig":
        # Mistral-7B v0.3: llama wiring, full attention (v0.2+ dropped
        # the sliding window).
        return cls(name="mistral-7b", vocab_size=32768, hidden_size=4096,
                   intermediate_size=14336, num_layers=32, num_heads=32,
                   num_kv_heads=8, rope_theta=1000000.0,
                   max_position_embeddings=32768)

    @classmethod
    def mistral_7b_v01(cls) -> "ModelConfig":
        # Mistral-7B v0.1: the original sliding-window checkpoint
        # (W=4096 over a 32k position range).
        return cls(name="mistral-7b-v01", vocab_size=32000,
                   hidden_size=4096, intermediate_size=14336,
                   num_layers=32, num_heads=32, num_kv_heads=8,
                   rope_theta=10000.0, max_position_embeddings=32768,
                   sliding_window=4096)

    @classmethod
    def phi3_mini(cls) -> "ModelConfig":
        # Phi-3-mini-4k: llama-shaped compute, fused-projection files,
        # sliding window 2047 (as the real config.json declares).
        return cls(name="phi3-mini", vocab_size=32064, hidden_size=3072,
                   intermediate_size=8192, num_layers=32, num_heads=32,
                   num_kv_heads=32, rope_theta=10000.0,
                   max_position_embeddings=4096, fused_proj=True,
                   sliding_window=2047)

    @classmethod
    def qwen3_30b_a3b(cls) -> "ModelConfig":
        # Qwen3-30B-A3B: 128-expert top-8 MoE with qk-norm attention and
        # narrow expert MLPs (3B active of 30B total).
        return cls(name="qwen3-30b-a3b", vocab_size=151936,
                   hidden_size=2048, intermediate_size=6144,
                   moe_intermediate_size=768, num_layers=48, num_heads=32,
                   num_kv_heads=4, head_dim=128, rope_theta=1000000.0,
                   rms_norm_eps=1e-6, max_position_embeddings=40960,
                   qk_norm=True, num_experts=128, num_experts_per_tok=8,
                   norm_topk_prob=True, qwen_moe=True)

    @classmethod
    def deepseek_v2_lite(cls) -> "ModelConfig":
        # DeepSeek-V2-Lite: MLA (latent KV rank 512 + 64 rope dims → the
        # paged cache holds 576 values/token instead of 16·384), 64
        # routed + 2 shared experts, greedy top-6, one dense first layer.
        # Real checkpoints add yarn scaling (factor 40, mscale 0.707 both
        # ways → attention factor cancels to 1.0) with the mscale²
        # softmax-scale fold live (mscale_all_dim 0.707 ≠ 0).
        return cls(name="deepseek-v2-lite", vocab_size=102400,
                   hidden_size=2048, intermediate_size=10944,
                   moe_intermediate_size=1408, num_layers=27,
                   num_heads=16, num_kv_heads=16, head_dim=64,
                   rope_theta=10000.0, rms_norm_eps=1e-6,
                   max_position_embeddings=163840,
                   rope_scaling=("yarn", 40.0, 32.0, 1.0, 4096, 1.0,
                                 True, 0.707),
                   kv_lora_rank=512, qk_nope_head_dim=128,
                   qk_rope_head_dim=64, v_head_dim=128,
                   num_experts=64, num_experts_per_tok=6,
                   n_shared_experts=2, first_k_dense_replace=1,
                   routed_scaling_factor=1.0, norm_topk_prob=False,
                   mla_yarn_mscale=True)

    @classmethod
    def deepseek_v3(cls) -> "ModelConfig":
        # DeepSeek-V3/R1 shape: 256-expert top-8 with sigmoid scoring +
        # learned selection bias, 8-group device-limited routing, MLA
        # with q compression, 3 dense prefix layers, yarn long context
        # (mscale folded into the softmax scale).
        return cls(name="deepseek-v3", vocab_size=129280,
                   hidden_size=7168, intermediate_size=18432,
                   moe_intermediate_size=2048, num_layers=61,
                   num_heads=128, num_kv_heads=128, head_dim=64,
                   rope_theta=10000.0, rms_norm_eps=1e-6,
                   max_position_embeddings=163840,
                   rope_scaling=("yarn", 40.0, 32.0, 1.0, 4096, 1.0,
                                 True, 1.0),
                   kv_lora_rank=512, q_lora_rank=1536,
                   qk_nope_head_dim=128, qk_rope_head_dim=64,
                   v_head_dim=128, num_experts=256,
                   num_experts_per_tok=8, n_shared_experts=1,
                   first_k_dense_replace=3, n_group=8, topk_group=4,
                   routed_scaling_factor=2.5, norm_topk_prob=True,
                   topk_method="group_limited_greedy",
                   moe_scoring="sigmoid", mla_yarn_mscale=True)

    @classmethod
    def gpt_oss_20b(cls) -> "ModelConfig":
        # GPT-OSS-20B: 32-expert top-4 clamped-GLU MoE, attention sinks,
        # alternating 128-token sliding layers, yarn long context.
        return cls(name="gpt-oss-20b", vocab_size=201088,
                   hidden_size=2880, intermediate_size=2880,
                   moe_intermediate_size=2880, num_layers=24,
                   num_heads=64, num_kv_heads=8, head_dim=64,
                   rope_theta=150000.0, rms_norm_eps=1e-5,
                   max_position_embeddings=131072,
                   rope_scaling=("yarn", 32.0, 32.0, 1.0, 4096,
                                 1.3465735902799727, False, 0.0),
                   attention_bias=True, sliding_window=128,
                   layer_sliding=tuple((i + 1) % 2 == 1
                                       for i in range(24)),
                   num_experts=32, num_experts_per_tok=4,
                   norm_topk_prob=False, gptoss=True)

    @classmethod
    def gemma2_9b(cls) -> "ModelConfig":
        # Gemma-2-9B: alternating local/global attention (W=4096 on even
        # layers), soft-caps, four-norm blocks, GeGLU, 256-dim heads.
        return cls(name="gemma2-9b", vocab_size=256000, hidden_size=3584,
                   intermediate_size=14336, num_layers=42, num_heads=16,
                   num_kv_heads=8, head_dim=256, rope_theta=10000.0,
                   rms_norm_eps=1e-6, max_position_embeddings=8192,
                   tie_word_embeddings=True, sliding_window=4096,
                   layer_sliding=tuple((i + 1) % 2 == 1
                                       for i in range(42)),
                   attn_logit_softcapping=50.0,
                   final_logit_softcapping=30.0,
                   query_pre_attn_scalar=256, gemma=True)

    @classmethod
    def gemma3_12b(cls) -> "ModelConfig":
        # Gemma-3-12B text stack: 5:1 local:global layers (W=1024),
        # per-layer rope bases (local 10k unscaled, global 1M with 8x
        # linear scaling), qk-norm, no soft-caps.
        return cls(name="gemma3-12b", vocab_size=262208,
                   hidden_size=3840, intermediate_size=15360,
                   num_layers=48, num_heads=16, num_kv_heads=8,
                   head_dim=256, rope_theta=1000000.0,
                   rope_local_base_freq=10000.0, rms_norm_eps=1e-6,
                   max_position_embeddings=131072,
                   rope_scaling=("linear", 8.0, 0.0, 0.0, 0),
                   tie_word_embeddings=True, qk_norm=True,
                   sliding_window=1024,
                   layer_sliding=tuple((i + 1) % 6 != 0
                                       for i in range(48)),
                   query_pre_attn_scalar=256, gemma=True)

    @classmethod
    def mixtral_8x7b(cls) -> "ModelConfig":
        # Mixtral-8x7B: the expert-parallel flagship (parallel/expert.py
        # top-k dispatch; experts shard over the mesh's ep axis).
        return cls(name="mixtral-8x7b", vocab_size=32000, hidden_size=4096,
                   intermediate_size=14336, num_layers=32, num_heads=32,
                   num_kv_heads=8, rope_theta=1000000.0,
                   max_position_embeddings=32768, num_experts=8,
                   num_experts_per_tok=2)

    @classmethod
    def tiny(cls, vocab_size: int = 256, num_experts: int = 0) -> "ModelConfig":
        """Small config for CPU tests."""
        return cls(name="tiny", vocab_size=vocab_size, hidden_size=64,
                   intermediate_size=128, num_layers=2, num_heads=4,
                   num_kv_heads=2, head_dim=16, rope_theta=10000.0,
                   max_position_embeddings=512, num_experts=num_experts)

    @classmethod
    def from_hf_config(cls, d: Dict[str, Any], name: str = "hf") -> "ModelConfig":
        """Build from a HuggingFace ``config.json`` dict (LlamaConfig/Qwen2Config).

        Unsupported architectures are REFUSED here, not approximated: a
        model that needs sliding-window masks or layer-body deltas this
        transformer does not implement must fail at load, never emit
        silently-wrong tokens."""
        mt = d.get("model_type", "llama")
        supported = ("llama", "mistral", "qwen2", "qwen3", "phi3",
                     "mixtral", "gemma2", "gemma3", "gemma3_text",
                     "qwen2_vl", "qwen2_5_vl",
                     "qwen3_moe", "deepseek_v2", "deepseek_v3",
                     "gpt_oss")
        _dsk = mt in ("deepseek_v2", "deepseek_v3")
        if _dsk:
            tkm = d.get("topk_method")
            ok = ((None, "greedy", "group_limited_greedy")
                  if mt == "deepseek_v2"
                  # V3/R1 checkpoints say "noaux_tc" — the aux-loss-free
                  # biased sigmoid selection with grouped top-k, exactly
                  # the sigmoid gate implemented here.
                  else (None, "noaux_tc", "group_limited_greedy"))
            if tkm not in ok:
                raise ValueError(
                    f"deepseek topk_method {tkm!r} is not implemented")
            sf = d.get("scoring_func")
            want_sf = "sigmoid" if mt == "deepseek_v3" else "softmax"
            if sf is not None and sf != want_sf:
                raise ValueError(
                    f"{mt} with scoring_func {sf!r} is not implemented "
                    f"(expected {want_sf!r})")
        if mt == "qwen3_moe":
            # Mixed sparse/dense layer schedules can't share the one
            # scanned layer body — refuse, never approximate.
            if d.get("decoder_sparse_step", 1) != 1 \
                    or d.get("mlp_only_layers"):
                raise ValueError(
                    "qwen3_moe with dense layers (decoder_sparse_step "
                    "!= 1 or mlp_only_layers) is not implemented")
        if mt not in supported:
            raise ValueError(
                f"unsupported model_type {mt!r} (supported: "
                f"{', '.join(supported)})")
        if mt in ("qwen2_vl", "qwen2_5_vl", "gemma3"):
            # Current transformers nests the text stack under
            # text_config (published checkpoints keep it top-level) —
            # flatten, keeping the outer model_type.
            d = {**d, **d.get("text_config", {}), "model_type": mt}
        if mt in ("gemma3", "gemma3_text"):
            mt = "gemma3_text"
            # transformers' to_diff_dict omits class-default keys, and
            # Gemma3TextConfig's defaults differ from the generic HF
            # fallbacks below (head_dim 256 ≠ hidden/heads, theta 1e6,
            # 262k vocab, tied embeddings) — overlay them first so a
            # diff-style config.json loads faithfully.
            d = {**{"vocab_size": 262208, "head_dim": 256,
                    "rope_theta": 1000000.0,
                    "max_position_embeddings": 131072,
                    "sliding_window": 4096, "rms_norm_eps": 1e-6,
                    "tie_word_embeddings": True,
                    "query_pre_attn_scalar": 256,
                    "intermediate_size": d.get(
                        "intermediate_size", 9216),
                    "num_key_value_heads": d.get(
                        "num_key_value_heads", 4)},
                 **d, "model_type": mt}
            rs_kind = (d.get("rope_scaling") or {}).get(
                "rope_type", (d.get("rope_scaling") or {}).get("type"))
            if rs_kind not in (None, "default", "linear"):
                raise ValueError(
                    f"gemma3 rope_scaling {rs_kind!r} is not implemented "
                    f"(global layers support linear scaling only)")
        layer_sliding = None
        if mt in ("gemma2", "gemma3_text", "gpt_oss"):
            # Alternating local/global layers: HF's layer_types (or the
            # shared default pattern — sliding on even-indexed layers).
            L = d["num_hidden_layers"]
            if mt == "gemma3_text":
                # Gemma-3 default pattern: every 6th layer is global.
                lt = d.get("layer_types") or [
                    "full_attention" if (i + 1) % 6 == 0
                    else "sliding_attention" for i in range(L)]
            else:
                lt = d.get("layer_types") or [
                    "sliding_attention" if (i + 1) % 2
                    else "full_attention" for i in range(L)]
            layer_sliding = tuple(t == "sliding_attention" for t in lt)
        # sliding_window is honored for ANY supported model_type — real
        # Phi-3 checkpoints declare it too (Phi-3-mini-4k ships 2047), not
        # just Mistral v0.1 (round-3 advisor finding). A declared window
        # at least max_position_embeddings is inert and normalized away so
        # the full-attention fast paths stay eligible.
        sw = d.get("sliding_window") or None
        if sw is not None \
                and mt in ("qwen2", "qwen3", "qwen2_vl", "qwen2_5_vl",
                           "qwen3_moe") \
                and not d.get("use_sliding_window", False):
            # Qwen2-family raw config.json declares-but-disables the
            # window (e.g. Qwen2.5-7B-Instruct-1M: sliding_window 32768,
            # use_sliding_window false — and HF's default for the gate is
            # False, so an omitted key also means full attention): HF
            # torch normalizes it to None; so must we. Mistral/Phi-3
            # have no gate — a set window is always live there.
            sw = None
        if sw is not None \
                and sw >= d.get("max_position_embeddings", 4096) \
                and mt != "gemma3_text":
            # An at-least-context-wide window never binds, so dropping
            # it keeps full-attention fast paths eligible. Gemma-3 is
            # EXEMPT: its sliding/full layer pattern also selects the
            # per-layer rope base, which must survive even when the
            # window itself is inert (the mask is harmless then).
            sw = None
        if sw is not None:
            # Qwen2-family per-layer windows: the first max_window_layers
            # layers run FULL attention, the rest SWA. A uniform window
            # can express the all-SWA (0) and all-full (>= L) extremes
            # only; a genuine mix must refuse, not approximate.
            mwl = d.get("max_window_layers")
            L = d["num_hidden_layers"]
            if mwl is not None and 0 < mwl < L:
                raise ValueError(
                    f"per-layer sliding window (max_window_layers={mwl} "
                    f"of {L}) is not implemented")
            if mwl is not None and mwl >= L:
                sw = None           # every layer full attention — inert
        if layer_sliding is not None and not any(layer_sliding):
            # Every layer declared full attention: a shipped
            # sliding_window value is inert (HF ignores it too).
            sw = None
        if sw is None:
            layer_sliding = None
        elif layer_sliding is not None and all(layer_sliding):
            layer_sliding = None        # uniform window, static fast path
        parsed_rs = cls._parse_rope_scaling(
            d.get("rope_scaling"),
            d.get("max_position_embeddings", 4096))
        return cls(
            name=name,
            vocab_size=d["vocab_size"],
            hidden_size=d["hidden_size"],
            intermediate_size=d["intermediate_size"],
            num_layers=d["num_hidden_layers"],
            num_heads=d["num_attention_heads"],
            num_kv_heads=d.get("num_key_value_heads", d["num_attention_heads"]),
            head_dim=d.get("head_dim"),
            rope_theta=d.get("rope_theta", 10000.0),
            rms_norm_eps=d.get("rms_norm_eps", 1e-5),
            max_position_embeddings=d.get("max_position_embeddings", 4096),
            tie_word_embeddings=d.get("tie_word_embeddings",
                                      mt == "gemma2"),
            attention_bias=d.get("attention_bias",
                                 d.get("model_type")
                                 in ("qwen2", "qwen2_vl", "qwen2_5_vl",
                                     "gpt_oss")),
            qk_norm=d.get("model_type") in ("qwen3", "qwen3_moe",
                                            "gemma3_text"),
            fused_proj=d.get("model_type") == "phi3",
            sliding_window=sw,
            layer_sliding=layer_sliding,
            # HF's Gemma2Config DEFAULTS the caps to 50/30 when the keys
            # are absent; an explicit null disables them. Mirror both.
            attn_logit_softcapping=(
                (d["attn_logit_softcapping"] or 0.0
                 if "attn_logit_softcapping" in d else 50.0)
                if mt == "gemma2" else 0.0),
            final_logit_softcapping=(
                (d["final_logit_softcapping"] or 0.0
                 if "final_logit_softcapping" in d else 30.0)
                if mt == "gemma2" else 0.0),
            query_pre_attn_scalar=(
                d.get("query_pre_attn_scalar", 256)
                if mt in ("gemma2", "gemma3_text") else None),
            gemma=mt in ("gemma2", "gemma3_text"),
            rope_local_base_freq=(d.get("rope_local_base_freq", 10000.0)
                                  if mt == "gemma3_text" else None),
            num_experts=(d.get("num_experts", 0) if mt == "qwen3_moe"
                         else d.get("n_routed_experts", 0) if _dsk
                         else d.get("num_local_experts", 0)),
            num_experts_per_tok=d.get("num_experts_per_tok", 2),
            moe_intermediate_size=d.get("moe_intermediate_size"),
            kv_lora_rank=(d.get("kv_lora_rank") or 0) if _dsk else 0,
            q_lora_rank=d.get("q_lora_rank") if _dsk else None,
            qk_nope_head_dim=d.get("qk_nope_head_dim", 0) if _dsk else 0,
            qk_rope_head_dim=d.get("qk_rope_head_dim", 0) if _dsk else 0,
            v_head_dim=d.get("v_head_dim", 0) if _dsk else 0,
            n_shared_experts=(d.get("n_shared_experts") or 0) if _dsk
            else 0,
            routed_scaling_factor=d.get("routed_scaling_factor", 1.0),
            # V3's "noaux_tc" IS grouped selection under sigmoid scoring.
            topk_method=("group_limited_greedy" if mt == "deepseek_v3"
                         else d.get("topk_method", "greedy")),
            n_group=d.get("n_group"),
            topk_group=d.get("topk_group"),
            first_k_dense_replace=(d.get("first_k_dense_replace", 0)
                                   if _dsk else 0),
            moe_scoring="sigmoid" if mt == "deepseek_v3" else "softmax",
            gptoss=mt == "gpt_oss",
            rope_interleave=bool(d.get("rope_interleave", True)),
            # The mscale² softmax-scale fold follows the CHECKPOINT, not
            # the model_type: DeepSeek's own modeling code (and vLLM)
            # apply it whenever yarn ships a nonzero mscale_all_dim —
            # real V2/V2-Lite checkpoints carry 0.707 — while HF's
            # in-tree V2 port omits it (round-4 advisor finding).
            mla_yarn_mscale=bool(
                _dsk and parsed_rs is not None and parsed_rs[0] == "yarn"
                and len(parsed_rs) > 7 and parsed_rs[7]),
            # HF defaults: Mixtral always normalizes top-k weights;
            # Qwen3MoeConfig defaults norm_topk_prob to FALSE when the
            # key is absent; the DeepSeek-V2 gate never normalizes.
            norm_topk_prob=bool(d.get("norm_topk_prob",
                                      mt != "qwen3_moe"))
            and mt != "deepseek_v2",
            qwen_moe=mt == "qwen3_moe",
            rope_scaling=parsed_rs,
        )

    @staticmethod
    def _parse_rope_scaling(rs: Optional[Dict[str, Any]],
                            max_position_embeddings: int = 4096
                            ) -> Optional[Tuple[Any, ...]]:
        """config.json:rope_scaling dict → the hashable tuple ops/rope.py
        takes. Unknown types raise at load time rather than silently
        mis-rotating positions (checkpoint-fidelity contract)."""
        if not rs:
            return None
        kind = rs.get("rope_type", rs.get("type"))
        if rs.get("mrope_section") and kind in (None, "default", "mrope"):
            # Qwen2-VL 3-D multimodal rope: (t, h, w) frequency-band
            # sections (ops/rope.py apply_mrope). Published checkpoints
            # say type "mrope"; transformers re-serializes it as
            # "default" + mrope_section.
            return ("mrope", tuple(int(s) for s in rs["mrope_section"]))
        if kind in (None, "default"):
            return None
        if kind == "llama3":
            return ("llama3", float(rs["factor"]),
                    float(rs["low_freq_factor"]),
                    float(rs["high_freq_factor"]),
                    int(rs["original_max_position_embeddings"]))
        if kind == "linear":
            return ("linear", float(rs["factor"]), 0.0, 0.0, 0)
        if kind == "yarn":
            # NTK-by-parts (YaRN, 2309.00071): low-frequency bands
            # interpolate by `factor`, high-frequency extrapolate, a
            # linear ramp blends between; cos/sin scale by the attention
            # factor (inferred from factor/mscale when not explicit —
            # HF modeling_rope_utils._compute_yarn_parameters).
            factor = float(rs["factor"])
            attn = rs.get("attention_factor")
            if attn is None:
                ms, msa = rs.get("mscale"), rs.get("mscale_all_dim")

                def _mscale(scale, m=1.0):
                    import math
                    return (0.1 * m * math.log(scale) + 1.0) if scale > 1 \
                        else 1.0

                attn = (_mscale(factor, ms) / _mscale(factor, msa)
                        if ms and msa else _mscale(factor))
            orig = int(rs.get("original_max_position_embeddings")
                       or max_position_embeddings)
            return ("yarn", factor,
                    float(rs.get("beta_fast") or 32.0),
                    float(rs.get("beta_slow") or 1.0),
                    orig, float(attn),
                    bool(rs.get("truncate", True)),
                    float(rs.get("mscale_all_dim") or 0.0))
        raise NotImplementedError(
            f"rope_scaling type {kind!r} not supported")


@dataclasses.dataclass
class EngineConfig:
    """Worker-engine runtime config (paged KV cache + continuous batching)."""

    page_size: int = 64                 # tokens per KV page (HBM granularity)
    num_pages: int = 1024               # KV pool size (per layer, per chip-shard)
    max_model_len: int = 2048           # max tokens per sequence
    max_batch_size: int = 8             # decode batch capacity
    max_prefill_tokens: int = 2048      # prefill token budget per step
    prefill_buckets: Tuple[int, ...] = (64, 128, 256, 512, 1024, 2048)
    enable_prefix_cache: bool = True
    # Decode steps fused into ONE compiled program per host round-trip
    # (lax.scan over the step body). >1 amortizes host↔device dispatch
    # latency across N tokens — the dominant cost when the chip sits
    # behind a network tunnel or under Python dispatch overhead. Finish
    # detection runs on host afterwards; tokens sampled past a stop are
    # discarded (bounded waste of N-1 steps worst case).
    decode_steps: int = 1
    # Top-k alternative logprobs computed inside every compiled step
    # (static k — 0 disables the top_k entirely; OpenAI callers may ask
    # for at most this many ``top_logprobs``).
    num_top_logprobs: int = 0
    # Parallel degrees of this instance's mesh.
    tp: int = 1
    dp: int = 1
    sp: int = 1
    # Offline (batch) requests are preempted by online ones.
    max_num_seqs: int = 256             # scheduler queue cap
    # Write-then-attend KV plumbing (round-5 "known residue" fix): the
    # pool rides the layer scan as a carry, each layer writes its fresh
    # K/V in place (aliased Pallas writer) BEFORE attending, and the
    # attention kernels read everything — including the current window /
    # token — from the pool. Kills the jit-call-boundary pool copies XLA
    # inserts around the post-scan writer (~10-15 GB per prefill call at
    # the bench shape). None = auto: on wherever the Pallas kernels are
    # on (pallas.enabled()), off on the pure-XLA path, resolved at
    # Engine init. Env XLLM_WRITE_THEN_ATTEND=0/1 overrides.
    write_then_attend: Optional[bool] = None
    # Pipelined decode: after dispatching fused burst k, start its
    # device→host copy asynchronously and — while the batch snapshot
    # still matches — speculatively dispatch burst k+1 from the
    # device-resident carries BEFORE blocking on burst k's readback, so
    # the host post (stop detection, page bookkeeping, prefix-cache
    # registration) overlaps the next burst's device compute. A wrong
    # speculation (finish/preempt/admit) is discarded and re-dispatched
    # from host truth; token streams are byte-identical either way
    # (pinned in tests/test_engine.py). None = auto: on when
    # decode_steps > 1, off for single-step decode. Env
    # XLLM_DECODE_PIPELINE=0/1 overrides.
    decode_pipeline: Optional[bool] = None
    # One-dispatch ragged mixed steps: when on, an interleaved iteration
    # with both running decoders and schedulable prefill windows packs
    # BOTH into one ragged batch (decode rows are length-1 continuation
    # windows) and launches ONE attention program
    # (ops/pallas/ragged_attention.py) instead of a decode burst plus a
    # prefill call. Pure-decode and pure-prefill iterations keep their
    # dedicated programs (the fused burst + speculation pipeline stays).
    # None = auto: off (opt-in while the kernel soaks). Env
    # XLLM_RAGGED_ATTN=0/1 overrides; read once at Engine init.
    ragged_attn: Optional[bool] = None
    # Token-budget prefill/decode interleaving (staggered admission,
    # arxiv 2512.16134): every engine iteration decodes the running set
    # FIRST (bounding TPOT by construction), then spends the residual of
    # the per-iteration token budget on chunked-prefill windows — the
    # prefill quantum shrinks under decode load instead of the engine
    # running prompt-priority steps that stall every live stream.
    # None = auto (on). Off restores the pre-interleaver prefill-first
    # routing (the control that shows the decode stall). Env
    # XLLM_INTERLEAVE=0/1 overrides.
    interleave: Optional[bool] = None
    # Per-iteration token budget the interleaver splits between the
    # decode burst and prefill windows. 0 = default from
    # max_prefill_tokens. Env XLLM_STEP_TOKEN_BUDGET overrides.
    step_token_budget: int = 0
    # Anti-starvation deadline (ms): once the oldest waiting prompt has
    # queued past this, the iteration's prefill budget is floored at one
    # minimum quantum (smallest prefill bucket) even if decode consumed
    # the whole token budget. Derived from the service plane's default
    # TTFT target (1000 ms): half the budget reserved for queueing
    # leaves the other half for the prefill itself. 0 = the floor
    # applies every iteration. Env XLLM_PREFILL_DEADLINE_MS overrides.
    prefill_deadline_ms: float = 500.0
    # Tiered KV spill (docs/KV_CACHE.md): when > 0, prefix-cache pages
    # evicted from HBM under allocation pressure are parked in a bounded
    # host-DRAM tier of this many MB instead of dropped, and restored
    # through the donated pool scatter on a later prefix hit. 0 = off
    # (evictions drop content, the pre-tier behavior). Env
    # XLLM_KV_SPILL_MB overrides.
    kv_spill_mb: float = 0.0
    # Optional disk tier behind the DRAM tier: blocks LRU-demoted from
    # DRAM land as raw header+bytes .kv files under this directory
    # (cold path; .npz can't round-trip ml_dtypes bfloat16), bounded by
    # kv_spill_disk_mb. Needs BOTH knobs: an empty dir OR a zero budget
    # means no disk tier (demotions drop). Env XLLM_KV_SPILL_DIR /
    # XLLM_KV_SPILL_DISK_MB override.
    kv_spill_dir: str = ""
    kv_spill_disk_mb: float = 0.0

    def __post_init__(self) -> None:
        if self.max_model_len % self.page_size != 0:
            raise ValueError(
                f"max_model_len={self.max_model_len} must be a multiple of "
                f"page_size={self.page_size}")
        self.max_pages_per_seq = self.max_model_len // self.page_size
        # Every chunked-prefill window start is a sum of earlier bucket
        # sizes, so starts stay page-aligned iff EVERY bucket is a page
        # multiple. The in-place prefill KV-write kernel requires that
        # alignment; mixed buckets (e.g. a 200-token bucket on 64-token
        # pages) keep the XLA scatter path instead of corrupting pools.
        self.prefill_page_aligned = all(
            b % self.page_size == 0 for b in self.prefill_buckets)
        env = os.environ.get("XLLM_WRITE_THEN_ATTEND", "").strip()
        if env in ("0", "false", "no"):
            self.write_then_attend = False
        elif env in ("1", "true", "yes"):
            self.write_then_attend = True
        env = os.environ.get("XLLM_DECODE_PIPELINE", "").strip()
        if env in ("0", "false", "no"):
            self.decode_pipeline = False
        elif env in ("1", "true", "yes"):
            self.decode_pipeline = True
        env = os.environ.get("XLLM_RAGGED_ATTN", "").strip()
        if env in ("0", "false", "no"):
            self.ragged_attn = False
        elif env in ("1", "true", "yes"):
            self.ragged_attn = True
        env = os.environ.get("XLLM_INTERLEAVE", "").strip()
        if env in ("0", "false", "no"):
            self.interleave = False
        elif env in ("1", "true", "yes"):
            self.interleave = True
        env = os.environ.get("XLLM_STEP_TOKEN_BUDGET", "").strip()
        if env:
            try:
                self.step_token_budget = int(env)
            except ValueError:
                pass
        env = os.environ.get("XLLM_PREFILL_DEADLINE_MS", "").strip()
        if env:
            try:
                self.prefill_deadline_ms = float(env)
            except ValueError:
                pass
        env = os.environ.get("XLLM_KV_SPILL_MB", "").strip()
        if env:
            try:
                self.kv_spill_mb = float(env)
            except ValueError:
                pass
        env = os.environ.get("XLLM_KV_SPILL_DIR", "").strip()
        if env:
            self.kv_spill_dir = env
        env = os.environ.get("XLLM_KV_SPILL_DISK_MB", "").strip()
        if env:
            try:
                self.kv_spill_disk_mb = float(env)
            except ValueError:
                pass


def load_json(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def options_from_env(**overrides: Any) -> ServiceOptions:
    """Build ServiceOptions honoring the reference's env toggles
    (``ENABLE_DECODE_RESPONSE_TO_SERVICE``, ``ENABLE_XLLM_DEBUG_LOG`` —
    http_service/service.cpp:54-55, common/utils.cpp:28-41)."""
    opts = ServiceOptions(**overrides)
    if os.environ.get("ENABLE_DECODE_RESPONSE_TO_SERVICE", "").lower() in (
            "1", "true", "yes"):
        opts.enable_decode_response_to_service = True
    raw_mc = os.environ.get("XLLM_MAX_CONCURRENCY", "").strip()
    if raw_mc and "max_concurrency" not in overrides:
        # Admission-gate ceiling override: the saturation harness
        # (benchmarks/service_bench.py --saturate) spawns a master that
        # must admit thousands of concurrent streams; there is no CLI
        # flag for it because only benchmarks legitimately raise it.
        try:
            opts.max_concurrency = max(1, int(raw_mc))
        except ValueError:
            pass
    return opts
