"""Chained MurmurHash3 block hashing for the cluster-wide prefix KV-cache index.

The global prefix-cache index keys KV blocks by a 128-bit chained hash:
``digest(block_i) = murmur3_x64_128(digest(block_{i-1}) || le32(tokens_i))``.
This mirrors the reference's chained block hashing
(``common/hash_util.cpp:16-42``) used by ``GlobalKVCacheMgr``
(``scheduler/managers/global_kvcache_mgr.cpp:71-129``), with a proper 16-byte
equality (the reference's ``Murmur3Key::operator==`` via ``strncmp`` is buggy
on embedded NUL bytes — hash_util.h:31-35 — and is deliberately not
replicated).

The hot path lives in the native library ``csrc/xllm_native.cpp`` (built once
on demand with the system C++ toolchain and loaded via ctypes). A pure-Python
implementation is kept both as a fallback and as a cross-check in tests.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading


from typing import List, Optional, Sequence
from xllm_service_tpu.utils.locks import make_lock

_MASK64 = (1 << 64) - 1


def _rotl64(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _MASK64


def _fmix64(k: int) -> int:
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & _MASK64
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & _MASK64
    k ^= k >> 33
    return k


def murmur3_x64_128_py(data: bytes, seed: int = 0) -> bytes:
    """Pure-Python MurmurHash3_x64_128. Returns 16 bytes (h1 || h2, LE)."""
    length = len(data)
    nblocks = length // 16
    # The native path takes a uint32 seed; mask identically here so both
    # implementations stay bit-identical for any Python int seed.
    seed &= 0xFFFFFFFF
    h1 = seed
    h2 = seed
    c1 = 0x87C37B91114253D5
    c2 = 0x4CF5AD432745937F

    for i in range(nblocks):
        k1, k2 = struct.unpack_from("<QQ", data, i * 16)
        k1 = (k1 * c1) & _MASK64
        k1 = _rotl64(k1, 31)
        k1 = (k1 * c2) & _MASK64
        h1 ^= k1
        h1 = _rotl64(h1, 27)
        h1 = (h1 + h2) & _MASK64
        h1 = (h1 * 5 + 0x52DCE729) & _MASK64
        k2 = (k2 * c2) & _MASK64
        k2 = _rotl64(k2, 33)
        k2 = (k2 * c1) & _MASK64
        h2 ^= k2
        h2 = _rotl64(h2, 31)
        h2 = (h2 + h1) & _MASK64
        h2 = (h2 * 5 + 0x38495AB5) & _MASK64

    tail = data[nblocks * 16:]
    k1 = 0
    k2 = 0
    tl = len(tail)
    for i in range(min(tl, 16) - 1, 7, -1):
        k2 ^= tail[i] << ((i - 8) * 8)
    if tl > 8:
        k2 = (k2 * c2) & _MASK64
        k2 = _rotl64(k2, 33)
        k2 = (k2 * c1) & _MASK64
        h2 ^= k2
    for i in range(min(tl, 8) - 1, -1, -1):
        k1 ^= tail[i] << (i * 8)
    if tl > 0:
        k1 = (k1 * c1) & _MASK64
        k1 = _rotl64(k1, 31)
        k1 = (k1 * c2) & _MASK64
        h1 ^= k1

    h1 ^= length
    h2 ^= length
    h1 = (h1 + h2) & _MASK64
    h2 = (h2 + h1) & _MASK64
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = (h1 + h2) & _MASK64
    h2 = (h2 + h1) & _MASK64
    return struct.pack("<QQ", h1, h2)


# ---------------------------------------------------------------------------
# Native library loading (built on demand from csrc/xllm_native.cpp).
# ---------------------------------------------------------------------------

_native_lock = make_lock("hashing.native", 95)
_native_lib: Optional[ctypes.CDLL] = None
_native_tried = False


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_native() -> Optional[str]:
    root = _repo_root()
    src = os.path.join(root, "csrc", "xllm_native.cpp")
    if not os.path.exists(src):
        return None
    out_dir = os.path.join(root, "build", "native")
    os.makedirs(out_dir, exist_ok=True)
    so = os.path.join(out_dir, "libxllm_native.so")
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    cxx = os.environ.get("CXX", "g++")
    # Compile to a process-unique temp name and rename atomically so a
    # concurrent process can never dlopen a partially written library.
    tmp = f"{so}.{os.getpid()}.tmp"
    cmd = [cxx, "-O2", "-std=c++17", "-shared", "-fPIC", src, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
    except Exception:  # noqa: BLE001 — no toolchain / compile failure:
        # None falls back to the pure-python murmur path
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return so


def _load_native() -> Optional[ctypes.CDLL]:
    global _native_lib, _native_tried
    with _native_lock:
        if _native_tried:
            return _native_lib
        _native_tried = True
        if os.environ.get("XLLM_DISABLE_NATIVE"):
            return None
        so = _build_native()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.xllm_murmur3_x64_128.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_uint32, ctypes.c_void_p]
            lib.xllm_prefix_block_hashes.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int32, ctypes.c_int32,
                ctypes.c_uint32, ctypes.c_void_p]
            lib.xllm_prefix_block_hashes.restype = ctypes.c_int32
            _native_lib = lib
        except OSError:
            _native_lib = None
        return _native_lib


def native_available() -> bool:
    return _load_native() is not None


def murmur3_x64_128(data: bytes, seed: int = 0) -> bytes:
    lib = _load_native()
    if lib is None:
        return murmur3_x64_128_py(data, seed)
    out = ctypes.create_string_buffer(16)
    lib.xllm_murmur3_x64_128(data, len(data), seed & 0xFFFFFFFF, out)
    return out.raw


def _as_i32(t: int) -> int:
    # Token ids are hashed as little-endian int32. Out-of-range values wrap
    # deterministically so the native and Python paths stay bit-identical.
    return ((t & 0xFFFFFFFF) ^ 0x80000000) - 0x80000000


def chained_block_hash_py(tokens: Sequence[int], prev: Optional[bytes],
                          seed: int = 0) -> bytes:
    buf = (prev or b"") + struct.pack(
        f"<{len(tokens)}i", *[_as_i32(t) for t in tokens])
    return murmur3_x64_128_py(buf, seed)


def prefix_block_hashes(tokens: Sequence[int], block_size: int,
                        seed: int = 0) -> List[bytes]:
    """Chained digests of every *complete* ``block_size`` window of ``tokens``.

    The trailing partial block is excluded: the prefix-cache index only tracks
    full blocks, matching the KV-page granularity of the worker.
    """
    n_blocks = len(tokens) // block_size
    if n_blocks == 0:
        return []
    lib = _load_native()
    if lib is None:
        out: List[bytes] = []
        prev: Optional[bytes] = None
        for b in range(n_blocks):
            d = chained_block_hash_py(
                tokens[b * block_size:(b + 1) * block_size], prev, seed)
            out.append(d)
            prev = d
        return out
    arr = (ctypes.c_int32 * (n_blocks * block_size))(
        *[_as_i32(t) for t in tokens[: n_blocks * block_size]])
    buf = ctypes.create_string_buffer(16 * n_blocks)
    lib.xllm_prefix_block_hashes(arr, n_blocks * block_size, block_size,
                                 seed & 0xFFFFFFFF, buf)
    raw = buf.raw
    return [raw[i * 16:(i + 1) * 16] for i in range(n_blocks)]


def prompt_digest(tokens: Sequence[int], seed: int = 0) -> str:
    """Whole-prompt content digest (hex) for the poison ledger
    (docs/ROBUSTNESS.md): unlike ``prefix_block_hashes`` it covers the
    trailing partial block too — two prompts quarantine together iff
    they are token-identical. Same int32 packing as the block hashes,
    so the digest is stable across the native and Python paths."""
    data = struct.pack(f"<{len(tokens)}i", *[_as_i32(t) for t in tokens])
    return murmur3_x64_128(data, seed).hex()
