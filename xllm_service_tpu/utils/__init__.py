from xllm_service_tpu.utils.hashing import (  # noqa: F401
    murmur3_x64_128,
    murmur3_x64_128_py,
    native_available,
    prefix_block_hashes,
)
from xllm_service_tpu.utils.misc import (  # noqa: F401
    AtomicCounter,
    OrderedFanInPools,
    is_port_available,
    json_path,
    pick_free_port,
    short_uuid,
)
from xllm_service_tpu.utils.types import (  # noqa: F401
    FinishReason,
    LogProb,
    OutputCallback,
    Request,
    RequestOutput,
    Routing,
    SamplingParams,
    SequenceOutput,
    Status,
    StatusCode,
    Usage,
)
