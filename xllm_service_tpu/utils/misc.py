"""Small shared helpers: short uuids, ordered fan-in pools, ports, json paths.

Python equivalents of the reference's ``common/`` substrate: ``ShortUUID``
(xllm/uuid.h), the 128 single-thread output pools that preserve per-request
token order (scheduler.h:113-120), port availability checks (utils.cpp:43-66)
and dot-path JSON access (json_reader.h).
"""

from __future__ import annotations

import queue
import secrets
import socket
import threading


from typing import Any, Callable, Dict, List, Optional
from xllm_service_tpu.utils.locks import make_lock
from xllm_service_tpu.utils import threads
from xllm_service_tpu.utils.threads import spawn

_ALPHABET = "23456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"


def short_uuid(length: int = 22) -> str:
    """URL-safe short random id (reference: common/xllm/uuid.{h,cpp}).

    One ``token_bytes`` call, not ``secrets.choice`` per character — the
    per-character form costs an urandom syscall each and profiled at
    ~4 ms per id on the request hot path (ids are identifiers, not key
    material; the tiny modulo bias is irrelevant)."""
    raw = secrets.token_bytes(length)
    n = len(_ALPHABET)
    return "".join(_ALPHABET[b % n] for b in raw)


def is_port_available(port: int, host: str = "127.0.0.1") -> bool:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind((host, port))
            return True
        except OSError:
            return False


def pick_free_port(host: str = "127.0.0.1") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def json_path(d: Dict[str, Any], path: str, default: Any = None) -> Any:
    """Dot-path JSON access: ``json_path(cfg, "a.b.c")``
    (reference: common/json_reader.h)."""
    cur: Any = d
    for part in path.split("."):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        else:
            return default
    return cur


class _SerialWorker:
    """A single-thread executor draining a FIFO queue."""

    def __init__(self, name: str) -> None:
        self._q: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        # Supervised (utils/threads.py): the per-callback handler below
        # protects siblings from a bad callback; the spawn handler makes
        # a crash of the drain loop itself visible instead of silent.
        self._thread = spawn("misc.fanin", self._run, thread_name=name)
        self._thread.start()

    def submit(self, fn: Callable[[], None]) -> None:
        self._q.put(fn)

    def _run(self) -> None:
        while True:
            fn = self._q.get()
            if fn is None:
                return
            try:
                fn()
            except Exception as e:
                # A bad callback must not kill the pool (its siblings'
                # token streams ride the same thread) — but the drop is
                # logged + counted (xllm_callback_errors_total), not
                # printed to an untailed stderr (xlint rule 16).
                threads.record_callback_error("misc.fanin", e)

    def stop(self) -> None:
        self._q.put(None)

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)


class OrderedFanInPools:
    """N single-thread pools; each request is pinned to one pool so its token
    stream is delivered in order while different requests run concurrently.

    Reproduces the reference's 128-pool token fan-in design
    (scheduler/scheduler.h:113-120, scheduler.cpp:348-369).
    """

    def __init__(self, num_pools: int = 128) -> None:
        self._pools = [_SerialWorker(f"fanin-{i}") for i in range(num_pools)]
        self._lock = make_lock("misc.pool", 90)
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def pool_for(self, request_id: str) -> int:
        with self._lock:
            idx = self._assignment.get(request_id)
            if idx is None:
                idx = self._next % len(self._pools)
                self._next += 1
                self._assignment[request_id] = idx
            return idx

    def submit(self, request_id: str, fn: Callable[[], None]) -> None:
        self._pools[self.pool_for(request_id)].submit(fn)

    def release(self, request_id: str) -> None:
        with self._lock:
            self._assignment.pop(request_id, None)

    def drain(self) -> None:
        """Block until every queued callback has run (test helper)."""
        done = threading.Barrier(len(self._pools) + 1)
        for p in self._pools:
            p.submit(lambda: done.wait())
        done.wait()

    def stop(self) -> None:
        for p in self._pools:
            p.stop()
        for p in self._pools:
            p.join(timeout=5)


class AtomicCounter:
    def __init__(self, start: int = 0) -> None:
        self._v = start
        self._lock = make_lock("misc.counter", 91)

    def inc(self, n: int = 1) -> int:
        with self._lock:
            self._v += n
            return self._v

    @property
    def value(self) -> int:
        with self._lock:
            return self._v
